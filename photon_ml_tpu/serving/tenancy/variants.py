"""Variant registry: N GLMix model variants served by ONE sharded scorer.

Photon-ML deployments are inherently multi-model — A/B candidates,
per-market models, ramped rollouts — but N full scorers would cost N
device tables, N compile caches, and N admission planes for models that
differ in a few thousand rows. This module serves every variant from the
shared scorer instead, exploiting the same structure the local/global
split of arxiv 1811.01564 exploits for training: a variant is a small
local deviation from the shared global model.

Mechanics (all riding the ``view`` hook of
:meth:`~photon_ml_tpu.serving.sharded.ShardedGameScorer.score_batch`):

- **Shared FE base, per-variant FE override.** Fixed-effect vectors are
  jit *arguments*; a variant carries its own ``fe_params`` dict (same
  keys, same shapes), so variant scoring reuses the one compiled program
  with zero retraces.
- **Per-variant RE overlay rows in the shared tables.** A delta row for
  variant ``v`` is written to a FRESH global row of the shared
  routing/table space (allocated past the base row range) — copy-on-write
  even when the entity exists in the base, so no other variant ever
  gathers it. The variant's entity index is the base index behind an
  :class:`~photon_ml_tpu.incremental.delta.OverlayIndexMap` redirecting
  just the touched entities to their private rows.
- **Fingerprint-chained per-variant deltas.** Each variant is an
  independent hash chain off the base artifact fingerprint
  (``delta.base_fingerprint`` must match the variant's chain head);
  applying, validating, and rolling back one variant never pauses or
  rewinds another — per-variant hot-swap isolation.

The ``base`` variant is special: it carries no view at all and scores
through the scorer's plain path, which makes single-variant tenancy
bitwise-identical to the non-tenant stack (the CI tenancy parity gate).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.incremental.delta import (
    DeltaArtifact,
    OverlayIndexMap,
    discover_deltas,
    load_delta,
)
from photon_ml_tpu.serving.artifact import ServingArtifact

_log = logging.getLogger("photon_ml_tpu.serving.tenancy")

BASE_VARIANT = "base"


@dataclasses.dataclass
class VariantState:
    """One variant's serving state. ``artifact``/``fe_params`` of ``None``
    mean "follow the live base scorer" (the base variant — and any variant
    that has not diverged yet), which is the zero-cost bitwise path."""

    variant_id: str
    generation: int = 0
    fingerprint: Optional[str] = None
    artifact: Optional[ServingArtifact] = None
    fe_params: Optional[Dict[str, object]] = None
    # cid -> entity id -> private global row in the SHARED table space
    overlay_rows: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict
    )
    swaps: int = 0
    rollbacks: int = 0

    @property
    def diverged(self) -> bool:
        return self.artifact is not None

    @property
    def overlay_row_count(self) -> int:
        return sum(len(m) for m in self.overlay_rows.values())


@dataclasses.dataclass
class VariantSwapReport:
    """Per-variant swap outcome (the tenancy analogue of ``SwapReport``)."""

    variant_id: str
    generation: int
    fingerprint: Optional[str]
    rows_updated: int
    new_overlay_rows: int
    blackout_s: float
    rolled_back: bool
    validation_metric: Optional[float] = None
    baseline_metric: Optional[float] = None


@dataclasses.dataclass
class _VariantUndo:
    """Inverse of one variant swap: the previous state object plus the old
    content of the variant-private rows the swap rewrote in place."""

    state: VariantState
    inplace: Dict[str, Tuple[np.ndarray, np.ndarray]]  # cid -> (rows, old)


class VariantScorer:
    """``score_batch`` facade for one variant: the shared scorer with the
    variant's ``(artifact, fe_params)`` view threaded through. Quacks
    enough like a ``GameScorer`` for ``MicroBatcher``/``ValidationGate``
    (``score_batch``/``compile_count``/``caches``)."""

    caches: Dict[str, object] = {}

    def __init__(self, registry: "VariantRegistry", variant_id: str, scorer=None):
        self._registry = registry
        self.variant_id = variant_id
        self._scorer = scorer if scorer is not None else registry.lead

    @property
    def compile_count(self) -> int:
        return self._scorer.compile_count

    @property
    def artifact(self):
        state = self._registry.state(self.variant_id)
        return state.artifact if state.diverged else self._scorer.artifact

    def cache_stats(self):
        return self._scorer.cache_stats()

    def residency_stats(self):
        fn = getattr(self._scorer, "residency_stats", None)
        return fn() if fn is not None else None

    def score_batch(self, requests, bucket_size=None, stages=None):
        view = self._registry.view(self.variant_id)
        if view is None:
            return self._scorer.score_batch(requests, bucket_size, stages=stages)
        return self._scorer.score_batch(
            requests, bucket_size, stages=stages, view=view
        )


class VariantRegistry:
    """Owns every variant's state and applies per-variant deltas to the
    shared scorer (all replicas).

    ``scorers`` is the replica list of ONE sharded scorer group (shared
    routing); the lead performs overlay writes, which fan out to every
    replica through ``update_random_effect_rows``'s
    write-everywhere-then-publish contract. ``base_fingerprint`` roots
    every variant's delta chain (the base artifact directory's content
    fingerprint when serving from disk; ``None`` for in-memory artifacts —
    chain checks then start from the first applied delta)."""

    def __init__(
        self,
        scorers,
        base_fingerprint: Optional[str] = None,
        gate=None,
        clock=time.perf_counter,
    ):
        scorers = (
            list(scorers) if isinstance(scorers, (list, tuple)) else [scorers]
        )
        if not scorers:
            raise ValueError("need at least one scorer")
        self._scorers = scorers
        self.lead = scorers[0]
        self.base_fingerprint = base_fingerprint
        self.gate = gate
        self._clock = clock
        self._lock = threading.RLock()
        self._states: Dict[str, VariantState] = {
            BASE_VARIANT: VariantState(
                variant_id=BASE_VARIANT, fingerprint=base_fingerprint
            )
        }
        self._undo: Dict[str, _VariantUndo] = {}
        self._baselines: Dict[str, float] = {}
        self._processed: Dict[str, set] = {}
        # next private global row per coordinate, past everything the base
        # artifact (and base hot swaps) can ever legitimately claim
        self._next_row: Dict[str, int] = {}
        self.delta_load_failures = 0

    # ------------------------------------------------------------ variants

    @property
    def variant_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._states)

    def add_variant(
        self, variant_id: str, fingerprint: Optional[str] = None
    ) -> VariantState:
        """Register a variant. It starts as an exact alias of the live
        base (no view, no overlay) and diverges on its first delta."""
        with self._lock:
            if variant_id in self._states:
                raise ValueError(f"variant {variant_id!r} already exists")
            state = VariantState(
                variant_id=variant_id,
                fingerprint=(
                    fingerprint
                    if fingerprint is not None
                    else self.base_fingerprint
                ),
            )
            self._states[variant_id] = state
            return state

    def state(self, variant_id: str) -> VariantState:
        with self._lock:
            state = self._states.get(variant_id)
            if state is None:
                raise KeyError(f"unknown variant {variant_id!r}")
            return state

    def view(self, variant_id: str):
        """The ``(artifact, fe_params)`` score view, or ``None`` for
        follow-the-base variants (the bitwise plain path)."""
        state = self.state(variant_id)
        if not state.diverged:
            return None
        return (state.artifact, state.fe_params)

    def scorer(self, variant_id: str, scorer=None) -> VariantScorer:
        self.state(variant_id)  # raise early on unknown ids
        return VariantScorer(self, variant_id, scorer=scorer)

    # ------------------------------------------------------------- swapping

    def _claim_rows(self, cid: str, k: int) -> List[int]:
        nxt = self._next_row.get(cid)
        if nxt is None:
            nxt = max(
                self.lead.routing[cid].n_rows,
                self.lead.artifact.tables[cid].n_entities,
            )
        rows = list(range(nxt, nxt + k))
        self._next_row[cid] = nxt + k
        return rows

    def apply_delta(self, variant_id: str, delta) -> VariantSwapReport:
        """Swap one delta (a ``DeltaArtifact`` or delta directory path)
        into ONE variant. Chain-checked against the variant's own head;
        every touched entity lands in (or stays in) the variant's private
        overlay rows, so concurrent scoring of other variants is never
        paused beyond the shared tables' ordinary row-write locking and
        never sees the new content."""
        if not isinstance(delta, DeltaArtifact):
            delta = load_delta(str(delta))
        with self._lock:
            return self._apply_delta_locked(variant_id, delta)

    def _apply_delta_locked(
        self, variant_id: str, delta: DeltaArtifact
    ) -> VariantSwapReport:
        state = self.state(variant_id)
        if (
            state.fingerprint is not None
            and delta.base_fingerprint is not None
            and delta.base_fingerprint != state.fingerprint
        ):
            raise ValueError(
                f"delta generation {delta.generation} chains to base "
                f"{delta.base_fingerprint}, variant {variant_id!r} is at "
                f"{state.fingerprint} — missing intermediate delta or wrong "
                "chain"
            )
        current_artifact = (
            state.artifact if state.diverged else self.lead.artifact
        )
        current_fe = (
            state.fe_params if state.diverged else self.lead._fe_params
        )

        # plan every mutation (and its inverse) before touching the tables
        import dataclasses as dc

        new_tables = dict(current_artifact.tables)
        overlay_rows = {
            cid: dict(m) for cid, m in state.overlay_rows.items()
        }
        write_plan: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        inplace_undo: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        new_overlay_rows = 0
        for cid, (ids, rows) in delta.re_rows.items():
            table = new_tables.get(cid)
            if table is None or not table.is_random_effect:
                raise ValueError(
                    f"delta touches {cid!r} which is not a random effect "
                    "of the base artifact"
                )
            if rows.shape[1] != table.dim:
                raise ValueError(
                    f"delta rows for {cid!r} have dim {rows.shape[1]}, "
                    f"base table has dim {table.dim}"
                )
            overlay = overlay_rows.setdefault(cid, {})
            targets = np.empty(len(ids), dtype=np.int64)
            added: Dict[str, int] = {}
            fresh_ids = [e for e in ids if e not in overlay]
            fresh_rows = (
                self._claim_rows(cid, len(fresh_ids)) if fresh_ids else []
            )
            fresh_iter = iter(fresh_rows)
            rewrite_pos: List[int] = []
            for i, eid in enumerate(ids):
                row = overlay.get(eid)
                if row is None:
                    # copy-on-write: even a base-resident entity gets a
                    # fresh private row for this variant
                    row = next(fresh_iter)
                    added[eid] = row
                    overlay[eid] = row
                    new_overlay_rows += 1
                else:
                    rewrite_pos.append(i)
                targets[i] = row
            if rewrite_pos:
                rewrite_rows = targets[np.asarray(rewrite_pos)]
                inplace_undo[cid] = (
                    rewrite_rows,
                    self.lead._providers[cid].host_rows(rewrite_rows),
                )
            write_plan[cid] = (targets, np.asarray(rows, dtype=np.float32))
            if added:
                new_tables[cid] = dc.replace(
                    table,
                    entity_index=OverlayIndexMap(table.entity_index, added),
                )
        new_fe = dict(current_fe)
        for cid, w in delta.fe_updates.items():
            table = new_tables.get(cid)
            if table is None or table.is_random_effect:
                raise ValueError(
                    f"delta replaces {cid!r} which is not a fixed effect "
                    "of the base artifact"
                )
            w = np.asarray(w, dtype=np.float32)
            if w.shape != (table.dim,):
                raise ValueError(
                    f"delta fixed-effect vector for {cid!r} has shape "
                    f"{w.shape}, base table has dim {table.dim}"
                )
            import jax.numpy as jnp

            new_fe[cid] = jnp.asarray(w)
            new_tables[cid] = dc.replace(table, weights=w)

        undo = _VariantUndo(state=state, inplace=inplace_undo)

        if (
            self.gate is not None
            and variant_id not in self._baselines
        ):
            self._baselines[variant_id] = self.gate.evaluate(
                self.scorer(variant_id)
            )

        # --------------- the variant's blackout: shared-table writes ----
        # blackout_s is request-path blocking time: sharded leads stage
        # into the spare generation half and return only the flip window
        # (see ShardedReTable.update_rows); a None return (single-table
        # lead) keeps wall-clock accounting.
        t0 = time.perf_counter()
        nonblocking_s = 0.0
        for cid, (targets, values) in write_plan.items():
            u0 = time.perf_counter()
            ret = self.lead.update_random_effect_rows(cid, targets, values)
            if isinstance(ret, float):
                nonblocking_s += max(0.0, (time.perf_counter() - u0) - ret)
            routing = getattr(self.lead, "routing", None)
            if routing is not None and cid in routing:
                # importance plane: a freshly claimed overlay row enters
                # with zero request frequency and would be the first
                # eviction victim despite being this variant's only copy —
                # seed the claim as one request so freq × norm ranks it
                # like any just-requested row (note_row_norms already ran
                # inside update_rows). No-op under the default policy.
                routing[cid].note_requests(targets)
        new_state = VariantState(
            variant_id=variant_id,
            generation=state.generation + 1,
            fingerprint=(
                delta.fingerprint
                if delta.fingerprint is not None
                else state.fingerprint
            ),
            artifact=dc.replace(current_artifact, tables=new_tables),
            fe_params=new_fe,
            overlay_rows=overlay_rows,
            swaps=state.swaps + 1,
            rollbacks=state.rollbacks,
        )
        self._states[variant_id] = new_state
        blackout_s = max(0.0, time.perf_counter() - t0 - nonblocking_s)
        # ----------------------------------------------------------------

        validation_metric: Optional[float] = None
        rolled_back = False
        baseline = self._baselines.get(variant_id)
        if self.gate is not None:
            validation_metric = self.gate.evaluate(self.scorer(variant_id))
            floor = baseline - self.gate.max_auc_regression
            if not validation_metric >= floor:  # NaN fails too
                _log.warning(
                    "variant %r validation gate failed: %.6f < floor %.6f "
                    "— rolling back this variant only",
                    variant_id, validation_metric, floor,
                )
                self._undo[variant_id] = undo
                self.rollback(variant_id)
                rolled_back = True
            else:
                self._baselines[variant_id] = validation_metric
        if not rolled_back:
            self._undo[variant_id] = undo
        final = self.state(variant_id)
        return VariantSwapReport(
            variant_id=variant_id,
            generation=final.generation,
            fingerprint=final.fingerprint,
            rows_updated=delta.num_rows_updated,
            new_overlay_rows=new_overlay_rows,
            blackout_s=blackout_s,
            rolled_back=rolled_back,
            validation_metric=validation_metric,
            baseline_metric=baseline,
        )

    def rollback(self, variant_id: str) -> VariantState:
        """Restore ONE variant's previous generation: its old state object
        plus the old bytes of any variant-private rows the last swap
        rewrote in place. Rows the swap newly allocated stay written but
        unreachable (no index references them), so no other variant — and
        no replica — needs any work. Returns the restored state."""
        with self._lock:
            undo = self._undo.pop(variant_id, None)
            if undo is None:
                raise ValueError(
                    f"variant {variant_id!r} has no generation to roll back"
                )
            for cid, (rows, old_values) in undo.inplace.items():
                self.lead.update_random_effect_rows(cid, rows, old_values)
            restored = dataclasses.replace(
                undo.state, rollbacks=undo.state.rollbacks + 1
            )
            self._states[variant_id] = restored
            return restored

    # ------------------------------------------------------------- watching

    def poll_directory(
        self, variant_id: str, watch_dir: str
    ) -> List[VariantSwapReport]:
        """Apply newly published deltas under ``watch_dir`` to ONE variant
        (name order = chain order; unreadable or unappliable deltas are
        skipped with the live generation kept, like the hot-swap watcher)."""
        processed = self._processed.setdefault(variant_id, set())
        reports: List[VariantSwapReport] = []
        for path in discover_deltas(watch_dir):
            if path in processed:
                continue
            try:
                delta = load_delta(path)
            except Exception as exc:
                self.delta_load_failures += 1
                _log.warning(
                    "variant %r: skipping unreadable delta %s: %s",
                    variant_id, path, exc,
                )
                continue
            if (
                delta.fingerprint is not None
                and delta.fingerprint == self.state(variant_id).fingerprint
            ):
                processed.add(path)
                continue
            try:
                reports.append(self.apply_delta(variant_id, delta))
            except Exception as exc:
                self.delta_load_failures += 1
                _log.warning(
                    "variant %r: delta %s failed to apply: %s",
                    variant_id, path, exc,
                )
                continue
            processed.add(path)
        return reports

    # ------------------------------------------------------------ reporting

    def stats(self) -> Dict[str, Dict[str, object]]:
        with self._lock:
            return {
                vid: {
                    "generation": s.generation,
                    "fingerprint": s.fingerprint,
                    "diverged": s.diverged,
                    "overlay_rows": s.overlay_row_count,
                    "swaps": s.swaps,
                    "rollbacks": s.rollbacks,
                }
                for vid, s in sorted(self._states.items())
            }
