"""Per-tenant admission quotas with priority-aware shedding.

One shared scorer means one shared device budget: a tenant replaying a
backfill at 50x its contracted rate would otherwise queue every other
tenant behind it (the classic noisy-neighbour failure the
``tenant_isolation`` scenario reproduces). ``TenantQuota`` is the
admission valve in front of the tenancy plane's batchers: a token bucket
per tenant (contracted ``rate`` req/s with ``burst`` headroom), plus an
optional *global* bucket modelling the machine's aggregate capacity,
whose last ``reserve_fraction`` is spendable only by the highest-priority
tenants — so when the box saturates, low-priority bulk traffic sheds
first and interactive tenants keep their SLO.

Sheds are charged to the *shedding tenant's* error budget by the caller
(``TenancyPlane``), never to the global SLO — a tenant exceeding its own
contract must not burn anyone else's budget, including the operator's.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, Mapping, Optional


@dataclasses.dataclass
class TenantBudget:
    """One tenant's admission contract: sustained ``rate`` requests/s,
    ``burst`` instantaneous headroom, and scheduling ``priority`` (higher
    = shed later when the global pool runs dry)."""

    rate: float
    burst: float
    priority: int = 0

    def __post_init__(self):
        if self.rate <= 0 or self.burst <= 0:
            raise ValueError(
                f"rate and burst must be positive, got rate={self.rate} "
                f"burst={self.burst}"
            )


class TenantQuota:
    def __init__(
        self,
        budgets: Mapping[str, TenantBudget],
        global_rate: Optional[float] = None,
        global_burst: Optional[float] = None,
        reserve_fraction: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if not 0.0 <= reserve_fraction < 1.0:
            raise ValueError(
                f"reserve_fraction must be in [0, 1), got {reserve_fraction}"
            )
        self._budgets = dict(budgets)
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = {t: b.burst for t, b in self._budgets.items()}
        self._last: Optional[float] = None
        self._global_rate = global_rate
        self._global_burst = (
            global_burst if global_burst is not None else global_rate
        )
        self._global_tokens = self._global_burst
        self._reserve = (
            reserve_fraction * self._global_burst
            if self._global_burst is not None
            else 0.0
        )
        self._top_priority = max(
            (b.priority for b in self._budgets.values()), default=0
        )
        self.admitted: Dict[str, int] = {}
        self.shed: Dict[str, int] = {}

    def _refill(self, now: float) -> None:
        last = self._last
        self._last = now
        if last is None:
            return
        dt = now - last
        if dt <= 0:
            return
        for tenant, budget in self._budgets.items():
            self._tokens[tenant] = min(
                budget.burst, self._tokens[tenant] + budget.rate * dt
            )
        if self._global_rate is not None:
            self._global_tokens = min(
                self._global_burst,
                self._global_tokens + self._global_rate * dt,
            )

    def try_admit(self, tenant: str, n: int = 1) -> bool:
        """Admit ``n`` requests for ``tenant`` or shed them. Tenants with
        no configured budget are admitted (quota is opt-in per tenant) but
        still draw from the global pool at priority 0."""
        with self._lock:
            self._refill(self._clock())
            budget = self._budgets.get(tenant)
            if budget is not None and self._tokens[tenant] < n:
                self.shed[tenant] = self.shed.get(tenant, 0) + n
                return False
            if self._global_rate is not None:
                priority = budget.priority if budget is not None else 0
                # the reserve is spendable only by top-priority tenants
                floor = 0.0 if priority >= self._top_priority else self._reserve
                if self._global_tokens - n < floor - 1e-9:
                    self.shed[tenant] = self.shed.get(tenant, 0) + n
                    return False
                self._global_tokens -= n
            if budget is not None:
                self._tokens[tenant] -= n
            self.admitted[tenant] = self.admitted.get(tenant, 0) + n
            return True

    def stats(self) -> Dict[str, object]:
        with self._lock:
            tenants = sorted(
                set(self._budgets) | set(self.admitted) | set(self.shed)
            )
            return {
                "tenants": {
                    t: {
                        "admitted": self.admitted.get(t, 0),
                        "shed": self.shed.get(t, 0),
                        "rate": (
                            self._budgets[t].rate
                            if t in self._budgets
                            else None
                        ),
                        "priority": (
                            self._budgets[t].priority
                            if t in self._budgets
                            else 0
                        ),
                    }
                    for t in tenants
                },
                "global_tokens": self._global_tokens,
                "reserve": self._reserve,
            }
