"""Tenancy plane: multi-model variant serving on one shared scorer.

See docs/SERVING.md ("Tenancy plane") for the architecture. Public
surface:

- :class:`VariantRegistry` / :class:`VariantScorer` — N fingerprint-
  chained delta-overlay variants over one sharded scorer's tables, with
  per-variant hot swap, validation gating, and rollback isolation.
- :class:`VariantRouter` — seeded deterministic (tenant, request_id) ->
  variant routing with hot-adjustable ramp percentages and pins.
- :class:`TenantQuota` / :class:`TenantBudget` — per-tenant token-bucket
  admission with priority-aware shedding from a shared global pool.
- :class:`TenancyPlane` — the assembled path: quota -> router -> one
  sealed batcher per variant; plus :func:`tag_requests` (tenant identity
  in the request id), :func:`build_tenant_slos` (independent error
  budgets, tenant-labeled gauges), and :func:`make_nearline_fn` (the
  nearline train->emit->swap loop body for scenarios).
"""

from photon_ml_tpu.serving.tenancy.variants import (
    BASE_VARIANT,
    VariantRegistry,
    VariantScorer,
    VariantState,
    VariantSwapReport,
)
from photon_ml_tpu.serving.tenancy.router import VariantRouter
from photon_ml_tpu.serving.tenancy.quota import TenantBudget, TenantQuota
from photon_ml_tpu.serving.tenancy.plane import (
    TenancyPlane,
    build_tenant_slos,
    make_nearline_fn,
    tag_request,
    tag_requests,
)

__all__ = [
    "BASE_VARIANT",
    "VariantRegistry",
    "VariantScorer",
    "VariantState",
    "VariantSwapReport",
    "VariantRouter",
    "TenantBudget",
    "TenantQuota",
    "TenancyPlane",
    "build_tenant_slos",
    "make_nearline_fn",
    "tag_request",
    "tag_requests",
]
