"""Synthetic-data generators and GAME factories for tests and examples.

Reference parity: photon-test-utils SparkTestUtils.scala:85-307 (seeded
per-task generators in three numerical regimes — benign, outlier/ill-
conditioned, invalid NaN/Inf — plus invalid-label draws) and
photon-api util/GameTestUtils.scala:41 (factories for labeled points,
fixed/random-effect datasets, coordinates and models). The reference ships
these in a main source set precisely so downstream tests can reuse them;
same here.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from photon_ml_tpu.data.game_data import FeatureShard, GameData
from photon_ml_tpu.types import TaskType


def _features(rng, n, d, regime: str) -> np.ndarray:
    """Dense features in one of the reference's three regimes."""
    X = rng.normal(size=(n, d))
    if regime == "benign":
        return X.astype(np.float32)
    if regime == "outlier":
        # heavy-tailed, badly scaled columns (ill-conditioned):
        # SparkTestUtils.generateSparseVectorWithOutliers
        scales = 10.0 ** rng.integers(-4, 5, size=d)
        X = X * scales
        mask = rng.random((n, d)) < 0.02
        X = np.where(mask, X * 1e4, X)
        return X.astype(np.float32)
    if regime == "invalid":
        # sprinkle NaN/Inf (generateSparseVectorWithInvalidValues)
        bad = rng.random((n, d)) < 0.05
        choice = rng.random((n, d))
        X = np.where(bad & (choice < 0.5), np.nan, X)
        X = np.where(bad & (choice >= 0.5), np.inf, X)
        return X.astype(np.float32)
    raise ValueError(f"unknown regime: {regime}")


def _labels(rng, z: np.ndarray, task: TaskType) -> np.ndarray:
    if task is TaskType.LOGISTIC_REGRESSION:
        return (1.0 / (1.0 + np.exp(-z)) > rng.random(len(z))).astype(np.float32)
    if task is TaskType.POISSON_REGRESSION:
        return rng.poisson(np.exp(np.clip(z, -10, 3))).astype(np.float32)
    if task is TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        return (z > 0).astype(np.float32)
    return (z + 0.1 * rng.normal(size=len(z))).astype(np.float32)


def draw_sample(
    task: TaskType,
    n: int = 200,
    d: int = 10,
    regime: str = "benign",
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(X, y, w_true) for one task/regime — the per-task draw* generators.

    ``regime='balanced'`` is implied for classification: labels come from
    the model probability so classes are roughly balanced at w ~ N(0,1).
    """
    rng = np.random.default_rng(seed)
    X = _features(rng, n, d, regime)
    w_true = rng.normal(size=d).astype(np.float32)
    with np.errstate(invalid="ignore", over="ignore"):
        z = np.nan_to_num(X, nan=0.0, posinf=0.0, neginf=0.0) @ w_true
        y = _labels(rng, z, task)
    return X, y, w_true


def draw_invalid_labels(
    task: TaskType, n: int = 50, seed: int = 0
) -> np.ndarray:
    """Labels that must fail validation (drawSampleFromInvalidLabels):
    NaN everywhere, negatives for Poisson, non-binary for classifiers."""
    rng = np.random.default_rng(seed)
    y = rng.normal(size=n).astype(np.float32)
    bad = rng.random(n) < 0.3
    if task is TaskType.POISSON_REGRESSION:
        return np.where(bad, -np.abs(y) - 1.0, np.abs(y)).astype(np.float32)
    if task.is_classification:
        return np.where(bad, 0.5, (y > 0).astype(np.float32)).astype(np.float32)
    return np.where(bad, np.nan, y).astype(np.float32)


def dense_to_shard(X: np.ndarray) -> FeatureShard:
    """Dense matrix → COO FeatureShard (test plumbing helper)."""
    rows, cols = np.nonzero(X)
    return FeatureShard(
        rows=rows, cols=cols, vals=X[rows, cols].astype(np.float32),
        dim=X.shape[1],
    )


def generate_fixed_effect_data(
    task: TaskType = TaskType.LOGISTIC_REGRESSION,
    n: int = 200,
    d: int = 10,
    shard_name: str = "global",
    seed: int = 0,
) -> Tuple[GameData, np.ndarray]:
    """GameData with one fixed-effect shard (GameTestUtils
    generateFixedEffectDataSet). Returns (data, w_true)."""
    X, y, w_true = draw_sample(task, n, d, seed=seed)
    return (
        GameData(labels=y, feature_shards={shard_name: dense_to_shard(X)},
                 id_tags={}),
        w_true,
    )


def generate_glmix_data(
    task: TaskType = TaskType.LINEAR_REGRESSION,
    n_entities: int = 10,
    rows_per_entity: int = 30,
    d_global: int = 10,
    d_entity: int = 4,
    re_type: str = "userId",
    global_shard: str = "global",
    re_shard: str = "per_entity",
    noise: float = 0.1,
    seed: int = 0,
) -> Tuple[GameData, Dict[str, np.ndarray]]:
    """Fixed + per-entity random-effect data (GameTestUtils
    generateRandomEffectDataSet + linear models). Returns
    (data, {'w_fixed': ..., 'w_<entity>': ...})."""
    rng = np.random.default_rng(seed)
    n = n_entities * rows_per_entity
    Xg = rng.normal(size=(n, d_global)).astype(np.float32)
    Xe = rng.normal(size=(n, d_entity)).astype(np.float32)
    entities = np.repeat(
        [f"e{i:04d}" for i in range(n_entities)], rows_per_entity
    )
    w_fixed = rng.normal(size=d_global).astype(np.float32)
    w_entity = {
        f"e{i:04d}": rng.normal(size=d_entity).astype(np.float32)
        for i in range(n_entities)
    }
    z = Xg @ w_fixed + np.array(
        [Xe[r] @ w_entity[entities[r]] for r in range(n)], dtype=np.float32
    )
    if task is TaskType.LINEAR_REGRESSION:
        y = (z + noise * rng.normal(size=n)).astype(np.float32)
    else:
        y = _labels(rng, z, task)
    data = GameData(
        labels=y,
        feature_shards={
            global_shard: dense_to_shard(Xg),
            re_shard: dense_to_shard(Xe),
        },
        id_tags={re_type: entities},
    )
    truth = {"w_fixed": w_fixed}
    truth.update({f"w_{k}": v for k, v in w_entity.items()})
    return data, truth


def generate_game_model(
    data: GameData,
    task: TaskType,
    coordinates: Dict[str, dict],
    seed: int = 0,
):
    """Random (untrained) GameModel matching a dataset's shapes
    (GameTestUtils generate*Model): coordinates maps cid →
    {'feature_shard': ..., optional 'random_effect_type': ...}."""
    import jax.numpy as jnp

    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.game import CoordinateMeta, GameModel
    from photon_ml_tpu.models.glm import GeneralizedLinearModel
    from photon_ml_tpu.models.random_effect import RandomEffectModel

    rng = np.random.default_rng(seed)
    models: Dict[str, object] = {}
    meta: Dict[str, CoordinateMeta] = {}
    for cid, spec in coordinates.items():
        shard = data.feature_shards[spec["feature_shard"]]
        re_type = spec.get("random_effect_type")
        meta[cid] = CoordinateMeta(
            feature_shard=spec["feature_shard"], random_effect_type=re_type
        )
        if re_type is None:
            models[cid] = GeneralizedLinearModel(
                coefficients=Coefficients(
                    means=jnp.asarray(
                        rng.normal(size=shard.dim).astype(np.float32)
                    )
                ),
                task=task,
            )
        else:
            entity_ids = sorted(set(map(str, data.id_tags[re_type])))
            models[cid] = RandomEffectModel.from_entity_coefficients(
                random_effect_type=re_type,
                task=task,
                entity_coefficients={
                    eid: {
                        j: float(rng.normal())
                        for j in range(shard.dim)
                    }
                    for eid in entity_ids
                },
                global_dim=shard.dim,
            )
    return GameModel(models=models, meta=meta, task=task)
