// Off-heap immutable feature index store ("PHIX" format).
//
// Reference parity: the role of PalDB in photon-ml — an mmap'd off-heap
// string->int store for feature index maps too large for the driver heap
// (util/PalDBIndexMap.scala:43: partitioned read-only stores opened per
// executor; PalDBIndexMapBuilder.scala:27). This is a from-scratch
// implementation: one file per partition holding two open-addressing hash
// tables (forward name->index and reverse index->name) plus the key blob,
// all accessed zero-copy through mmap so any number of processes share one
// page-cache copy.
//
// File layout (little-endian, 8-byte aligned):
//   Header   { magic "PHIX", u32 version=1, u64 num_slots (pow2),
//              u64 num_entries, u64 fwd_off, u64 rev_off, u64 keys_off,
//              u64 keys_len }
//   FwdSlot  [num_slots] { u64 key_off, u32 key_len, u32 index }
//            (empty slot: key_off == EMPTY)
//   RevSlot  [num_slots] { u64 index_plus1 (0 = empty), u64 key_off,
//              u32 key_len, u32 _pad }
//   keys blob
//
// Exposed as a plain C ABI consumed via ctypes; a pure-Python fallback
// reader of the same format lives in photon_ml_tpu/indexmap/offheap.py.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t EMPTY = ~0ULL;

#pragma pack(push, 1)
struct Header {
  char magic[4];
  uint32_t version;
  uint64_t num_slots;
  uint64_t num_entries;
  uint64_t fwd_off;
  uint64_t rev_off;
  uint64_t keys_off;
  uint64_t keys_len;
};
struct FwdSlot {
  uint64_t key_off;
  uint32_t key_len;
  uint32_t index;
};
struct RevSlot {
  uint64_t index_plus1;
  uint64_t key_off;
  uint32_t key_len;
  uint32_t pad;
};
#pragma pack(pop)

uint64_t fnv1a(const char* s, uint64_t n) {
  uint64_t h = 14695981039346656037ULL;
  for (uint64_t i = 0; i < n; ++i) {
    h ^= static_cast<unsigned char>(s[i]);
    h *= 1099511628211ULL;
  }
  return h;
}

uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

uint64_t pow2_slots(uint64_t n) {
  // load factor <= 0.7, minimum 16 slots
  uint64_t want = (n * 10) / 7 + 1;
  uint64_t s = 16;
  while (s < want) s <<= 1;
  return s;
}

struct Store {
  void* map;
  uint64_t map_len;
  const Header* header;
  const FwdSlot* fwd;
  const RevSlot* rev;
  const char* keys;
};

}  // namespace

extern "C" {

// Build one partition file. keys: concatenated UTF-8 bytes; key_offs[i] is
// the byte offset of key i; key_lens[i] its length; indices[i] its (global)
// feature index. Returns 0 on success, negative errno-style codes otherwise.
int phix_build(const char* path, const char* keys, const uint64_t* key_offs,
               const uint32_t* key_lens, const uint32_t* indices, uint64_t n) {
  const uint64_t slots = pow2_slots(n);
  const uint64_t mask = slots - 1;

  FwdSlot* fwd = static_cast<FwdSlot*>(malloc(slots * sizeof(FwdSlot)));
  RevSlot* rev = static_cast<RevSlot*>(calloc(slots, sizeof(RevSlot)));
  if (!fwd || !rev) {
    free(fwd);
    free(rev);
    return -12;  // ENOMEM
  }
  for (uint64_t i = 0; i < slots; ++i) fwd[i].key_off = EMPTY;

  uint64_t keys_len = 0;
  for (uint64_t i = 0; i < n; ++i) {
    const char* k = keys + key_offs[i];
    const uint64_t klen = key_lens[i];
    if (key_offs[i] + klen > keys_len) keys_len = key_offs[i] + klen;

    uint64_t slot = fnv1a(k, klen) & mask;
    while (fwd[slot].key_off != EMPTY) {
      if (fwd[slot].key_len == klen &&
          memcmp(keys + fwd[slot].key_off, k, klen) == 0) {
        free(fwd);
        free(rev);
        return -17;  // EEXIST: duplicate key
      }
      slot = (slot + 1) & mask;
    }
    fwd[slot].key_off = key_offs[i];
    fwd[slot].key_len = static_cast<uint32_t>(klen);
    fwd[slot].index = indices[i];

    uint64_t rslot = splitmix64(indices[i]) & mask;
    while (rev[rslot].index_plus1 != 0) rslot = (rslot + 1) & mask;
    rev[rslot].index_plus1 = static_cast<uint64_t>(indices[i]) + 1;
    rev[rslot].key_off = key_offs[i];
    rev[rslot].key_len = static_cast<uint32_t>(klen);
  }

  Header h;
  memcpy(h.magic, "PHIX", 4);
  h.version = 1;
  h.num_slots = slots;
  h.num_entries = n;
  h.fwd_off = sizeof(Header);
  h.rev_off = h.fwd_off + slots * sizeof(FwdSlot);
  h.keys_off = h.rev_off + slots * sizeof(RevSlot);
  h.keys_len = keys_len;

  FILE* f = fopen(path, "wb");
  if (!f) {
    free(fwd);
    free(rev);
    return -2;  // ENOENT-ish: cannot open for write
  }
  int rc = 0;
  if (fwrite(&h, sizeof(Header), 1, f) != 1 ||
      fwrite(fwd, sizeof(FwdSlot), slots, f) != slots ||
      fwrite(rev, sizeof(RevSlot), slots, f) != slots ||
      (keys_len > 0 && fwrite(keys, 1, keys_len, f) != keys_len)) {
    rc = -5;  // EIO
  }
  if (fclose(f) != 0) rc = rc ? rc : -5;
  free(fwd);
  free(rev);
  return rc;
}

void* phix_open(const char* path) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < (off_t)sizeof(Header)) {
    close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  close(fd);  // mapping keeps the file alive
  if (map == MAP_FAILED) return nullptr;

  const Header* h = static_cast<const Header*>(map);
  if (memcmp(h->magic, "PHIX", 4) != 0 || h->version != 1) {
    munmap(map, st.st_size);
    return nullptr;
  }
  Store* s = new Store;
  s->map = map;
  s->map_len = st.st_size;
  s->header = h;
  s->fwd = reinterpret_cast<const FwdSlot*>(static_cast<char*>(map) + h->fwd_off);
  s->rev = reinterpret_cast<const RevSlot*>(static_cast<char*>(map) + h->rev_off);
  s->keys = static_cast<char*>(map) + h->keys_off;
  return s;
}

int64_t phix_get(void* handle, const char* key, uint32_t key_len) {
  const Store* s = static_cast<const Store*>(handle);
  const uint64_t mask = s->header->num_slots - 1;
  uint64_t slot = fnv1a(key, key_len) & mask;
  while (s->fwd[slot].key_off != EMPTY) {
    if (s->fwd[slot].key_len == key_len &&
        memcmp(s->keys + s->fwd[slot].key_off, key, key_len) == 0) {
      return static_cast<int64_t>(s->fwd[slot].index);
    }
    slot = (slot + 1) & mask;
  }
  return -1;
}

// Batch lookup: m packed keys -> out[i] = index or -1.
void phix_get_batch(void* handle, const char* keys, const uint64_t* offs,
                    const uint32_t* lens, int64_t* out, uint64_t m) {
  for (uint64_t i = 0; i < m; ++i) {
    out[i] = phix_get(handle, keys + offs[i], lens[i]);
  }
}

// Reverse lookup: copy the name for `index` into buf (truncated to buflen);
// returns the full name length, or -1 if the index is absent.
int64_t phix_name_at(void* handle, uint32_t index, char* buf, uint32_t buflen) {
  const Store* s = static_cast<const Store*>(handle);
  const uint64_t mask = s->header->num_slots - 1;
  uint64_t slot = splitmix64(index) & mask;
  const uint64_t want = static_cast<uint64_t>(index) + 1;
  while (s->rev[slot].index_plus1 != 0) {
    if (s->rev[slot].index_plus1 == want) {
      const uint32_t n = s->rev[slot].key_len;
      const uint32_t c = n < buflen ? n : buflen;
      memcpy(buf, s->keys + s->rev[slot].key_off, c);
      return static_cast<int64_t>(n);
    }
    slot = (slot + 1) & mask;
  }
  return -1;
}

uint64_t phix_num_entries(void* handle) {
  return static_cast<const Store*>(handle)->header->num_entries;
}

// FNV-1a over m packed keys (partition routing done vectorized host-side).
void phix_hash_batch(const char* keys, const uint64_t* offs,
                     const uint32_t* lens, uint64_t* out, uint64_t m) {
  for (uint64_t i = 0; i < m; ++i) {
    out[i] = fnv1a(keys + offs[i], lens[i]);
  }
}

void phix_close(void* handle) {
  Store* s = static_cast<Store*>(handle);
  munmap(s->map, s->map_len);
  delete s;
}

}  // extern "C"
