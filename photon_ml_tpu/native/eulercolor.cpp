// Euler-split edge coloring for regular bipartite multigraphs.
//
// Used by photon_ml_tpu/ops/routing.py to route static permutations through
// a radix-128 Clos/Benes network: a proper deg-edge-coloring of the
// (src-row, dst-row) incidence multigraph assigns each element an
// intermediate lane such that the permutation factors into
// (within-row shuffle) o (per-lane row movement) o (within-row shuffle).
//
// The reference framework has no analog (Spark shuffles move data by hash);
// this is TPU-native machinery: it turns arbitrary static gathers/scatters
// into dense lane-shuffle stages the VPU executes at vector speed.
//
// Algorithm: classic Euler-split halving. A multigraph where every node has
// even degree decomposes its edges into two halves, each regular of half
// degree: pair consecutive edges at every node (complete, since degrees are
// even), walk the resulting 2-regular "partner" cycles alternating between
// src-pairings and dst-pairings, and 2-color edges alternately along each
// cycle. Recursing log2(deg) times yields a proper deg-coloring. O(E log deg).
//
// Memory layout notes: edges are processed as contiguous class segments of
// one permuted id array (radix-sort style, no per-class allocations); all
// id arrays are int32 to halve the cache footprint of the pointer-chasing
// cycle walk, which is the runtime bottleneck.
//
// C ABI only (ctypes-friendly); no exceptions across the boundary.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Pair consecutive edges of ids[0..m) grouped by key (node id per edge).
// partner[e] = the other edge of e's pair at this node side. counts/order
// are caller-provided scratch (counts sized n_nodes+1, order sized >= m).
void pair_by_node(const int32_t* ids, int64_t m, const int32_t* key,
                  int32_t n_nodes, int64_t* counts, int32_t* order,
                  int32_t* partner) {
  std::memset(counts, 0, sizeof(int64_t) * (static_cast<size_t>(n_nodes) + 1));
  for (int64_t i = 0; i < m; ++i) counts[key[ids[i]] + 1]++;
  for (int32_t n = 0; n < n_nodes; ++n) counts[n + 1] += counts[n];
  for (int64_t i = 0; i < m; ++i) order[counts[key[ids[i]]]++] = ids[i];
  // Runs have even length, so consecutive pairs never cross a node boundary.
  for (int64_t i = 0; i < m; i += 2) {
    partner[order[i]] = order[i + 1];
    partner[order[i + 1]] = order[i];
  }
}

// One class segment at one level: pair on both sides, 2-color along the
// partner cycles, then stable-partition into next_ids at [lo, lo+m/2) /
// [lo+m/2, hi). Segments touch disjoint edge ids and disjoint output
// ranges, so segments at one level run on different threads with no
// synchronization beyond per-thread counts/order scratch. The coloring is
// deterministic regardless of thread schedule (each cycle walk starts from
// the lowest-position unvisited edge of its own segment).
void process_segment(const int32_t* seg, int64_t m, int64_t lo,
                     const int32_t* src, const int32_t* dst, int32_t n_src,
                     int32_t n_dst, int32_t cbit, int64_t* counts,
                     int32_t* order, int32_t* partner_src,
                     int32_t* partner_dst, uint8_t* state, int32_t* color,
                     int32_t* next_ids) {
  pair_by_node(seg, m, src, n_src, counts, order, partner_src);
  pair_by_node(seg, m, dst, n_dst, counts, order, partner_dst);
  for (int64_t i = 0; i < m; ++i) state[seg[i]] = 0;
  for (int64_t i = 0; i < m; ++i) {
    const int32_t e0 = seg[i];
    if (state[e0] & 1) continue;
    int32_t e = e0;
    uint8_t b = 0;
    bool via_src = true;
    do {
      state[e] = static_cast<uint8_t>(1 | (b << 1));
      e = via_src ? partner_src[e] : partner_dst[e];
      via_src = !via_src;
      b ^= 1;
    } while (e != e0);
  }
  // Alternating 2-coloring along even cycles puts exactly half each way.
  int64_t h0 = lo, h1 = lo + m / 2;
  for (int64_t i = 0; i < m; ++i) {
    const int32_t e = seg[i];
    if (state[e] & 2) {
      color[e] |= cbit;
      next_ids[h1++] = e;
    } else {
      next_ids[h0++] = e;
    }
  }
}

}  // namespace

extern "C" {

// Proper `deg`-edge-coloring of a bipartite multigraph in which every src
// node and every dst node has exactly `deg` incident edges. `deg` must be a
// power of two. Writes color[e] in [0, deg). Returns 0 on success.
int euler_color(int64_t n_edges, int32_t deg, const int32_t* src,
                const int32_t* dst, int32_t n_src, int32_t n_dst,
                int32_t* color) {
  if (deg <= 0 || (deg & (deg - 1)) != 0) return 1;
  if (n_edges != static_cast<int64_t>(n_src) * deg ||
      n_edges != static_cast<int64_t>(n_dst) * deg)
    return 2;
  if (n_edges > INT32_MAX) return 3;
  std::memset(color, 0, sizeof(int32_t) * static_cast<size_t>(n_edges));
  if (deg == 1) return 0;

  int32_t levels = 0;
  for (int32_t d = deg; d > 1; d >>= 1) levels++;

  const int32_t n_nodes_max = n_src > n_dst ? n_src : n_dst;
  std::vector<int32_t> ids(n_edges), next_ids(n_edges);
  std::vector<int32_t> partner_src(n_edges), partner_dst(n_edges);
  std::vector<uint8_t> state(n_edges);  // bit 0: visited, bit 1: color bit
  std::vector<int64_t> seg_starts{0}, next_starts;

  unsigned hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 1;
  const size_t max_threads = n_edges >= (1 << 20) ? hw : 1;

  // Scratch for the sequential path, shared across levels/segments.
  std::vector<int64_t> counts(static_cast<size_t>(n_nodes_max) + 1);
  std::vector<int32_t> order(n_edges);

  for (int64_t e = 0; e < n_edges; ++e) ids[e] = static_cast<int32_t>(e);
  seg_starts.push_back(n_edges);

  for (int32_t level = 0; level < levels; ++level) {
    const size_t n_segs = seg_starts.size() - 1;
    const int32_t cbit = 1 << (levels - 1 - level);
    const size_t n_threads =
        n_segs < max_threads ? n_segs : max_threads;
    if (n_threads <= 1) {
      for (size_t s = 0; s < n_segs; ++s) {
        const int64_t lo = seg_starts[s], hi = seg_starts[s + 1];
        process_segment(ids.data() + lo, hi - lo, lo, src, dst, n_src, n_dst,
                        cbit, counts.data(), order.data(), partner_src.data(),
                        partner_dst.data(), state.data(), color,
                        next_ids.data());
      }
    } else {
      // Segments are independent (disjoint edges, disjoint output ranges):
      // farm them out with per-thread counts/order scratch.
      int64_t max_m = 0;
      for (size_t s = 0; s < n_segs; ++s) {
        const int64_t m = seg_starts[s + 1] - seg_starts[s];
        if (m > max_m) max_m = m;
      }
      std::atomic<size_t> next_seg{0};
      std::vector<std::thread> workers;
      workers.reserve(n_threads);
      for (size_t t = 0; t < n_threads; ++t) {
        workers.emplace_back([&]() {
          std::vector<int64_t> counts(static_cast<size_t>(n_nodes_max) + 1);
          std::vector<int32_t> order(static_cast<size_t>(max_m));
          for (;;) {
            const size_t s = next_seg.fetch_add(1);
            if (s >= n_segs) break;
            const int64_t lo = seg_starts[s], hi = seg_starts[s + 1];
            process_segment(ids.data() + lo, hi - lo, lo, src, dst, n_src,
                            n_dst, cbit, counts.data(), order.data(),
                            partner_src.data(), partner_dst.data(),
                            state.data(), color, next_ids.data());
          }
        });
      }
      for (auto& w : workers) w.join();
    }
    next_starts.clear();
    next_starts.reserve(2 * n_segs + 1);
    next_starts.push_back(0);
    for (size_t s = 0; s < n_segs; ++s) {
      const int64_t lo = seg_starts[s], hi = seg_starts[s + 1];
      next_starts.push_back(lo + (hi - lo) / 2);
      next_starts.push_back(hi);
    }
    ids.swap(next_ids);
    seg_starts.swap(next_starts);
  }
  return 0;
}

}  // extern "C"
