// Threaded stable LSD radix argsort for non-negative int64 key pairs.
//
// The routing/tiling data prep (ops/sparse_perm.py, parallel/
// grid_features.py, data/random_effect.py) is dominated by np.lexsort over
// COO index pairs at 1e7-1e9 entries; numpy's lexsort is single-threaded
// comparison-ish sort. This is the native replacement: byte-wise LSD radix
// over only the bytes the key range actually uses, parallel histogram +
// stable per-thread scatter, sorting an index permutation (argsort) so the
// Python side can reorder any number of payload arrays.
//
// Contract (see photon_ml_tpu/utils/nativesort.py):
//   argsort_pairs(n, hi, lo, out, n_threads) -> 0 on success
//   - keys must be non-negative; sort order = (hi, lo) lexicographic,
//     stable w.r.t. input order (ties keep original positions).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// One stable counting pass over 8-bit digits of key[idx_in[i]] >> shift.
void radix_pass(int64_t n, const int64_t* key, int shift,
                const int64_t* idx_in, int64_t* idx_out, int n_threads) {
  const int RADIX = 256;
  std::vector<std::vector<int64_t>> hist(
      (size_t)n_threads, std::vector<int64_t>(RADIX, 0));
  std::vector<std::thread> ts;
  int64_t chunk = (n + n_threads - 1) / n_threads;

  for (int t = 0; t < n_threads; ++t) {
    ts.emplace_back([&, t]() {
      int64_t lo = t * chunk, hi2 = std::min(n, lo + chunk);
      auto& h = hist[(size_t)t];
      for (int64_t i = lo; i < hi2; ++i) {
        h[(key[idx_in[i]] >> shift) & 0xFF]++;
      }
    });
  }
  for (auto& th : ts) th.join();
  ts.clear();

  // exclusive prefix over (digit, thread): all smaller digits first, then
  // earlier threads of the same digit -> stable scatter
  std::vector<std::vector<int64_t>> offs(
      (size_t)n_threads, std::vector<int64_t>(RADIX, 0));
  int64_t run = 0;
  for (int d = 0; d < RADIX; ++d) {
    for (int t = 0; t < n_threads; ++t) {
      offs[(size_t)t][d] = run;
      run += hist[(size_t)t][d];
    }
  }

  for (int t = 0; t < n_threads; ++t) {
    ts.emplace_back([&, t]() {
      int64_t lo = t * chunk, hi2 = std::min(n, lo + chunk);
      auto& o = offs[(size_t)t];
      for (int64_t i = lo; i < hi2; ++i) {
        int64_t v = idx_in[i];
        int d = (int)((key[v] >> shift) & 0xFF);
        idx_out[o[d]++] = v;
      }
    });
  }
  for (auto& th : ts) th.join();
}

int significant_bytes(int64_t n, const int64_t* key, int n_threads) {
  std::vector<int64_t> maxes((size_t)n_threads, 0);
  std::vector<std::thread> ts;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    ts.emplace_back([&, t]() {
      int64_t lo = t * chunk, hi2 = std::min(n, lo + chunk), m = 0;
      for (int64_t i = lo; i < hi2; ++i)
        if (key[i] > m) m = key[i];
      maxes[(size_t)t] = m;
    });
  }
  for (auto& th : ts) th.join();
  int64_t m = 0;
  for (auto v : maxes)
    if (v > m) m = v;
  int bytes = 0;
  while (m > 0) {
    ++bytes;
    m >>= 8;
  }
  return bytes;
}

}  // namespace

namespace {

int significant_bits(int64_t n, const int64_t* key, int n_threads) {
  int bytes = significant_bytes(n, key, n_threads);
  return 8 * bytes;  // byte granularity is enough for pass counting below
}

// One stable pass over 8-bit digits of packed keys, carrying (key, idx)
// together: sequential reads, no random gather through the permutation.
void packed_pass(int64_t n, const uint64_t* key_in, const int64_t* idx_in,
                 uint64_t* key_out, int64_t* idx_out, int shift,
                 int n_threads) {
  const int RADIX = 256;
  std::vector<std::vector<int64_t>> hist(
      (size_t)n_threads, std::vector<int64_t>(RADIX, 0));
  std::vector<std::thread> ts;
  int64_t chunk = (n + n_threads - 1) / n_threads;

  for (int t = 0; t < n_threads; ++t) {
    ts.emplace_back([&, t]() {
      int64_t lo = t * chunk, hi2 = std::min(n, lo + chunk);
      auto& h = hist[(size_t)t];
      for (int64_t i = lo; i < hi2; ++i) h[(key_in[i] >> shift) & 0xFF]++;
    });
  }
  for (auto& th : ts) th.join();
  ts.clear();

  std::vector<std::vector<int64_t>> offs(
      (size_t)n_threads, std::vector<int64_t>(RADIX, 0));
  int64_t run = 0;
  for (int d = 0; d < RADIX; ++d) {
    for (int t = 0; t < n_threads; ++t) {
      offs[(size_t)t][d] = run;
      run += hist[(size_t)t][d];
    }
  }

  for (int t = 0; t < n_threads; ++t) {
    ts.emplace_back([&, t]() {
      int64_t lo = t * chunk, hi2 = std::min(n, lo + chunk);
      auto& o = offs[(size_t)t];
      for (int64_t i = lo; i < hi2; ++i) {
        int d = (int)((key_in[i] >> shift) & 0xFF);
        int64_t pos = o[d]++;
        key_out[pos] = key_in[i];
        idx_out[pos] = idx_in[i];
      }
    });
  }
  for (auto& th : ts) th.join();
}

}  // namespace

extern "C" {

// Stable argsort of (hi, lo) pairs, non-negative int64 keys. out must hold
// n int64. Returns 0 on success, nonzero on bad arguments.
int argsort_pairs(int64_t n, const int64_t* hi, const int64_t* lo,
                  int64_t* out, int n_threads) {
  if (n < 0 || n_threads < 1) return 1;
  if (n == 0) return 0;

  int bits_hi = significant_bits(n, hi, n_threads);
  int bits_lo = lo ? significant_bits(n, lo, n_threads) : 0;

  if (bits_hi + bits_lo <= 63) {
    // packed path: one combined key, (key, idx) carried together through
    // every pass — all sequential reads
    std::vector<uint64_t> ka((size_t)n), kb((size_t)n);
    std::vector<int64_t> ia((size_t)n), ib((size_t)n);
    {
      std::vector<std::thread> ts;
      int64_t chunk = (n + n_threads - 1) / n_threads;
      for (int t = 0; t < n_threads; ++t) {
        ts.emplace_back([&, t]() {
          int64_t s = t * chunk, e = std::min(n, s + chunk);
          for (int64_t i = s; i < e; ++i) {
            ka[(size_t)i] =
                ((uint64_t)hi[i] << bits_lo) | (lo ? (uint64_t)lo[i] : 0);
            ia[(size_t)i] = i;
          }
        });
      }
      for (auto& th : ts) th.join();
    }
    uint64_t* kc = ka.data();
    uint64_t* kn = kb.data();
    int64_t* ic = ia.data();
    int64_t* in_ = ib.data();
    int total_bytes = (bits_hi + bits_lo + 7) / 8;
    for (int b = 0; b < total_bytes; ++b) {
      packed_pass(n, kc, ic, kn, in_, 8 * b, n_threads);
      std::swap(kc, kn);
      std::swap(ic, in_);
    }
    std::memcpy(out, ic, (size_t)n * sizeof(int64_t));
    return 0;
  }

  // wide-key fallback: sort the permutation with indirect key reads
  std::vector<int64_t> tmp((size_t)n);
  int64_t* cur = out;
  int64_t* nxt = tmp.data();
  for (int64_t i = 0; i < n; ++i) cur[i] = i;
  for (const int64_t* key : {lo, hi}) {
    if (key == nullptr) continue;
    int bytes = significant_bytes(n, key, n_threads);
    for (int b = 0; b < bytes; ++b) {
      radix_pass(n, key, 8 * b, cur, nxt, n_threads);
      std::swap(cur, nxt);
    }
  }
  if (cur != out) std::memcpy(out, cur, (size_t)n * sizeof(int64_t));
  return 0;
}
}
