// Columnar Avro record decoder for the training-data hot path.
//
// The reference reads TrainingExampleAvro through Spark's JVM Avro readers;
// this framework's portable fallback is the pure-Python codec in
// io/avro.py (~2e4 records/s). This decoder walks the SAME binary record
// stream natively and emits columnar buffers — numeric columns, string
// columns (arena + offsets), and per-bag feature streams whose keys
// ("name\x01term", the index-map key format) land in one byte arena — so
// Python touches O(unique features) strings instead of O(nnz).
//
// The schema is compiled (in Python, io/native_reader.py) to a flat field
// program; anything outside the supported shapes falls back to the Python
// codec. Supported field shapes, matching every schema in io/schemas.py:
//   double | float | long | int | boolean | string | bytes
//   union [null, X] / [X, null] of the above
//   array<record{name:string, term:string, value:double}>   (feature bags)
//   map<string>                                              (metadataMap)
//
// C ABI only (ctypes); no exceptions across the boundary. Bounds-checked:
// malformed input yields a null handle, never UB.

#include <algorithm>
#include <cstdint>
#include <memory>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

#include <zlib.h>

namespace {

enum Kind {
  K_DOUBLE = 0,
  K_FLOAT = 1,
  K_LONG = 2,
  K_INT = 3,
  K_BOOL = 4,
  K_STRING = 5,
  K_BYTES = 6,
  K_FEATURES = 7,
  K_STRMAP = 8,
};

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  int64_t read_long() {
    uint64_t acc = 0;
    int shift = 0;
    while (true) {
      if (p >= end || shift > 63) {
        ok = false;
        return 0;
      }
      uint8_t b = *p++;
      acc |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
    }
    return static_cast<int64_t>(acc >> 1) ^ -static_cast<int64_t>(acc & 1);
  }

  double read_double() {
    if (end - p < 8) {
      ok = false;
      return 0.0;
    }
    double v;
    std::memcpy(&v, p, 8);
    p += 8;
    return v;
  }

  float read_float() {
    if (end - p < 4) {
      ok = false;
      return 0.0f;
    }
    float v;
    std::memcpy(&v, p, 4);
    p += 4;
    return v;
  }

  // Returns (offset into buffer, length); content stays in the input.
  std::string_view read_str() {
    int64_t n = read_long();
    if (!ok || n < 0 || end - p < n) {
      ok = false;
      return {};
    }
    std::string_view sv(reinterpret_cast<const char*>(p),
                        static_cast<size_t>(n));
    p += n;
    return sv;
  }

  bool read_bool() {
    if (p >= end) {
      ok = false;
      return false;
    }
    return *p++ != 0;
  }
};

struct StrCol {
  std::vector<int64_t> off;
  std::vector<int32_t> len;  // -1 = absent
};

struct Bag {
  std::vector<int32_t> rec;
  std::vector<float> val;
  std::vector<int64_t> key_off;
  std::vector<int32_t> key_len;
};

struct Result {
  int64_t n_rows = 0;
  std::vector<std::vector<double>> num_cols;
  std::vector<std::vector<uint8_t>> num_present;
  std::vector<StrCol> str_cols;
  std::vector<uint8_t> str_arena;
  std::vector<Bag> bags;
  std::vector<uint8_t> key_arena;
};

void append_str(Result& r, int32_t col, std::string_view sv) {
  r.str_cols[col].off.push_back(static_cast<int64_t>(r.str_arena.size()));
  r.str_cols[col].len.push_back(static_cast<int32_t>(sv.size()));
  r.str_arena.insert(r.str_arena.end(), sv.begin(), sv.end());
}

void append_absent(Result& r, int32_t col) {
  r.str_cols[col].off.push_back(0);
  r.str_cols[col].len.push_back(-1);
}

// Raw-deflate (Avro "deflate" codec: no zlib header, windowBits -15) one
// payload, appending to `out`. Returns false on any corruption.
bool inflate_raw(const uint8_t* src, int64_t len, std::vector<uint8_t>& out) {
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, -15) != Z_OK) return false;
  zs.next_in = const_cast<Bytef*>(src);
  zs.avail_in = static_cast<uInt>(len);
  int ret = Z_OK;
  bool good = true;
  while (ret != Z_STREAM_END) {
    size_t old = out.size();
    size_t grow = std::max<size_t>(static_cast<size_t>(len) * 3 + 4096,
                                   size_t{1} << 16);
    out.resize(old + grow);
    zs.next_out = out.data() + old;
    zs.avail_out = static_cast<uInt>(grow);
    ret = inflate(&zs, Z_NO_FLUSH);
    out.resize(old + grow - zs.avail_out);
    if (ret == Z_STREAM_END) break;
    if (ret == Z_OK) continue;
    // Z_BUF_ERROR with output space left means the input ran dry
    // (truncated payload); everything else is corruption
    good = false;
    break;
  }
  inflateEnd(&zs);
  return good;
}

}  // namespace

extern "C" {

// program: n_fields * 3 int32s — (kind, nullmode, capture).
//   nullmode: 0 = plain, 1 = union with null as branch 0, 2 = null branch 1.
//   capture: kinds 0-4 -> numeric column id; 5-6 -> string column id;
//            7 -> bag id; 8 ignored (tags define string columns
//            tag_col_base + i). -1 = skip.
// tags: concatenated tag key bytes with lengths; matched map entries are
// captured into string columns tag_col_base..tag_col_base+n_tags-1.
static void* avro_decode_impl(const uint8_t* buf, int64_t len,
                              int64_t n_records, const int32_t* program,
                              int32_t n_fields, int32_t n_num_cols,
                              int32_t n_str_cols, int32_t n_bags,
                              const uint8_t* tag_bytes,
                              const int32_t* tag_lens, int32_t n_tags,
                              int32_t tag_col_base) {
  // A record is at least one byte, so a count beyond the payload size is
  // corrupt; rejecting here also bounds the reserve() below.
  if (n_records < 0 || n_records > len) return nullptr;
  // unique_ptr so a mid-decode bad_alloc (huge corrupt payloads) unwinds
  // the partially-built result instead of leaking it past the catch
  auto res_owner = std::make_unique<Result>();
  Result* res = res_owner.get();
  res->num_cols.resize(n_num_cols);
  res->num_present.resize(n_num_cols);
  for (auto& c : res->num_cols) c.reserve(n_records);
  for (auto& c : res->num_present) c.reserve(n_records);
  res->str_cols.resize(n_str_cols);
  res->bags.resize(n_bags);

  std::vector<std::string_view> tags(n_tags);
  {
    int64_t off = 0;
    for (int32_t i = 0; i < n_tags; ++i) {
      tags[i] = std::string_view(reinterpret_cast<const char*>(tag_bytes) + off,
                                 static_cast<size_t>(tag_lens[i]));
      off += tag_lens[i];
    }
  }

  Cursor c{buf, buf + len};
  for (int64_t rec = 0; rec < n_records && c.ok; ++rec) {
    // per-record bookkeeping so absent nullable captures stay aligned
    std::vector<int8_t> num_seen(n_num_cols, 0);
    std::vector<int8_t> str_seen(n_str_cols, 0);

    for (int32_t f = 0; f < n_fields && c.ok; ++f) {
      int32_t kind = program[f * 3];
      int32_t nullmode = program[f * 3 + 1];
      int32_t capture = program[f * 3 + 2];
      bool absent = false;
      if (nullmode) {
        int64_t branch = c.read_long();
        if (!c.ok) break;
        int64_t null_branch = (nullmode == 1) ? 0 : 1;
        if (branch == null_branch) absent = true;
      }
      switch (kind) {
        case K_DOUBLE:
        case K_FLOAT:
        case K_LONG:
        case K_INT:
        case K_BOOL: {
          double v = 0.0;
          if (!absent) {
            if (kind == K_DOUBLE) v = c.read_double();
            else if (kind == K_FLOAT) v = c.read_float();
            else if (kind == K_BOOL) v = c.read_bool() ? 1.0 : 0.0;
            else v = static_cast<double>(c.read_long());
          }
          if (capture >= 0) {
            res->num_cols[capture].push_back(v);
            res->num_present[capture].push_back(absent ? 0 : 1);
            num_seen[capture] = 1;
          }
          break;
        }
        case K_STRING:
        case K_BYTES: {
          if (absent) {
            if (capture >= 0) {
              append_absent(*res, capture);
              str_seen[capture] = 1;
            }
            break;
          }
          std::string_view sv = c.read_str();
          if (!c.ok) break;
          if (capture >= 0) {
            append_str(*res, capture, sv);
            str_seen[capture] = 1;
          }
          break;
        }
        case K_FEATURES: {
          if (absent) break;
          Bag* bag = capture >= 0 ? &res->bags[capture] : nullptr;
          while (c.ok) {
            int64_t n = c.read_long();
            if (!c.ok || n == 0) break;
            if (n < 0) {
              n = -n;
              c.read_long();  // block byte size, unused
            }
            for (int64_t i = 0; i < n && c.ok; ++i) {
              std::string_view name = c.read_str();
              std::string_view term = c.read_str();
              double value = c.read_double();
              if (!c.ok) break;
              if (bag) {
                bag->rec.push_back(static_cast<int32_t>(rec));
                bag->val.push_back(static_cast<float>(value));
                bag->key_off.push_back(
                    static_cast<int64_t>(res->key_arena.size()));
                // index-map key: name, or name + '\x01' + term
                int32_t klen = static_cast<int32_t>(name.size());
                res->key_arena.insert(res->key_arena.end(), name.begin(),
                                      name.end());
                if (!term.empty()) {
                  res->key_arena.push_back(0x01);
                  res->key_arena.insert(res->key_arena.end(), term.begin(),
                                        term.end());
                  klen += 1 + static_cast<int32_t>(term.size());
                }
                bag->key_len.push_back(klen);
              }
            }
          }
          break;
        }
        case K_STRMAP: {
          if (absent) break;
          const bool match_tags = capture >= 0;
          while (c.ok) {
            int64_t n = c.read_long();
            if (!c.ok || n == 0) break;
            if (n < 0) {
              n = -n;
              c.read_long();
            }
            for (int64_t i = 0; i < n && c.ok; ++i) {
              std::string_view key = c.read_str();
              std::string_view val = c.read_str();
              if (!c.ok) break;
              if (!match_tags) continue;
              for (int32_t t = 0; t < n_tags; ++t) {
                if (key == tags[t]) {
                  int32_t col = tag_col_base + t;
                  if (str_seen[col]) {  // duplicate key: last wins
                    res->str_cols[col].off.pop_back();
                    res->str_cols[col].len.pop_back();
                  }
                  append_str(*res, col, val);
                  str_seen[col] = 1;
                }
              }
            }
          }
          break;
        }
        default:
          c.ok = false;
      }
    }
    if (!c.ok) break;
    // align every captured column to rec+1 entries
    for (int32_t i = 0; i < n_num_cols; ++i) {
      if (!num_seen[i]) {
        res->num_cols[i].push_back(0.0);
        res->num_present[i].push_back(0);
      }
    }
    for (int32_t i = 0; i < n_str_cols; ++i) {
      if (!str_seen[i]) append_absent(*res, i);
    }
    res->n_rows = rec + 1;
  }
  if (!c.ok || res->n_rows != n_records) {
    return nullptr;
  }
  return res_owner.release();
}

void* avro_decode(const uint8_t* buf, int64_t len, int64_t n_records,
                  const int32_t* program, int32_t n_fields,
                  int32_t n_num_cols, int32_t n_str_cols, int32_t n_bags,
                  const uint8_t* tag_bytes, const int32_t* tag_lens,
                  int32_t n_tags, int32_t tag_col_base) {
  // No exception may cross the C ABI: corrupt counts can still drive
  // allocations past memory; surface that as a null handle, not terminate.
  try {
    return avro_decode_impl(buf, len, n_records, program, n_fields,
                            n_num_cols, n_str_cols, n_bags, tag_bytes,
                            tag_lens, n_tags, tag_col_base);
  } catch (...) {
    return nullptr;
  }
}

// Whole-file fast path: inflate + columnar-decode in ONE native call.
//
// `file_buf` is the raw container file; (p_off[i], p_len[i]) frame payload
// i (p_count[i] records), `deflate` selects the Avro raw-deflate codec.
// Because ctypes releases the GIL for the duration of a foreign call, the
// ENTIRE inflate+decode window for a file runs GIL-free — decode-pool
// threads working on different files genuinely overlap, where the old
// path bounced through Python (zlib slice + b"".join) between payloads
// and serialized every worker on the interpreter lock.
void* avro_decode_packed(const uint8_t* file_buf, int64_t file_len,
                         const int64_t* p_off, const int64_t* p_len,
                         const int64_t* p_count, int32_t n_payloads,
                         int32_t deflate, const int32_t* program,
                         int32_t n_fields, int32_t n_num_cols,
                         int32_t n_str_cols, int32_t n_bags,
                         const uint8_t* tag_bytes, const int32_t* tag_lens,
                         int32_t n_tags, int32_t tag_col_base) {
  try {
    std::vector<uint8_t> blob;
    int64_t n_records = 0;
    int64_t total_payload = 0;
    for (int32_t i = 0; i < n_payloads; ++i) {
      if (p_off[i] < 0 || p_len[i] < 0 || p_count[i] < 0 ||
          p_off[i] + p_len[i] > file_len)
        return nullptr;
      n_records += p_count[i];
      total_payload += p_len[i];
    }
    blob.reserve(static_cast<size_t>(deflate ? total_payload * 3
                                             : total_payload));
    for (int32_t i = 0; i < n_payloads; ++i) {
      const uint8_t* src = file_buf + p_off[i];
      if (deflate) {
        if (!inflate_raw(src, p_len[i], blob)) return nullptr;
      } else {
        blob.insert(blob.end(), src, src + p_len[i]);
      }
    }
    return avro_decode_impl(blob.data(), static_cast<int64_t>(blob.size()),
                            n_records, program, n_fields, n_num_cols,
                            n_str_cols, n_bags, tag_bytes, tag_lens, n_tags,
                            tag_col_base);
  } catch (...) {
    return nullptr;
  }
}

int64_t res_n_rows(void* h) { return static_cast<Result*>(h)->n_rows; }

const double* res_num_col(void* h, int32_t i) {
  return static_cast<Result*>(h)->num_cols[i].data();
}
const uint8_t* res_num_present(void* h, int32_t i) {
  return static_cast<Result*>(h)->num_present[i].data();
}
const uint8_t* res_str_arena(void* h, int64_t* len) {
  auto* r = static_cast<Result*>(h);
  *len = static_cast<int64_t>(r->str_arena.size());
  return r->str_arena.data();
}
const int64_t* res_str_off(void* h, int32_t i) {
  return static_cast<Result*>(h)->str_cols[i].off.data();
}
const int32_t* res_str_len(void* h, int32_t i) {
  return static_cast<Result*>(h)->str_cols[i].len.data();
}
int64_t res_bag_count(void* h, int32_t b) {
  return static_cast<int64_t>(static_cast<Result*>(h)->bags[b].rec.size());
}
const int32_t* res_bag_rec(void* h, int32_t b) {
  return static_cast<Result*>(h)->bags[b].rec.data();
}
const float* res_bag_val(void* h, int32_t b) {
  return static_cast<Result*>(h)->bags[b].val.data();
}
const int64_t* res_bag_key_off(void* h, int32_t b) {
  return static_cast<Result*>(h)->bags[b].key_off.data();
}
const int32_t* res_bag_key_len(void* h, int32_t b) {
  return static_cast<Result*>(h)->bags[b].key_len.data();
}
const uint8_t* res_key_arena(void* h, int64_t* len) {
  auto* r = static_cast<Result*>(h);
  *len = static_cast<int64_t>(r->key_arena.size());
  return r->key_arena.data();
}
void res_free(void* h) { delete static_cast<Result*>(h); }

// ---- key dedup: ids[i] = dense id of key i; unique keys listed by first
// appearance (the same order DefaultIndexMap assigns) ----

struct Dedup {
  std::vector<int32_t> ids;
  std::vector<int64_t> u_off;
  std::vector<int32_t> u_len;
};

void* key_dedup(const uint8_t* arena, const int64_t* offs,
                const int32_t* lens, int64_t n) {
  auto* d = new Dedup();
  d->ids.resize(n);
  std::unordered_map<std::string_view, int32_t> seen;
  seen.reserve(static_cast<size_t>(n) / 4 + 16);
  for (int64_t i = 0; i < n; ++i) {
    std::string_view sv(reinterpret_cast<const char*>(arena) + offs[i],
                        static_cast<size_t>(lens[i]));
    auto it = seen.find(sv);
    if (it == seen.end()) {
      int32_t id = static_cast<int32_t>(d->u_off.size());
      seen.emplace(sv, id);
      d->u_off.push_back(offs[i]);
      d->u_len.push_back(lens[i]);
      d->ids[i] = id;
    } else {
      d->ids[i] = it->second;
    }
  }
  return d;
}

int64_t dedup_n_unique(void* h) {
  return static_cast<int64_t>(static_cast<Dedup*>(h)->u_off.size());
}
const int32_t* dedup_ids(void* h) { return static_cast<Dedup*>(h)->ids.data(); }
const int64_t* dedup_u_off(void* h) {
  return static_cast<Dedup*>(h)->u_off.data();
}
const int32_t* dedup_u_len(void* h) {
  return static_cast<Dedup*>(h)->u_len.data();
}
void dedup_free(void* h) { delete static_cast<Dedup*>(h); }

}  // extern "C"
