"""Per-entity dimensionality reduction for random-effect problems.

Reference parity: photon-api projector/ — Projector.scala:32 (contract),
IndexMapProjector.scala:42 (dense remap original→projected built from an
entity's observed features :164), ProjectionMatrix.scala:32 (Gaussian random
projection :95, ``w_projected = Bᵀ x``; ProjectionMatrixBroadcast.scala:31
shares ONE matrix across all entities), ProjectorType (INDEX_MAP / RANDOM /
IDENTITY). The reference's projector README recommends index-map projection
as the default (exact, exploits sparsity); random projection suits entities
with very few samples in huge feature spaces.

TPU-first notes: index-map projection happens once at dataset build (host
numpy) and makes every local problem dense-small — the key trick that lets
per-entity solves run as vmap lanes on the MXU. The random projection matrix
is never materialized over the full feature space: rows are generated
deterministically per column id from a seeded counter RNG, so any subset of
columns can be (re)generated identically at build, export, or scoring time —
the broadcast-free equivalent of ProjectionMatrixBroadcast.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Tuple

import numpy as np


class ProjectorType(enum.Enum):
    """Reference projector/ProjectorType.scala."""

    INDEX_MAP = "index_map"
    RANDOM = "random"
    IDENTITY = "identity"


@dataclasses.dataclass(frozen=True)
class IndexMapProjector:
    """Exact remap of an entity's observed feature subset to a dense local
    space (reference IndexMapProjector.scala:42).

    ``global_cols`` is the sorted unique array of observed global feature
    indices; local index j corresponds to global index global_cols[j].
    """

    global_cols: np.ndarray
    global_dim: int

    @classmethod
    def from_observed(cls, cols: np.ndarray, global_dim: int) -> "IndexMapProjector":
        return cls(
            global_cols=np.unique(np.asarray(cols, dtype=np.int64)),
            global_dim=int(global_dim),
        )

    @property
    def projected_dim(self) -> int:
        return int(self.global_cols.size)

    def project_cols(self, cols: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Map global column indices to local ones. Returns (local_idx, mask);
        mask is False for columns outside the projected space (those features
        are DROPPED, matching the reference's projected-space semantics)."""
        cols = np.asarray(cols, dtype=np.int64)
        pos = np.searchsorted(self.global_cols, cols)
        pos_c = np.minimum(pos, max(self.projected_dim - 1, 0))
        mask = (
            (pos < self.projected_dim) & (self.global_cols[pos_c] == cols)
            if self.projected_dim
            else np.zeros(cols.shape, dtype=bool)
        )
        return pos_c, mask

    def project_coefficients_back(self, w_local: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Local coefficients → (global_cols, values) sparse pairs
        (reference projectCoefficients: exact scatter back)."""
        return self.global_cols.copy(), np.asarray(w_local, dtype=np.float32)


@dataclasses.dataclass(frozen=True)
class RandomProjectionMatrix:
    """Gaussian random projection shared by all entities (reference
    ProjectionMatrix.scala:32,95 + ProjectionMatrixBroadcast.scala:31).

    B has shape [global_dim, projected_dim] with entries
    N(0, 1/projected_dim); x_projected = Bᵀ x. Rows are generated lazily and
    deterministically from (seed, column), never materializing B.
    """

    projected_dim: int
    global_dim: int
    seed: int = 0

    # Columns are generated in fixed chunks so any subset can be produced with
    # one vectorized standard_normal call per TOUCHED chunk (not per column):
    # chunk i is the deterministic stream Philox(key=(seed, i)), and column c
    # is row c % CHUNK of chunk c // CHUNK.
    _CHUNK = 4096

    def rows(self, cols: np.ndarray) -> np.ndarray:
        """B[cols, :] — [len(cols), projected_dim], deterministic per col."""
        cols = np.asarray(cols, dtype=np.int64)
        out = np.empty((cols.size, self.projected_dim), dtype=np.float32)
        chunk_of = cols // self._CHUNK
        for chunk in np.unique(chunk_of):
            sel = chunk_of == chunk
            block = np.random.Generator(
                np.random.Philox(key=(self.seed, int(chunk)))
            ).standard_normal((self._CHUNK, self.projected_dim), dtype=np.float32)
            out[sel] = block[cols[sel] % self._CHUNK]
        return out / np.float32(np.sqrt(self.projected_dim))

    def project_coo(
        self,
        sample_idx: np.ndarray,
        cols: np.ndarray,
        vals: np.ndarray,
        num_samples: int,
    ) -> np.ndarray:
        """COO features → dense projected [num_samples, projected_dim]:
        out[s] = Σ_nz v · B[c]."""
        cols = np.asarray(cols, dtype=np.int64)
        uniq, inv = np.unique(cols, return_inverse=True)
        b_sub = self.rows(uniq)
        out = np.zeros((num_samples, self.projected_dim), dtype=np.float32)
        np.add.at(
            out,
            np.asarray(sample_idx, dtype=np.int64),
            np.asarray(vals, dtype=np.float32)[:, None] * b_sub[inv],
        )
        return out

    def project_coefficients_back(
        self, w_projected: np.ndarray, cols: Optional[np.ndarray] = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        """w_original = B · w_projected, restricted to ``cols`` (default: the
        whole global space — reference projectCoefficients semantics)."""
        if cols is None:
            cols = np.arange(self.global_dim, dtype=np.int64)
        return (
            np.asarray(cols, dtype=np.int64),
            self.rows(cols) @ np.asarray(w_projected, dtype=np.float32),
        )


@dataclasses.dataclass(frozen=True)
class IdentityProjector:
    """No-op projection: local space == global space (ProjectorType.IDENTITY)."""

    global_dim: int

    @property
    def projected_dim(self) -> int:
        return self.global_dim

    def project_cols(self, cols: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        cols = np.asarray(cols, dtype=np.int64)
        return cols, np.ones(cols.shape, dtype=bool)

    def project_coefficients_back(self, w_local: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        return (
            np.arange(self.global_dim, dtype=np.int64),
            np.asarray(w_local, dtype=np.float32),
        )
