"""Supervised background threads: crash containment for daemons.

Every long-lived background thread in the system — the admission
controller, the continuous batcher's per-replica workers, the delta
watcher — used to be a bare ``threading.Thread``: one uncaught exception
and the daemon died *silently* while the rest of the process kept
running degraded with no signal (the admission ``_run`` loop was the
motivating bug). :class:`SupervisedThread` wraps the body:

* a crash is **captured**, recorded as a structured failure +
  ``resilience.thread.*`` counters + an optional :class:`AnomalyEvent`,
  never propagated to nowhere;
* the body is **restarted** after deterministic exponential backoff, up
  to ``max_restarts``;
* past the cap the thread is declared **dead**: one final
  ``thread_dead`` record, the ``on_dead`` callback fires, and
  :meth:`health` turns unhealthy so ``/healthz`` can flip to 503 with a
  ``degraded`` reason — while the rest of the process keeps serving.

Two body shapes:

* ``mode="tick"`` — ``target()`` is one iteration; the supervisor loops
  it until the stop event is set (the body does its own idle waiting).
* ``mode="loop"`` — ``target()`` runs its own long loop and returns on
  clean shutdown; a return without a crash ends the thread.

Restarts re-enter ``target`` on the same OS thread (no respawn), so
``Thread`` identity, name, and daemon-ness are stable for the thread's
whole supervised life.
"""
from __future__ import annotations

import logging
import threading
import traceback
from typing import Any, Callable, Dict, Optional

from photon_ml_tpu.resilience.failures import record_failure

__all__ = ["SupervisedThread"]

logger = logging.getLogger(__name__)


class SupervisedThread:
    def __init__(
        self,
        name: str,
        target: Callable[[], Any],
        *,
        mode: str = "tick",
        stop_event: Optional[threading.Event] = None,
        max_restarts: int = 5,
        restart_backoff_s: float = 0.05,
        backoff: float = 2.0,
        max_backoff_s: float = 2.0,
        daemon: bool = True,
        emitter: Optional[Any] = None,
        on_dead: Optional[Callable[["SupervisedThread"], None]] = None,
    ):
        if mode not in ("tick", "loop"):
            raise ValueError(f"mode must be 'tick' or 'loop', got {mode!r}")
        self.name = name
        self._target = target
        self._mode = mode
        self.stop_event = stop_event if stop_event is not None else threading.Event()
        self.max_restarts = int(max_restarts)
        self.restart_backoff_s = float(restart_backoff_s)
        self.backoff = float(backoff)
        self.max_backoff_s = float(max_backoff_s)
        self._emitter = emitter
        self._on_dead = on_dead
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=daemon
        )
        self._lock = threading.Lock()
        self.crashes = 0
        self.restarts = 0
        self.dead = False
        self.last_error: Optional[str] = None

    # ------------------------------------------------------------ control
    def start(self) -> None:
        self._thread.start()

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        self.stop_event.set()
        if self._thread.is_alive():
            self._thread.join(timeout)

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    def is_alive(self) -> bool:
        return self._thread.is_alive()

    # ------------------------------------------------------------ the loop
    def _run(self) -> None:
        while not self.stop_event.is_set():
            try:
                if self._mode == "tick":
                    self._target()
                    continue
                self._target()
                return  # loop body exited cleanly
            except BaseException as exc:  # noqa: BLE001 - that's the job
                if self.stop_event.is_set():
                    return  # shutdown race: drop the error quietly
                if not self._note_crash(exc):
                    return  # declared dead
                # deterministic backoff before re-entering the body; the
                # stop event interrupts the wait so shutdown stays fast
                n = min(self.restarts, 16)
                delay = min(
                    self.restart_backoff_s * (self.backoff ** (n - 1)),
                    self.max_backoff_s,
                )
                if self.stop_event.wait(delay):
                    return

    def _note_crash(self, exc: BaseException) -> bool:
        """Record one crash; True = restart, False = declared dead."""
        tb = traceback.format_exception_only(type(exc), exc)[-1].strip()
        with self._lock:
            self.crashes += 1
            self.last_error = tb
            dying = self.crashes > self.max_restarts
            if not dying:
                self.restarts += 1
        from photon_ml_tpu.telemetry.metrics import get_registry

        reg = get_registry()
        reg.count("resilience.thread.crashes")
        reg.count(f"resilience.thread.{self.name}.crashes")
        record_failure(
            "thread_crash", f"thread.{self.name}", tb, crashes=self.crashes
        )
        logger.warning(
            "supervised thread %s crashed (%d/%d): %s",
            self.name, self.crashes, self.max_restarts + 1, tb,
        )
        self._emit_anomaly("thread_crash", tb)
        if dying:
            with self._lock:
                self.dead = True
            reg.count("resilience.thread.deaths")
            reg.count(f"resilience.thread.{self.name}.deaths")
            record_failure(
                "thread_dead",
                f"thread.{self.name}",
                f"gave up after {self.crashes} crashes: {tb}",
            )
            self._emit_anomaly("thread_dead", tb)
            if self._on_dead is not None:
                try:
                    self._on_dead(self)
                except Exception:
                    logger.exception("on_dead callback raised")
            return False
        reg.count("resilience.thread.restarts")
        reg.count(f"resilience.thread.{self.name}.restarts")
        return True

    def _emit_anomaly(self, kind: str, detail: str) -> None:
        if self._emitter is None:
            return
        try:
            from photon_ml_tpu.event import AnomalyEvent

            self._emitter.send_event(
                AnomalyEvent(
                    kind=kind,
                    coordinate_id=self.name,
                    outer_iteration=-1,
                    objective_value=float("nan"),
                    detail=detail,
                )
            )
        except Exception:
            logger.exception("anomaly emission raised")

    # ------------------------------------------------------------ readers
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "name": self.name,
                "alive": self._thread.is_alive(),
                "dead": self.dead,
                "crashes": self.crashes,
                "restarts": self.restarts,
                "last_error": self.last_error,
            }

    def health(self) -> Dict[str, Any]:
        """Health contribution: unhealthy once declared dead."""
        with self._lock:
            doc: Dict[str, Any] = {
                "healthy": not self.dead,
                "name": self.name,
                "restarts": self.restarts,
            }
            if self.dead:
                doc["degraded"] = (
                    f"thread {self.name} dead after {self.crashes} crashes:"
                    f" {self.last_error}"
                )
            return doc
