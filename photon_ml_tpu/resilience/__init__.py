"""Failure plane: fault injection, retry/backoff, supervised threads.

The JAX/XLA runtime dropped the fault tolerance the reference Photon-ML
inherited from Spark (lineage recompute, task retry). This package is the
replacement — three small pieces every hot path plugs into:

* :mod:`~photon_ml_tpu.resilience.faultpoints` — named, seeded,
  deterministic fault-injection sites (``PHOTON_FAULTS=``); the disabled
  path is a dict-miss no-op, bitwise-invisible to training output.
* :mod:`~photon_ml_tpu.resilience.retry` — :class:`RetryPolicy` with
  bounded attempts, deterministic backoff/jitter, and retryable-exception
  classification, wired into every transient-IO seam.
* :mod:`~photon_ml_tpu.resilience.supervisor` — :class:`SupervisedThread`
  crash containment for background daemons: capture → record → restart
  with backoff → declared dead + ``/healthz`` degraded.

Shared accounting lives in :mod:`~photon_ml_tpu.resilience.failures`
(structured failure ring + ``resilience.*`` counters + sink fan-out).
See docs/RELIABILITY.md for the fault-point catalog and degraded modes.
"""
from photon_ml_tpu.resilience.failures import (
    add_failure_sink,
    clear_failures,
    recent_failures,
    record_failure,
    remove_failure_sink,
)
from photon_ml_tpu.resilience.faultpoints import (
    FatalInjectedFault,
    FaultSpec,
    InjectedFault,
    arm_fault,
    armed_faults,
    configure_faults,
    disarm_fault,
    fault_point,
    fault_stats,
    parse_fault_env,
    register_fault_site,
    registered_fault_sites,
    reset_faults,
)
from photon_ml_tpu.resilience.retry import (
    DEFAULT_IO_RETRY,
    RetryExhausted,
    RetryPolicy,
)
from photon_ml_tpu.resilience.supervisor import SupervisedThread

__all__ = [
    "InjectedFault",
    "FatalInjectedFault",
    "FaultSpec",
    "fault_point",
    "register_fault_site",
    "registered_fault_sites",
    "configure_faults",
    "arm_fault",
    "disarm_fault",
    "reset_faults",
    "armed_faults",
    "fault_stats",
    "parse_fault_env",
    "RetryPolicy",
    "RetryExhausted",
    "DEFAULT_IO_RETRY",
    "SupervisedThread",
    "record_failure",
    "recent_failures",
    "add_failure_sink",
    "remove_failure_sink",
    "clear_failures",
]
