"""Bounded retry with deterministic backoff for transient-IO seams.

Spark gave the reference Photon-ML task retry for free; here every IO
seam that can fail transiently — part-file decode, block-cache load/
store, delta-artifact loads, admission scatter — wraps its body in a
:class:`RetryPolicy`:

* **bounded attempts** with exponential backoff capped at ``max_delay_s``;
* **deterministic jitter** — a hash of ``(site, attempt)`` rather than a
  global RNG draw, so retry timing never perturbs seeded randomness
  anywhere else (bitwise-invisibility contract) and chaos runs replay
  identically;
* **classification** — ``retryable`` exception types minus explicit
  ``non_retryable`` carve-outs (``FileNotFoundError`` is a normal cache
  miss, not a transient fault; :class:`FatalInjectedFault` exercises the
  exhaustion path);
* **accounting** — ``resilience.retry.<site>.{attempts,retries,exhausted,
  recovered}`` counters plus a structured failure record (and anomaly
  fan-out) on exhaustion.

Sleeps go through the policy's injectable ``sleep`` so tests run at full
speed. The singleton :data:`DEFAULT_IO_RETRY` is what the built-in seams
use; callers needing different bounds construct their own policy.
"""
from __future__ import annotations

import dataclasses
import time
import zlib
from typing import Any, Callable, Optional, Tuple, Type

from photon_ml_tpu.resilience.failures import record_failure
from photon_ml_tpu.resilience.faultpoints import FatalInjectedFault

__all__ = ["RetryPolicy", "RetryExhausted", "DEFAULT_IO_RETRY"]


class RetryExhausted(RuntimeError):
    """All attempts failed. ``__cause__`` is the final underlying error."""

    def __init__(self, site: str, attempts: int, last: BaseException):
        super().__init__(
            f"{site}: {attempts} attempts exhausted "
            f"({type(last).__name__}: {last})"
        )
        self.site = site
        self.attempts = attempts
        self.last = last


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Deterministic bounded retry. ``run(site, fn)`` returns ``fn()``'s
    value, retrying classified-transient failures; raises
    :class:`RetryExhausted` (cause = last error) when attempts run out,
    and re-raises non-retryable errors immediately."""

    max_attempts: int = 3
    base_delay_s: float = 0.02
    max_delay_s: float = 2.0
    backoff: float = 2.0
    jitter: float = 0.25          # fraction of the delay, deterministic
    retryable: Tuple[Type[BaseException], ...] = (OSError, TimeoutError)
    non_retryable: Tuple[Type[BaseException], ...] = (
        FileNotFoundError,
        IsADirectoryError,
        NotADirectoryError,
        FatalInjectedFault,
    )
    sleep: Callable[[float], None] = time.sleep

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")

    def is_retryable(self, exc: BaseException) -> bool:
        if isinstance(exc, self.non_retryable):
            return False
        return isinstance(exc, self.retryable)

    def delay_for(self, site: str, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based). Jitter is a
        pure function of (site, attempt): no RNG state touched."""
        delay = self.base_delay_s * (self.backoff ** (attempt - 1))
        delay = min(delay, self.max_delay_s)
        frac = zlib.crc32(f"{site}:{attempt}".encode()) / 2**32
        return delay * (1.0 + self.jitter * frac)

    def run(
        self,
        site: str,
        fn: Callable[..., Any],
        *args: Any,
        on_retry: Optional[Callable[[int, BaseException], None]] = None,
        **kwargs: Any,
    ) -> Any:
        from photon_ml_tpu.telemetry.metrics import get_registry

        reg = get_registry()
        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            reg.count(f"resilience.retry.{site}.attempts")
            try:
                result = fn(*args, **kwargs)
            except BaseException as exc:  # noqa: BLE001 - classified below
                last = exc
                if not self.is_retryable(exc):
                    raise
                if attempt == self.max_attempts:
                    break
                reg.count(f"resilience.retry.{site}.retries")
                if on_retry is not None:
                    on_retry(attempt, exc)
                self.sleep(self.delay_for(site, attempt))
                continue
            if attempt > 1:
                reg.count(f"resilience.retry.{site}.recovered")
            return result
        reg.count(f"resilience.retry.{site}.exhausted")
        record_failure(
            "retry_exhausted",
            site,
            f"{self.max_attempts} attempts: {type(last).__name__}: {last}",
            attempts=self.max_attempts,
            error=type(last).__name__,
        )
        raise RetryExhausted(site, self.max_attempts, last) from last


# The policy every built-in transient-IO seam uses. Three attempts with
# ~20/40ms backoff: enough to ride out EINTR-class flakes without turning
# a permanently bad file into a multi-second stall.
DEFAULT_IO_RETRY = RetryPolicy()
