"""Deterministic fault-injection sites: the chaos seam of the failure plane.

The reference Photon-ML inherited fault tolerance from Spark and never had
to *test* it — lineage recompute was exercised by every flaky executor in
the fleet. This runtime has no fleet doing free chaos testing, so the
failure plane carries its own: every hardened IO seam and background
thread declares a named **fault point** (``fault_point("stream.read_part_file")``)
that tests and the CI chaos gate can arm to raise a fault at a precise,
reproducible moment.

Design contract (mirrors the telemetry disabled-path contract):

* **Disabled path is a dict-miss no-op.** When nothing is armed the whole
  call is one falsy check on an empty dict — no RNG draw, no counter, no
  lock. Arming machinery must be bitwise-invisible to training/serving
  output; the CI disabled-path parity gate pins this by diffing model
  bytes with and without a never-firing armed site.
* **Deterministic triggers.** ``once:N`` fires exactly on the Nth call,
  ``every:N`` on every Nth call, ``prob:P[:seed]`` draws from a dedicated
  per-site ``random.Random(seed)`` — independent of global RNG state and
  reproducible across runs. No wall clock anywhere.
* **Sites self-register at import** via :func:`register_fault_site`, so
  the chaos harness can enumerate every seam
  (:func:`registered_fault_sites`) and assert coverage.

Arming: ``PHOTON_FAULTS="site=once:2,site2=every:5,site3=prob:0.5:7"`` or
programmatic :func:`configure_faults` / :func:`arm_fault`. Injected
faults raise :class:`InjectedFault` (an ``OSError`` subclass, so every
transient-IO retry classification catches it) unless the spec appends
``!fatal``, which raises :class:`FatalInjectedFault` — classified as
non-retryable, for exercising exhaustion/degraded paths.
"""
from __future__ import annotations

import os
import random
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, Optional

__all__ = [
    "InjectedFault",
    "FatalInjectedFault",
    "FaultSpec",
    "register_fault_site",
    "registered_fault_sites",
    "fault_point",
    "configure_faults",
    "arm_fault",
    "disarm_fault",
    "reset_faults",
    "armed_faults",
    "fault_stats",
    "parse_fault_env",
]

_ENV_VAR = "PHOTON_FAULTS"


class InjectedFault(OSError):
    """Raised by an armed fault point. Subclasses ``OSError`` so the
    default transient-IO retry classification treats it as retryable —
    a chaos run exercises the exact recovery path a real flaky read
    would take."""


class FatalInjectedFault(RuntimeError):
    """Non-retryable injected fault (``!fatal`` suffix): exercises retry
    exhaustion, supervisor death, and degraded modes."""


@dataclass
class FaultSpec:
    """One armed site. ``mode``: ``once`` (fire exactly on call number
    ``param``), ``every`` (every ``param``-th call), ``prob`` (each call
    fires with probability ``param`` from a seeded per-site RNG)."""

    site: str
    mode: str                     # "once" | "every" | "prob"
    param: float                  # N for once/every, p for prob
    seed: int = 0                 # prob mode only
    fatal: bool = False
    calls: int = 0
    trips: int = 0
    _rng: Optional[random.Random] = field(default=None, repr=False)

    def __post_init__(self) -> None:
        if self.mode not in ("once", "every", "prob"):
            raise ValueError(f"unknown fault mode {self.mode!r}")
        if self.mode in ("once", "every") and int(self.param) < 1:
            raise ValueError(f"{self.mode} trigger needs N >= 1")
        if self.mode == "prob":
            if not (0.0 <= self.param <= 1.0):
                raise ValueError("prob trigger needs 0 <= p <= 1")
            self._rng = random.Random(self.seed)

    def should_fire(self) -> bool:
        self.calls += 1
        if self.mode == "once":
            fire = self.calls == int(self.param)
        elif self.mode == "every":
            fire = self.calls % int(self.param) == 0
        else:  # prob
            fire = self._rng.random() < self.param
        if fire:
            self.trips += 1
        return fire


# site name -> human description; populated at import time by every module
# that owns a fault point, so the chaos harness can enumerate the seams.
_SITES: Dict[str, str] = {}
# site name -> FaultSpec; EMPTY unless explicitly armed. fault_point()'s
# disabled path is a single falsy check on this dict.
_ARMED: Dict[str, FaultSpec] = {}
_LOCK = threading.Lock()
_ENV_LOADED = False


def register_fault_site(name: str, description: str) -> str:
    """Declare a named injection seam (idempotent). Returns the name so
    modules can bind it to a constant at import."""
    with _LOCK:
        _SITES.setdefault(name, description)
    return name


def registered_fault_sites() -> Dict[str, str]:
    """All declared sites (name -> description). The chaos harness
    asserts its coverage list matches this exactly."""
    _load_env_once()
    with _LOCK:
        return dict(_SITES)


def parse_fault_env(value: str) -> Dict[str, FaultSpec]:
    """Parse a ``PHOTON_FAULTS`` string:
    ``site=once:2,site2=every:5,site3=prob:0.25:7,site4=once:1!fatal``."""
    specs: Dict[str, FaultSpec] = {}
    for item in value.split(","):
        item = item.strip()
        if not item:
            continue
        if "=" not in item:
            raise ValueError(f"bad fault spec {item!r} (want site=mode:...)")
        site, _, trigger = item.partition("=")
        site = site.strip()
        fatal = trigger.endswith("!fatal")
        if fatal:
            trigger = trigger[: -len("!fatal")]
        parts = trigger.split(":")
        mode = parts[0].strip()
        if mode in ("once", "every"):
            if len(parts) != 2:
                raise ValueError(f"bad fault spec {item!r} (want {mode}:N)")
            spec = FaultSpec(site, mode, float(int(parts[1])), fatal=fatal)
        elif mode == "prob":
            if len(parts) not in (2, 3):
                raise ValueError(
                    f"bad fault spec {item!r} (want prob:p[:seed])"
                )
            seed = int(parts[2]) if len(parts) == 3 else 0
            spec = FaultSpec(site, mode, float(parts[1]), seed=seed, fatal=fatal)
        else:
            raise ValueError(f"unknown fault mode {mode!r} in {item!r}")
        specs[site] = spec
    return specs


def _load_env_once() -> None:
    global _ENV_LOADED
    if _ENV_LOADED:
        return
    with _LOCK:
        if _ENV_LOADED:
            return
        _ENV_LOADED = True
        value = os.environ.get(_ENV_VAR, "")
        if value:
            _ARMED.update(parse_fault_env(value))


def configure_faults(specs: Dict[str, FaultSpec] | str) -> None:
    """Replace the armed set (programmatic equivalent of the env var).
    Accepts either a parsed dict or a raw spec string."""
    if isinstance(specs, str):
        specs = parse_fault_env(specs)
    global _ENV_LOADED
    with _LOCK:
        _ENV_LOADED = True  # explicit config overrides env loading
        _ARMED.clear()
        _ARMED.update(specs)


def arm_fault(
    site: str,
    mode: str,
    param: float,
    seed: int = 0,
    fatal: bool = False,
) -> FaultSpec:
    """Arm one site, keeping others as they are."""
    spec = FaultSpec(site, mode, param, seed=seed, fatal=fatal)
    _load_env_once()
    with _LOCK:
        _ARMED[site] = spec
    return spec


def disarm_fault(site: str) -> None:
    with _LOCK:
        _ARMED.pop(site, None)


def reset_faults() -> None:
    """Disarm everything and forget the env was read (tests)."""
    global _ENV_LOADED
    with _LOCK:
        _ARMED.clear()
        _ENV_LOADED = False


def armed_faults() -> Dict[str, FaultSpec]:
    _load_env_once()
    with _LOCK:
        return dict(_ARMED)


def fault_stats() -> Dict[str, Dict[str, int]]:
    """Per-armed-site call/trip counts (chaos assertions read this)."""
    with _LOCK:
        return {
            name: {"calls": spec.calls, "trips": spec.trips}
            for name, spec in _ARMED.items()
        }


def fault_point(name: str) -> None:
    """The injection seam. Unarmed: one falsy check on an empty dict —
    no lock, no RNG, bitwise-invisible. Armed: consult the site's
    deterministic trigger and raise when it fires."""
    if not _ARMED and _ENV_LOADED:
        return
    _load_env_once()
    spec = _ARMED.get(name)
    if spec is None:
        return
    with _LOCK:
        fire = spec.should_fire()
    if not fire:
        return
    # counted outside the lock: the registry has its own
    from photon_ml_tpu.telemetry.metrics import get_registry

    get_registry().count(f"resilience.fault.{name}.trips")
    exc = FatalInjectedFault if spec.fatal else InjectedFault
    raise exc(f"injected fault at {name} (call {spec.calls})")
