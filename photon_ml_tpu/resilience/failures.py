"""Structured failure records: the shared sink for every resilience event.

Retry exhaustion, supervised-thread crashes, skipped blocks, and rejected
delta artifacts all funnel through :func:`record_failure`, which

* appends a structured record to a bounded in-process ring (the failure
  flight recorder — :func:`recent_failures` feeds ``/healthz`` detail and
  post-mortems),
* bumps ``resilience.failures`` / ``resilience.failures.<kind>`` counters
  in the process-global :class:`MetricsRegistry`,
* logs one WARNING, and
* fans out to registered sinks (the training progress ledger attaches one
  so resilience events land next to convergence records; sink errors are
  swallowed — a broken observer must never re-fail the failure path).

Records carry no wall-clock field at the resilience layer: ordering is the
monotonically increasing ``seq``. Timestamps belong to whichever sink
persists the record (the progress ledger stamps its own).
"""
from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "record_failure",
    "recent_failures",
    "add_failure_sink",
    "remove_failure_sink",
    "clear_failures",
]

logger = logging.getLogger(__name__)

_LOCK = threading.Lock()
_RING: deque = deque(maxlen=256)
_SINKS: List[Callable[[Dict[str, Any]], None]] = []
_SEQ = 0


def record_failure(
    kind: str,
    site: str,
    detail: str = "",
    **extra: Any,
) -> Dict[str, Any]:
    """Record one resilience event. ``kind`` is the failure class
    (``retry_exhausted``, ``thread_crash``, ``thread_dead``,
    ``block_skipped``, ``delta_rejected``, ...); ``site`` names the seam
    or thread."""
    global _SEQ
    with _LOCK:
        _SEQ += 1
        rec: Dict[str, Any] = {
            "seq": _SEQ,
            "kind": str(kind),
            "site": str(site),
            "detail": str(detail),
        }
        for key, value in extra.items():
            rec[key] = value
        _RING.append(rec)
        sinks = list(_SINKS)
    from photon_ml_tpu.telemetry.metrics import get_registry

    reg = get_registry()
    reg.count("resilience.failures")
    reg.count(f"resilience.failures.{kind}")
    logger.warning("resilience: %s at %s: %s", kind, site, detail)
    for sink in sinks:
        try:
            sink(dict(rec))
        except Exception:  # noqa: BLE001 - observers must not re-fail us
            logger.exception("resilience failure sink raised")
    return rec


def recent_failures(n: Optional[int] = None) -> List[Dict[str, Any]]:
    """Most recent failure records, oldest first."""
    with _LOCK:
        items = list(_RING)
    return items if n is None else items[-n:]


def add_failure_sink(sink: Callable[[Dict[str, Any]], None]) -> None:
    with _LOCK:
        if sink not in _SINKS:
            _SINKS.append(sink)


def remove_failure_sink(sink: Callable[[Dict[str, Any]], None]) -> None:
    with _LOCK:
        if sink in _SINKS:
            _SINKS.remove(sink)


def clear_failures() -> None:
    """Drop the ring (tests). Sinks stay attached."""
    global _SEQ
    with _LOCK:
        _RING.clear()
        _SEQ = 0
