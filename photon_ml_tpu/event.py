"""In-process event pub/sub: the observability extension point.

Reference parity: photon-client event/{Event,EventEmitter,EventListener}.scala
and the concrete events fired from Driver.scala:120,162,186 —
PhotonSetupEvent, TrainingStartEvent, PhotonOptimizationLogEvent,
TrainingFinishEvent. Listeners are registered by instance (or by dotted class
name, matching the reference's ``--event-listeners`` flag, Params.scala:186)
and receive every emitted event; listener exceptions are isolated so a bad
listener cannot kill training.
"""

from __future__ import annotations

import dataclasses
import importlib
import logging
from typing import Any, Dict, List, Optional, Tuple

_log = logging.getLogger("photon_ml_tpu.event")


@dataclasses.dataclass(frozen=True)
class Event:
    """Base event (reference event/Event.scala:27)."""


@dataclasses.dataclass(frozen=True)
class PhotonSetupEvent(Event):
    """Driver configured and about to run (Driver.scala:120)."""

    params: Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TrainingStartEvent(Event):
    """Training phase entered (Driver.scala:162)."""

    task: str


@dataclasses.dataclass(frozen=True)
class PhotonOptimizationLogEvent(Event):
    """Per-model optimization telemetry (Driver.scala:186)."""

    coordinate_id: Optional[str]
    regularization_weight: float
    objective_value: float
    iterations: int
    convergence_reason: str


@dataclasses.dataclass(frozen=True)
class TrainingFinishEvent(Event):
    """Training phase finished."""

    task: str
    wall_seconds: float


@dataclasses.dataclass(frozen=True)
class ScoringStartEvent(Event):
    """Online/offline scoring phase entered (serving replay, serve CLI)."""

    model_id: str
    num_requests: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class ScoringFinishEvent(Event):
    """Scoring phase finished; carries the serving metrics snapshot."""

    model_id: str
    num_requests: int
    wall_seconds: float
    metrics: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class ModelSwapEvent(Event):
    """A hot-swap attempt on a live scorer (serving.hotswap). Fired for
    successful swaps AND rollbacks (``rolled_back`` distinguishes them)."""

    model_id: str
    generation: int
    fingerprint: Optional[str]
    coordinates: Tuple[str, ...]
    rows_updated: int
    blackout_s: float
    rolled_back: bool = False
    validation_metric: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class SolverStatsEvent(Event):
    """Per-bucket telemetry from the convergence-adaptive random-effect
    driver (opt.tracking.SolverStats), emitted by the coordinate-descent
    driver after each random-effect update."""

    coordinate_id: Optional[str]
    bucket: int
    optimizer: str
    num_entities: int
    rounds: int
    dispatch_widths: Tuple[int, ...]
    iterations_p50: float
    iterations_p99: float
    executed_lane_iterations: int
    lockstep_lane_iterations: int
    wasted_lane_fraction: float

    @classmethod
    def from_stats(cls, coordinate_id: Optional[str], stats) -> "SolverStatsEvent":
        """Build from an opt.tracking.SolverStats (duck-typed to avoid an
        import cycle: event is imported from everywhere)."""
        return cls(
            coordinate_id=coordinate_id,
            bucket=stats.bucket,
            optimizer=stats.optimizer,
            num_entities=stats.num_entities,
            rounds=stats.rounds,
            dispatch_widths=tuple(stats.dispatch_widths),
            iterations_p50=stats.iterations_p50,
            iterations_p99=stats.iterations_p99,
            executed_lane_iterations=stats.executed_lane_iterations,
            lockstep_lane_iterations=stats.lockstep_lane_iterations,
            wasted_lane_fraction=stats.wasted_lane_fraction,
        )


@dataclasses.dataclass(frozen=True)
class TransferStatsEvent(Event):
    """Per-outer-iteration score-plane transfer accounting from the
    coordinate-descent driver (opt.tracking.TransferStats deltas): row-length
    score arrays moved host<->device plus host score-plane re-sums. On the
    device plane the steady state is all-zero row transfers."""

    score_plane: str
    outer_iteration: int
    num_rows: int
    row_transfers_h2d: int
    row_transfers_d2h: int
    row_bytes_h2d: int
    row_bytes_d2h: int
    host_score_sums: int
    device_plane_updates: int


@dataclasses.dataclass(frozen=True)
class AnomalyEvent(Event):
    """The divergence watchdog tripped during training: a non-finite
    objective, an objective increase beyond tolerance, or repeated
    line-search failure while the gradient is still large. ``kind`` names
    the trigger; ``detail`` carries the offending values. Listeners see it
    before the driver aborts (the /healthz endpoint flips unhealthy on the
    same signal)."""

    kind: str
    coordinate_id: Optional[str]
    outer_iteration: int
    objective_value: float
    detail: Dict[str, Any] = dataclasses.field(default_factory=dict)


class EventListener:
    """Receives every event from an emitter (EventListener.scala)."""

    def on_event(self, event: Event) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def close(self) -> None:
        """Called when the emitter shuts down."""


class EventEmitter:
    """Mixin/owner of a listener list (reference EventEmitter.scala:24).

    Drivers inherit from (or hold) this and call ``send_event``.
    """

    def __init__(self) -> None:
        self._listeners: List[EventListener] = []
        #: Count of listener exceptions swallowed by ``send_event`` /
        #: ``clear_listeners`` (isolation keeps training alive; this keeps
        #: the failures observable — telemetry ledgers assert it is zero).
        self.listener_errors: int = 0

    def register_listener(self, listener: EventListener) -> None:
        self._listeners.append(listener)

    def register_listener_class(self, dotted_name: str) -> None:
        """Instantiate a listener from ``package.module.ClassName`` — the
        reference's ``--event-listeners`` CLI contract (Params.scala:186)."""
        module_name, _, class_name = dotted_name.rpartition(".")
        if not module_name:
            raise ValueError(f"listener name must be dotted path, got {dotted_name!r}")
        try:
            module = importlib.import_module(module_name)
        except ImportError as e:
            raise ValueError(
                f"cannot register event listener {dotted_name!r}: module "
                f"{module_name!r} failed to import ({e})"
            ) from e
        try:
            cls = getattr(module, class_name)
        except AttributeError:
            raise ValueError(
                f"cannot register event listener {dotted_name!r}: module "
                f"{module_name!r} has no attribute {class_name!r}"
            ) from None
        try:
            listener = cls()
        except TypeError as e:
            raise ValueError(
                f"cannot register event listener {dotted_name!r}: "
                f"{class_name!r} is not an instantiable listener class ({e})"
            ) from e
        if not hasattr(listener, "on_event"):
            raise ValueError(
                f"cannot register event listener {dotted_name!r}: "
                f"{class_name!r} has no on_event method"
            )
        self.register_listener(listener)

    def send_event(self, event: Event) -> None:
        for listener in self._listeners:
            try:
                listener.on_event(event)
            except Exception:  # noqa: BLE001 - listener isolation
                self.listener_errors += 1
                _log.exception("event listener %r failed", listener)

    def clear_listeners(self) -> None:
        for listener in self._listeners:
            try:
                listener.close()
            except Exception:  # noqa: BLE001
                self.listener_errors += 1
                _log.exception("event listener %r failed to close", listener)
        self._listeners = []
