"""Avro training data → GameData (feature bags merged into shards).

Reference parity: data/avro/AvroDataReader.scala:53 — readMerged(paths,
featureShardConfigurations) merges one or more "feature bag" array fields
of each record into a single sparse vector per feature shard, building or
reusing name→index maps per shard; GameConverters.scala:29 extracts
response/offset/weight/uid plus id tags (top-level field first, then
metadataMap — reference GameConverters.getValueFromRow).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from photon_ml_tpu.data.game_data import FeatureShard, GameData
from photon_ml_tpu.indexmap import (
    INTERCEPT_KEY,
    DefaultIndexMap,
    IndexMap,
    feature_key,
)
from photon_ml_tpu.io.avro import read_avro_dir


def write_training_examples(
    path: str,
    records: Iterable[dict],
) -> int:
    """Write TrainingExampleAvro records (each a dict with label, features=
    [(name, term, value)...], optional uid/weight/offset/metadataMap and
    extra feature-bag fields). The inverse of this module's reader; also the
    equivalent of dev-scripts/libsvm_text_to_trainingexample_avro.py."""
    from photon_ml_tpu.io.avro import write_avro_file
    from photon_ml_tpu.io import schemas as _schemas

    extra_bags: List[str] = []
    materialized = []
    for rec in records:
        out = dict(rec)
        for bag in list(out):
            if bag in ("uid", "label", "metadataMap", "weight", "offset"):
                continue
            val = out[bag]
            if isinstance(val, (list, tuple)):
                out[bag] = [
                    {"name": n, "term": t, "value": float(v)} for n, t, v in val
                ]
                if bag != "features" and bag not in extra_bags:
                    extra_bags.append(bag)
        out.setdefault("features", [])
        materialized.append(out)

    schema = dict(_schemas.TRAINING_EXAMPLE)
    if extra_bags:
        schema = dict(schema)
        schema["fields"] = list(schema["fields"]) + [
            {
                "name": bag,
                "type": {"type": "array", "items": "FeatureAvro"},
                "default": [],
            }
            for bag in extra_bags
        ]
    return write_avro_file(path, schema, materialized)


@dataclasses.dataclass(frozen=True)
class FeatureShardConfiguration:
    """Which record fields (feature bags) make up one shard, and whether the
    shard gets an intercept column (reference
    FeatureShardConfiguration in GameTrainingParams)."""

    feature_bags: Sequence[str]
    add_intercept: bool = True


def _record_features(record: dict, bags: Sequence[str]):
    for bag in bags:
        arr = record.get(bag)
        if not arr:
            continue
        for f in arr:
            yield feature_key(f["name"], f["term"]), float(f["value"])


def build_index_maps(
    paths: Sequence[str] | str,
    shard_configs: Dict[str, FeatureShardConfiguration],
) -> Dict[str, IndexMap]:
    """Scan pass: distinct feature keys per shard → dense indices
    (reference 'default index map' path, GameDriver.scala:46-85)."""
    if isinstance(paths, str):
        paths = [paths]
    native = _build_index_maps_native(paths, shard_configs)
    if native is not None:
        return native
    keys: Dict[str, dict] = {sid: {} for sid in shard_configs}
    for path in paths:
        for record in read_avro_dir(path):
            for sid, cfg in shard_configs.items():
                bucket = keys[sid]
                for key, _ in _record_features(record, cfg.feature_bags):
                    if key not in bucket:
                        bucket[key] = len(bucket)
    out: Dict[str, IndexMap] = {}
    for sid, cfg in shard_configs.items():
        bucket = keys[sid]
        if cfg.add_intercept and INTERCEPT_KEY not in bucket:
            bucket[INTERCEPT_KEY] = len(bucket)
        out[sid] = DefaultIndexMap(bucket)
    return out


def read_game_data(
    paths: Sequence[str] | str,
    shard_configs: Dict[str, FeatureShardConfiguration],
    index_maps: Optional[Dict[str, IndexMap]] = None,
    id_tags: Sequence[str] = (),
    response_field: str = "label",
    offset_field: str = "offset",
    weight_field: str = "weight",
    uid_field: str = "uid",
    is_response_required: bool = True,
) -> tuple[GameData, Dict[str, IndexMap], List[Optional[str]]]:
    """Read Avro dirs/files into a GameData. Returns (data, index_maps, uids).

    Unmapped features (absent from a provided index map) are dropped, like
    the reference's scoring path over a fixed training index.
    """
    if isinstance(paths, str):
        paths = [paths]

    native = _read_game_data_native(
        paths, shard_configs, index_maps, id_tags,
        response_field, offset_field, weight_field, uid_field,
        is_response_required,
    )
    if native is not None:
        return native

    if index_maps is None:
        index_maps = build_index_maps(paths, shard_configs)

    labels: List[float] = []
    offsets: List[float] = []
    weights: List[float] = []
    uids: List[Optional[str]] = []
    tag_values: Dict[str, List[str]] = {t: [] for t in id_tags}
    coo: Dict[str, tuple] = {
        sid: ([], [], []) for sid in shard_configs
    }  # rows, cols, vals

    row = 0
    for path in paths:
        for record in read_avro_dir(path):
            label = record.get(response_field)
            if label is None:
                if is_response_required:
                    raise ValueError(f"record {row} has no '{response_field}'")
                label = np.nan
            labels.append(float(label))
            off = record.get(offset_field)
            offsets.append(0.0 if off is None else float(off))
            wt = record.get(weight_field)  # explicit 0.0 weight is preserved
            weights.append(1.0 if wt is None else float(wt))
            uids.append(record.get(uid_field))
            meta = record.get("metadataMap") or {}
            for tag in id_tags:
                v = record.get(tag)
                if v is None:  # null top-level field falls back to metadataMap
                    v = meta.get(tag)
                if v is None:
                    raise ValueError(f"record {row} missing id tag '{tag}'")
                tag_values[tag].append(str(v))
            for sid, cfg in shard_configs.items():
                imap = index_maps[sid]
                rows, cols, vals = coo[sid]
                for key, value in _record_features(record, cfg.feature_bags):
                    idx = imap.get_index(key)
                    if idx >= 0:
                        rows.append(row)
                        cols.append(idx)
                        vals.append(value)
                if cfg.add_intercept:
                    idx = imap.get_index(INTERCEPT_KEY)
                    if idx >= 0:
                        rows.append(row)
                        cols.append(idx)
                        vals.append(1.0)
            row += 1

    shards = {
        sid: FeatureShard(
            rows=np.asarray(rows, dtype=np.int64),
            cols=np.asarray(cols, dtype=np.int64),
            vals=np.asarray(vals, dtype=np.float32),
            dim=len(index_maps[sid]),
        )
        for sid, (rows, cols, vals) in coo.items()
    }
    data = GameData(
        labels=np.asarray(labels, dtype=np.float32),
        feature_shards=shards,
        id_tags={t: np.asarray(v) for t, v in tag_values.items()},
        offsets=np.asarray(offsets, dtype=np.float32),
        weights=np.asarray(weights, dtype=np.float32),
    )
    return data, index_maps, uids


def list_data_files(paths: Sequence[str] | str) -> List[str]:
    """Part files of one or more dataset dirs/files, in read order — the
    file-granular view `read_game_data` concatenates over."""
    if isinstance(paths, str):
        paths = [paths]
    return _part_files(paths)


def file_row_counts(paths: Sequence[str] | str) -> List[tuple]:
    """``(path, row_count)`` per part file via a container framing scan —
    no record decode, no decompression. Streaming block planners use this
    to lay out fixed-size example blocks across file boundaries without
    materializing the dataset."""
    from photon_ml_tpu.io.native_reader import container_block_counts

    return [
        (path, int(sum(container_block_counts(path))))
        for path in list_data_files(paths)
    ]


def iter_game_data(
    paths: Sequence[str] | str,
    shard_configs: Dict[str, FeatureShardConfiguration],
    index_maps: Dict[str, IndexMap],
    id_tags: Sequence[str] = (),
    response_field: str = "label",
    offset_field: str = "offset",
    weight_field: str = "weight",
    uid_field: str = "uid",
    is_response_required: bool = True,
):
    """File-granular variant of :func:`read_game_data`: yields
    ``(path, GameData, uids)`` one part file at a time instead of
    concatenating the whole dataset.

    ``index_maps`` must be prebuilt (e.g. :func:`build_index_maps` or a
    loaded off-heap map): every yielded piece then shares one stable column
    space, so downstream block shapes are identical across files and
    nothing retraces. Peak memory is one decoded file, not the dataset.
    """
    if index_maps is None:
        raise ValueError(
            "iter_game_data requires prebuilt index_maps; build them once "
            "with build_index_maps() so file pieces share a stable index"
        )
    for path in list_data_files(paths):
        data, _, uids = read_game_data(
            [path],
            shard_configs,
            index_maps=index_maps,
            id_tags=id_tags,
            response_field=response_field,
            offset_field=offset_field,
            weight_field=weight_field,
            uid_field=uid_field,
            is_response_required=is_response_required,
        )
        yield path, data, uids


def _part_files(paths: Sequence[str]) -> List[str]:
    from photon_ml_tpu.io.avro import list_part_files

    files: List[str] = []
    for path in paths:
        files.extend(list_part_files(path))
    return files


def _decode_columnar_files(
    files: Sequence[str],
    numeric_fields: Sequence[str],
    string_fields: Sequence[str],
    bags: Sequence[str],
    tags: Sequence[str],
):
    """Decode every part file through the native path with one file read
    each; None -> caller falls back to the Python codec."""
    from photon_ml_tpu.io import native_reader as nr
    from photon_ml_tpu.io.avro import MAGIC, AvroSchema, _Reader, _decode

    columnar = []
    for path in files:
        with open(path, "rb") as f:
            raw = f.read()
        r = _Reader(raw)
        if r.read(4) != MAGIC:
            return None
        meta = _decode(r, {"type": "map", "values": "bytes"})
        root = AvroSchema(meta["avro.schema"].decode("utf-8")).root
        plan = nr.compile_program(
            root,
            numeric_fields=numeric_fields,
            string_fields=string_fields,
            bags=bags,
            tags=tags,
        )
        if plan is None:
            return None
        cf = nr.read_columnar_file(path, plan, data=raw)
        if cf is None:
            return None
        columnar.append((plan, cf))
    return columnar


def _all_bags_of(shard_configs: Dict[str, FeatureShardConfiguration]) -> List[str]:
    bags: List[str] = []
    for cfg in shard_configs.values():
        for bag in cfg.feature_bags:
            if bag not in bags:
                bags.append(bag)
    return bags


def _concat_bag_streams(columnar, feature_bags: Sequence[str]):
    """Concatenate one shard's bag streams over all files: global row ids,
    values, and key (offset, len) into the joined arena."""
    recs, vals, koffs, klens, arenas = [], [], [], [], []
    arena_base = 0
    row_base = 0
    for _, cf in columnar:
        for bag in feature_bags:
            rec, val, koff, klen = cf.bags[bag]
            recs.append(rec + row_base)
            vals.append(val)
            koffs.append(koff + arena_base)
            klens.append(klen)
        arenas.append(cf.key_arena)
        arena_base += len(cf.key_arena)
        row_base += cf.n_rows
    rows = np.concatenate(recs) if recs else np.zeros(0, np.int64)
    values = np.concatenate(vals) if vals else np.zeros(0, np.float32)
    key_off = np.concatenate(koffs) if koffs else np.zeros(0, np.int64)
    key_len = np.concatenate(klens) if klens else np.zeros(0, np.int32)
    return rows, values, key_off, key_len, b"".join(arenas)


def _read_game_data_native(
    paths: Sequence[str],
    shard_configs: Dict[str, FeatureShardConfiguration],
    index_maps: Optional[Dict[str, IndexMap]],
    id_tags: Sequence[str],
    response_field: str,
    offset_field: str,
    weight_field: str,
    uid_field: str,
    is_response_required: bool,
):
    """Columnar fast path through native/avrodecode.cpp; None -> caller
    falls back to the record-at-a-time Python codec (unsupported schema
    shape, codec, or missing native toolchain). One decode pass builds both
    the index maps and the COO shards (the Python path scans twice).

    Feature-index assignment order differs from the Python path (keys are
    numbered per bag stream, not per record) — ids are run-internal either
    way; persisted artifacts are name-keyed.
    """
    from photon_ml_tpu.io import native_reader as nr

    if not nr.native_available():
        return None
    files = _part_files(paths)
    if not files:
        return None
    columnar = _decode_columnar_files(
        files,
        numeric_fields=[response_field, offset_field, weight_field],
        string_fields=[uid_field, *id_tags],
        bags=_all_bags_of(shard_configs),
        tags=id_tags,
    )
    if columnar is None:
        return None

    n = sum(cf.n_rows for _, cf in columnar)

    def num_col(field, default):
        out = np.full(n, default, dtype=np.float32)
        present = np.zeros(n, dtype=bool)
        at = 0
        for plan, cf in columnar:
            m = cf.n_rows
            if field in plan.num_fields:
                out[at : at + m] = np.where(
                    cf.num_present[field], cf.num[field], default
                )
                present[at : at + m] = cf.num_present[field]
            at += m
        return out, present

    labels, labels_present = num_col(response_field, np.nan)
    if is_response_required and not labels_present.all():
        row = int(np.flatnonzero(~labels_present)[0])
        raise ValueError(f"record {row} has no '{response_field}'")
    offsets, _ = num_col(offset_field, 0.0)
    weights, _ = num_col(weight_field, 1.0)

    def str_col(field, which="strs"):
        out: List[Optional[str]] = []
        for _, cf in columnar:
            cols = cf.strs if which == "strs" else cf.tag_strs
            if field in cols:
                out.extend(nr.decode_strings(cols[field]))
            else:
                out.extend([None] * cf.n_rows)
        return out

    uids = str_col(uid_field)
    tag_values: Dict[str, np.ndarray] = {}
    for tag in id_tags:
        # top-level field wins over the metadataMap entry (reference
        # GameConverters.getValueFromRow)
        top = str_col(tag)
        from_map = str_col(tag, which="tags")
        vals = [t if t is not None else m for t, m in zip(top, from_map)]
        missing = [i for i, v in enumerate(vals) if v is None]
        if missing:
            raise ValueError(f"record {missing[0]} missing id tag '{tag}'")
        tag_values[tag] = np.asarray(vals)

    shards: Dict[str, FeatureShard] = {}
    out_maps: Dict[str, IndexMap] = {}
    for sid, cfg in shard_configs.items():
        rows, values, key_off, key_len, arena = _concat_bag_streams(
            columnar, cfg.feature_bags
        )
        ids, uniques = nr.dedup_keys(arena, key_off, key_len)
        if index_maps is not None:
            imap = index_maps[sid]
            lut = np.asarray(imap.get_indices(uniques), dtype=np.int64)
            cols = lut[ids] if len(ids) else np.zeros(0, np.int64)
            keep = cols >= 0  # unmapped features drop (scoring semantics)
            rows, cols, values = rows[keep], cols[keep], values[keep]
        else:
            key_to_id = {k: i for i, k in enumerate(uniques)}
            if cfg.add_intercept and INTERCEPT_KEY not in key_to_id:
                key_to_id[INTERCEPT_KEY] = len(key_to_id)
            imap = DefaultIndexMap(key_to_id)
            cols = ids
        if cfg.add_intercept:
            icpt = imap.get_index(INTERCEPT_KEY)
            if icpt >= 0:
                rows = np.concatenate([rows, np.arange(n, dtype=np.int64)])
                cols = np.concatenate(
                    [cols, np.full(n, icpt, dtype=np.int64)]
                )
                values = np.concatenate(
                    [values, np.ones(n, dtype=np.float32)]
                )
        out_maps[sid] = imap
        shards[sid] = FeatureShard(
            rows=rows.astype(np.int64),
            cols=cols.astype(np.int64),
            vals=values.astype(np.float32),
            dim=len(imap),
        )

    data = GameData(
        labels=labels,
        feature_shards=shards,
        id_tags=tag_values,
        offsets=offsets,
        weights=weights,
    )
    return data, out_maps, uids


def _build_index_maps_native(
    paths: Sequence[str],
    shard_configs: Dict[str, FeatureShardConfiguration],
) -> Optional[Dict[str, IndexMap]]:
    """Columnar scan for the standalone index-build (one native decode of
    the bag streams + native key dedup); None -> Python fallback.

    Key-id assignment order differs from the Python scan (per bag stream,
    not per record) — ids are run-internal, artifacts are name-keyed.
    """
    from photon_ml_tpu.io import native_reader as nr

    if not nr.native_available():
        return None
    files = _part_files(paths)
    if not files:
        return None
    columnar = _decode_columnar_files(
        files, [], [], _all_bags_of(shard_configs), []
    )
    if columnar is None:
        return None

    out: Dict[str, IndexMap] = {}
    for sid, cfg in shard_configs.items():
        _, _, key_off, key_len, arena = _concat_bag_streams(
            columnar, cfg.feature_bags
        )
        _, uniques = nr.dedup_keys(arena, key_off, key_len)
        key_to_id = {k: i for i, k in enumerate(uniques)}
        if cfg.add_intercept and INTERCEPT_KEY not in key_to_id:
            key_to_id[INTERCEPT_KEY] = len(key_to_id)
        out[sid] = DefaultIndexMap(key_to_id)
    return out
