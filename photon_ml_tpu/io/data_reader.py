"""Avro training data → GameData (feature bags merged into shards).

Reference parity: data/avro/AvroDataReader.scala:53 — readMerged(paths,
featureShardConfigurations) merges one or more "feature bag" array fields
of each record into a single sparse vector per feature shard, building or
reusing name→index maps per shard; GameConverters.scala:29 extracts
response/offset/weight/uid plus id tags (top-level field first, then
metadataMap — reference GameConverters.getValueFromRow).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from photon_ml_tpu.data.game_data import FeatureShard, GameData
from photon_ml_tpu.indexmap import (
    INTERCEPT_KEY,
    DefaultIndexMap,
    IndexMap,
    feature_key,
)
from photon_ml_tpu.io.avro import read_avro_dir


def write_training_examples(
    path: str,
    records: Iterable[dict],
) -> int:
    """Write TrainingExampleAvro records (each a dict with label, features=
    [(name, term, value)...], optional uid/weight/offset/metadataMap and
    extra feature-bag fields). The inverse of this module's reader; also the
    equivalent of dev-scripts/libsvm_text_to_trainingexample_avro.py."""
    from photon_ml_tpu.io.avro import write_avro_file
    from photon_ml_tpu.io import schemas as _schemas

    extra_bags: List[str] = []
    materialized = []
    for rec in records:
        out = dict(rec)
        for bag in list(out):
            if bag in ("uid", "label", "metadataMap", "weight", "offset"):
                continue
            val = out[bag]
            if isinstance(val, (list, tuple)):
                out[bag] = [
                    {"name": n, "term": t, "value": float(v)} for n, t, v in val
                ]
                if bag != "features" and bag not in extra_bags:
                    extra_bags.append(bag)
        out.setdefault("features", [])
        materialized.append(out)

    schema = dict(_schemas.TRAINING_EXAMPLE)
    if extra_bags:
        schema = dict(schema)
        schema["fields"] = list(schema["fields"]) + [
            {
                "name": bag,
                "type": {"type": "array", "items": "FeatureAvro"},
                "default": [],
            }
            for bag in extra_bags
        ]
    return write_avro_file(path, schema, materialized)


@dataclasses.dataclass(frozen=True)
class FeatureShardConfiguration:
    """Which record fields (feature bags) make up one shard, and whether the
    shard gets an intercept column (reference
    FeatureShardConfiguration in GameTrainingParams)."""

    feature_bags: Sequence[str]
    add_intercept: bool = True


def _record_features(record: dict, bags: Sequence[str]):
    for bag in bags:
        arr = record.get(bag)
        if not arr:
            continue
        for f in arr:
            yield feature_key(f["name"], f["term"]), float(f["value"])


def build_index_maps(
    paths: Sequence[str] | str,
    shard_configs: Dict[str, FeatureShardConfiguration],
) -> Dict[str, IndexMap]:
    """Scan pass: distinct feature keys per shard → dense indices
    (reference 'default index map' path, GameDriver.scala:46-85)."""
    if isinstance(paths, str):
        paths = [paths]
    keys: Dict[str, dict] = {sid: {} for sid in shard_configs}
    for path in paths:
        for record in read_avro_dir(path):
            for sid, cfg in shard_configs.items():
                bucket = keys[sid]
                for key, _ in _record_features(record, cfg.feature_bags):
                    if key not in bucket:
                        bucket[key] = len(bucket)
    out: Dict[str, IndexMap] = {}
    for sid, cfg in shard_configs.items():
        bucket = keys[sid]
        if cfg.add_intercept and INTERCEPT_KEY not in bucket:
            bucket[INTERCEPT_KEY] = len(bucket)
        out[sid] = DefaultIndexMap(bucket)
    return out


def read_game_data(
    paths: Sequence[str] | str,
    shard_configs: Dict[str, FeatureShardConfiguration],
    index_maps: Optional[Dict[str, IndexMap]] = None,
    id_tags: Sequence[str] = (),
    response_field: str = "label",
    offset_field: str = "offset",
    weight_field: str = "weight",
    uid_field: str = "uid",
    is_response_required: bool = True,
) -> tuple[GameData, Dict[str, IndexMap], List[Optional[str]]]:
    """Read Avro dirs/files into a GameData. Returns (data, index_maps, uids).

    Unmapped features (absent from a provided index map) are dropped, like
    the reference's scoring path over a fixed training index.
    """
    if isinstance(paths, str):
        paths = [paths]
    if index_maps is None:
        index_maps = build_index_maps(paths, shard_configs)

    labels: List[float] = []
    offsets: List[float] = []
    weights: List[float] = []
    uids: List[Optional[str]] = []
    tag_values: Dict[str, List[str]] = {t: [] for t in id_tags}
    coo: Dict[str, tuple] = {
        sid: ([], [], []) for sid in shard_configs
    }  # rows, cols, vals

    row = 0
    for path in paths:
        for record in read_avro_dir(path):
            label = record.get(response_field)
            if label is None:
                if is_response_required:
                    raise ValueError(f"record {row} has no '{response_field}'")
                label = np.nan
            labels.append(float(label))
            off = record.get(offset_field)
            offsets.append(0.0 if off is None else float(off))
            wt = record.get(weight_field)  # explicit 0.0 weight is preserved
            weights.append(1.0 if wt is None else float(wt))
            uids.append(record.get(uid_field))
            meta = record.get("metadataMap") or {}
            for tag in id_tags:
                v = record.get(tag)
                if v is None:  # null top-level field falls back to metadataMap
                    v = meta.get(tag)
                if v is None:
                    raise ValueError(f"record {row} missing id tag '{tag}'")
                tag_values[tag].append(str(v))
            for sid, cfg in shard_configs.items():
                imap = index_maps[sid]
                rows, cols, vals = coo[sid]
                for key, value in _record_features(record, cfg.feature_bags):
                    idx = imap.get_index(key)
                    if idx >= 0:
                        rows.append(row)
                        cols.append(idx)
                        vals.append(value)
                if cfg.add_intercept:
                    idx = imap.get_index(INTERCEPT_KEY)
                    if idx >= 0:
                        rows.append(row)
                        cols.append(idx)
                        vals.append(1.0)
            row += 1

    shards = {
        sid: FeatureShard(
            rows=np.asarray(rows, dtype=np.int64),
            cols=np.asarray(cols, dtype=np.int64),
            vals=np.asarray(vals, dtype=np.float32),
            dim=len(index_maps[sid]),
        )
        for sid, (rows, cols, vals) in coo.items()
    }
    data = GameData(
        labels=np.asarray(labels, dtype=np.float32),
        feature_shards=shards,
        id_tags={t: np.asarray(v) for t, v in tag_values.items()},
        offsets=np.asarray(offsets, dtype=np.float32),
        weights=np.asarray(weights, dtype=np.float32),
    )
    return data, index_maps, uids
