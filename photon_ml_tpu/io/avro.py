"""Minimal Avro binary codec + object container files (spec-conformant).

No external Avro dependency exists in this environment, so the subset of the
Avro 1.x specification the reference's wire formats need is implemented here:
primitives, records, arrays, maps, unions, enums and fixed, plus the object
container file framing (magic, metadata map, sync-marker-delimited blocks,
null/deflate codecs). Files interoperate with the reference's
photon-avro-schemas records (TrainingExampleAvro etc.).

Reference parity: the schemas live in photon-avro-schemas/src/main/avro/*;
serialization call sites are photon-client data/avro/AvroUtils.scala:46 and
ModelProcessingUtils.scala:58.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Dict, Iterable, Iterator, List, Optional

MAGIC = b"Obj\x01"
SYNC_SIZE = 16
DEFAULT_SYNC_INTERVAL = 64 * 1024  # bytes of serialized data per block

_PRIMITIVES = {"null", "boolean", "int", "long", "float", "double", "bytes", "string"}


class AvroSchema:
    """A parsed schema plus the registry of named types it defines."""

    def __init__(self, schema: Any):
        if isinstance(schema, str) and schema.lstrip().startswith(("{", "[")):
            schema = json.loads(schema)
        self.named: Dict[str, Any] = {}
        self.root = self._resolve(schema)

    def _resolve(self, s: Any) -> Any:
        """Normalize: register named types, inline name references."""
        if isinstance(s, str):
            if s in _PRIMITIVES:
                return s
            if s in self.named:
                return self.named[s]
            raise ValueError(f"unknown type name: {s}")
        if isinstance(s, list):  # union
            return [self._resolve(b) for b in s]
        if isinstance(s, dict):
            t = s.get("type")
            if t in ("record", "enum", "fixed"):
                out = dict(s)
                self._register(out)
                if t == "record":
                    out["fields"] = [
                        dict(f, type=self._resolve(f["type"])) for f in s["fields"]
                    ]
                return out
            if t == "array":
                return {"type": "array", "items": self._resolve(s["items"])}
            if t == "map":
                return {"type": "map", "values": self._resolve(s["values"])}
            if isinstance(t, (dict, list)):
                return self._resolve(t)
            if t in _PRIMITIVES:
                return t
        raise ValueError(f"unsupported schema: {s!r}")

    def _register(self, s: Dict[str, Any]) -> None:
        name = s["name"]
        ns = s.get("namespace")
        self.named[name] = s
        if ns:
            self.named[f"{ns}.{name}"] = s

    def to_json(self) -> str:
        """Serialize with named types defined once and referenced by name
        afterwards (spec parsers reject duplicate definitions)."""
        seen: set = set()

        def ser(s: Any) -> Any:
            if isinstance(s, str):
                return s
            if isinstance(s, list):
                return [ser(b) for b in s]
            t = s.get("type")
            if t in ("record", "enum", "fixed"):
                full = (
                    f"{s['namespace']}.{s['name']}" if s.get("namespace")
                    else s["name"]
                )
                if full in seen:
                    return s["name"]
                seen.add(full)
                out = {k: v for k, v in s.items() if k != "fields"}
                if t == "record":
                    out["fields"] = [
                        {"name": f["name"], "type": ser(f["type"]),
                         **({"default": f["default"]} if "default" in f else {})}
                        for f in s["fields"]
                    ]
                return out
            if t == "array":
                return {"type": "array", "items": ser(s["items"])}
            if t == "map":
                return {"type": "map", "values": ser(s["values"])}
            return s

        return json.dumps(ser(self.root))


# ---------------------------------------------------------------- encoding

def _write_long(out: BinaryIO, n: int) -> None:
    """Zigzag varint (Avro spec 'int and long')."""
    n = (n << 1) ^ (n >> 63)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def _union_branch(schema: List[Any], value: Any) -> int:
    """Pick the union branch for a Python value (None/bool/num/str/bytes/
    dict/list matched structurally)."""
    def kind(s: Any) -> str:
        return s if isinstance(s, str) else s["type"]

    for i, branch in enumerate(schema):
        k = kind(branch)
        if value is None and k == "null":
            return i
        if isinstance(value, bool) and k == "boolean":
            return i
        if isinstance(value, str) and k in ("string", "enum"):
            return i
        if isinstance(value, (bytes, bytearray)) and k in ("bytes", "fixed"):
            return i
        if isinstance(value, bool):
            continue
        if isinstance(value, int) and k in ("int", "long", "float", "double"):
            return i
        if isinstance(value, float) and k in ("float", "double"):
            return i
        if isinstance(value, dict) and k in ("record", "map"):
            return i
        if isinstance(value, (list, tuple)) and k == "array":
            return i
    raise ValueError(f"no union branch in {schema} for {value!r}")


def _encode(out: BinaryIO, schema: Any, value: Any) -> None:
    if isinstance(schema, list):
        i = _union_branch(schema, value)
        _write_long(out, i)
        _encode(out, schema[i], value)
        return
    t = schema if isinstance(schema, str) else schema["type"]
    if t == "null":
        return
    if t == "boolean":
        out.write(b"\x01" if value else b"\x00")
    elif t in ("int", "long"):
        _write_long(out, int(value))
    elif t == "float":
        out.write(struct.pack("<f", float(value)))
    elif t == "double":
        out.write(struct.pack("<d", float(value)))
    elif t == "bytes":
        _write_long(out, len(value))
        out.write(value)
    elif t == "string":
        raw = value.encode("utf-8")
        _write_long(out, len(raw))
        out.write(raw)
    elif t == "record":
        for f in schema["fields"]:
            if f["name"] in value:
                v = value[f["name"]]
            elif "default" in f:
                v = f["default"]
            else:
                raise ValueError(f"missing field {f['name']}")
            _encode(out, f["type"], v)
    elif t == "array":
        if value:
            _write_long(out, len(value))
            for item in value:
                _encode(out, schema["items"], item)
        _write_long(out, 0)
    elif t == "map":
        if value:
            _write_long(out, len(value))
            for k, v in value.items():
                _encode(out, "string", k)
                _encode(out, schema["values"], v)
        _write_long(out, 0)
    elif t == "enum":
        _write_long(out, schema["symbols"].index(value))
    elif t == "fixed":
        if len(value) != schema["size"]:
            raise ValueError("fixed size mismatch")
        out.write(value)
    else:
        raise ValueError(f"cannot encode type {t}")


# ---------------------------------------------------------------- decoding

class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos : self.pos + n]
        if len(b) != n:
            raise EOFError("truncated avro data")
        self.pos += n
        return b

    def read_long(self) -> int:
        shift, acc = 0, 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)


def _decode(r: _Reader, schema: Any) -> Any:
    if isinstance(schema, list):
        i = r.read_long()
        if not 0 <= i < len(schema):
            raise ValueError(f"union branch index {i} out of range")
        return _decode(r, schema[i])
    t = schema if isinstance(schema, str) else schema["type"]
    if t == "null":
        return None
    if t == "boolean":
        return r.read(1) != b"\x00"
    if t in ("int", "long"):
        return r.read_long()
    if t == "float":
        return struct.unpack("<f", r.read(4))[0]
    if t == "double":
        return struct.unpack("<d", r.read(8))[0]
    if t == "bytes":
        return r.read(r.read_long())
    if t == "string":
        return r.read(r.read_long()).decode("utf-8")
    if t == "record":
        return {f["name"]: _decode(r, f["type"]) for f in schema["fields"]}
    if t == "array":
        return _read_blocks(r, lambda rr: _decode(rr, schema["items"]))
    if t == "map":
        return dict(
            _read_blocks(
                r, lambda rr: (_decode(rr, "string"), _decode(rr, schema["values"]))
            )
        )
    if t == "enum":
        i = r.read_long()
        if not 0 <= i < len(schema["symbols"]):
            raise ValueError(f"enum index {i} out of range")
        return schema["symbols"][i]
    if t == "fixed":
        return r.read(schema["size"])
    raise ValueError(f"cannot decode type {t}")


# ------------------------------------------------- schema resolution (read)

_PROMOTIONS = {
    "int": ("long", "float", "double"),
    "long": ("float", "double"),
    "float": ("double",),
    "string": ("bytes",),
    "bytes": ("string",),
}


def _type_kind(s: Any) -> str:
    return s if isinstance(s, str) else s["type"]


def _names_compatible(w: Any, r: Any) -> bool:
    wn = w.get("name") if isinstance(w, dict) else None
    rn = r.get("name") if isinstance(r, dict) else None
    # unqualified comparison; aliases are not supported
    if wn is None or rn is None:
        return True
    return wn.split(".")[-1] == rn.split(".")[-1]


def canonical_form(s: Any) -> Any:
    """Structural normal form for schema equivalence: strips doc/order/
    namespace decoration so two spellings of one schema compare equal (and
    take the fast non-resolving decode path)."""
    if isinstance(s, list):
        return [canonical_form(b) for b in s]
    if isinstance(s, str):
        return s
    t = s["type"]
    out: Dict[str, Any] = {"type": t}
    if "name" in s:
        out["name"] = s["name"].split(".")[-1]
    if t == "record":
        out["fields"] = [
            {"name": f["name"], "type": canonical_form(f["type"])}
            for f in s["fields"]
        ]
    elif t == "array":
        out["items"] = canonical_form(s["items"])
    elif t == "map":
        out["values"] = canonical_form(s["values"])
    elif t == "enum":
        out["symbols"] = list(s["symbols"])
    elif t == "fixed":
        out["size"] = s["size"]
    return out


def _match_reader_branch(writer: Any, reader_union: List[Any]) -> Optional[Any]:
    wk = _type_kind(writer)
    for branch in reader_union:
        rk = _type_kind(branch)
        if rk == wk and _names_compatible(writer, branch):
            return branch
    for branch in reader_union:
        if _type_kind(branch) in _PROMOTIONS.get(wk, ()):
            return branch
    return None


def _default_value(schema: Any, default: Any) -> Any:
    """JSON default -> runtime value (Avro spec: bytes/fixed defaults are
    codepoint-latin-1 strings; union defaults use the first branch).
    Containers are copied fresh per call so records never share state."""
    if isinstance(schema, list):
        return _default_value(schema[0], default)
    t = _type_kind(schema)
    if t in ("bytes", "fixed") and isinstance(default, str):
        return default.encode("latin-1")
    if t == "record":
        out = {}
        for f in schema["fields"]:
            if isinstance(default, dict) and f["name"] in default:
                out[f["name"]] = _default_value(f["type"], default[f["name"]])
            elif "default" in f:
                out[f["name"]] = _default_value(f["type"], f["default"])
            else:
                raise ValueError(f"record default missing field {f['name']}")
        return out
    if t == "array":
        return [_default_value(schema["items"], v) for v in default]
    if t == "map":
        return {k: _default_value(schema["values"], v) for k, v in default.items()}
    if t in ("float", "double"):
        return float(default)  # int JSON default -> float value
    return default


def _default_factory(schema: Any, default: Any):
    """Compile a zero-arg factory for a reader default: the JSON->runtime
    conversion happens once here; per record only containers are copied
    (records must never share mutable state)."""
    value = _default_value(schema, default)
    if isinstance(value, (dict, list)):
        import copy

        return lambda value=value: copy.deepcopy(value)
    return lambda value=value: value


def _read_blocks(r: _Reader, item_fn) -> List[Any]:
    """Shared array block framing: count-prefixed blocks, 0 terminates,
    negative count carries a discarded byte-size prefix."""
    out: List[Any] = []
    while True:
        n = r.read_long()
        if n == 0:
            return out
        if n < 0:
            n = -n
            r.read_long()
        for _ in range(n):
            out.append(item_fn(r))


def compile_resolver(writer: Any, reader: Any):
    """Compile (writer schema -> reader schema) resolution into a decode
    closure ``fn(_Reader) -> value`` (Avro spec 'Schema Resolution': fields
    matched by name, defaults for reader-only fields, writer-only fields
    skipped, numeric and string<->bytes promotions, union re-matching).
    All schema walking happens here, once — not per record."""
    if isinstance(writer, list):
        # an unresolvable branch only errors if a datum actually uses it
        # (the spec errors per-datum; union narrowing is legal evolution)
        def _branch_fn(b):
            try:
                return compile_resolver(b, reader)
            except ValueError as e:
                msg = str(e)

                def fail(r: _Reader, msg=msg):
                    raise ValueError(msg)

                return fail

        branch_fns = [_branch_fn(b) for b in writer]

        def union_fn(r: _Reader, fns=branch_fns):
            i = r.read_long()
            if not 0 <= i < len(fns):
                raise ValueError(f"union branch index {i} out of range")
            return fns[i](r)

        return union_fn
    if isinstance(reader, list):
        target = _match_reader_branch(writer, reader)
        if target is None:
            raise ValueError(
                f"writer type {_type_kind(writer)!r} matches no reader union branch"
            )
        return compile_resolver(writer, target)

    wk, rk = _type_kind(writer), _type_kind(reader)
    if wk != rk:
        if rk not in _PROMOTIONS.get(wk, ()):
            raise ValueError(f"cannot resolve writer {wk!r} to reader {rk!r}")
        if rk in ("float", "double"):
            return lambda r: float(_decode(r, writer))
        if rk == "bytes":
            return lambda r: _decode(r, writer).encode("utf-8")
        if rk == "string":
            return lambda r: _decode(r, writer).decode("utf-8")
        return lambda r: _decode(r, writer)  # int -> long

    if wk == "record":
        if not _names_compatible(writer, reader):
            raise ValueError(
                f"record name mismatch: {writer.get('name')} vs {reader.get('name')}"
            )
        reader_fields = {f["name"]: f for f in reader["fields"]}
        # ops: (field name to set | None for skip, decode fn)
        ops = []
        for wf in writer["fields"]:
            rf = reader_fields.get(wf["name"])
            if rf is None:
                ops.append((None, lambda r, s=wf["type"]: _decode(r, s)))
            else:
                ops.append((wf["name"], compile_resolver(wf["type"], rf["type"])))
        written = {f["name"] for f in writer["fields"]}
        defaulted = []
        for rf in reader["fields"]:
            if rf["name"] not in written:
                if "default" not in rf:
                    raise ValueError(
                        f"reader field {rf['name']!r} absent from writer and "
                        "has no default"
                    )
                defaulted.append(
                    (rf["name"], _default_factory(rf["type"], rf["default"]))
                )

        def record_fn(r: _Reader):
            out: Dict[str, Any] = {}
            for name, fn in ops:
                v = fn(r)
                if name is not None:
                    out[name] = v
            for name, make in defaulted:
                out[name] = make()
            return out

        return record_fn
    if wk == "array":
        item = compile_resolver(writer["items"], reader["items"])
        return lambda r: _read_blocks(r, item)
    if wk == "map":
        value = compile_resolver(writer["values"], reader["values"])

        def map_fn(r: _Reader):
            pairs = _read_blocks(
                r, lambda rr: (_decode(rr, "string"), value(rr))
            )
            return dict(pairs)

        return map_fn
    if wk == "enum":
        symbols = list(writer["symbols"])
        known = set(reader["symbols"])
        # Avro spec (1.9+): a writer symbol absent from the reader's enum
        # resolves to the reader's default symbol when one is declared.
        fallback = reader.get("default")
        if fallback is not None and fallback not in known:
            raise ValueError(
                f"enum default {fallback!r} is not one of the reader's "
                f"symbols {sorted(known)}"
            )

        def enum_fn(r: _Reader):
            i = r.read_long()
            if not 0 <= i < len(symbols):
                raise ValueError(f"enum index {i} out of range")
            sym = symbols[i]
            if sym not in known:
                if fallback is not None:
                    return fallback
                raise ValueError(
                    f"enum symbol {sym!r} unknown to reader and the reader "
                    "enum declares no default"
                )
            return sym

        return enum_fn
    if wk == "fixed":
        if writer["size"] != reader["size"]:
            raise ValueError("fixed size mismatch between writer and reader")
        size = writer["size"]
        return lambda r: r.read(size)
    return lambda r: _decode(r, writer)  # identical primitive


# ----------------------------------------------------- object container file

def write_avro_file(
    path: str,
    schema: AvroSchema | Any,
    records: Iterable[Dict[str, Any]],
    codec: str = "deflate",
    sync_interval: int = DEFAULT_SYNC_INTERVAL,
) -> int:
    """Write an Avro object container file; returns the record count."""
    if not isinstance(schema, AvroSchema):
        schema = AvroSchema(schema)
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported codec: {codec}")
    sync = os.urandom(SYNC_SIZE)
    count_total = 0
    with open(path, "wb") as f:
        f.write(MAGIC)
        meta = {
            "avro.schema": schema.to_json().encode("utf-8"),
            "avro.codec": codec.encode("utf-8"),
        }
        _encode(f, {"type": "map", "values": "bytes"}, meta)
        f.write(sync)

        block = io.BytesIO()
        block_count = 0

        def flush() -> None:
            nonlocal block, block_count
            if block_count == 0:
                return
            payload = block.getvalue()
            if codec == "deflate":
                # Avro deflate = raw DEFLATE stream (no zlib header)
                payload = zlib.compress(payload)[2:-4]
            _write_long(f, block_count)
            _write_long(f, len(payload))
            f.write(payload)
            f.write(sync)
            block = io.BytesIO()
            block_count = 0

        for rec in records:
            _encode(block, schema.root, rec)
            block_count += 1
            count_total += 1
            if block.tell() >= sync_interval:
                flush()
        flush()
    return count_total


def read_avro_file(
    path: str, schema: Optional[AvroSchema] = None
) -> Iterator[Dict[str, Any]]:
    """Iterate records of an Avro object container file.

    Decoding uses the writer schema embedded in the file. When a reader
    ``schema`` is given and differs, records are resolved to it per the
    Avro spec (fields matched by name, reader-only fields take their
    defaults, writer-only fields are skipped, numeric and string<->bytes
    promotions applied); a root-record-name mismatch raises.
    """
    with open(path, "rb") as f:
        data = f.read()
    r = _Reader(data)
    if r.read(4) != MAGIC:
        raise ValueError(f"{path}: not an Avro object container file")
    meta = _decode(r, {"type": "map", "values": "bytes"})
    writer_schema = AvroSchema(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    sync = r.read(SYNC_SIZE)
    if schema is not None:
        want = schema.root.get("name") if isinstance(schema.root, dict) else None
        got = (
            writer_schema.root.get("name")
            if isinstance(writer_schema.root, dict)
            else None
        )
        if want is not None and got is not None and want.split(".")[-1] != got.split(".")[-1]:
            raise ValueError(
                f"{path}: contains {got!r} records, expected {want!r}"
            )
        # structural comparison: doc/order/namespace spelling differences
        # must not force the (slower) resolving path
        if canonical_form(writer_schema.root) != canonical_form(schema.root):
            decode_fn = compile_resolver(writer_schema.root, schema.root)
        else:
            decode_fn = None
    else:
        decode_fn = None
    while r.pos < len(r.buf):
        n = r.read_long()
        size = r.read_long()
        payload = r.read(size)
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        elif codec != "null":
            raise ValueError(f"unsupported codec: {codec}")
        br = _Reader(payload)
        for _ in range(n):
            if decode_fn is not None:
                yield decode_fn(br)
            else:
                yield _decode(br, writer_schema.root)
        if r.read(SYNC_SIZE) != sync:
            raise ValueError(f"{path}: sync marker mismatch (corrupt file)")


def list_part_files(path: str) -> list:
    """The container files under a path: [path] for a file, else the
    sorted part-*.avro files of the directory (one listing rule shared by
    every reader)."""
    if os.path.isfile(path):
        return [path]
    return [
        os.path.join(path, n)
        for n in sorted(os.listdir(path))
        if n.endswith(".avro") and not n.startswith(".")
    ]


def read_avro_dir(path: str, schema: Optional[AvroSchema] = None) -> Iterator[Dict[str, Any]]:
    """Read all part files of a directory (the reference's part-*.avro
    layout), or a single file when given one."""
    for p in list_part_files(path):
        yield from read_avro_file(p, schema)
