"""GAME model persistence: the reference's on-disk layout, Avro coefficients.

Reference parity: data/avro/ModelProcessingUtils.scala:58 —
``saveGameModelsToHDFS`` (:71) / ``loadGameModelFromHDFS`` (:136) with layout

    <dir>/model-metadata.json
    <dir>/fixed-effect/<coordinate>/id-info            (featureShardId [+ extra lines])
    <dir>/fixed-effect/<coordinate>/coefficients/part-00000.avro
    <dir>/random-effect/<coordinate>/id-info           (reType, featureShardId [+ extra lines])
    <dir>/random-effect/<coordinate>/coefficients/part-*.avro
    <dir>/matrix-factorization/<coordinate>/{rowEffect,colEffect}/part-*.avro

Each GLM is one BayesianLinearModelAvro record: means/variances as
name-term-value triples (nonzeros only), modelClass naming the reference's
model class for cross-compat. Loading without index maps builds a compact
index per shard from the scanned features, exactly like the reference
(:128-133 doc).

id-info files are byte-identical to the reference's (the reference loader
destructures them with exact arity — ModelProcessingUtils.scala:156/182 —
so extra lines would throw scala.MatchError there). The writer's extra
facts live in model-metadata.json instead, under ``featureShards``:
``dim`` (the dense dimension — sparse records drop zero coefficients, so
the reloaded vectors would otherwise shrink) and ``positional`` (for
no-index-map saves: feature names are original integer indices; the loader
restores them to those exact positions instead of encounter-order
renumbering, which would permute coefficients whenever any zero was
dropped). JSON readers ignore unknown keys, so the reference still parses
the metadata; files written by the reference load here as before, and the
loader also still honors the legacy ``dim=N`` / ``names=positional``
id-info tokens that round-3 saves emitted.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.parallel.mesh import fetch_global

from photon_ml_tpu.indexmap import (
    NAME_TERM_DELIMITER,
    DefaultIndexMap,
    IndexMap,
)
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro import read_avro_dir, write_avro_file
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import CoordinateMeta, GameModel
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.models.matrix_factorization import MatrixFactorizationModel
from photon_ml_tpu.models.random_effect import RandomEffectModel
from photon_ml_tpu.types import TaskType

FIXED_EFFECT = "fixed-effect"
RANDOM_EFFECT = "random-effect"
MATRIX_FACTORIZATION = "matrix-factorization"
ID_INFO = "id-info"
COEFFICIENTS = "coefficients"
METADATA_FILE = "model-metadata.json"

# Reference class names (BayesianLinearModelAvro.modelClass), for files the
# reference pipeline can attribute to the right GLM subclass.
_MODEL_CLASS = {
    TaskType.LOGISTIC_REGRESSION:
        "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
    TaskType.LINEAR_REGRESSION:
        "com.linkedin.photon.ml.supervised.regression.LinearRegressionModel",
    TaskType.POISSON_REGRESSION:
        "com.linkedin.photon.ml.supervised.regression.PoissonRegressionModel",
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        "com.linkedin.photon.ml.supervised.classification.SmoothedHingeLossLinearSVMModel",
}
_CLASS_TO_TASK = {v: k for k, v in _MODEL_CLASS.items()}


def _split_key(key: str) -> Tuple[str, str]:
    name, _, term = key.partition(NAME_TERM_DELIMITER)
    return name, term


def _name_term_values(
    values: Dict[int, float], index_map: Optional[IndexMap]
) -> List[dict]:
    out = []
    for idx, val in values.items():
        if val == 0.0:
            continue
        if index_map is not None:
            key = index_map.get_feature_name(int(idx))
            if key is None:
                continue
            name, term = _split_key(key)
        else:
            name, term = str(idx), ""
        out.append({"name": name, "term": term, "value": float(val)})
    return out


def _glm_record(
    model_id: str,
    task: TaskType,
    means: Dict[int, float],
    variances: Optional[Dict[int, float]],
    index_map: Optional[IndexMap],
) -> dict:
    return {
        "modelId": model_id,
        "modelClass": _MODEL_CLASS[task],
        "means": _name_term_values(means, index_map),
        "variances": (
            _name_term_values(variances, index_map) if variances else None
        ),
        "lossFunction": None,
    }


def _dense_to_sparse(arr) -> Dict[int, float]:
    a = fetch_global(arr)
    (nz,) = np.nonzero(a)
    return {int(i): float(a[i]) for i in nz}


def save_game_model(
    model: GameModel,
    output_dir: str,
    index_maps: Optional[Dict[str, IndexMap]] = None,
    model_name: str = "photon-ml-tpu",
    configurations: Optional[dict] = None,
    num_output_files_per_random_effect: int = 1,
    write: Optional[bool] = None,
) -> None:
    """Write a GAME model directory (see module docstring for layout).

    Multi-host: sharded model arrays are gathered on EVERY process (the
    gathers are collectives), but by default only process 0 writes files —
    ``write`` overrides (e.g. True for per-host local-disk copies). Callers
    in a cluster should barrier (``multihost.barrier``) before reading the
    saved model from another process.
    """
    import jax

    from photon_ml_tpu.algorithm.factored_random_effect import (
        FactoredRandomEffectModel,
    )

    if write is None:
        write = jax.process_index() == 0

    # Per-shard facts the reference's id-info format cannot carry (it is
    # arity-checked by the reference loader); persisted in metadata instead.
    feature_shards: Dict[str, dict] = {}
    for cid, sub in model.models.items():
        shard = model.meta[cid].feature_shard
        imap = (index_maps or {}).get(shard)
        if isinstance(sub, GeneralizedLinearModel):
            dim = int(sub.coefficients.means.shape[0])
        elif isinstance(sub, RandomEffectModel):
            dim = int(sub.global_dim)
        elif isinstance(sub, FactoredRandomEffectModel):
            dim = int(sub.projection_matrix.shape[0])
        else:
            continue  # the save loop below raises for unknown types
        ent = feature_shards.setdefault(
            shard, {"dim": 0, "positional": imap is None}
        )
        ent["dim"] = max(ent["dim"], dim)

    if write:
        os.makedirs(output_dir, exist_ok=True)
        save_game_model_metadata(
            output_dir, model.task, model_name=model_name,
            configurations=configurations,
            feature_shards=feature_shards,
        )

    for cid, sub in model.models.items():
        meta = model.meta[cid]
        imap = (index_maps or {}).get(meta.feature_shard)
        if isinstance(sub, GeneralizedLinearModel):
            cdir = os.path.join(output_dir, FIXED_EFFECT, cid)
            means = _dense_to_sparse(sub.coefficients.means)
            variances = (
                _dense_to_sparse(sub.coefficients.variances)
                if sub.coefficients.variances is not None
                else None
            )
            if write:
                os.makedirs(os.path.join(cdir, COEFFICIENTS), exist_ok=True)
                with open(os.path.join(cdir, ID_INFO), "w") as f:
                    f.write(meta.feature_shard + "\n")
                write_avro_file(
                    os.path.join(cdir, COEFFICIENTS, "part-00000.avro"),
                    schemas.bayesian_linear_model_schema(),
                    [_glm_record(cid, model.task, means, variances, imap)],
                )
        elif isinstance(sub, RandomEffectModel):
            _save_random_effect(
                sub, os.path.join(output_dir, RANDOM_EFFECT, cid),
                model.task, imap, num_output_files_per_random_effect, meta,
                write,
            )
        elif isinstance(sub, FactoredRandomEffectModel):
            # Materialize per-entity global-space coefficients (w = B·w_lat)
            # so the saved artifact scores identically as a plain RE model;
            # additionally persist the latent factors + projection matrix
            # under matrix-factorization/ (LatentFactorAvro, reference
            # :450-516) so the factored structure is not lost.
            effective = _factored_to_effective_re(sub, meta)
            _save_random_effect(
                effective, os.path.join(output_dir, RANDOM_EFFECT, cid),
                model.task, imap, num_output_files_per_random_effect, meta,
                write,
            )
            _save_factored_latents(
                sub, os.path.join(output_dir, MATRIX_FACTORIZATION, cid), meta,
                write,
            )
        else:
            raise ValueError(f"cannot save sub-model type {type(sub)} for {cid}")


def _factored_to_effective_re(sub, meta: CoordinateMeta) -> RandomEffectModel:
    B = fetch_global(sub.projection_matrix)  # [d, k]
    latent = sub.latent
    entity_coefs: Dict[str, Dict[int, float]] = {}
    for b, ids in enumerate(latent.entity_ids):
        w_b = fetch_global(latent.coefficients[b])  # [Eb, k]
        eff = w_b @ B.T  # [Eb, d]
        for e, eid in enumerate(ids):
            (nz,) = np.nonzero(eff[e])
            entity_coefs[eid] = {int(i): float(eff[e, i]) for i in nz}
    return RandomEffectModel.from_entity_coefficients(
        random_effect_type=latent.random_effect_type,
        task=latent.task,
        entity_coefficients=entity_coefs,
        global_dim=B.shape[0],
    )


def _save_factored_latents(
    sub, out_dir: str, meta: CoordinateMeta, write: bool = True
) -> None:
    latent = sub.latent
    gathered = [fetch_global(c) for c in latent.coefficients]
    B = fetch_global(sub.projection_matrix)
    if not write:
        return  # collectives done; record building is writer-only work
    records = []
    for b, ids in enumerate(latent.entity_ids):
        w_b = gathered[b]
        for e, eid in enumerate(ids):
            records.append(
                {"effectId": str(eid), "latentFactor": [float(v) for v in w_b[e]]}
            )
    row_dir = os.path.join(out_dir, latent.random_effect_type)
    os.makedirs(row_dir, exist_ok=True)
    write_avro_file(
        os.path.join(row_dir, "part-00000.avro"),
        schemas.latent_factor_schema(),
        records,
    )
    # The projection matrix B: one latent vector per feature column index.
    col_dir = os.path.join(out_dir, "projection")
    os.makedirs(col_dir, exist_ok=True)
    write_avro_file(
        os.path.join(col_dir, "part-00000.avro"),
        schemas.latent_factor_schema(),
        (
            {"effectId": str(i), "latentFactor": [float(v) for v in B[i]]}
            for i in range(B.shape[0])
        ),
    )


def _save_random_effect(
    sub: RandomEffectModel,
    cdir: str,
    task: TaskType,
    imap: Optional[IndexMap],
    num_files: int,
    meta: CoordinateMeta,
    write: bool = True,
) -> None:
    # gathers (items/variances fetch sharded arrays) run on every host;
    # only the writer touches the filesystem
    items = list(sub.items())
    variances = _re_variances(sub)
    if not write:
        return
    os.makedirs(os.path.join(cdir, COEFFICIENTS), exist_ok=True)
    with open(os.path.join(cdir, ID_INFO), "w") as f:
        f.write(f"{sub.random_effect_type}\n{meta.feature_shard}\n")
    num_files = max(1, min(num_files, max(1, len(items))))
    per_file = -(-len(items) // num_files) if items else 1
    for p in range(num_files):
        chunk = items[p * per_file : (p + 1) * per_file]
        write_avro_file(
            os.path.join(cdir, COEFFICIENTS, f"part-{p:05d}.avro"),
            schemas.bayesian_linear_model_schema(),
            (
                _glm_record(eid, task, coefs, variances.get(eid), imap)
                for eid, coefs in chunk
            ),
        )


def _re_variances(sub: RandomEffectModel) -> Dict[str, Dict[int, float]]:
    """Per-entity sparse global-space variances (INDEX_MAP/IDENTITY only —
    variances are not back-projectable through a random projection)."""
    out: Dict[str, Dict[int, float]] = {}
    for b, ids in enumerate(sub.entity_ids):
        if sub.variances[b] is None:
            continue
        # the None-check above is host metadata (process-uniform), so these
        # collectives still run in lockstep on every host
        var_b = fetch_global(sub.variances[b])
        idx_b = fetch_global(sub.proj_indices[b])
        ok_b = fetch_global(sub.proj_valid[b])
        for e, eid in enumerate(ids):
            out[eid] = {
                int(i): float(v)
                for i, v, ok in zip(idx_b[e], var_b[e], ok_b[e])
                if ok
            }
    return out


def save_game_model_metadata(
    output_dir: str,
    task: TaskType,
    model_name: str = "photon-ml-tpu",
    configurations: Optional[dict] = None,
    feature_shards: Optional[Dict[str, dict]] = None,
) -> None:
    """model-metadata.json (reference saveGameModelMetadataToHDFS :517).

    ``feature_shards`` maps shard id → {"dim": int, "positional": bool};
    an extra JSON key the reference parser ignores (id-info itself must
    stay arity-exact for the reference loader).
    """
    os.makedirs(output_dir, exist_ok=True)
    payload = {
        "modelType": task.name,
        "modelName": model_name,
        "configurations": configurations or {},
    }
    if feature_shards:
        payload["featureShards"] = feature_shards
    with open(os.path.join(output_dir, METADATA_FILE), "w") as f:
        json.dump(payload, f, indent=2)


def load_game_model_metadata(models_dir: str) -> dict:
    with open(os.path.join(models_dir, METADATA_FILE)) as f:
        return json.load(f)


class _MapBuilder:
    """Growing name->index map with an O(1) next-index counter."""

    __slots__ = ("map", "next")

    def __init__(self) -> None:
        self.map: Dict[str, int] = {}
        self.next = 0


def _record_sparse(
    record: dict,
    field: str,
    imap: Optional[IndexMap],
    builder: Optional["_MapBuilder"],
    positional: bool = False,
    dropped: Optional[List[int]] = None,
) -> Dict[int, float]:
    """NameTermValue list → {index: value}; builds a compact index on the
    fly when no map is given (reference load-without-index behavior).
    Coefficients whose feature is absent from a provided map are counted
    into ``dropped`` (a one-element list) — silently losing model weight
    against a mismatched index must at least be visible to the caller."""
    out: Dict[int, float] = {}
    arr = record.get(field) or []
    for ntv in arr:
        key = (
            ntv["name"]
            if not ntv["term"]
            else f"{ntv['name']}{NAME_TERM_DELIMITER}{ntv['term']}"
        )
        if imap is not None:
            idx = imap.get_index(key)
            if idx < 0:
                if dropped is not None:
                    dropped[0] += 1
                continue
        else:
            assert builder is not None
            if key not in builder.map:
                if positional:
                    # names=positional saves name features by original
                    # index; honor it (encounter-order would permute
                    # whenever a zero coefficient was dropped)
                    if ntv["term"] or not key.isdigit():
                        raise ValueError(
                            f"positional model has non-numeric feature "
                            f"name {key!r}"
                        )
                    idx_new = int(key)
                else:
                    idx_new = builder.next
                builder.map[key] = idx_new
                builder.next = max(builder.next, idx_new + 1)
            idx = builder.map[key]
        out[idx] = float(ntv["value"])
    return out


def _note_declared_dim(shard_dims: Dict[str, int], shard: str, tokens) -> None:
    for t in tokens:
        if t.startswith("dim="):
            shard_dims[shard] = max(shard_dims.get(shard, 0), int(t[4:]))


def load_game_model(
    models_dir: str,
    index_maps: Optional[Dict[str, IndexMap]] = None,
) -> Tuple[GameModel, Dict[str, IndexMap]]:
    """Load a GAME model directory → (GameModel, per-shard index maps)."""
    metadata = load_game_model_metadata(models_dir)
    task = TaskType[metadata["modelType"]]
    models: Dict[str, object] = {}
    meta: Dict[str, CoordinateMeta] = {}
    builders: Dict[str, _MapBuilder] = {}
    # Declared dims / positional-ness: from metadata featureShards (current
    # format) or legacy dim=/names=positional id-info tokens (round-3 saves).
    shard_dims: Dict[str, int] = {}
    positional_shards = set()
    for shard, ent in (metadata.get("featureShards") or {}).items():
        shard_dims[shard] = int(ent.get("dim", 0))
        if ent.get("positional"):
            positional_shards.add(shard)

    dropped = [0]  # coefficients lost to a mismatched provided index map

    def map_for(shard: str) -> Tuple[Optional[IndexMap], Optional[_MapBuilder]]:
        if index_maps is not None and shard in index_maps:
            return index_maps[shard], None
        return None, builders.setdefault(shard, _MapBuilder())

    fe_dir = os.path.join(models_dir, FIXED_EFFECT)
    if os.path.isdir(fe_dir):
        for cid in sorted(os.listdir(fe_dir)):
            cdir = os.path.join(fe_dir, cid)
            with open(os.path.join(cdir, ID_INFO)) as f:
                tokens = f.read().split()
            shard = tokens[0]
            _note_declared_dim(shard_dims, shard, tokens)
            positional = shard in positional_shards or "names=positional" in tokens
            imap, builder = map_for(shard)
            records = list(
                read_avro_dir(os.path.join(cdir, COEFFICIENTS))
            )
            if len(records) != 1:
                raise ValueError(
                    f"{cid}: expected one fixed-effect GLM, got {len(records)}"
                )
            rec = records[0]
            # count drops on means only: variances share the same feature
            # keys, and double-counting would report a 2x mismatch
            means = _record_sparse(
                rec, "means", imap, builder, positional, dropped=dropped
            )
            variances = _record_sparse(rec, "variances", imap, builder, positional)
            models[cid] = (rec, means, variances or None)
            meta[cid] = CoordinateMeta(feature_shard=shard)

    re_specs: Dict[str, tuple] = {}
    re_dir = os.path.join(models_dir, RANDOM_EFFECT)
    if os.path.isdir(re_dir):
        for cid in sorted(os.listdir(re_dir)):
            cdir = os.path.join(re_dir, cid)
            with open(os.path.join(cdir, ID_INFO)) as f:
                tokens = f.read().split()
            re_type, shard = tokens[:2]
            _note_declared_dim(shard_dims, shard, tokens)
            positional = shard in positional_shards or "names=positional" in tokens
            imap, builder = map_for(shard)
            entity_coefs: Dict[str, Dict[int, float]] = {}
            entity_vars: Dict[str, Dict[int, float]] = {}
            for rec in read_avro_dir(os.path.join(cdir, COEFFICIENTS)):
                eid = rec["modelId"]
                entity_coefs[eid] = _record_sparse(
                    rec, "means", imap, builder, positional, dropped=dropped
                )
                v = _record_sparse(rec, "variances", imap, builder, positional)
                if v:
                    entity_vars[eid] = v
            re_specs[cid] = (re_type, shard, entity_coefs, entity_vars)
            meta[cid] = CoordinateMeta(
                feature_shard=shard, random_effect_type=re_type
            )

    if not models and not re_specs:
        raise ValueError(f"no models could be loaded from: {models_dir}")
    if dropped[0]:
        import logging

        logging.getLogger("photon_ml_tpu").warning(
            "%d model coefficients were DROPPED because their features are "
            "absent from the provided index maps — scores will differ from "
            "the saved model (was the index built from different data?)",
            dropped[0],
        )

    # Finalize index maps (builders are complete only after every coordinate
    # sharing the shard has been scanned).
    out_maps: Dict[str, IndexMap] = dict(index_maps or {})
    for shard, builder in builders.items():
        out_maps[shard] = DefaultIndexMap(builder.map)

    def _shard_dim(shard: str) -> int:
        built = builders.get(shard)
        return max(
            len(out_maps[shard]),
            built.next if built else 0,
            shard_dims.get(shard, 0),
        )

    final: Dict[str, object] = {}
    for cid, payload in models.items():
        rec, means, variances = payload
        shard = meta[cid].feature_shard
        dim = _shard_dim(shard)
        w = np.zeros(dim, dtype=np.float32)
        for i, v in means.items():
            w[i] = v
        var = None
        if variances:
            var = np.zeros(dim, dtype=np.float32)
            for i, v in variances.items():
                var[i] = v
        final[cid] = GeneralizedLinearModel(
            coefficients=Coefficients(
                means=jnp.asarray(w),
                variances=jnp.asarray(var) if var is not None else None,
            ),
            task=task,
        )
    for cid, (re_type, shard, entity_coefs, entity_vars) in re_specs.items():
        final[cid] = RandomEffectModel.from_entity_coefficients(
            random_effect_type=re_type,
            task=task,
            entity_coefficients=entity_coefs,
            global_dim=_shard_dim(shard),
            entity_variances=entity_vars or None,
        )

    return GameModel(models=final, meta=meta, task=task), out_maps


# ------------------------------------------------------- matrix factorization

def save_matrix_factorization_model(
    model: MatrixFactorizationModel, output_dir: str
) -> None:
    """LatentFactorAvro dirs per effect type (reference :450-516)."""
    for effect, factors, index in (
        (model.row_effect_type, model.row_factors, model.row_index),
        (model.col_effect_type, model.col_factors, model.col_index),
    ):
        edir = os.path.join(output_dir, effect)
        os.makedirs(edir, exist_ok=True)
        order = sorted(index, key=index.get)
        write_avro_file(
            os.path.join(edir, "part-00000.avro"),
            schemas.latent_factor_schema(),
            (
                {
                    "effectId": str(eid),
                    "latentFactor": [float(v) for v in factors[index[eid]]],
                }
                for eid in order
            ),
        )


def load_matrix_factorization_model(
    input_dir: str, row_effect_type: str, col_effect_type: str
) -> MatrixFactorizationModel:
    def load(effect: str):
        recs = list(read_avro_dir(os.path.join(input_dir, effect)))
        index = {r["effectId"]: i for i, r in enumerate(recs)}
        factors = np.array(
            [r["latentFactor"] for r in recs], dtype=np.float32
        )
        return factors, index

    row_factors, row_index = load(row_effect_type)
    col_factors, col_index = load(col_effect_type)
    return MatrixFactorizationModel(
        row_effect_type=row_effect_type,
        col_effect_type=col_effect_type,
        row_factors=row_factors,
        col_factors=col_factors,
        row_index=row_index,
        col_index=col_index,
    )
