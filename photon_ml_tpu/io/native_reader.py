"""Native columnar Avro reading: the C++ data-loader fast path.

The generic Python codec (io/avro.py) builds a dict per record — fine for
models and scores, a bottleneck for training data (~2e4 records/s). This
module compiles the writer schema to a flat field program and hands whole
decompressed container blocks to ``native/avrodecode.cpp``, which emits
columnar buffers: numeric columns, string columns (byte arena + offsets),
and per-feature-bag streams whose "name\\x01term" keys live in one arena.
Feature-key deduplication also runs natively, so Python materializes
O(unique features) strings instead of O(nnz) — the role Spark's JVM Avro
readers play for the reference (AvroDataReader.scala:53).

Schema shapes outside the supported set (see avrodecode.cpp header) return
``None`` from :func:`compile_program`; callers fall back to the Python
codec transparently.
"""

from __future__ import annotations

import ctypes
import logging
import subprocess
import zlib
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.io.avro import MAGIC, SYNC_SIZE, AvroSchema, _decode, _Reader

logger = logging.getLogger("photon_ml_tpu")

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
_SRC = _NATIVE_DIR / "avrodecode.cpp"
_LIB = _NATIVE_DIR / "_avrodecode.so"

_lib = None
_lib_tried = False

K_DOUBLE, K_FLOAT, K_LONG, K_INT, K_BOOL, K_STRING, K_BYTES = range(7)
K_FEATURES, K_STRMAP = 7, 8

_PRIMITIVES = {
    "double": K_DOUBLE,
    "float": K_FLOAT,
    "long": K_LONG,
    "int": K_INT,
    "boolean": K_BOOL,
    "string": K_STRING,
    "bytes": K_BYTES,
}

_c_i64 = ctypes.c_int64
_c_i32 = ctypes.c_int32
_c_p = ctypes.c_void_p


def _load_native():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    try:
        from photon_ml_tpu.utils.nativelib import build_and_load

        lib = build_and_load(_SRC, _LIB, ldflags=("-lz",))
        if lib is None:
            raise RuntimeError("native avro decoder unavailable")
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(_c_i32)
        i64p = ctypes.POINTER(_c_i64)
        lib.avro_decode.restype = _c_p
        lib.avro_decode.argtypes = [
            u8p, _c_i64, _c_i64, i32p, _c_i32, _c_i32, _c_i32, _c_i32,
            u8p, i32p, _c_i32, _c_i32,
        ]
        try:
            # one GIL-released inflate+decode call per file (see .cpp); a
            # stale .so without the symbol degrades to the per-payload path
            lib.avro_decode_packed.restype = _c_p
            lib.avro_decode_packed.argtypes = [
                u8p, _c_i64, i64p, i64p, i64p, _c_i32, _c_i32,
                i32p, _c_i32, _c_i32, _c_i32, _c_i32,
                u8p, i32p, _c_i32, _c_i32,
            ]
            lib.has_packed = True
        except AttributeError:  # pragma: no cover - stale prebuilt .so
            lib.has_packed = False
        lib.res_n_rows.restype = _c_i64
        lib.res_n_rows.argtypes = [_c_p]
        lib.res_num_col.restype = ctypes.POINTER(ctypes.c_double)
        lib.res_num_col.argtypes = [_c_p, _c_i32]
        lib.res_num_present.restype = u8p
        lib.res_num_present.argtypes = [_c_p, _c_i32]
        lib.res_str_arena.restype = u8p
        lib.res_str_arena.argtypes = [_c_p, i64p]
        lib.res_str_off.restype = i64p
        lib.res_str_off.argtypes = [_c_p, _c_i32]
        lib.res_str_len.restype = i32p
        lib.res_str_len.argtypes = [_c_p, _c_i32]
        lib.res_bag_count.restype = _c_i64
        lib.res_bag_count.argtypes = [_c_p, _c_i32]
        lib.res_bag_rec.restype = i32p
        lib.res_bag_rec.argtypes = [_c_p, _c_i32]
        lib.res_bag_val.restype = ctypes.POINTER(ctypes.c_float)
        lib.res_bag_val.argtypes = [_c_p, _c_i32]
        lib.res_bag_key_off.restype = i64p
        lib.res_bag_key_off.argtypes = [_c_p, _c_i32]
        lib.res_bag_key_len.restype = i32p
        lib.res_bag_key_len.argtypes = [_c_p, _c_i32]
        lib.res_key_arena.restype = u8p
        lib.res_key_arena.argtypes = [_c_p, i64p]
        lib.res_free.restype = None
        lib.res_free.argtypes = [_c_p]
        lib.key_dedup.restype = _c_p
        lib.key_dedup.argtypes = [u8p, i64p, i32p, _c_i64]
        lib.dedup_n_unique.restype = _c_i64
        lib.dedup_n_unique.argtypes = [_c_p]
        lib.dedup_ids.restype = i32p
        lib.dedup_ids.argtypes = [_c_p]
        lib.dedup_u_off.restype = i64p
        lib.dedup_u_off.argtypes = [_c_p]
        lib.dedup_u_len.restype = i32p
        lib.dedup_u_len.argtypes = [_c_p]
        lib.dedup_free.restype = None
        lib.dedup_free.argtypes = [_c_p]
        _lib = lib
    except Exception as e:  # pragma: no cover - toolchain-dependent
        logger.info("avrodecode native build unavailable (%s)", e)
        _lib = None
    return _lib


def native_available() -> bool:
    return _load_native() is not None


def _classify(ftype) -> Optional[Tuple[int, int]]:
    """Field type -> (kind, nullmode) or None if unsupported."""
    nullmode = 0
    if isinstance(ftype, list):
        if len(ftype) != 2:
            return None
        if ftype[0] == "null":
            nullmode, ftype = 1, ftype[1]
        elif ftype[1] == "null":
            nullmode, ftype = 2, ftype[0]
        else:
            return None
    if isinstance(ftype, str):
        kind = _PRIMITIVES.get(ftype)
        return None if kind is None else (kind, nullmode)
    if isinstance(ftype, dict):
        t = ftype.get("type")
        if t == "array":
            items = ftype.get("items")
            if not (
                isinstance(items, dict)
                and items.get("type") == "record"
                and [f["name"] for f in items.get("fields", [])]
                == ["name", "term", "value"]
                and [f["type"] for f in items["fields"]]
                == ["string", "string", "double"]
            ):
                return None
            return (K_FEATURES, nullmode)
        if t == "map" and ftype.get("values") == "string":
            return (K_STRMAP, nullmode)
    return None


class ColumnarPlan:
    """Compiled field program + column bookkeeping for one schema."""

    def __init__(self, program, num_fields, str_fields, bag_fields, tags):
        self.program = program              # np.int32 [n_fields * 3]
        self.num_fields = num_fields        # field name -> numeric col id
        self.str_fields = str_fields        # field name -> string col id
        self.bag_fields = bag_fields        # bag name -> bag id
        self.tags = tags                    # tag name -> string col id
        self.n_str_cols = len(str_fields) + len(tags)
        self.tag_col_base = len(str_fields)


def compile_program(
    schema_root,
    numeric_fields: Sequence[str],
    string_fields: Sequence[str],
    bags: Sequence[str],
    tags: Sequence[str] = (),
) -> Optional[ColumnarPlan]:
    """Compile a record schema into the native field program; None when the
    schema (or a requested capture) falls outside the supported shapes."""
    if not isinstance(schema_root, dict) or schema_root.get("type") != "record":
        return None
    num_fields: Dict[str, int] = {}
    str_fields: Dict[str, int] = {}
    bag_fields: Dict[str, int] = {}
    prog: List[int] = []
    for f in schema_root.get("fields", []):
        name = f["name"]
        cls = _classify(f["type"])
        if cls is None:
            return None
        kind, nullmode = cls
        capture = -1
        if kind <= K_BOOL and name in numeric_fields:
            capture = num_fields.setdefault(name, len(num_fields))
        elif kind <= K_BOOL and name in string_fields:
            # a requested string capture (id tag) with a numeric schema type:
            # the Python codec stringifies it; this path can't — fall back
            return None
        elif kind in (K_STRING, K_BYTES) and name in string_fields:
            capture = str_fields.setdefault(name, len(str_fields))
        elif kind == K_FEATURES and name in bags:
            capture = bag_fields.setdefault(name, len(bag_fields))
        elif kind == K_STRMAP and name == "metadataMap" and tags:
            # tag matching applies ONLY to the metadataMap field, mirroring
            # the Python path (data_reader reads record["metadataMap"])
            capture = 0
        prog.extend([kind, nullmode, capture])
    missing_bags = set(bags) - set(bag_fields)
    if missing_bags:
        return None  # requested bag absent from schema: fall back
    tag_cols = {t: len(str_fields) + i for i, t in enumerate(tags)}
    return ColumnarPlan(
        np.asarray(prog, dtype=np.int32), num_fields, str_fields,
        bag_fields, tag_cols,
    )


class ColumnarFile:
    """Decoded columns of one container file (all arrays numpy copies)."""

    def __init__(self, n_rows, num, num_present, strs, tag_strs, bags, key_arena):
        self.n_rows = n_rows
        self.num = num                  # name -> float64 [n]
        self.num_present = num_present  # name -> bool [n]
        self.strs = strs                # top-level field -> (arena, off, len)
        self.tag_strs = tag_strs        # metadataMap tag -> (arena, off, len)
        self.bags = bags                # name -> (rec, val, key_off, key_len)
        self.key_arena = key_arena      # bytes


def _np_from(ptr, n, dtype):
    if n == 0:
        return np.zeros(0, dtype=dtype)
    return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dtype, copy=True)


def _scan_container_offsets(
    path: str, data: Optional[bytes] = None
) -> Optional[Tuple[bytes, List[int], List[int], List[int], str]]:
    """Parse the container framing of one Avro file into per-container-block
    payload POSITIONS — no payload bytes are copied and nothing is
    decompressed (the packed native decode inflates straight out of the
    file buffer).

    Returns ``(data, offsets, lengths, counts, codec)`` where container
    block *i* holds ``counts[i]`` records in
    ``data[offsets[i]:offsets[i]+lengths[i]]``, or None when the codec is
    unsupported."""
    if data is None:
        with open(path, "rb") as f:
            data = f.read()
    r = _Reader(data)
    if r.read(4) != MAGIC:
        raise ValueError(f"{path}: not an Avro object container file")
    meta = _decode(r, {"type": "map", "values": "bytes"})
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    if codec not in ("null", "deflate"):
        return None
    sync = r.read(SYNC_SIZE)
    offsets: List[int] = []
    lengths: List[int] = []
    counts: List[int] = []
    while r.pos < len(r.buf):
        n = r.read_long()
        size = r.read_long()
        if size < 0 or r.pos + size > len(r.buf):
            raise ValueError(f"{path}: container block overruns file")
        offsets.append(r.pos)
        lengths.append(size)
        counts.append(n)
        r.pos += size
        if r.read(SYNC_SIZE) != sync:
            raise ValueError(f"{path}: sync marker mismatch (corrupt file)")
    return data, offsets, lengths, counts, codec


def _scan_container(
    path: str, data: Optional[bytes] = None
) -> Optional[Tuple[List[bytes], List[int], str]]:
    """Like :func:`_scan_container_offsets` but materializes the payload
    byte slices — ``(payloads, counts, codec)`` — for callers that feed the
    per-payload (Python-inflate) decode path."""
    scanned = _scan_container_offsets(path, data)
    if scanned is None:
        return None
    data, offsets, lengths, counts, codec = scanned
    payloads = [data[o:o + l] for o, l in zip(offsets, lengths)]
    return payloads, counts, codec


def container_block_counts(
    path: str, data: Optional[bytes] = None
) -> List[int]:
    """Per-container-block record counts of one Avro file (framing scan only,
    no decompression or record decode). The streaming block planner uses this
    to size blocks without pulling data through the decoder."""
    scanned = _scan_container(path, data)
    if scanned is None:
        raise ValueError(f"{path}: unsupported avro codec for framing scan")
    return scanned[1]


def read_columnar_file(
    path: str,
    plan: ColumnarPlan,
    data: Optional[bytes] = None,
    block_start: int = 0,
    block_count: Optional[int] = None,
) -> Optional[ColumnarFile]:
    """Decode one container file through the native path (None on any
    mismatch: different schema shape, unsupported codec, decode error).
    ``data`` passes already-read file bytes (header sniffing shares one
    read with decoding). ``block_start``/``block_count`` restrict decoding
    to a contiguous range of *container* blocks — the unit of chunked
    out-of-core reads; only the selected payloads are decompressed, and the
    resulting columns are bitwise-identical to the matching row range of a
    whole-file read."""
    lib = _load_native()
    if lib is None:
        return None
    scanned = _scan_container_offsets(path, data)
    if scanned is None:
        return None
    data, offsets, lengths, counts, codec = scanned
    n_payloads = len(offsets)
    if block_start < 0 or block_start > n_payloads:
        raise ValueError(
            f"{path}: block_start={block_start} out of range "
            f"[0, {n_payloads}]"
        )
    stop = (
        n_payloads
        if block_count is None
        else min(block_start + max(block_count, 0), n_payloads)
    )
    sel = slice(block_start, stop)
    n_records = sum(counts[sel])
    tag_names = sorted(plan.tags, key=plan.tags.get)
    tag_bytes = b"".join(t.encode("utf-8") for t in tag_names)
    tag_lens = np.asarray(
        [len(t.encode("utf-8")) for t in tag_names], dtype=np.int32
    )
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(_c_i32)
    i64p = ctypes.POINTER(_c_i64)
    prog = np.ascontiguousarray(plan.program)

    handle = None
    if getattr(lib, "has_packed", False):
        # fast path: ONE foreign call does inflate + columnar decode for
        # the whole selected range, so the GIL stays released for the full
        # decode window and pool workers on other files run concurrently
        offs_a = np.asarray(offsets[sel], dtype=np.int64)
        lens_a = np.asarray(lengths[sel], dtype=np.int64)
        cnts_a = np.asarray(counts[sel], dtype=np.int64)
        handle = lib.avro_decode_packed(
            ctypes.cast(ctypes.c_char_p(data), u8p),
            len(data),
            offs_a.ctypes.data_as(i64p),
            lens_a.ctypes.data_as(i64p),
            cnts_a.ctypes.data_as(i64p),
            stop - block_start,
            1 if codec == "deflate" else 0,
            prog.ctypes.data_as(i32p),
            len(plan.program) // 3,
            len(plan.num_fields),
            plan.n_str_cols,
            len(plan.bag_fields),
            ctypes.cast(ctypes.c_char_p(tag_bytes), u8p),
            tag_lens.ctypes.data_as(i32p),
            len(tag_names),
            plan.tag_col_base,
        )
    if not handle:
        # per-payload path: Python-side inflate + join, then one decode call
        payloads = [data[o:o + l] for o, l in
                    zip(offsets[sel], lengths[sel])]
        if codec == "deflate":
            payloads = [zlib.decompress(p, -15) for p in payloads]
        blob = b"".join(payloads)
        handle = lib.avro_decode(
            ctypes.cast(ctypes.c_char_p(blob), u8p),
            len(blob),
            n_records,
            prog.ctypes.data_as(i32p),
            len(plan.program) // 3,
            len(plan.num_fields),
            plan.n_str_cols,
            len(plan.bag_fields),
            ctypes.cast(ctypes.c_char_p(tag_bytes), u8p),
            tag_lens.ctypes.data_as(i32p),
            len(tag_names),
            plan.tag_col_base,
        )
    if not handle:
        logger.warning("%s: native decode failed; python fallback", path)
        return None
    try:
        n = int(lib.res_n_rows(handle))
        num = {}
        num_present = {}
        for name, i in plan.num_fields.items():
            num[name] = _np_from(lib.res_num_col(handle, i), n, np.float64)
            num_present[name] = (
                _np_from(lib.res_num_present(handle, i), n, np.uint8) > 0
            )
        arena_len = _c_i64()
        arena_ptr = lib.res_str_arena(handle, ctypes.byref(arena_len))
        arena = (
            ctypes.string_at(arena_ptr, arena_len.value)
            if arena_len.value
            else b""
        )
        def str_col(i):
            return (
                arena,
                _np_from(lib.res_str_off(handle, i), n, np.int64),
                _np_from(lib.res_str_len(handle, i), n, np.int32),
            )

        strs = {name: str_col(i) for name, i in plan.str_fields.items()}
        tag_strs = {name: str_col(i) for name, i in plan.tags.items()}
        karena_len = _c_i64()
        karena_ptr = lib.res_key_arena(handle, ctypes.byref(karena_len))
        key_arena = (
            ctypes.string_at(karena_ptr, karena_len.value)
            if karena_len.value
            else b""
        )
        bags = {}
        for name, b in plan.bag_fields.items():
            cnt = int(lib.res_bag_count(handle, b))
            bags[name] = (
                _np_from(lib.res_bag_rec(handle, b), cnt, np.int64),
                _np_from(lib.res_bag_val(handle, b), cnt, np.float32),
                _np_from(lib.res_bag_key_off(handle, b), cnt, np.int64),
                _np_from(lib.res_bag_key_len(handle, b), cnt, np.int32),
            )
        return ColumnarFile(n, num, num_present, strs, tag_strs, bags, key_arena)
    finally:
        lib.res_free(handle)


def dedup_keys(
    arena: bytes, offs: np.ndarray, lens: np.ndarray
) -> Tuple[np.ndarray, List[str]]:
    """(dense ids aligned with offs/lens, unique keys in first-appearance
    order — the id assignment DefaultIndexMap would produce)."""
    lib = _load_native()
    assert lib is not None
    n = len(offs)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    h = lib.key_dedup(
        ctypes.cast(ctypes.c_char_p(arena), u8p),
        np.ascontiguousarray(offs, dtype=np.int64).ctypes.data_as(
            ctypes.POINTER(_c_i64)
        ),
        np.ascontiguousarray(lens, dtype=np.int32).ctypes.data_as(
            ctypes.POINTER(_c_i32)
        ),
        n,
    )
    try:
        ids = _np_from(lib.dedup_ids(h), n, np.int64)
        nu = int(lib.dedup_n_unique(h))
        u_off = _np_from(lib.dedup_u_off(h), nu, np.int64)
        u_len = _np_from(lib.dedup_u_len(h), nu, np.int32)
        uniques = [
            arena[u_off[i] : u_off[i] + u_len[i]].decode("utf-8")
            for i in range(nu)
        ]
        return ids, uniques
    finally:
        lib.dedup_free(h)


def decode_strings(col: Tuple[bytes, np.ndarray, np.ndarray]) -> List[Optional[str]]:
    """Materialize a string column (None where absent)."""
    arena, off, ln = col
    return [
        None if ln[i] < 0 else arena[off[i] : off[i] + ln[i]].decode("utf-8")
        for i in range(len(off))
    ]
