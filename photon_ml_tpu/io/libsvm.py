"""LibSVM text input format + libsvm→TrainingExampleAvro converter.

Reference parity: io/deprecated/LibSVMInputDataFormat.scala:31 —
``[label] [idx]:[val] ...``, 1-based indices by default (``zero_based``
flips), labels mapped to {0,1} by sign for classification, optional
intercept appended as the last column with an identity index map — and
dev-scripts/libsvm_text_to_trainingexample_avro.py (feature name = index,
empty term). BASELINE config 1 (a1a logistic) enters through here.
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.data.game_data import FeatureShard, GameData
from photon_ml_tpu.indexmap import INTERCEPT_KEY, DefaultIndexMap, IndexMap


def _parse_line(line: str, zero_based: bool) -> Tuple[float, List[int], List[float]]:
    parts = line.split()
    label = float(parts[0])
    idxs: List[int] = []
    vals: List[float] = []
    for item in parts[1:]:
        if item.startswith("#"):  # trailing comment
            break
        i, _, v = item.partition(":")
        idx = int(i) - (0 if zero_based else 1)
        if idx < 0:
            raise ValueError(f"feature index {i} underflows (zero_based={zero_based})")
        idxs.append(idx)
        vals.append(float(v))
    return label, idxs, vals


def iter_libsvm(path: str, zero_based: bool = False):
    """Yield (label, indices, values) per data line of a file or directory."""
    paths = [path]
    if os.path.isdir(path):
        # skip subdirectories and marker files (_SUCCESS etc.), like the
        # part-file conventions of the avro readers
        paths = sorted(
            p for n in os.listdir(path)
            if not n.startswith((".", "_"))
            and os.path.isfile(p := os.path.join(path, n))
        )
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                yield _parse_line(line, zero_based)


def read_libsvm(
    path: str,
    feature_dimension: Optional[int] = None,
    use_intercept: bool = True,
    zero_based: bool = False,
    binarize_labels: bool = True,
) -> Tuple[GameData, IndexMap]:
    """LibSVM file/dir → GameData with one 'features' shard.

    Labels: ``binarize_labels`` maps by sign to {0,1} (the reference's
    classification path; a1a uses ±1). The index map is identity-style
    (feature key = column index as string; intercept last)."""
    rows: List[int] = []
    cols: List[int] = []
    vals: List[float] = []
    labels: List[float] = []
    max_idx = -1
    for r, (label, idxs, vs) in enumerate(iter_libsvm(path, zero_based)):
        labels.append((1.0 if label > 0 else 0.0) if binarize_labels else label)
        rows.extend([r] * len(idxs))
        cols.extend(idxs)
        vals.extend(vs)
        if idxs:
            max_idx = max(max_idx, max(idxs))
    n = len(labels)
    d = feature_dimension if feature_dimension is not None else max_idx + 1
    if max_idx >= d:
        # features beyond a declared dimension are dropped — the same
        # semantics as scoring over a fixed training index (a1a's test split
        # has indices its train split never saw)
        keep = np.asarray(cols) < d
        rows = list(np.asarray(rows)[keep])
        cols = list(np.asarray(cols)[keep])
        vals = list(np.asarray(vals)[keep])
    dim = d + 1 if use_intercept else d
    if use_intercept:
        rows.extend(range(n))
        cols.extend([d] * n)
        vals.extend([1.0] * n)
    name_to_index = {str(i): i for i in range(d)}
    if use_intercept:
        name_to_index[INTERCEPT_KEY] = d
    data = GameData(
        labels=np.asarray(labels, dtype=np.float32),
        feature_shards={
            "features": FeatureShard(
                rows=np.asarray(rows, dtype=np.int64),
                cols=np.asarray(cols, dtype=np.int64),
                vals=np.asarray(vals, dtype=np.float32),
                dim=dim,
            )
        },
        id_tags={},
    )
    return data, DefaultIndexMap(name_to_index)


def libsvm_to_training_example_avro(
    input_path: str,
    output_path: str,
    regression: bool = False,
    zero_based: bool = False,
) -> int:
    """dev-scripts/libsvm_text_to_trainingexample_avro.py equivalent:
    feature name = index string, term empty; classification labels mapped
    by sign to {0,1} unless ``regression``."""
    from photon_ml_tpu.io.data_reader import write_training_examples

    records = []
    for label, idxs, vs in iter_libsvm(input_path, zero_based):
        if not regression:
            label = 1.0 if label > 0 else 0.0
        records.append(
            {
                "label": float(label),
                "features": [(str(i), "", float(v)) for i, v in zip(idxs, vs)],
            }
        )
    return write_training_examples(output_path, records)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="libsvm-to-avro",
        description="Convert LibSVM text to TrainingExampleAvro "
                    "(dev-scripts/libsvm_text_to_trainingexample_avro.py).",
    )
    p.add_argument("input_path")
    p.add_argument("output_path")
    p.add_argument("-r", "--regression", action="store_true",
                   help="keep raw labels instead of sign-binarizing")
    p.add_argument("--zero-based", action="store_true")
    args = p.parse_args(argv)
    n = libsvm_to_training_example_avro(
        args.input_path, args.output_path,
        regression=args.regression, zero_based=args.zero_based,
    )
    print(f"wrote {n} records to {args.output_path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
