"""Scored-item persistence (ScoringResultAvro).

Reference parity: data/avro/ScoreProcessingUtils.scala:29 — ScoredItem
(predictionScore, label?, weight?, uid?, idTag map) ↔ ScoringResultAvro.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro import read_avro_dir, write_avro_file


@dataclasses.dataclass
class ScoredItem:
    """One scored datum (reference scoring/ScoredItem.scala)."""

    prediction_score: float
    label: Optional[float] = None
    weight: Optional[float] = None
    uid: Optional[str] = None
    id_tags: Dict[str, str] = dataclasses.field(default_factory=dict)


def save_scores(
    path: str,
    items: Iterable[ScoredItem],
    model_id: str,
    records_per_file: int = 1_000_000,
    file_sizes: Optional[List[int]] = None,
) -> int:
    """Write ScoringResultAvro part files under ``path``; returns count.

    ``file_sizes`` forces an exact per-file record partition (the reference
    --num-files contract: exactly N part files, empty ones included);
    zero-sized entries may only TRAIL the list (records are assigned in
    order). Otherwise files roll over every ``records_per_file`` records."""
    os.makedirs(path, exist_ok=True)
    schema = schemas.scoring_result_schema()
    total = 0
    part = 0
    batch: List[dict] = []
    sizes = list(file_sizes) if file_sizes is not None else None

    def _current_cap() -> int:
        if sizes is None:
            return records_per_file
        return sizes[part] if part < len(sizes) else max(sizes[-1], 1)

    def flush(force: bool = False) -> None:
        nonlocal part, batch
        if batch or force:
            write_avro_file(
                os.path.join(path, f"part-{part:05d}.avro"), schema, batch
            )
            part += 1
            batch = []

    for item in items:
        batch.append(
            {
                "uid": item.uid,
                "label": None if item.label is None else float(item.label),
                "modelId": model_id,
                "predictionScore": float(item.prediction_score),
                "weight": None if item.weight is None else float(item.weight),
                "metadataMap": dict(item.id_tags) or None,
            }
        )
        total += 1
        if len(batch) >= _current_cap():
            flush()
    flush()
    if sizes is not None:
        while part < len(sizes):
            flush(force=True)  # empty trailing parts keep the exact count
    return total


def load_scores(path: str) -> Iterator[ScoredItem]:
    for rec in read_avro_dir(path):
        yield ScoredItem(
            prediction_score=rec["predictionScore"],
            label=rec.get("label"),
            weight=rec.get("weight"),
            uid=rec.get("uid"),
            id_tags=rec.get("metadataMap") or {},
        )
