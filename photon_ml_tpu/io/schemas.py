"""The photon-avro-schemas record schemas, as Python dicts.

Reference parity: photon-avro-schemas/src/main/avro/*.avsc — field-for-field
identical (names, order, union shapes, defaults), so files are byte-level
interoperable with the reference pipeline. Doc strings trimmed.
"""

from photon_ml_tpu.io.avro import AvroSchema

_NS = "com.linkedin.photon.avro.generated"

FEATURE = {
    "name": "FeatureAvro",
    "namespace": _NS,
    "type": "record",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

NAME_TERM_VALUE = {
    "name": "NameTermValueAvro",
    "namespace": _NS,
    "type": "record",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

TRAINING_EXAMPLE = {
    "name": "TrainingExampleAvro",
    "namespace": _NS,
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": FEATURE}},
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
    ],
}

BAYESIAN_LINEAR_MODEL = {
    "name": "BayesianLinearModelAvro",
    "namespace": _NS,
    "type": "record",
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {"name": "means", "type": {"type": "array", "items": NAME_TERM_VALUE}},
        {
            "name": "variances",
            "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
            "default": None,
        },
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
    ],
}

LATENT_FACTOR = {
    "name": "LatentFactorAvro",
    "namespace": _NS,
    "type": "record",
    "fields": [
        {"name": "effectId", "type": "string"},
        {"name": "latentFactor", "type": {"type": "array", "items": "double"}},
    ],
}

FEATURE_SUMMARIZATION_RESULT = {
    "name": "FeatureSummarizationResultAvro",
    "namespace": _NS,
    "type": "record",
    "fields": [
        {"name": "featureName", "type": "string"},
        {"name": "featureTerm", "type": "string"},
        {"name": "metrics", "type": {"type": "map", "values": "double"}},
    ],
}

SCORING_RESULT = {
    "name": "ScoringResultAvro",
    "namespace": _NS,
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "modelId", "type": "string"},
        {"name": "predictionScore", "type": "double"},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
    ],
}


def training_example_schema() -> AvroSchema:
    return AvroSchema(TRAINING_EXAMPLE)


def bayesian_linear_model_schema() -> AvroSchema:
    return AvroSchema(BAYESIAN_LINEAR_MODEL)


def latent_factor_schema() -> AvroSchema:
    return AvroSchema(LATENT_FACTOR)


def feature_summarization_schema() -> AvroSchema:
    return AvroSchema(FEATURE_SUMMARIZATION_RESULT)


def scoring_result_schema() -> AvroSchema:
    return AvroSchema(SCORING_RESULT)
