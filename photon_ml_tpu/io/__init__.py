"""IO layer: Avro wire format, data readers, model persistence.

Replaces photon-client's data/avro/* (AvroDataReader.scala:53,
ModelProcessingUtils.scala:58, ScoreProcessingUtils.scala:29) and the
photon-avro-schemas module. The Avro object-container codec is implemented
in-tree (no JVM Avro library): the on-disk format is identical, so files
written by the reference pipeline are readable here and vice versa.
"""

from photon_ml_tpu.io.avro import (
    AvroSchema,
    read_avro_file,
    write_avro_file,
)
from photon_ml_tpu.io import schemas

__all__ = ["AvroSchema", "read_avro_file", "write_avro_file", "schemas"]
