from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    RandomEffectDataset,
    ReBucket,
    build_random_effect_dataset,
)

__all__ = [
    "RandomEffectDataConfiguration",
    "RandomEffectDataset",
    "ReBucket",
    "build_random_effect_dataset",
]
