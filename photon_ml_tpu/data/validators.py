"""Fail-fast input sanity checking.

Reference parity: photon-client data/DataValidators.scala:29 — per-task row
checks (finite features, finite labels, task-specific label ranges,
non-negative weights, finite offsets) with VALIDATE_FULL (every row) vs
VALIDATE_SAMPLE (a fraction) vs VALIDATE_DISABLED modes. All checks run and
every failure is reported together, matching the reference's accumulate-then-
throw behavior.

Host-side by design: validation happens once at ingest on numpy arrays, never
inside a jit program.
"""

from __future__ import annotations

import enum
from typing import List, Optional

import numpy as np

from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.ops.features import DenseFeatures, EllFeatures
from photon_ml_tpu.types import TaskType


class DataValidationType(enum.Enum):
    """Reference DataValidationType (data/DataValidators.scala)."""

    VALIDATE_FULL = "validate_full"
    VALIDATE_SAMPLE = "validate_sample"
    VALIDATE_DISABLED = "validate_disabled"


class DataValidationError(ValueError):
    """Raised with ALL failed checks listed, one per line."""

    def __init__(self, failures: List[str]):
        self.failures = failures
        super().__init__(
            "Data validation failed:\n" + "\n".join(f"  - {f}" for f in failures)
        )


_SAMPLE_FRACTION = 0.10  # VALIDATE_SAMPLE fraction


def _spill_values_matrix(feats, n: int) -> Optional[np.ndarray]:
    """KP-cap spill entries as a row-aligned [n, k] padded matrix."""
    if getattr(feats, "spill_rows", None) is None:
        return None
    sr = np.asarray(feats.spill_rows)
    sv = np.asarray(feats.spill_vals)
    order = np.argsort(sr, kind="stable")
    sr, sv = sr[order], sv[order]
    counts = np.bincount(sr, minlength=n)
    k = max(int(counts.max()), 1)
    starts = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    slots = np.arange(sr.size, dtype=np.int64) - starts[sr]
    out = np.zeros((n, k), dtype=np.float32)
    out[sr, slots] = sv
    return out


def _engine_values(feats) -> np.ndarray:
    """Per-row explicit feature values of one engine as [n, *] (padding
    slots are 0.0 and vacuously finite, so they never mask a NaN/Inf)."""
    if isinstance(feats, DenseFeatures):
        return np.asarray(feats.matrix)
    if isinstance(feats, EllFeatures):
        return np.asarray(feats.values)
    from photon_ml_tpu.ops.sparse_perm import (
        BenesSparseFeatures,
        ColumnSplitFeatures,
        _ZeroColumnsBlock,
    )

    if isinstance(feats, _ZeroColumnsBlock):
        return np.zeros((feats.num_rows_, 1), dtype=np.float32)
    if isinstance(feats, ColumnSplitFeatures):
        parts = [_engine_values(blk) for blk in feats.blocks]
        if feats.hot_matrix is not None:
            parts.append(np.asarray(feats.hot_matrix))
        return np.concatenate(parts, axis=1)
    if isinstance(feats, BenesSparseFeatures):
        parts = [np.asarray(feats.ell_values)]
        n = feats.num_rows_
    else:
        from photon_ml_tpu.ops.fused_perm import FusedBenesFeatures

        if not isinstance(feats, FusedBenesFeatures):
            raise TypeError(f"unknown feature matrix type {type(feats)!r}")
        n = feats.num_rows_
        parts = [np.asarray(feats.ell_flat).reshape(-1, feats.ell_k)[:n]]
    if feats.hot_matrix is not None:
        parts.append(np.asarray(feats.hot_matrix))
    spill = _spill_values_matrix(feats, n)
    if spill is not None:
        parts.append(spill)
    return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=1)


def _feature_values(data: LabeledData) -> np.ndarray:
    return _engine_values(data.features)


def validate_labeled_data(
    data: LabeledData,
    task: TaskType,
    mode: DataValidationType = DataValidationType.VALIDATE_FULL,
    seed: int = 0,
) -> None:
    """Run the reference's per-task checks; raise DataValidationError listing
    every failed check (DataValidators.sanityCheckData semantics)."""
    if mode is DataValidationType.VALIDATE_DISABLED:
        return

    labels = np.asarray(data.labels)
    weights = np.asarray(data.weights)
    offsets = np.asarray(data.offsets)
    values = _feature_values(data)

    if mode is DataValidationType.VALIDATE_SAMPLE:
        n = labels.shape[0]
        take = max(1, int(n * _SAMPLE_FRACTION))
        idx = np.random.default_rng(seed).choice(n, size=take, replace=False)
        labels, weights, offsets, values = (
            labels[idx],
            weights[idx],
            offsets[idx],
            values[idx],
        )

    # Padding rows (weight 0) are synthetic and exempt from label checks.
    live = weights > 0
    failures: List[str] = []

    if not np.all(np.isfinite(values)):
        failures.append("features contain NaN or Inf")
    if not np.all(np.isfinite(labels[live])):
        failures.append("labels contain NaN or Inf")
    if not np.all(np.isfinite(offsets)):
        failures.append("offsets contain NaN or Inf")
    if not np.all(np.isfinite(weights)):
        failures.append("weights contain NaN or Inf")
    elif np.any(weights < 0):
        failures.append("weights contain negative values")

    finite_live = labels[live][np.isfinite(labels[live])]
    if task.is_classification:
        # binary labels (reference: validate binary label check)
        if finite_live.size and not np.all(
            (finite_live == 0.0) | (finite_live == 1.0)
        ):
            failures.append(f"labels for {task.value} must be 0 or 1")
    elif task is TaskType.POISSON_REGRESSION:
        if finite_live.size and np.any(finite_live < 0):
            failures.append("labels for poisson_regression must be non-negative")

    if failures:
        raise DataValidationError(failures)
