"""GAME training data: per-row responses + feature shards + id tags.

Reference parity: data/GameDatum.scala:38 (response/offset/weight, a
featureShardContainer, and idTagToValueMap naming the entity each row belongs
to for every random-effect type) and data/GameConverters.scala:29 (DataFrame
row -> GameDatum). Struct-of-arrays instead of an RDD of per-row objects:
one numpy column per field, features kept as COO per shard so both the
fixed-effect ELL layout and the random-effect grouped blocks can be built
from the same source without re-reading input.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np


@dataclasses.dataclass
class FeatureShard:
    """One feature bag/shard in COO form over its own feature space
    (reference "feature shards" merged from feature bags,
    AvroDataReader.scala:84-145)."""

    rows: np.ndarray
    cols: np.ndarray
    vals: np.ndarray
    dim: int

    def slice_rows(self, row_mask: np.ndarray) -> "FeatureShard":
        """Subset to rows where mask is True, renumbering rows densely."""
        keep = row_mask[self.rows]
        new_index = np.cumsum(row_mask) - 1
        return FeatureShard(
            rows=new_index[self.rows[keep]],
            cols=self.cols[keep],
            vals=self.vals[keep],
            dim=self.dim,
        )

    def take_rows(self, indices: np.ndarray) -> "FeatureShard":
        """Gather rows by index, allowing repeats (bootstrap resampling);
        output row r holds the nonzeros of input row indices[r]."""
        indices = np.asarray(indices, dtype=np.int64)
        order = np.argsort(self.rows, kind="stable")
        r_sorted = self.rows[order]
        starts = np.searchsorted(r_sorted, indices, side="left")
        ends = np.searchsorted(r_sorted, indices, side="right")
        counts = ends - starts
        total = int(counts.sum())
        # positions into `order`, one contiguous run per selected row
        run_offsets = np.repeat(np.cumsum(counts) - counts, counts)
        pos = np.arange(total) - run_offsets + np.repeat(starts, counts)
        nz = order[pos]
        return FeatureShard(
            rows=np.repeat(np.arange(len(indices), dtype=np.int64), counts),
            cols=self.cols[nz],
            vals=self.vals[nz],
            dim=self.dim,
        )


@dataclasses.dataclass
class GameData:
    """All rows of a GAME train/validation set (host container; device
    arrays are built per-coordinate)."""

    labels: np.ndarray                      # [n]
    feature_shards: Dict[str, FeatureShard]
    id_tags: Dict[str, np.ndarray]          # re_type -> per-row entity id (str)
    offsets: Optional[np.ndarray] = None    # [n]
    weights: Optional[np.ndarray] = None    # [n]

    def __post_init__(self) -> None:
        n = len(self.labels)
        self.labels = np.asarray(self.labels, dtype=np.float32)
        self.offsets = (
            np.zeros(n, dtype=np.float32)
            if self.offsets is None
            else np.asarray(self.offsets, dtype=np.float32)
        )
        self.weights = (
            np.ones(n, dtype=np.float32)
            if self.weights is None
            else np.asarray(self.weights, dtype=np.float32)
        )
        for t, ids in self.id_tags.items():
            if len(ids) != n:
                raise ValueError(f"id tag {t} has {len(ids)} rows, expected {n}")

    @property
    def num_rows(self) -> int:
        return len(self.labels)

    def slice_rows(self, row_mask: np.ndarray) -> "GameData":
        """Row-subset view (fresh arrays; ELL cache not carried over)."""
        row_mask = np.asarray(row_mask, dtype=bool)
        return GameData(
            labels=self.labels[row_mask],
            feature_shards={
                sid: s.slice_rows(row_mask)
                for sid, s in self.feature_shards.items()
            },
            id_tags={t: np.asarray(v)[row_mask] for t, v in self.id_tags.items()},
            offsets=self.offsets[row_mask],
            weights=self.weights[row_mask],
        )

    def take_rows(self, indices: np.ndarray) -> "GameData":
        """Gather rows by index with repeats allowed (bootstrap resamples)."""
        indices = np.asarray(indices, dtype=np.int64)
        return GameData(
            labels=self.labels[indices],
            feature_shards={
                sid: s.take_rows(indices)
                for sid, s in self.feature_shards.items()
            },
            id_tags={t: np.asarray(v)[indices] for t, v in self.id_tags.items()},
            offsets=self.offsets[indices],
            weights=self.weights[indices],
        )

    def ell_features(self, shard_name: str):
        """Device ELL layout of one shard, built once and cached (validation
        re-scores the same data after every coordinate update)."""
        return self.sparse_features(shard_name, engine="ell")

    def sparse_features(self, shard_name: str, engine: str = "auto"):
        """Device sparse layout of one shard, built once and cached.

        engine:
        - "ell"   — padded row-sparse gather/scatter layout (XLA).
        - "benes" — permutation-routed engine (ops/sparse_perm.py): vector-
          speed matvec/rmatvec on TPU, with a one-time host routing cost.
        - "fused" — same routing executed as fused Pallas kernels
          (ops/fused_perm.py): ~3x less HBM traffic per linear map on TPU
          by byte accounting. Opt-in until an on-hardware A/B records a
          win (bench.py --engine fused / dev-scripts/tpu_validate_fused.py);
          "auto" only prefers measured engines.
        - "auto"  — "benes" on a TPU backend with a shard large enough for
          the routing prep to pay for itself (measured 26.2M example-
          passes/s vs ELL's 2.2M in round 2); "ell" everywhere else.
        """
        if engine not in ("auto", "ell", "benes", "fused"):
            raise ValueError(
                f"unknown sparse engine {engine!r}; expected auto/ell/benes/fused"
            )
        cache = getattr(self, "_feat_cache", None)
        if cache is None:
            cache = {}
            self._feat_cache = cache
        shard = self.feature_shards[shard_name]
        if engine == "auto":
            import jax

            on_accel = jax.default_backend() != "cpu"
            if on_accel and shard.rows.size >= (1 << 20):
                # the measured on-hardware winner (TPU_MEASUREMENTS.json /
                # dev-scripts/tpu_validate_fused.py: fused ~2x benes at the
                # headline workload); the probe degrades to stage-by-stage
                # if the fused kernels fail to lower on this backend
                from photon_ml_tpu.ops.fused_perm import fused_engine_works

                engine = "fused" if fused_engine_works() else "benes"
            else:
                engine = "ell"
        key = (shard_name, engine)
        if key not in cache:
            if engine in ("benes", "fused"):
                if engine == "benes":
                    from photon_ml_tpu.ops.sparse_perm import from_coo
                else:
                    from photon_ml_tpu.ops.fused_perm import from_coo

                cache[key] = from_coo(
                    shard.rows, shard.cols, shard.vals, (self.num_rows, shard.dim)
                )
            else:
                from photon_ml_tpu.ops.features import from_scipy_like

                cache[key] = from_scipy_like(
                    shard.rows, shard.cols, shard.vals, (self.num_rows, shard.dim)
                )
        return cache[key]
