"""Random-effect dataset: entity-grouped padded blocks for vmap'd solves.

Reference parity: data/RandomEffectDataSet.scala:47 (build :240-277 — groupBy
entity with a custom partitioner; active-data reservoir cap :287-388; passive
data :399-446; Pearson feature selection :457-471), data/LocalDataSet.scala:36
(per-entity in-memory dataset, feature selection :221-287, reservoir :289-320),
and projector/IndexMapProjectorRDD.scala:31 (per-entity index map built from
that entity's observed features :164).

TPU-native redesign: instead of an RDD of per-entity Scala objects, the whole
coordinate's data is a handful of dense padded blocks

    X [E, S, D_local]   labels/offsets/weights [E, S]   proj_indices [E, D_local]

where E = entities in a bucket, S = that bucket's max samples/entity, and
D_local = that bucket's max per-entity projected dimension. Entities are
size-bucketed so padding waste stays bounded; one ``vmap`` of the local solver
per bucket replaces millions of ``mapValues`` closures. Per-entity index-map
projection (a sorted list of the entity's observed global feature ids) makes
local problems dense and small — the MXU-friendly layout — exactly the role
the reference's IndexMapProjector plays. Samples beyond the active cap form
the passive set: projected through the same per-entity map, score-only.

All grouping/projection runs host-side in vectorized numpy at data-prep time
(the analog of the reference's one-time shuffle), producing arrays that shard
over the mesh's entity axis with zero training-time communication.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from photon_ml_tpu.projector import ProjectorType, RandomProjectionMatrix


@dataclasses.dataclass(frozen=True)
class RandomEffectDataConfiguration:
    """Reference RandomEffectDataConfiguration.scala:42 (string mini-language
    ``reType,shard,numPartitions,activeCap,passiveLB,featureRatio,projector``
    with ``index_map``/``identity``/``random=k``) as a typed config.
    numPartitions is superseded by size-bucketing."""

    random_effect_type: str
    active_data_upper_bound: Optional[int] = None   # max active samples/entity
    passive_data_lower_bound: Optional[int] = None  # min samples for an entity to keep passive rows
    features_to_samples_ratio: Optional[float] = None  # cap D_local <= ratio * n_samples
    max_local_features: Optional[int] = None        # hard cap on D_local
    num_buckets: int = 1
    seed: int = 0
    # Projection of per-entity problems (reference ProjectorType):
    # INDEX_MAP (default, exact remap of observed features), IDENTITY
    # (local space == global space), RANDOM (shared Gaussian matrix,
    # ``projected_dim`` required — the `random=k` mini-language arm).
    projector: ProjectorType = ProjectorType.INDEX_MAP
    projected_dim: Optional[int] = None

    def __post_init__(self) -> None:
        if self.projector is ProjectorType.RANDOM:
            if not self.projected_dim:
                raise ValueError("RANDOM projector requires projected_dim (random=k)")
            if (
                self.features_to_samples_ratio is not None
                or self.max_local_features is not None
            ):
                raise ValueError(
                    "feature selection (features_to_samples_ratio / "
                    "max_local_features) does not apply to the RANDOM "
                    "projector; the projection itself bounds the local dim"
                )


@struct.dataclass
class ReBucket:
    """One size-bucket of entities, fully padded (device pytree)."""

    X: jax.Array             # [E, S, D] local-projected dense features
    labels: jax.Array        # [E, S]
    offsets: jax.Array       # [E, S]
    weights: jax.Array       # [E, S] (0 = padding)
    sample_pos: jax.Array    # [E, S] int32 original row index (0 where padding)
    proj_indices: jax.Array  # [E, D] int32 global feature id per local column
    proj_valid: jax.Array    # [E, D] bool: local column is a real feature

    @property
    def num_entities(self) -> int:
        return self.X.shape[0]

    @property
    def max_samples(self) -> int:
        return self.X.shape[1]

    @property
    def local_dim(self) -> int:
        return self.X.shape[2]


@struct.dataclass
class RePassiveRows:
    """Passive (score-only) rows of one bucket, local-projected. Offsets are
    not stored: passive scoring is the raw x.w gather; score algebra composes
    offsets at the coordinate level."""

    X: jax.Array            # [P, D]
    entity_index: jax.Array  # [P] int32 row into the bucket's entity axis
    sample_pos: jax.Array   # [P] int32 original row index


@dataclasses.dataclass
class RandomEffectDataset:
    """All buckets of one random-effect coordinate + host-side id maps."""

    config: RandomEffectDataConfiguration
    buckets: List[ReBucket]
    passive: List[Optional[RePassiveRows]]   # parallel to buckets
    entity_ids: List[List[str]]              # per bucket, per entity row
    entity_to_loc: Dict[str, Tuple[int, int]]  # id -> (bucket, row)
    num_rows: int                            # total rows in the source data
    global_dim: int

    @property
    def num_entities(self) -> int:
        return sum(len(ids) for ids in self.entity_ids)

    def update_offsets(self, offsets: np.ndarray) -> "RandomEffectDataset":
        """Rebuild the per-bucket offset blocks from a full-data offset vector
        (the residual trick: Coordinate.updateModel / addScoresToOffsets)."""
        offsets = np.asarray(offsets, dtype=np.float32)
        new_buckets = []
        for b in self.buckets:
            pos = np.asarray(b.sample_pos)
            wt = np.asarray(b.weights)
            off = np.where(wt > 0, offsets[pos], 0.0).astype(np.float32)
            new_buckets.append(b.replace(offsets=jnp.asarray(off)))
        return dataclasses.replace(self, buckets=new_buckets)


def _expand_nnz(
    act_rows: np.ndarray, row_start: np.ndarray, row_end: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Flatten the CSR slices of ``act_rows`` into (sample_index, flat_index)
    pairs: sample_index points back into act_rows, flat_index into fc/fv."""
    cnt = row_end[act_rows] - row_start[act_rows]
    total = int(cnt.sum())
    rep = np.repeat(np.arange(len(act_rows), dtype=np.int64), cnt)
    within = np.arange(total, dtype=np.int64) - np.repeat(np.cumsum(cnt) - cnt, cnt)
    return rep, row_start[act_rows][rep] + within


def _local_dense(
    act_rows: np.ndarray,
    local_cols: np.ndarray,
    row_start: np.ndarray,
    row_end: np.ndarray,
    fc: np.ndarray,
    fv: np.ndarray,
    out: np.ndarray,
) -> None:
    """Scatter the rows' features into ``out[sample, local_col]`` (features
    outside local_cols are dropped — index-map projection semantics)."""
    rep, fidx = _expand_nnz(act_rows, row_start, row_end)
    c, v = fc[fidx], fv[fidx]
    j = np.searchsorted(local_cols, c)
    j_clip = np.minimum(j, max(len(local_cols) - 1, 0))
    match = (j < len(local_cols)) & (local_cols[j_clip] == c) if len(local_cols) else np.zeros(len(c), dtype=bool)
    out[rep[match], j_clip[match]] = v[match]


def _pearson_scores(x: np.ndarray, y: np.ndarray, w: np.ndarray) -> np.ndarray:
    """|Pearson correlation| of each local feature with the label over one
    entity's samples (reference LocalDataSet.scala:221-287). Constant features
    score 0 except an all-constant nonzero column (intercept-like) which the
    reference keeps — we emulate by scoring it +inf."""
    wsum = max(w.sum(), 1e-12)
    mx = (w[:, None] * x).sum(0) / wsum
    my = float((w * y).sum() / wsum)
    dx = x - mx
    dy = y - my
    cov = (w[:, None] * dx * dy[:, None]).sum(0) / wsum
    vx = (w[:, None] * dx * dx).sum(0) / wsum
    vy = float((w * dy * dy).sum() / wsum)
    denom = np.sqrt(np.maximum(vx * vy, 0.0))
    corr = np.where(denom > 1e-12, np.abs(cov) / np.maximum(denom, 1e-12), 0.0)
    # constant nonzero column (e.g. intercept): keep it (reference keeps
    # intercept during feature selection)
    const_nonzero = (vx <= 1e-12) & (np.abs(mx) > 0)
    return np.where(const_nonzero, np.inf, corr)


def build_random_effect_dataset(
    entity_ids: Sequence,
    feature_rows: np.ndarray,
    feature_cols: np.ndarray,
    feature_vals: np.ndarray,
    global_dim: int,
    labels: np.ndarray,
    config: RandomEffectDataConfiguration,
    offsets: Optional[np.ndarray] = None,
    weights: Optional[np.ndarray] = None,
) -> RandomEffectDataset:
    """Group rows by entity, cap/sample, project, bucket, and pad.

    entity_ids: per-row entity key (len n). feature_*: COO triplets over the
    global feature space. Rows with entities are ALL consumed: up to the active
    cap into solver blocks, the remainder into passive (score-only) rows.
    """
    n = len(entity_ids)
    labels = np.asarray(labels, dtype=np.float32)
    offsets = np.zeros(n, dtype=np.float32) if offsets is None else np.asarray(offsets, dtype=np.float32)
    weights = np.ones(n, dtype=np.float32) if weights is None else np.asarray(weights, dtype=np.float32)
    rng = np.random.default_rng(config.seed)

    ids = np.asarray([str(e) for e in entity_ids])
    order = np.argsort(ids, kind="stable")
    sorted_ids = ids[order]
    uniq, starts = np.unique(sorted_ids, return_index=True)
    ends = np.append(starts[1:], n)

    # CSR-ify the COO features once (row-sorted)
    feature_rows = np.asarray(feature_rows, dtype=np.int64)
    feature_cols = np.asarray(feature_cols, dtype=np.int64)
    feature_vals = np.asarray(feature_vals, dtype=np.float32)
    forder = np.argsort(feature_rows, kind="stable")
    fr, fc, fv = feature_rows[forder], feature_cols[forder], feature_vals[forder]
    row_start = np.searchsorted(fr, np.arange(n))
    row_end = np.searchsorted(fr, np.arange(n) + 1)

    cap = config.active_data_upper_bound
    entities = []  # (id, active_rows, passive_rows, local_cols)
    for e_i, (s, t) in enumerate(zip(starts, ends)):
        rows = order[s:t]
        if cap is not None and len(rows) > cap:
            # reservoir-equivalent: uniform random subset without replacement
            # (reference RandomEffectDataSet.scala:325-388)
            keep = rng.choice(len(rows), size=cap, replace=False)
            keep_mask = np.zeros(len(rows), dtype=bool)
            keep_mask[keep] = True
            active_rows = rows[keep_mask]
            lb = config.passive_data_lower_bound
            passive_rows = rows[~keep_mask] if (lb is None or len(rows) >= lb) else np.empty(0, dtype=np.int64)
        else:
            active_rows = rows
            passive_rows = np.empty(0, dtype=np.int64)

        if config.projector is ProjectorType.RANDOM:
            # shared Gaussian projection: no per-entity column map
            local_cols = np.empty(0, dtype=np.int64)
            entities.append((uniq[e_i], active_rows, passive_rows, local_cols))
            continue
        if config.projector is ProjectorType.IDENTITY:
            local_cols = np.arange(global_dim, dtype=np.int64)
        else:
            # per-entity observed features (from ACTIVE data only, reference
            # IndexMapProjectorRDD.scala:164)
            cols_parts = [fc[row_start[r]:row_end[r]] for r in active_rows]
            local_cols = np.unique(np.concatenate(cols_parts)) if cols_parts else np.empty(0, dtype=np.int64)

        # feature selection cap (ratio * samples, hard cap)
        d_cap = None
        if config.features_to_samples_ratio is not None:
            d_cap = max(int(config.features_to_samples_ratio * len(active_rows)), 1)
        if config.max_local_features is not None:
            d_cap = min(d_cap, config.max_local_features) if d_cap is not None else config.max_local_features
        if d_cap is not None and len(local_cols) > d_cap:
            # rank by |Pearson| on a small dense local matrix
            xm = np.zeros((len(active_rows), len(local_cols)), dtype=np.float32)
            _local_dense(active_rows, local_cols, row_start, row_end, fc, fv, xm)
            scores = _pearson_scores(xm, labels[active_rows], weights[active_rows])
            top = np.argsort(-scores, kind="stable")[:d_cap]
            local_cols = np.sort(local_cols[top])

        entities.append((uniq[e_i], active_rows, passive_rows, local_cols))

    rproj = (
        RandomProjectionMatrix(
            projected_dim=int(config.projected_dim),
            global_dim=int(global_dim),
            seed=config.seed,
        )
        if config.projector is ProjectorType.RANDOM
        else None
    )

    # size-bucketing by (samples, local dim) product to bound padding waste
    nb = max(1, min(config.num_buckets, len(entities)))
    sizes = np.array(
        [
            len(a) * (rproj.projected_dim if rproj else max(len(lc), 1))
            for (_, a, _, lc) in entities
        ]
    )
    bucket_edges = np.quantile(sizes, np.linspace(0, 1, nb + 1)[1:-1]) if nb > 1 else []
    bucket_of = np.searchsorted(bucket_edges, sizes, side="left") if nb > 1 else np.zeros(len(entities), dtype=int)

    buckets: List[ReBucket] = []
    passives: List[Optional[RePassiveRows]] = []
    bucket_ids: List[List[str]] = []
    entity_to_loc: Dict[str, Tuple[int, int]] = {}

    for b in range(nb):
        members = [entities[i] for i in range(len(entities)) if bucket_of[i] == b]
        if not members:
            continue
        bi = len(buckets)
        E = len(members)
        S = max(len(a) for (_, a, _, _) in members)
        D = (
            rproj.projected_dim
            if rproj
            else max(max(len(lc), 1) for (_, _, _, lc) in members)
        )
        X = np.zeros((E, S, D), dtype=np.float32)
        lab = np.zeros((E, S), dtype=np.float32)
        off = np.zeros((E, S), dtype=np.float32)
        wt = np.zeros((E, S), dtype=np.float32)
        pos = np.zeros((E, S), dtype=np.int32)
        pidx = np.zeros((E, D), dtype=np.int32)
        pval = np.zeros((E, D), dtype=bool)
        ids_b: List[str] = []

        dlocs = np.array([len(lc) for (_, _, _, lc) in members], dtype=np.int64)
        for e, (eid, _, _, local_cols) in enumerate(members):
            ids_b.append(str(eid))
            entity_to_loc[str(eid)] = (bi, e)
            if rproj is None:
                pidx[e, : len(local_cols)] = local_cols
                pval[e, : len(local_cols)] = True
        if rproj is not None:
            # projected-space coordinates are all live; back-projection to the
            # original space goes through the shared matrix, not pidx
            pval[:, :] = True

        # Flat key space entity*(G+1)+col is globally sorted (entities ascend,
        # each local_cols list is sorted), so ONE searchsorted resolves every
        # nonzero's local column — no per-sample Python loops.
        G1 = global_dim + 1
        flat_cols = (
            np.concatenate([lc for (_, _, _, lc) in members])
            if dlocs.sum()
            else np.empty(0, dtype=np.int64)
        )
        flat_keys = np.repeat(np.arange(E, dtype=np.int64), dlocs) * G1 + flat_cols
        dstart = np.concatenate([[0], np.cumsum(dlocs)[:-1]])

        def local_scatter(rows_g: np.ndarray, e_of: np.ndarray, fill) -> None:
            """Resolve (row, global col, val) triplets of ``rows_g`` to
            (sample index into rows_g, local col, val); dropped features
            (outside the entity's projected space) are skipped."""
            rep, fidx = _expand_nnz(rows_g, row_start, row_end)
            c, v = fc[fidx], fv[fidx]
            qk = e_of[rep] * G1 + c
            ii = np.searchsorted(flat_keys, qk)
            ii_c = np.minimum(ii, max(len(flat_keys) - 1, 0))
            match = (
                (ii < len(flat_keys)) & (flat_keys[ii_c] == qk)
                if len(flat_keys)
                else np.zeros(len(qk), dtype=bool)
            )
            j = ii_c - dstart[e_of[rep]]
            fill(rep[match], j[match], v[match])

        alens = np.array([len(a) for (_, a, _, _) in members], dtype=np.int64)
        act = (
            np.concatenate([a for (_, a, _, _) in members])
            if alens.sum()
            else np.empty(0, dtype=np.int64)
        )
        e_act = np.repeat(np.arange(E, dtype=np.int64), alens)
        s_act = (
            np.concatenate([np.arange(l, dtype=np.int64) for l in alens])
            if alens.sum()
            else np.empty(0, dtype=np.int64)
        )
        lab[e_act, s_act] = labels[act]
        off[e_act, s_act] = offsets[act]
        wt[e_act, s_act] = weights[act]
        pos[e_act, s_act] = act

        def random_project(rows_g: np.ndarray) -> np.ndarray:
            """x_projected = Bᵀ x per sample of ``rows_g`` (RANDOM projector)."""
            rep, fidx = _expand_nnz(rows_g, row_start, row_end)
            return rproj.project_coo(rep, fc[fidx], fv[fidx], len(rows_g))

        if rproj is not None:
            X[e_act, s_act] = random_project(act)
        else:
            local_scatter(
                act, e_act, lambda k, j, v: X.__setitem__((e_act[k], s_act[k], j), v)
            )

        plens = np.array([len(p) for (_, _, p, _) in members], dtype=np.int64)
        n_pas = int(plens.sum())
        pas = (
            np.concatenate([p for (_, _, p, _) in members])
            if n_pas
            else np.empty(0, dtype=np.int64)
        )
        e_pas = np.repeat(np.arange(E, dtype=np.int64), plens)
        pX = np.zeros((n_pas, D), dtype=np.float32)
        if rproj is not None:
            pX = random_project(pas)
        else:
            local_scatter(pas, e_pas, lambda k, j, v: pX.__setitem__((k, j), v))

        buckets.append(
            ReBucket(
                X=jnp.asarray(X),
                labels=jnp.asarray(lab),
                offsets=jnp.asarray(off),
                weights=jnp.asarray(wt),
                sample_pos=jnp.asarray(pos),
                proj_indices=jnp.asarray(pidx),
                proj_valid=jnp.asarray(pval),
            )
        )
        passives.append(
            RePassiveRows(
                X=jnp.asarray(pX),
                entity_index=jnp.asarray(e_pas.astype(np.int32)),
                sample_pos=jnp.asarray(pas.astype(np.int32)),
            )
            if n_pas
            else None
        )
        bucket_ids.append(ids_b)

    return RandomEffectDataset(
        config=config,
        buckets=buckets,
        passive=passives,
        entity_ids=bucket_ids,
        entity_to_loc=entity_to_loc,
        num_rows=n,
        global_dim=int(global_dim),
    )
