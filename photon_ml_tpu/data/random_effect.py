"""Random-effect dataset: entity-grouped padded blocks for vmap'd solves.

Reference parity: data/RandomEffectDataSet.scala:47 (build :240-277 — groupBy
entity with a custom partitioner; active-data reservoir cap :287-388; passive
data :399-446; Pearson feature selection :457-471), data/LocalDataSet.scala:36
(per-entity in-memory dataset, feature selection :221-287, reservoir :289-320),
and projector/IndexMapProjectorRDD.scala:31 (per-entity index map built from
that entity's observed features :164).

TPU-native redesign: instead of an RDD of per-entity Scala objects, the whole
coordinate's data is a handful of dense padded blocks

    X [E, S, D_local]   labels/offsets/weights [E, S]   proj_indices [E, D_local]

where E = entities in a bucket, S = that bucket's max samples/entity, and
D_local = that bucket's max per-entity projected dimension. Entities are
size-bucketed so padding waste stays bounded; one ``vmap`` of the local solver
per bucket replaces millions of ``mapValues`` closures. Per-entity index-map
projection (a sorted list of the entity's observed global feature ids) makes
local problems dense and small — the MXU-friendly layout — exactly the role
the reference's IndexMapProjector plays. Samples beyond the active cap form
the passive set: projected through the same per-entity map, score-only.

All grouping/projection runs host-side in vectorized numpy at data-prep time
(the analog of the reference's one-time shuffle), producing arrays that shard
over the mesh's entity axis with zero training-time communication.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from photon_ml_tpu.utils.nativesort import lexsort_pairs
from flax import struct

from photon_ml_tpu.projector import ProjectorType, RandomProjectionMatrix


@dataclasses.dataclass(frozen=True)
class RandomEffectDataConfiguration:
    """Reference RandomEffectDataConfiguration.scala:42 (string mini-language
    ``reType,shard,numPartitions,activeCap,passiveLB,featureRatio,projector``
    with ``index_map``/``identity``/``random=k``) as a typed config.
    numPartitions is superseded by size-bucketing."""

    random_effect_type: str
    active_data_upper_bound: Optional[int] = None   # max active samples/entity
    passive_data_lower_bound: Optional[int] = None  # min samples for an entity to keep passive rows
    features_to_samples_ratio: Optional[float] = None  # cap D_local <= ratio * n_samples
    max_local_features: Optional[int] = None        # hard cap on D_local
    num_buckets: int = 1
    seed: int = 0
    # Projection of per-entity problems (reference ProjectorType):
    # INDEX_MAP (default, exact remap of observed features), IDENTITY
    # (local space == global space), RANDOM (shared Gaussian matrix,
    # ``projected_dim`` required — the `random=k` mini-language arm).
    projector: ProjectorType = ProjectorType.INDEX_MAP
    projected_dim: Optional[int] = None

    def __post_init__(self) -> None:
        if self.projector is ProjectorType.RANDOM:
            if not self.projected_dim:
                raise ValueError("RANDOM projector requires projected_dim (random=k)")
            if (
                self.features_to_samples_ratio is not None
                or self.max_local_features is not None
            ):
                raise ValueError(
                    "feature selection (features_to_samples_ratio / "
                    "max_local_features) does not apply to the RANDOM "
                    "projector; the projection itself bounds the local dim"
                )


@struct.dataclass
class ReBucket:
    """One size-bucket of entities, fully padded (device pytree)."""

    X: jax.Array             # [E, S, D] local-projected dense features
    labels: jax.Array        # [E, S]
    offsets: jax.Array       # [E, S]
    weights: jax.Array       # [E, S] (0 = padding)
    sample_pos: jax.Array    # [E, S] int32 original row index (0 where padding)
    proj_indices: jax.Array  # [E, D] int32 global feature id per local column
    proj_valid: jax.Array    # [E, D] bool: local column is a real feature

    @property
    def num_entities(self) -> int:
        return self.X.shape[0]

    @property
    def max_samples(self) -> int:
        return self.X.shape[1]

    @property
    def local_dim(self) -> int:
        return self.X.shape[2]


@struct.dataclass
class RePassiveRows:
    """Passive (score-only) rows of one bucket, local-projected. Offsets are
    not stored: passive scoring is the raw x.w gather; score algebra composes
    offsets at the coordinate level."""

    X: jax.Array            # [P, D]
    entity_index: jax.Array  # [P] int32 row into the bucket's entity axis
    sample_pos: jax.Array   # [P] int32 original row index


@dataclasses.dataclass
class RandomEffectDataset:
    """All buckets of one random-effect coordinate + host-side id maps."""

    config: RandomEffectDataConfiguration
    buckets: List[ReBucket]
    passive: List[Optional[RePassiveRows]]   # parallel to buckets
    entity_ids: List[List[str]]              # per bucket, per entity row
    entity_to_loc: Dict[str, Tuple[int, int]]  # id -> (bucket, row)
    num_rows: int                            # total rows in the source data
    global_dim: int
    # row -> slot in the concatenation of per-bucket flattened active score
    # blocks [E*S] (bucket order, each followed by its passive block [P]),
    # with one trailing zero slot for rows no bucket covers. The inverse of
    # the sample_pos scatter: scoring becomes a single gather, which stays
    # vectorized on backends (CPU, TPU) where scatter-add serializes.
    row_gather: Optional[jax.Array] = dataclasses.field(
        default=None, repr=False, compare=False
    )

    @property
    def num_entities(self) -> int:
        return sum(len(ids) for ids in self.entity_ids)

    def to_summary_string(self) -> str:
        """Reference RandomEffectDataSet.toSummaryString
        (RandomEffectDataSet.scala:204-228): active/passive sample counts
        plus this layout's padding accounting. Device-side reductions only
        (collective-safe on sharded buckets — callers must invoke this
        symmetrically on every process, never behind per-process branches)."""
        import jax.numpy as jnp

        active = 0
        cells = 0
        for b in self.buckets:
            active += int(jnp.sum(b.weights > 0))
            cells += int(np.prod(b.weights.shape))
        passive = sum(
            0 if p is None else int(p.sample_pos.shape[0])
            for p in self.passive
        )
        pad = cells / active if active else float("nan")
        return (
            f"random-effect dataset '{self.config.random_effect_type}': "
            f"{self.num_entities} entities in {len(self.buckets)} buckets, "
            f"{active} active samples (padding {pad:.2f}x), "
            f"{passive} passive samples, global dim {self.global_dim}"
        )

    def update_offsets(self, offsets: np.ndarray) -> "RandomEffectDataset":
        """Rebuild the per-bucket offset blocks from a full-data offset vector
        (the residual trick: Coordinate.updateModel / addScoresToOffsets)."""
        from photon_ml_tpu.parallel.mesh import fetch_global

        offsets = np.asarray(offsets, dtype=np.float32)
        new_buckets = []
        for b in self.buckets:
            pos = fetch_global(b.sample_pos)
            wt = fetch_global(b.weights)
            off = np.where(wt > 0, offsets[pos], 0.0).astype(np.float32)
            new_buckets.append(b.replace(offsets=jnp.asarray(off)))
        return dataclasses.replace(self, buckets=new_buckets)

    def gather_index(self) -> jax.Array:
        """The cached ``row_gather`` permutation, built from host copies of
        the bucket layout on first use for datasets that were not produced by
        :func:`build_random_effect_dataset` (which precomputes it so the
        steady-state training loop never touches host memory)."""
        if self.row_gather is None:
            from photon_ml_tpu.parallel.mesh import fetch_global

            self.row_gather = _build_row_gather(
                self.num_rows,
                [
                    (fetch_global(b.sample_pos), fetch_global(b.weights))
                    for b in self.buckets
                ],
                [
                    None if p is None else np.asarray(fetch_global(p.sample_pos))
                    for p in self.passive
                ],
            )
        return self.row_gather

    def update_offsets_device(self, offsets: jax.Array) -> "RandomEffectDataset":
        """Device-plane ``update_offsets``: regroup a full-data device offset
        vector into the entity-grouped [E, S] blocks with one jitted gather
        per bucket. ``sample_pos`` IS the precomputed row -> (bucket, lane,
        slot) permutation from build time, so no host rebuild happens — the
        whole regroup is a device gather masked by the active-slot mask."""
        new_buckets = [
            b.replace(
                offsets=_regroup_offsets(offsets, b.sample_pos, b.weights)
            )
            for b in self.buckets
        ]
        return dataclasses.replace(self, buckets=new_buckets)


def _build_row_gather(
    num_rows: int,
    actives: List[Tuple[np.ndarray, np.ndarray]],
    passive_pos: List[Optional[np.ndarray]],
) -> jax.Array:
    """Invert the (sample_pos, weights>0) scatter into a row -> source-slot
    index over the concatenation [active_b0 | passive_b0 | active_b1 | ...]
    plus one trailing zero slot (rows outside every bucket gather 0.0).
    Active rows are unique across (bucket, lane, slot), so each row has
    exactly one source and the gather reproduces the scatter bitwise."""
    total = sum(pos.size for pos, _ in actives) + sum(
        0 if sp is None else sp.size for sp in passive_pos
    )
    inv = np.full(num_rows, total, dtype=np.int32)
    base = 0
    for (pos, wt), sp in zip(actives, passive_pos):
        flat_pos = np.asarray(pos).ravel()
        m = np.asarray(wt).ravel() > 0
        inv[flat_pos[m]] = (base + np.nonzero(m)[0]).astype(np.int32)
        base += flat_pos.size
        if sp is not None:
            inv[np.asarray(sp)] = (
                base + np.arange(sp.size, dtype=np.int32)
            )
            base += sp.size
    return jnp.asarray(inv)


@jax.jit
def _regroup_offsets(
    offsets: jax.Array, sample_pos: jax.Array, weights: jax.Array
) -> jax.Array:
    """offsets[sample_pos] masked to active slots — the device-resident
    equivalent of the host rebuild in :meth:`RandomEffectDataset
    .update_offsets` (padding slots carry sample_pos 0; the mask keeps their
    offsets at exactly 0 like the host path)."""
    return jnp.where(weights > 0, offsets[sample_pos], 0.0)


def _expand_nnz(
    act_rows: np.ndarray, row_start: np.ndarray, row_end: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Flatten the CSR slices of ``act_rows`` into (sample_index, flat_index)
    pairs: sample_index points back into act_rows, flat_index into fc/fv."""
    cnt = row_end[act_rows] - row_start[act_rows]
    total = int(cnt.sum())
    rep = np.repeat(np.arange(len(act_rows), dtype=np.int64), cnt)
    within = np.arange(total, dtype=np.int64) - np.repeat(np.cumsum(cnt) - cnt, cnt)
    return rep, row_start[act_rows][rep] + within


def _local_dense(
    act_rows: np.ndarray,
    local_cols: np.ndarray,
    row_start: np.ndarray,
    row_end: np.ndarray,
    fc: np.ndarray,
    fv: np.ndarray,
    out: np.ndarray,
) -> None:
    """Scatter the rows' features into ``out[sample, local_col]`` (features
    outside local_cols are dropped — index-map projection semantics)."""
    rep, fidx = _expand_nnz(act_rows, row_start, row_end)
    c, v = fc[fidx], fv[fidx]
    j = np.searchsorted(local_cols, c)
    j_clip = np.minimum(j, max(len(local_cols) - 1, 0))
    match = (j < len(local_cols)) & (local_cols[j_clip] == c) if len(local_cols) else np.zeros(len(c), dtype=bool)
    out[rep[match], j_clip[match]] = v[match]


def _pearson_scores_flat(
    ukeys: np.ndarray,
    ecol: np.ndarray,
    n_ent: int,
    nz_keys: np.ndarray,
    nz_v: np.ndarray,
    y_nz: np.ndarray,
    w_nz: np.ndarray,
    e_act: np.ndarray,
    y_act: np.ndarray,
    w_act: np.ndarray,
) -> np.ndarray:
    """|weighted Pearson| per (entity, local column), computed from segment
    sums over the nonzeros only — the vectorized equivalent of
    :func:`_pearson_scores` over every entity at once (zero feature values
    contribute nothing to the x-moments but their samples still weight the
    label moments, identical to the dense formula)."""
    W = np.bincount(e_act, weights=w_act, minlength=n_ent)
    W = np.maximum(W, 1e-12)
    my = np.bincount(e_act, weights=w_act * y_act, minlength=n_ent) / W
    vy = (
        np.bincount(e_act, weights=w_act * y_act * y_act, minlength=n_ent) / W
        - my * my
    )
    kidx = np.searchsorted(ukeys, nz_keys)
    m = len(ukeys)
    Sx = np.bincount(kidx, weights=w_nz * nz_v, minlength=m)
    Sxx = np.bincount(kidx, weights=w_nz * nz_v * nz_v, minlength=m)
    Sxy = np.bincount(kidx, weights=w_nz * nz_v * y_nz, minlength=m)
    We = W[ecol]
    mx = Sx / We
    cov = Sxy / We - mx * my[ecol]
    vx = Sxx / We - mx * mx
    denom = np.sqrt(np.maximum(vx * vy[ecol], 0.0))
    corr = np.where(denom > 1e-12, np.abs(cov) / np.maximum(denom, 1e-12), 0.0)
    const_nonzero = (vx <= 1e-12) & (np.abs(mx) > 0)
    return np.where(const_nonzero, np.inf, corr)


def _pearson_scores(x: np.ndarray, y: np.ndarray, w: np.ndarray) -> np.ndarray:
    """|Pearson correlation| of each local feature with the label over one
    entity's samples (reference LocalDataSet.scala:221-287). Constant features
    score 0 except an all-constant nonzero column (intercept-like) which the
    reference keeps — we emulate by scoring it +inf."""
    wsum = max(w.sum(), 1e-12)
    mx = (w[:, None] * x).sum(0) / wsum
    my = float((w * y).sum() / wsum)
    dx = x - mx
    dy = y - my
    cov = (w[:, None] * dx * dy[:, None]).sum(0) / wsum
    vx = (w[:, None] * dx * dx).sum(0) / wsum
    vy = float((w * dy * dy).sum() / wsum)
    denom = np.sqrt(np.maximum(vx * vy, 0.0))
    corr = np.where(denom > 1e-12, np.abs(cov) / np.maximum(denom, 1e-12), 0.0)
    # constant nonzero column (e.g. intercept): keep it (reference keeps
    # intercept during feature selection)
    const_nonzero = (vx <= 1e-12) & (np.abs(mx) > 0)
    return np.where(const_nonzero, np.inf, corr)


def _plan_buckets(samples: np.ndarray, dims: np.ndarray, nb: int) -> np.ndarray:
    """Entity → bucket assignment minimizing total padded cells.

    Exact DP over ≤512 candidate boundaries on entities sorted by
    (samples, dims): the cost of a bucket spanning sorted ranks (j, i] is
    count x maxS x maxD — the REAL padded-cell bill of one [E, maxS, maxD]
    block, with the two maxima tracked separately (a product surrogate can
    underestimate ~1000x when samples and dims anti-correlate). O(512² x
    nb) regardless of entity count (candidates are count-quantile
    collapsed, so boundaries are optimal at ~0.2% count granularity).
    The reference bounds the same skew with its partitioner + active cap
    (RandomEffectDataSet.scala:287-388); with dense padded blocks the
    bucket boundaries ARE the balancing mechanism, so they are optimized.
    """
    n = len(samples)
    if nb <= 1 or n <= 1:
        return np.zeros(n, dtype=np.int64)
    order = np.lexsort((dims, samples))
    s_sorted = samples[order].astype(np.float64)
    d_sorted = dims[order].astype(np.float64)
    m = min(512, n)
    bounds = np.unique((np.arange(1, m + 1, dtype=np.int64) * n) // m)  # prefix counts
    G = len(bounds)
    # group g covers sorted ranks [bounds[g-1], bounds[g]); sorted by
    # samples, so a range's maxS is its LAST group's max; maxD needs a
    # running max per range start
    starts = np.concatenate([[0], bounds[:-1]])
    grp_maxS = np.maximum.reduceat(s_sorted, starts)
    grp_maxD = np.maximum.reduceat(d_sorted, starts)
    # maxD[j, i-1] = max of groups j..i-1 (suffix cummax per row); an extra
    # all-zero row for j = G keeps the cand matrix rectangular (that column
    # is forbidden below anyway)
    maxD = np.zeros((G + 1, G))
    for j in range(G):
        maxD[j, j:] = np.maximum.accumulate(grp_maxD[j:])
    C = np.concatenate([[0], bounds]).astype(np.float64)  # [G+1] prefix counts

    # dp[j] = min cost of the first j candidate groups with at most k
    # buckets; splits[k][i-1] remembers the argmin boundary for backtrack
    dp = np.full(G + 1, np.inf)
    dp[0] = 0.0
    row = np.arange(G)[:, None]
    col = np.arange(G + 1)[None, :]
    forbid = col > row  # bucket (j, i] needs j <= i-1, i = row+1
    splits = []
    for _ in range(nb):
        # cand[i-1, j] = dp[j] + (C[i] - C[j]) * maxS(j,i] * maxD(j,i]
        cand = (
            dp[None, :]
            + (C[1:, None] - C[None, :]) * grp_maxS[:, None] * maxD.T
        )  # maxD.T is [G, G+1]: rows i-1, cols j (col G forbidden below)
        cand[forbid] = np.inf
        arg = np.argmin(cand, axis=1)                      # [G]
        best = cand[np.arange(G), arg]
        new_dp = np.concatenate([[0.0], np.minimum(best, dp[1:])])
        # keep the one-fewer-buckets solution where it is already better
        arg = np.where(best <= dp[1:], arg, -1)            # -1 = no new cut
        splits.append(arg)
        dp = new_dp

    # backtrack from the last group through the remembered argmins
    cuts = []
    i = G
    for k in range(len(splits) - 1, -1, -1):
        if i == 0:
            break
        j = int(splits[k][i - 1])
        if j < 0:
            continue  # this level added no bucket ending at i
        cuts.append((j, i))
        i = j
    assert i == 0, "bucket DP backtrack failed to reach the start"
    cuts.reverse()

    bucket_of = np.zeros(n, dtype=np.int64)
    for b, (j, i) in enumerate(cuts):
        lo, hi = int(C[j]), int(C[i])
        bucket_of[order[lo:hi]] = b
    return bucket_of


def build_random_effect_dataset(
    entity_ids: Sequence,
    feature_rows: np.ndarray,
    feature_cols: np.ndarray,
    feature_vals: np.ndarray,
    global_dim: int,
    labels: np.ndarray,
    config: RandomEffectDataConfiguration,
    offsets: Optional[np.ndarray] = None,
    weights: Optional[np.ndarray] = None,
) -> RandomEffectDataset:
    """Group rows by entity, cap/sample, project, bucket, and pad.

    entity_ids: per-row entity key (len n). feature_*: COO triplets over the
    global feature space. Rows with entities are ALL consumed: up to the active
    cap into solver blocks, the remainder into passive (score-only) rows.
    """
    n = len(entity_ids)
    labels = np.asarray(labels, dtype=np.float32)
    offsets = np.zeros(n, dtype=np.float32) if offsets is None else np.asarray(offsets, dtype=np.float32)
    weights = np.ones(n, dtype=np.float32) if weights is None else np.asarray(weights, dtype=np.float32)
    rng = np.random.default_rng(config.seed)

    # Entity codes: np.unique on the raw array (no per-row Python str()); the
    # string form is only materialized once per ENTITY for the id maps.
    ids_arr = np.asarray(entity_ids)
    uniq_raw, codes = np.unique(ids_arr, return_inverse=True)
    uniq = uniq_raw.astype(str)
    n_ent = len(uniq)
    counts = np.bincount(codes, minlength=n_ent)

    # CSR-ify the COO features once (row-sorted)
    feature_rows = np.asarray(feature_rows, dtype=np.int64)
    feature_cols = np.asarray(feature_cols, dtype=np.int64)
    feature_vals = np.asarray(feature_vals, dtype=np.float32)
    forder = lexsort_pairs(feature_rows)
    fr, fc, fv = feature_rows[forder], feature_cols[forder], feature_vals[forder]
    row_start = np.searchsorted(fr, np.arange(n))
    row_end = np.searchsorted(fr, np.arange(n) + 1)

    # ---- active/passive split, all entities at once -----------------------
    # Group rows by entity (random order within an entity when capping) and
    # keep the first `cap` per entity: a uniform without-replacement subset —
    # the vectorized equivalent of the reference's per-entity reservoir
    # (RandomEffectDataSet.scala:325-388).
    cap = config.active_data_upper_bound
    if cap is not None:
        perm = np.lexsort((rng.random(n), codes))
    else:
        perm = lexsort_pairs(codes)
    codes_p = codes[perm]
    ent_start_p = np.searchsorted(codes_p, np.arange(n_ent))
    rank_p = np.arange(n, dtype=np.int64) - ent_start_p[codes_p]
    if cap is not None:
        active_m = rank_p < cap
        lb = config.passive_data_lower_bound
        pas_m = ~active_m
        if lb is not None:
            pas_m &= counts[codes_p] >= lb
    else:
        active_m = np.ones(n, dtype=bool)
        pas_m = np.zeros(n, dtype=bool)
    act = perm[active_m]            # active rows, grouped by entity
    e_act_g = codes_p[active_m]     # entity code per active row
    s_act_g = rank_p[active_m]      # slot within entity
    pas = perm[pas_m]
    e_pas_g = codes_p[pas_m]
    acounts = np.bincount(e_act_g, minlength=n_ent)

    # Active nnz, expanded once (reused by projection + Pearson + scatter).
    rep_a, fidx_a = _expand_nnz(act, row_start, row_end)
    nz_e = e_act_g[rep_a]           # entity code per active nonzero
    nz_c = fc[fidx_a]
    nz_v = fv[fidx_a]

    rproj = (
        RandomProjectionMatrix(
            projected_dim=int(config.projected_dim),
            global_dim=int(global_dim),
            seed=config.seed,
        )
        if config.projector is ProjectorType.RANDOM
        else None
    )
    identity = config.projector is ProjectorType.IDENTITY
    G1 = global_dim + 1

    # ---- per-entity local column maps (INDEX_MAP), no entity loop ---------
    if rproj is not None or identity:
        ukeys = np.empty(0, dtype=np.int64)
        ecol = np.empty(0, dtype=np.int64)
        ucol = np.empty(0, dtype=np.int64)
        dlocs = (
            np.full(n_ent, global_dim, dtype=np.int64)
            if identity
            else np.zeros(n_ent, dtype=np.int64)
        )
    else:
        # observed (entity, col) pairs from ACTIVE data only (reference
        # IndexMapProjectorRDD.scala:164); np.unique returns them sorted by
        # entity then column — exactly the flat local-col layout.
        ukeys = np.unique(nz_e * G1 + nz_c)
        ecol = ukeys // G1
        ucol = ukeys % G1
        dlocs = np.bincount(ecol, minlength=n_ent)

        # feature-selection caps (ratio * samples, hard cap)
        d_cap_e = None
        if config.features_to_samples_ratio is not None:
            d_cap_e = np.maximum(
                (config.features_to_samples_ratio * acounts).astype(np.int64), 1
            )
        if config.max_local_features is not None:
            hard = int(config.max_local_features)
            d_cap_e = np.full(n_ent, hard, dtype=np.int64) if d_cap_e is None else np.minimum(d_cap_e, hard)
        if d_cap_e is not None and np.any(dlocs > d_cap_e):
            scores = _pearson_scores_flat(
                ukeys,
                ecol,
                n_ent,
                nz_keys=nz_e * G1 + nz_c,
                nz_v=nz_v,
                y_nz=labels[act][rep_a],
                w_nz=weights[act][rep_a],
                e_act=e_act_g,
                y_act=labels[act],
                w_act=weights[act],
            )
            # top-k per entity, stable on ties by column order (the flat
            # layout is column-sorted per entity, matching the reference's
            # stable argsort over local columns)
            sel = np.lexsort((np.arange(len(ukeys)), -scores, ecol))
            estart = np.searchsorted(ecol[sel], np.arange(n_ent))
            r2 = np.arange(len(ukeys), dtype=np.int64) - estart[ecol[sel]]
            kept = np.sort(sel[r2 < d_cap_e[ecol[sel]]])
            ukeys, ecol, ucol = ukeys[kept], ecol[kept], ucol[kept]
            dlocs = np.bincount(ecol, minlength=n_ent)

    dstart = np.zeros(n_ent + 1, dtype=np.int64)
    np.cumsum(dlocs, out=dstart[1:])

    # ---- size-bucketing by (samples x local dim) --------------------------
    # Split points are chosen by a small DP that MINIMIZES total padded
    # cells (sum over buckets of count x in-bucket max size): under a Zipf
    # entity-size tail, count-quantiles lump the giant head entities into a
    # bucket with thousands of medium ones (~3x padding measured) and
    # mass-quantiles stretch the tail bucket instead (~6x); the DP places
    # both kinds of boundary where they pay (tests/test_ragged_stress.py
    # gates the measured overhead at <2x).
    nb = max(1, min(config.num_buckets, n_ent))
    dims_e = (
        np.full(n_ent, rproj.projected_dim, dtype=np.int64)
        if rproj
        else np.maximum(dlocs, 1)
    )
    bucket_of = _plan_buckets(acounts, dims_e, nb)
    nb = int(bucket_of.max()) + 1 if n_ent else 1

    # Resolve every active nonzero's local column once (INDEX_MAP only).
    if rproj is None and not identity:
        qk = nz_e * G1 + nz_c
        ii = np.searchsorted(ukeys, qk)
        ii_c = np.minimum(ii, max(len(ukeys) - 1, 0))
        nz_match = (
            (ii < len(ukeys)) & (ukeys[ii_c] == qk)
            if len(ukeys)
            else np.zeros(len(qk), dtype=bool)
        )
        nz_j = ii_c - dstart[nz_e]  # local column per active nonzero
    elif identity:
        nz_match = np.ones(len(nz_c), dtype=bool)
        nz_j = nz_c

    def _project_rows(rows_g: np.ndarray) -> np.ndarray:
        """x_projected = B^T x per sample of ``rows_g`` (RANDOM projector)."""
        rep, fidx = _expand_nnz(rows_g, row_start, row_end)
        return rproj.project_coo(rep, fc[fidx], fv[fidx], len(rows_g))

    buckets: List[ReBucket] = []
    passives: List[Optional[RePassiveRows]] = []
    bucket_ids: List[List[str]] = []
    entity_to_loc: Dict[str, Tuple[int, int]] = {}
    host_actives: List[Tuple[np.ndarray, np.ndarray]] = []
    host_passive_pos: List[Optional[np.ndarray]] = []

    for b in range(nb):
        ent_m = bucket_of == b
        E = int(ent_m.sum())
        if E == 0:
            continue
        bi = len(buckets)
        # Cost-sorted dispatch: entity rows within the bucket are ordered by
        # DESCENDING active sample count (stable), so lockstep lanes carry
        # similar per-iteration work and the adaptive driver's compacted
        # prefixes keep heavy (slow-converging) entities co-scheduled.
        codes_b = np.nonzero(ent_m)[0]
        order_b = np.argsort(-acounts[codes_b], kind="stable")
        new_e = np.zeros(n_ent, dtype=np.int64)  # entity code -> row within bucket
        new_e[codes_b[order_b]] = np.arange(E, dtype=np.int64)
        S = int(acounts[ent_m].max())
        D = int(
            rproj.projected_dim
            if rproj
            else max(int(np.maximum(dlocs[ent_m], 1).max()), 1)
        )

        lab = np.zeros((E, S), dtype=np.float32)
        off = np.zeros((E, S), dtype=np.float32)
        wt = np.zeros((E, S), dtype=np.float32)
        pos = np.zeros((E, S), dtype=np.int32)
        rm = ent_m[e_act_g]
        er, sr = new_e[e_act_g[rm]], s_act_g[rm]
        lab[er, sr] = labels[act[rm]]
        off[er, sr] = offsets[act[rm]]
        wt[er, sr] = weights[act[rm]]
        pos[er, sr] = act[rm]

        pidx = np.zeros((E, D), dtype=np.int32)
        pval = np.zeros((E, D), dtype=bool)
        if rproj is not None:
            # projected-space coordinates are all live; back-projection goes
            # through the shared matrix, not pidx
            pval[:, :] = True
        elif identity:
            pidx[:, :] = np.arange(global_dim, dtype=np.int32)[None, :]
            pval[:, :] = True
        else:
            km = ent_m[ecol]
            jj = np.arange(len(ukeys), dtype=np.int64) - dstart[ecol]
            pidx[new_e[ecol[km]], jj[km]] = ucol[km]
            pval[new_e[ecol[km]], jj[km]] = True

        X = np.zeros((E, S, D), dtype=np.float32)
        if rproj is not None:
            X[er, sr] = _project_rows(act[rm])
        else:
            zm = ent_m[nz_e] & nz_match
            X[new_e[nz_e[zm]], s_act_g[rep_a[zm]], nz_j[zm]] = nz_v[zm]

        pm = ent_m[e_pas_g]
        pas_b = pas[pm]
        n_pas = len(pas_b)
        pX = np.zeros((n_pas, D), dtype=np.float32)
        if n_pas:
            if rproj is not None:
                pX = _project_rows(pas_b)
            else:
                rep_p, fidx_p = _expand_nnz(pas_b, row_start, row_end)
                pc, pv_ = fc[fidx_p], fv[fidx_p]
                pe = e_pas_g[pm][rep_p]
                if identity:
                    pX[rep_p, pc] = pv_
                else:
                    qk = pe * G1 + pc
                    ii = np.searchsorted(ukeys, qk)
                    ii_c = np.minimum(ii, max(len(ukeys) - 1, 0))
                    match = (
                        (ii < len(ukeys)) & (ukeys[ii_c] == qk)
                        if len(ukeys)
                        else np.zeros(len(qk), dtype=bool)
                    )
                    jcol = ii_c - dstart[pe]
                    pX[rep_p[match], jcol[match]] = pv_[match]

        ids_b = uniq[codes_b[order_b]].tolist()
        entity_to_loc.update(
            (eid, (bi, e)) for e, eid in enumerate(ids_b)
        )

        buckets.append(
            ReBucket(
                X=jnp.asarray(X),
                labels=jnp.asarray(lab),
                offsets=jnp.asarray(off),
                weights=jnp.asarray(wt),
                sample_pos=jnp.asarray(pos),
                proj_indices=jnp.asarray(pidx),
                proj_valid=jnp.asarray(pval),
            )
        )
        passives.append(
            RePassiveRows(
                X=jnp.asarray(pX),
                entity_index=jnp.asarray(new_e[e_pas_g[pm]].astype(np.int32)),
                sample_pos=jnp.asarray(pas_b.astype(np.int32)),
            )
            if n_pas
            else None
        )
        bucket_ids.append(ids_b)
        host_actives.append((pos, wt))
        host_passive_pos.append(
            pas_b.astype(np.int32) if n_pas else None
        )

    return RandomEffectDataset(
        config=config,
        buckets=buckets,
        passive=passives,
        entity_ids=bucket_ids,
        entity_to_loc=entity_to_loc,
        num_rows=n,
        global_dim=int(global_dim),
        row_gather=_build_row_gather(n, host_actives, host_passive_pos),
    )


def pad_entities_to_multiple(
    dataset: RandomEffectDataset, multiple: int
) -> RandomEffectDataset:
    """Pad every bucket's entity axis to a multiple (weight-0 entities with
    no real samples/features). Padded entity lanes carry no entity ids, so
    model extraction and scoring ignore them; padding once at build time
    keeps model/array shapes stable across coordinate-descent updates."""
    if multiple <= 1:
        return dataset
    new_buckets = []
    padded_any = False
    for b in dataset.buckets:
        pad = (-b.num_entities) % multiple
        if pad == 0:
            new_buckets.append(b)
            continue
        padded_any = True
        def pad0(a):
            return jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
        new_buckets.append(
            ReBucket(
                X=pad0(b.X),
                labels=pad0(b.labels),
                offsets=pad0(b.offsets),
                weights=pad0(b.weights),
                sample_pos=pad0(b.sample_pos),
                proj_indices=pad0(b.proj_indices),
                proj_valid=pad0(b.proj_valid),
            )
        )
    if not padded_any:
        return dataset
    # entity padding grows the flattened [E*S] blocks: the cached row_gather
    # slots shift, so drop it and let gather_index() rebuild lazily
    return dataclasses.replace(
        dataset, buckets=new_buckets, row_gather=None
    )


def place_dataset(dataset: RandomEffectDataset, mesh, axis_names) -> "RandomEffectDataset":
    """Shard every bucket's entity axis over the given mesh axes (replicated
    otherwise). Entity solves are independent, so this is pure data
    parallelism with zero collectives inside the vmap'd solver."""
    from jax.sharding import PartitionSpec as P

    from photon_ml_tpu.parallel.mesh import place

    def put(a):
        return place(a, mesh, P(axis_names, *([None] * (a.ndim - 1))))

    new_buckets = [jax.tree.map(put, b) for b in dataset.buckets]
    return dataclasses.replace(dataset, buckets=new_buckets)
