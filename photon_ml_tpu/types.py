"""Core shared types and enums.

Reference parity: photon-lib constants (TaskType.scala:20-24, Types.scala:21-43,
MathConst.scala). Spark-specific storage levels have no equivalent here.
"""

from __future__ import annotations

import enum

# Type aliases mirroring reference Types.scala:21-43. Sample ids are positions
# into dense arrays rather than RDD keys.
CoordinateId = str
FeatureShardId = str
REType = str  # random effect type, e.g. "userId"
REId = str  # a single random effect entity id


class TaskType(enum.Enum):
    """Training task (reference TaskType.scala:20-24)."""

    LINEAR_REGRESSION = "linear_regression"
    LOGISTIC_REGRESSION = "logistic_regression"
    POISSON_REGRESSION = "poisson_regression"
    SMOOTHED_HINGE_LOSS_LINEAR_SVM = "smoothed_hinge_loss_linear_svm"

    @property
    def is_classification(self) -> bool:
        return self in (
            TaskType.LOGISTIC_REGRESSION,
            TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
        )


class NormalizationType(enum.Enum):
    """Feature normalization modes (reference NormalizationType)."""

    NONE = "none"
    SCALE_WITH_MAX_MAGNITUDE = "scale_with_max_magnitude"
    SCALE_WITH_STANDARD_DEVIATION = "scale_with_standard_deviation"
    STANDARDIZATION = "standardization"


class RegularizationType(enum.Enum):
    """Regularization family (reference RegularizationType)."""

    NONE = "none"
    L1 = "l1"
    L2 = "l2"
    ELASTIC_NET = "elastic_net"


class ConvergenceReason(enum.Enum):
    """Why an optimizer stopped (reference util/ConvergenceReason.scala:21).

    Encoded as int32 device-side; see opt/solver_state.py.
    """

    NOT_CONVERGED = 0
    MAX_ITERATIONS = 1
    FUNCTION_VALUES_CONVERGED = 2
    GRADIENT_CONVERGED = 3
    OBJECTIVE_NOT_IMPROVING = 4


# Numerical constants (reference constants/MathConst.scala).
POSITIVE_RESPONSE_THRESHOLD = 0.5
EPSILON = 1e-7
