from photon_ml_tpu.stat.summary import BasicStatisticalSummary, summarize

__all__ = ["BasicStatisticalSummary", "summarize"]
