"""Per-feature summary statistics for normalization and diagnostics.

Reference parity: stat/BasicStatisticalSummary.scala:50, which wrapped Spark
MLlib's MultivariateOnlineSummarizer (weighted mean/variance/min/max/nnz/count)
computed with a treeAggregate. Here it is one jit-compiled pass over the batch
— and because every op is a reduction over the batch axis, running it on
data sharded over a mesh's batch axis makes XLA insert the psums automatically.

Variance is the unbiased weighted sample variance matching MLlib's estimator
so normalization factors line up with the reference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.ops.features import DenseFeatures, EllFeatures


@struct.dataclass
class BasicStatisticalSummary:
    mean: jax.Array           # [d] weighted mean
    variance: jax.Array       # [d] unbiased weighted variance
    num_nonzeros: jax.Array   # [d] weighted count of nonzero entries
    max_abs: jax.Array        # [d] max |x| (0 for all-zero features)
    min_val: jax.Array        # [d] min over observed values incl. implicit zeros
    max_val: jax.Array        # [d] max over observed values incl. implicit zeros
    count: jax.Array          # scalar total weight
    mean_abs: jax.Array       # [d] weighted mean of |x| (reference meanAbs,
    #                           used by ExpectedMagnitude feature importance)


def _dense_stats(matrix, weights):
    wsum = jnp.sum(weights)
    w = weights[:, None]
    s1 = jnp.sum(w * matrix, axis=0)
    s2 = jnp.sum(w * matrix * matrix, axis=0)
    sabs = jnp.sum(w * jnp.abs(matrix), axis=0)
    nnz = jnp.sum(jnp.where(matrix != 0, w, 0.0), axis=0)
    mx = jnp.max(jnp.where(weights[:, None] > 0, matrix, -jnp.inf), axis=0)
    mn = jnp.min(jnp.where(weights[:, None] > 0, matrix, jnp.inf), axis=0)
    return s1, s2, sabs, nnz, mn, mx, wsum


def _ell_stats(feats: EllFeatures, weights):
    d = feats.num_cols
    wsum = jnp.sum(weights)
    w = weights[:, None]
    wv = w * feats.values
    zeros = lambda: jnp.zeros((d,), dtype=feats.values.dtype)
    s1 = zeros().at[feats.indices].add(wv)
    s2 = zeros().at[feats.indices].add(wv * feats.values)
    sabs = zeros().at[feats.indices].add(jnp.abs(wv))
    nnz = zeros().at[feats.indices].add(jnp.where(feats.values != 0, w, 0.0))
    # min/max over EXPLICIT values; implicit zeros folded in afterwards
    mx = jnp.full((d,), -jnp.inf, dtype=feats.values.dtype).at[feats.indices].max(
        jnp.where((feats.values != 0) & (w > 0), feats.values, -jnp.inf)
    )
    mn = jnp.full((d,), jnp.inf, dtype=feats.values.dtype).at[feats.indices].min(
        jnp.where((feats.values != 0) & (w > 0), feats.values, jnp.inf)
    )
    return s1, s2, sabs, nnz, mn, mx, wsum


def _benes_stats(feats, weights):
    """Stats through the permutation engine's own linear maps: the weighted
    sums are rmatvec-style reductions; min/max route the row-weight mask to
    the column-grouped side once and reduce per column there."""
    d = feats.dim
    wsum = jnp.sum(weights)
    ell = feats.ell_values
    hot = feats.hot_matrix
    sp = feats.spill_vals
    s1 = feats.rmatvec(weights)
    s2 = feats.rmatvec_sq(weights)
    sabs = feats._rmatvec_impl(
        jnp.abs(ell), None if hot is None else jnp.abs(hot), weights,
        None if sp is None else jnp.abs(sp),
    )
    nnz = feats._rmatvec_impl(
        (ell != 0).astype(ell.dtype),
        None if hot is None else (hot != 0).astype(ell.dtype),
        weights,
        None if sp is None else (sp != 0).astype(ell.dtype),
    )
    # live-row mask routed to CSC slot order: explicit entries of columns
    # are contiguous there, so per-column min/max are row reductions
    n, k = ell.shape
    mask_ell = jnp.broadcast_to((weights > 0)[:, None], (n, k)).astype(ell.dtype)
    mask_flat = feats._pad_ell(mask_ell.reshape(-1))
    dkp = feats.csc_values.shape[0] * feats.csc_values.shape[1]
    mask_csc = feats._to_csc(mask_flat)[:dkp].reshape(feats.csc_values.shape)
    live = (feats.csc_values != 0) & (mask_csc > 0)
    mx = jnp.max(
        jnp.where(live, feats.csc_values, -jnp.inf), axis=1
    )
    mn = jnp.min(
        jnp.where(live, feats.csc_values, jnp.inf), axis=1
    )
    mn, mx = _fold_hot_minmax(mn, mx, hot, feats.hot_cols, weights)
    mn, mx = _fold_spill_minmax(mn, mx, feats, weights)
    return s1, s2, sabs, nnz, mn, mx, wsum


def _fused_stats(feats, weights):
    """Stats through the fused engine's transformed linear maps; min/max
    route the live-masked values to the column-grouped side once (plain
    permutation — stats run once, not per optimizer step)."""
    wsum = jnp.sum(weights)
    s1 = feats.rmatvec(weights)
    s2 = feats.rmatvec_sq(weights)
    sabs = feats._rmatvec_impl(weights, transform="abs")
    nnz = feats._rmatvec_impl(weights, transform="nnz")

    w_slots = feats.weights_to_slots(weights)
    live = (feats.ell_flat != 0) & (w_slots > 0)
    big = jnp.asarray(jnp.inf, feats.ell_flat.dtype)
    mx = jnp.max(
        feats.csc_view(jnp.where(live, feats.ell_flat, -big)), axis=1
    )
    mn = jnp.min(
        feats.csc_view(jnp.where(live, feats.ell_flat, big)), axis=1
    )
    hot = feats.hot_matrix
    mn, mx = _fold_hot_minmax(mn, mx, hot, feats.hot_cols, weights)
    mn, mx = _fold_spill_minmax(mn, mx, feats, weights)
    return s1, s2, sabs, nnz, mn, mx, wsum


def _split_stats(feats, weights):
    """Stats for a ColumnSplitFeatures: per-block engine stats concatenated
    on the column axis, the global hot side folded in afterwards."""
    from photon_ml_tpu.ops.fused_perm import FusedBenesFeatures
    from photon_ml_tpu.ops.sparse_perm import (
        BenesSparseFeatures,
        _ZeroColumnsBlock,
    )

    wsum = jnp.sum(weights)
    parts = []
    for blk in feats.blocks:
        if isinstance(blk, _ZeroColumnsBlock):
            d_b = blk.num_cols_
            z = jnp.zeros((d_b,), dtype=jnp.float32)
            parts.append((
                z, z, z, z,
                jnp.full((d_b,), jnp.inf, dtype=jnp.float32),
                jnp.full((d_b,), -jnp.inf, dtype=jnp.float32),
                wsum,
            ))
        elif isinstance(blk, BenesSparseFeatures):
            parts.append(_benes_stats(blk, weights))
        elif isinstance(blk, FusedBenesFeatures):
            parts.append(_fused_stats(blk, weights))
        else:
            raise TypeError(f"unknown column block type {type(blk)!r}")
    d = feats.num_cols_
    # pinned grid layouts give uniform block widths that may overhang the
    # true column count; trim like ColumnSplitFeatures.rmatvec does
    s1, s2, sabs, nnz, mn, mx = (
        jnp.concatenate([p[i] for p in parts])[:d] for i in range(6)
    )
    hot = feats.hot_matrix
    if hot is not None:
        w = weights[:, None]
        hc = feats.hot_cols
        s1 = s1.at[hc].add(jnp.sum(w * hot, axis=0))
        s2 = s2.at[hc].add(jnp.sum(w * hot * hot, axis=0))
        sabs = sabs.at[hc].add(jnp.sum(w * jnp.abs(hot), axis=0))
        nnz = nnz.at[hc].add(jnp.sum(jnp.where(hot != 0, w, 0.0), axis=0))
        mn, mx = _fold_hot_minmax(mn, mx, hot, hc, weights)
    return s1, s2, sabs, nnz, mn, mx, wsum


def _fold_spill_minmax(mn, mx, feats, weights):
    """Fold a KP-cap spill side's values into per-column min/max — shared by
    both permutation engines' stats paths."""
    sv = feats.spill_vals
    if sv is None:
        return mn, mx
    live = (sv != 0) & (weights[feats.spill_rows] > 0)
    big = jnp.asarray(jnp.inf, sv.dtype)
    mn = mn.at[feats.spill_cols].min(jnp.where(live, sv, big))
    mx = mx.at[feats.spill_cols].max(jnp.where(live, sv, -big))
    return mn, mx


def _fold_hot_minmax(mn, mx, hot, hot_cols, weights):
    """Fold a hot-column dense side's per-column min/max into (mn, mx) —
    shared by both permutation engines' stats paths."""
    if hot is None:
        return mn, mx
    hlive = (hot != 0) & (weights > 0)[:, None]
    hmx = jnp.max(jnp.where(hlive, hot, -jnp.inf), axis=0)
    hmn = jnp.min(jnp.where(hlive, hot, jnp.inf), axis=0)
    return mn.at[hot_cols].min(hmn), mx.at[hot_cols].max(hmx)


def summarize(data: LabeledData) -> BasicStatisticalSummary:
    from photon_ml_tpu.ops.fused_perm import FusedBenesFeatures
    from photon_ml_tpu.ops.sparse_perm import (
        BenesSparseFeatures,
        ColumnSplitFeatures,
    )

    feats = data.features
    if isinstance(feats, DenseFeatures):
        s1, s2, sabs, nnz, mn, mx, wsum = _dense_stats(feats.matrix, data.weights)
        sparse = False
    elif isinstance(feats, ColumnSplitFeatures):
        s1, s2, sabs, nnz, mn, mx, wsum = _split_stats(feats, data.weights)
        sparse = True
    elif isinstance(feats, BenesSparseFeatures):
        s1, s2, sabs, nnz, mn, mx, wsum = _benes_stats(feats, data.weights)
        sparse = True
    elif isinstance(feats, FusedBenesFeatures):
        s1, s2, sabs, nnz, mn, mx, wsum = _fused_stats(feats, data.weights)
        sparse = True
    else:
        s1, s2, sabs, nnz, mn, mx, wsum = _ell_stats(feats, data.weights)
        sparse = True

    mean = s1 / jnp.maximum(wsum, 1e-30)
    # unbiased weighted variance (MLlib): (s2 - wsum*mean^2) / (wsum - 1)
    var = jnp.maximum(s2 - wsum * mean * mean, 0.0) / jnp.maximum(wsum - 1.0, 1e-30)

    if sparse:
        # features with implicit zeros extend min/max to include 0
        has_implicit_zero = nnz < wsum
        mx = jnp.where(jnp.isneginf(mx), 0.0, jnp.where(has_implicit_zero, jnp.maximum(mx, 0.0), mx))
        mn = jnp.where(jnp.isposinf(mn), 0.0, jnp.where(has_implicit_zero, jnp.minimum(mn, 0.0), mn))
    else:
        mx = jnp.where(jnp.isneginf(mx), 0.0, mx)
        mn = jnp.where(jnp.isposinf(mn), 0.0, mn)

    max_abs = jnp.maximum(jnp.abs(mx), jnp.abs(mn))
    return BasicStatisticalSummary(
        mean=mean,
        variance=var,
        num_nonzeros=nnz,
        max_abs=max_abs,
        min_val=mn,
        max_val=mx,
        count=wsum,
        mean_abs=sabs / jnp.maximum(wsum, 1e-30),
    )
