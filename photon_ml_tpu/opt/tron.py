"""TRON: trust-region Newton method, fully on device.

Reference parity: optimization/TRON.scala:80 (itself a port of LIBLINEAR's
tron.cpp): outer trust-region loop (:148-250) with truncated conjugate-gradient
inner solves over Hessian-vector products (:275-335), eta/sigma trust-radius
constants (:97-98), maxNumImprovementFailures=5, defaults maxIter=15,
≤20 CG iterations, tol=1e-5 (:253-259).

In the reference every CG step paid a Spark treeAggregate for its
Hessian-vector product (HessianVectorAggregator.scala:145); here each Hv is a
fused XLA computation (or a psum'd sharded one), and the entire outer loop is
one ``lax.while_loop`` program.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from photon_ml_tpu.losses.objective import GlmObjective
from photon_ml_tpu.opt.config import OptimizerConfig
from photon_ml_tpu.opt.lbfgs import _project_box, resolve_box
from photon_ml_tpu.opt.state import (
    SolveResult,
    absolute_tolerances,
    function_values_converged,
    gradient_converged,
)
from photon_ml_tpu.types import ConvergenceReason

# Trust-region update constants (reference TRON.scala:97-98 / LIBLINEAR).
ETA0, ETA1, ETA2 = 1e-4, 0.25, 0.75
SIGMA1, SIGMA2, SIGMA3 = 0.25, 0.5, 4.0


class _CgState(NamedTuple):
    s: jax.Array
    r: jax.Array
    d: jax.Array
    rtr: jax.Array
    it: jax.Array
    done: jax.Array


def _truncated_cg(hess_vec, g, delta, max_cg: int, cg_tol: float):
    """Steihaug truncated CG: approximately solve H s = -g with ||s|| <= delta.

    Returns (s, r) where r is the final residual -g - H s (used for the
    predicted-reduction formula, reference TRON.scala:275-335).
    """
    r0 = -g
    stop_norm = cg_tol * jnp.linalg.norm(g)
    init = _CgState(
        s=jnp.zeros_like(g),
        r=r0,
        d=r0,
        rtr=jnp.dot(r0, r0),
        it=jnp.int32(0),
        done=jnp.sqrt(jnp.dot(r0, r0)) <= stop_norm,
    )

    def cond(c: _CgState):
        return (~c.done) & (c.it < max_cg)

    def body(c: _CgState) -> _CgState:
        hd = hess_vec(c.d)
        dhd = jnp.dot(c.d, hd)
        alpha = c.rtr / jnp.where(dhd <= 0, 1e-30, dhd)
        s_try = c.s + alpha * c.d

        # Negative curvature or boundary hit: move to the trust-region edge
        # along d and stop.
        hit = (dhd <= 0) | (jnp.linalg.norm(s_try) > delta)
        std = jnp.dot(c.s, c.d)
        dd = jnp.dot(c.d, c.d)
        ss = jnp.dot(c.s, c.s)
        rad = jnp.sqrt(jnp.maximum(std * std + dd * (delta * delta - ss), 0.0))
        tau = (-std + rad) / jnp.maximum(dd, 1e-30)
        s_edge = c.s + tau * c.d
        r_edge = c.r - tau * hd

        s_new = jnp.where(hit, s_edge, s_try)
        r_new = jnp.where(hit, r_edge, c.r - alpha * hd)
        rtr_new = jnp.dot(r_new, r_new)
        converged = jnp.sqrt(rtr_new) <= stop_norm
        beta = rtr_new / jnp.maximum(c.rtr, 1e-30)
        d_new = jnp.where(hit | converged, c.d, r_new + beta * c.d)
        return _CgState(
            s=s_new,
            r=r_new,
            d=d_new,
            rtr=rtr_new,
            it=c.it + 1,
            done=hit | converged,
        )

    out = jax.lax.while_loop(cond, body, init)
    return out.s, out.r


class _TronState(NamedTuple):
    """Resumable TRON loop state: carries the trust radius and init-derived
    tolerances so chunked execution (``tron_chunk`` every K iterations)
    follows the one-shot trajectory exactly."""

    w: jax.Array
    f: jax.Array
    g: jax.Array
    delta: jax.Array
    it: jax.Array
    failures: jax.Array
    reason: jax.Array
    history: jax.Array
    w_hist: jax.Array     # [max_iter+1, d] coefficients (or [0] when off)
    abs_f_tol: jax.Array
    abs_g_tol: jax.Array


def tron_init(
    objective: GlmObjective,
    w0: jax.Array,
    data,
    l2_weight: jax.Array,
    config: OptimizerConfig = OptimizerConfig.tron(),
) -> _TronState:
    if not objective.has_hessian:
        raise ValueError(
            "TRON requires a twice-differentiable objective; smoothed hinge "
            "is first-order only (use LBFGS, reference OptimizerFactory.scala)"
        )
    max_iter = config.max_iterations
    dtype = w0.dtype

    f0, g0 = objective.value_and_grad(w0, data, l2_weight)
    g0_norm = jnp.linalg.norm(g0)
    abs_f_tol, abs_g_tol = absolute_tolerances(f0, g0_norm, config.tolerance)

    history0 = jnp.full((max_iter + 1,), jnp.nan, dtype=dtype).at[0].set(f0)
    w_hist0 = (
        jnp.full((max_iter + 1,) + w0.shape, jnp.nan, dtype=dtype).at[0].set(w0)
        if config.track_coefficients
        else jnp.zeros((0,), dtype=dtype)
    )
    return _TronState(
        w=w0,
        f=f0,
        g=g0,
        delta=g0_norm,  # initial radius = ||g0|| (reference TRON.scala:112)
        it=jnp.int32(0),
        failures=jnp.int32(0),
        reason=jnp.where(
            g0_norm <= abs_g_tol,
            jnp.int32(ConvergenceReason.GRADIENT_CONVERGED.value),
            jnp.int32(ConvergenceReason.NOT_CONVERGED.value),
        ),
        history=history0,
        w_hist=w_hist0,
        abs_f_tol=abs_f_tol,
        abs_g_tol=abs_g_tol,
    )


def tron_chunk(
    objective: GlmObjective,
    state: _TronState,
    data,
    l2_weight: jax.Array,
    config: OptimizerConfig = OptimizerConfig.tron(),
    box=None,
    num_iters=None,
) -> _TronState:
    """Advance by at most ``num_iters`` outer iterations (None = to the
    end); same chunking contract as ``lbfgs_chunk``."""
    max_iter = config.max_iterations
    box_lo, box_hi, has_box = resolve_box(box, config)
    it_stop = None if num_iters is None else state.it + jnp.int32(num_iters)

    def cond(s: _TronState):
        c = (s.reason == ConvergenceReason.NOT_CONVERGED.value) & (s.it < max_iter)
        if it_stop is not None:
            c = c & (s.it < it_stop)
        return c

    def body(s: _TronState) -> _TronState:
        hv = lambda v: objective.hessian_vec(s.w, v, data, l2_weight)
        step, resid = _truncated_cg(
            hv, s.g, s.delta, config.max_cg_iterations, config.cg_tolerance
        )
        w_try = s.w + step
        if has_box:
            w_try = _project_box(w_try, box_lo, box_hi)
            step = w_try - s.w
        f_try, g_try = objective.value_and_grad(w_try, data, l2_weight)

        gs = jnp.dot(s.g, step)
        prered = -0.5 * (gs - jnp.dot(step, resid))
        actred = s.f - f_try
        snorm = jnp.linalg.norm(step)

        # Trust-radius update (reference TRON.scala:200-240 / LIBLINEAR).
        denom = f_try - s.f - gs
        alpha = jnp.where(
            -actred <= gs,
            SIGMA3,
            jnp.maximum(SIGMA1, -0.5 * (gs / jnp.where(jnp.abs(denom) < 1e-30, 1e-30, denom))),
        )
        delta = jnp.where(
            actred < ETA0 * prered,
            jnp.minimum(jnp.maximum(alpha, SIGMA1) * snorm, SIGMA2 * s.delta),
            jnp.where(
                actred < ETA1 * prered,
                jnp.maximum(SIGMA1 * s.delta, jnp.minimum(alpha * snorm, SIGMA2 * s.delta)),
                jnp.where(
                    actred < ETA2 * prered,
                    jnp.maximum(SIGMA1 * s.delta, jnp.minimum(alpha * snorm, SIGMA3 * s.delta)),
                    jnp.maximum(s.delta, jnp.minimum(alpha * snorm, SIGMA3 * s.delta)),
                ),
            ),
        )

        accept = actred > ETA0 * prered
        failures = jnp.where(accept, s.failures, s.failures + 1)
        w_new = jnp.where(accept, w_try, s.w)
        f_new = jnp.where(accept, f_try, s.f)
        g_new = jnp.where(accept, g_try, s.g)

        it = s.it + 1
        g_conv = gradient_converged(jnp.linalg.norm(g_new), s.abs_g_tol)
        f_conv = accept & function_values_converged(s.f, f_new, s.abs_f_tol)
        too_many_failures = failures >= config.max_improvement_failures
        degenerate = (prered <= 0) & (actred <= 0)
        reason = jnp.where(
            g_conv,
            ConvergenceReason.GRADIENT_CONVERGED.value,
            jnp.where(
                f_conv,
                ConvergenceReason.FUNCTION_VALUES_CONVERGED.value,
                jnp.where(
                    too_many_failures | degenerate,
                    ConvergenceReason.OBJECTIVE_NOT_IMPROVING.value,
                    jnp.where(
                        it >= max_iter,
                        ConvergenceReason.MAX_ITERATIONS.value,
                        ConvergenceReason.NOT_CONVERGED.value,
                    ),
                ),
            ),
        ).astype(jnp.int32)

        return _TronState(
            w=w_new,
            f=f_new,
            g=g_new,
            delta=delta,
            it=it,
            failures=failures,
            reason=reason,
            history=s.history.at[it].set(f_new),
            w_hist=(
                s.w_hist.at[it].set(w_new)
                if config.track_coefficients
                else s.w_hist
            ),
            abs_f_tol=s.abs_f_tol,
            abs_g_tol=s.abs_g_tol,
        )

    return jax.lax.while_loop(cond, body, state)


def tron_finalize(
    state: _TronState, config: OptimizerConfig = OptimizerConfig.tron()
) -> SolveResult:
    """Convert a (fully run) loop state into the public SolveResult."""
    reason = jnp.where(
        state.reason == ConvergenceReason.NOT_CONVERGED.value,
        jnp.int32(ConvergenceReason.MAX_ITERATIONS.value),
        state.reason,
    )
    return SolveResult(
        w=state.w,
        value=state.f,
        grad_norm=jnp.linalg.norm(state.g),
        iterations=state.it,
        reason=reason,
        value_history=state.history,
        w_history=state.w_hist if config.track_coefficients else None,
    )


def tron_solve(
    objective: GlmObjective,
    w0: jax.Array,
    data,
    l2_weight: jax.Array,
    config: OptimizerConfig = OptimizerConfig.tron(),
    box=None,
) -> SolveResult:
    state = tron_init(objective, w0, data, l2_weight, config)
    state = tron_chunk(objective, state, data, l2_weight, config, box=box)
    return tron_finalize(state, config)
