"""OWL-QN: Orthant-Wise Limited-memory Quasi-Newton for L1 / elastic net.

Reference parity: optimization/OWLQN.scala:40, which wrapped
``breeze.optimize.OWLQN``; the L1 weight is applied at the optimizer level —
never inside the smooth objective (the L2 part of elastic net stays in the
objective). Algorithm follows Andrew & Gao (2007):

- pseudo-gradient: subgradient of f(w) + l1*||w||_1 choosing the orthant of
  steepest descent at w_j = 0
- two-loop direction computed from SMOOTH gradient history, then aligned
  (projected) against the pseudo-gradient
- line search over orthant-projected points pi(w + t*d; xi) with a
  backtracking sufficient-decrease condition on F = f + l1*||w||_1
  (Breeze's OWLQN uses the same backtracking scheme)

Box constraints compose with L1 exactly as in the reference: OWLQN.scala:46
passes the constraint map up to LBFGS.scala:72, which projects the iterate
into the box after each accepted step; here the projected point's value and
gradient are recomputed so the curvature pairs stay consistent.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from photon_ml_tpu.losses.objective import GlmObjective
from photon_ml_tpu.opt.config import OptimizerConfig
from photon_ml_tpu.opt.lbfgs import (
    _project_box,
    resolve_box,
    resolve_history_dtype,
    two_loop_direction,
    update_history,
)
from photon_ml_tpu.opt.state import (
    SolveResult,
    absolute_tolerances,
    function_values_converged,
    gradient_converged,
)
from photon_ml_tpu.types import ConvergenceReason


def pseudo_gradient(w: jax.Array, g: jax.Array, l1: jax.Array) -> jax.Array:
    """Subgradient of f + l1*|w|_1 with steepest-descent tie-breaking at 0."""
    at_zero = w == 0
    pg_nonzero = g + l1 * jnp.sign(w)
    # at w_j = 0 the subdifferential is [g - l1, g + l1]; the minimal-norm
    # element is 0 if the interval contains 0, else the closest endpoint.
    pg_zero = jnp.where(g + l1 < 0, g + l1, jnp.where(g - l1 > 0, g - l1, 0.0))
    return jnp.where(at_zero, pg_zero, pg_nonzero)


def _project_orthant(w: jax.Array, xi: jax.Array) -> jax.Array:
    """pi(w; xi): zero out coordinates that left the orthant xi."""
    return jnp.where(jnp.sign(w) == xi, w, 0.0)


class _OwlqnState(NamedTuple):
    """Resumable OWL-QN loop state (see _LbfgsState): carries the L1 weight
    and the init-derived tolerances so chunked execution — ``owlqn_chunk``
    every K iterations — follows the one-shot trajectory exactly."""

    w: jax.Array
    f: jax.Array          # smooth f (no L1)
    g: jax.Array          # smooth gradient
    F: jax.Array          # f + l1*|w|_1
    s_hist: jax.Array
    y_hist: jax.Array
    rho: jax.Array
    count: jax.Array
    it: jax.Array
    reason: jax.Array
    history: jax.Array
    w_hist: jax.Array     # [max_iter+1, d] coefficients (or [0] when off)
    l1: jax.Array         # scalar L1 weight (traced)
    abs_f_tol: jax.Array
    abs_g_tol: jax.Array


def owlqn_init(
    objective: GlmObjective,
    w0: jax.Array,
    data,
    l2_weight: jax.Array,
    l1_weight: jax.Array,
    config: OptimizerConfig = OptimizerConfig(),
) -> _OwlqnState:
    m = config.history_length
    max_iter = config.max_iterations
    dim = w0.shape[-1]
    dtype = w0.dtype
    l1 = jnp.asarray(l1_weight, dtype=dtype)

    f0, g0 = objective.value_and_grad(w0, data, l2_weight)
    F0 = f0 + l1 * jnp.sum(jnp.abs(w0))
    pg0 = pseudo_gradient(w0, g0, l1)
    pg0_norm = jnp.linalg.norm(pg0)
    abs_f_tol, abs_g_tol = absolute_tolerances(F0, pg0_norm, config.tolerance)

    hdtype = resolve_history_dtype(config, dtype)
    history0 = jnp.full((max_iter + 1,), jnp.nan, dtype=dtype).at[0].set(F0)
    w_hist0 = (
        jnp.full((max_iter + 1, dim), jnp.nan, dtype=dtype).at[0].set(w0)
        if config.track_coefficients
        else jnp.zeros((0,), dtype=dtype)
    )
    return _OwlqnState(
        w=w0,
        f=f0,
        g=g0,
        F=F0,
        s_hist=jnp.zeros((m, dim), dtype=hdtype),
        y_hist=jnp.zeros((m, dim), dtype=hdtype),
        rho=jnp.zeros((m,), dtype=dtype),
        count=jnp.int32(0),
        it=jnp.int32(0),
        reason=jnp.int32(ConvergenceReason.NOT_CONVERGED.value),
        history=history0,
        w_hist=w_hist0,
        l1=l1,
        abs_f_tol=abs_f_tol,
        abs_g_tol=abs_g_tol,
    )


def owlqn_chunk(
    objective: GlmObjective,
    state: _OwlqnState,
    data,
    l2_weight: jax.Array,
    config: OptimizerConfig = OptimizerConfig(),
    box=None,
    num_iters=None,
) -> _OwlqnState:
    """Advance by at most ``num_iters`` outer iterations (None = to the
    end); same chunking contract as ``lbfgs_chunk``."""
    box_lo, box_hi, has_box = resolve_box(box, config)
    max_iter = config.max_iterations
    dtype = state.w.dtype
    l1 = state.l1
    it_stop = None if num_iters is None else state.it + jnp.int32(num_iters)

    GAMMA = 1e-4  # sufficient-decrease constant (Andrew & Gao use 1e-4)
    BACKTRACK = 0.5

    def cond(s: _OwlqnState):
        c = (s.reason == ConvergenceReason.NOT_CONVERGED.value) & (s.it < max_iter)
        if it_stop is not None:
            c = c & (s.it < it_stop)
        return c

    def body(s: _OwlqnState) -> _OwlqnState:
        pg = pseudo_gradient(s.w, s.g, l1)
        d = two_loop_direction(pg, s.s_hist, s.y_hist, s.rho, s.count)
        # align direction with -pg (zero disagreeing coordinates)
        d = jnp.where(d * pg < 0, d, 0.0)
        # orthant to search in: sign(w), or sign(-pg) where w = 0
        xi = jnp.where(s.w != 0, jnp.sign(s.w), jnp.sign(-pg))

        t0 = jnp.where(s.count == 0, 1.0 / jnp.maximum(jnp.linalg.norm(d), 1e-12), 1.0)

        class _LS(NamedTuple):
            t: jax.Array
            i: jax.Array
            w_t: jax.Array
            f_t: jax.Array
            g_t: jax.Array
            F_t: jax.Array
            ok: jax.Array

        def ls_cond(c: _LS):
            return (~c.ok) & (c.i < config.max_line_search_iterations)

        def ls_body(c: _LS) -> _LS:
            w_t = _project_orthant(s.w + c.t * d, xi)
            f_t, g_t = objective.value_and_grad(w_t, data, l2_weight)
            F_t = f_t + l1 * jnp.sum(jnp.abs(w_t))
            # sufficient decrease vs directional derivative of F along the
            # PROJECTED step (Andrew & Gao eq. for the projected path)
            ok = F_t <= s.F + GAMMA * jnp.dot(pg, w_t - s.w)
            return _LS(
                t=jnp.where(ok, c.t, c.t * BACKTRACK),
                i=c.i + 1,
                w_t=w_t,
                f_t=f_t,
                g_t=g_t,
                F_t=F_t,
                ok=ok,
            )

        ls0 = _LS(
            t=t0.astype(dtype),
            i=jnp.int32(0),
            w_t=s.w,
            f_t=s.f,
            g_t=s.g,
            F_t=s.F,
            ok=jnp.bool_(False),
        )
        ls = jax.lax.while_loop(ls_cond, ls_body, ls0)

        w_new = jnp.where(ls.ok, ls.w_t, s.w)
        f_new = jnp.where(ls.ok, ls.f_t, s.f)
        g_new = jnp.where(ls.ok, ls.g_t, s.g)
        F_new = jnp.where(ls.ok, ls.F_t, s.F)
        if has_box:
            # post-step projection (reference LBFGS.scala:72, inherited by
            # OWLQN); recompute at the projected point so curvature pairs
            # and convergence checks see the true state — but only when the
            # projection actually clipped something (bounds inactive or a
            # failed line search leave w unchanged, and the line-search
            # f/g are already exact there)
            w_proj = _project_box(w_new, box_lo, box_hi)
            clipped = jnp.any(w_proj != w_new)

            def _recompute(_):
                f_p, g_p = objective.value_and_grad(w_proj, data, l2_weight)
                return f_p, g_p, f_p + l1 * jnp.sum(jnp.abs(w_proj))

            def _reuse(_):
                return f_new, g_new, F_new

            f_new, g_new, F_new = jax.lax.cond(clipped, _recompute, _reuse, None)
            w_new = w_proj

        s_vec = w_new - s.w
        y_vec = g_new - s.g
        s_hist, y_hist, rho, count = update_history(
            s.s_hist, s.y_hist, s.rho, s.count, s_vec, y_vec
        )

        it = s.it + 1
        pg_new = pseudo_gradient(w_new, g_new, l1)
        g_conv = gradient_converged(jnp.linalg.norm(pg_new), s.abs_g_tol)
        f_conv = ls.ok & function_values_converged(s.F, F_new, s.abs_f_tol)
        no_step = ~ls.ok
        reason = jnp.where(
            g_conv,
            ConvergenceReason.GRADIENT_CONVERGED.value,
            jnp.where(
                f_conv,
                ConvergenceReason.FUNCTION_VALUES_CONVERGED.value,
                jnp.where(
                    no_step,
                    ConvergenceReason.OBJECTIVE_NOT_IMPROVING.value,
                    jnp.where(
                        it >= max_iter,
                        ConvergenceReason.MAX_ITERATIONS.value,
                        ConvergenceReason.NOT_CONVERGED.value,
                    ),
                ),
            ),
        ).astype(jnp.int32)

        return _OwlqnState(
            w=w_new,
            f=f_new,
            g=g_new,
            F=F_new,
            s_hist=s_hist,
            y_hist=y_hist,
            rho=rho,
            count=count,
            it=it,
            reason=reason,
            history=s.history.at[it].set(F_new),
            w_hist=(
                s.w_hist.at[it].set(w_new)
                if config.track_coefficients
                else s.w_hist
            ),
            l1=s.l1,
            abs_f_tol=s.abs_f_tol,
            abs_g_tol=s.abs_g_tol,
        )

    return jax.lax.while_loop(cond, body, state)


def owlqn_finalize(
    state: _OwlqnState, config: OptimizerConfig = OptimizerConfig()
) -> SolveResult:
    """Convert a (fully run) loop state into the public SolveResult."""
    reason = jnp.where(
        state.reason == ConvergenceReason.NOT_CONVERGED.value,
        jnp.int32(ConvergenceReason.MAX_ITERATIONS.value),
        state.reason,
    )
    pg_final = pseudo_gradient(state.w, state.g, state.l1)
    return SolveResult(
        w=state.w,
        value=state.F,
        grad_norm=jnp.linalg.norm(pg_final),
        iterations=state.it,
        reason=reason,
        value_history=state.history,
        w_history=state.w_hist if config.track_coefficients else None,
    )


def owlqn_solve(
    objective: GlmObjective,
    w0: jax.Array,
    data,
    l2_weight: jax.Array,
    l1_weight: jax.Array,
    config: OptimizerConfig = OptimizerConfig(),
    box=None,
) -> SolveResult:
    state = owlqn_init(objective, w0, data, l2_weight, l1_weight, config)
    state = owlqn_chunk(objective, state, data, l2_weight, config, box=box)
    return owlqn_finalize(state, config)
