"""Solver dispatch: pick LBFGS / OWL-QN / TRON from configuration.

Reference parity: OptimizerFactory.scala:27 — OWL-QN is selected automatically
whenever the regularization has a positive L1 component; TRON is rejected for
first-order-only objectives. ``l2_weight``/``l1_weight`` are traced scalars so
λ sweeps reuse one compiled program.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.losses.objective import GlmObjective
from photon_ml_tpu.opt.config import GlmOptimizationConfiguration, OptimizerType
from photon_ml_tpu.opt.lbfgs import lbfgs_solve
from photon_ml_tpu.opt.owlqn import owlqn_solve
from photon_ml_tpu.opt.state import SolveResult
from photon_ml_tpu.opt.tron import tron_solve


def solve(
    objective: GlmObjective,
    w0,
    data,
    configuration: GlmOptimizationConfiguration,
    l2_weight=None,
    l1_weight=None,
    box=None,
) -> SolveResult:
    """Run the configured solver. The optimizer CHOICE is static (python
    branch, resolved at trace time); the regularization WEIGHTS are traced.

    l2_weight / l1_weight default to the values implied by the configuration
    but may be overridden (warm-started λ sweeps). An explicit ``l1_weight``
    is authoritative: a concrete 0 / 0.0 disables OWL-QN even if the
    configuration's own regularization_weight implies L1; a traced scalar
    selects OWL-QN (the choice must be static under jit).
    """
    cfg = configuration.optimizer_config
    l2 = jnp.asarray(configuration.l2_weight if l2_weight is None else l2_weight, dtype=w0.dtype)
    if l1_weight is None:
        use_owlqn = configuration.l1_weight > 0
        l1_value = configuration.l1_weight
    elif isinstance(l1_weight, (int, float, np.floating, np.integer)) and float(l1_weight) == 0.0:
        use_owlqn = False
        l1_value = 0.0
    else:
        use_owlqn = True
        l1_value = l1_weight
    if use_owlqn:
        l1 = jnp.asarray(l1_value, dtype=w0.dtype)
        if cfg.optimizer is OptimizerType.TRON:
            raise ValueError("TRON does not support L1 regularization (use LBFGS/OWL-QN)")
        return owlqn_solve(objective, w0, data, l2, l1, cfg, box=box)
    if cfg.optimizer is OptimizerType.TRON:
        return tron_solve(objective, w0, data, l2, cfg, box=box)
    return lbfgs_solve(objective, w0, data, l2, cfg, box=box)
