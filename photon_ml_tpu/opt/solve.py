"""Solver dispatch: pick LBFGS / OWL-QN / TRON from configuration.

Reference parity: OptimizerFactory.scala:27 — OWL-QN is selected automatically
whenever the regularization has a positive L1 component; TRON is rejected for
first-order-only objectives. ``l2_weight``/``l1_weight`` are traced scalars so
λ sweeps reuse one compiled program.

Beyond the one-shot ``solve``, this module exposes the resumable
init/chunk/finalize triple used by the convergence-adaptive random-effect
driver: ``solve_init`` builds a solver-specific loop state, ``solve_chunk``
advances it by at most K outer iterations (carrying L-BFGS memory / OWL-QN
orthant state / TRON trust radius across calls), and ``solve_finalize`` turns
the state into a ``SolveResult``. ``solve(...)`` is exactly
``solve_finalize(solve_chunk(solve_init(...)))`` with no iteration cap, so
chunked execution follows the identical per-lane trajectory.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.losses.objective import GlmObjective
from photon_ml_tpu.opt.config import GlmOptimizationConfiguration, OptimizerType
from photon_ml_tpu.opt.lbfgs import (
    _LbfgsState,
    lbfgs_chunk,
    lbfgs_finalize,
    lbfgs_init,
    lbfgs_solve,
)
from photon_ml_tpu.opt.owlqn import (
    _OwlqnState,
    owlqn_chunk,
    owlqn_finalize,
    owlqn_init,
    owlqn_solve,
)
from photon_ml_tpu.opt.state import SolveResult
from photon_ml_tpu.opt.tron import (
    _TronState,
    tron_chunk,
    tron_finalize,
    tron_init,
    tron_solve,
)


def _resolve_l1(configuration: GlmOptimizationConfiguration, l1_weight):
    """Return (use_owlqn, l1_value) following the override semantics of
    ``solve``: None → configuration-implied; a concrete 0 disables OWL-QN;
    anything else (incl. a traced scalar) selects it."""
    if l1_weight is None:
        return configuration.l1_weight > 0, configuration.l1_weight
    if isinstance(l1_weight, (int, float, np.floating, np.integer)) and float(l1_weight) == 0.0:
        return False, 0.0
    return True, l1_weight


def solver_kind(configuration: GlmOptimizationConfiguration, l1_weight=None) -> str:
    """Static solver choice for a configuration: 'owlqn' | 'tron' | 'lbfgs'.

    Raises for the invalid TRON+L1 combination, mirroring ``solve``.
    """
    cfg = configuration.optimizer_config
    use_owlqn, _ = _resolve_l1(configuration, l1_weight)
    if use_owlqn:
        if cfg.optimizer is OptimizerType.TRON:
            raise ValueError("TRON does not support L1 regularization (use LBFGS/OWL-QN)")
        return "owlqn"
    if cfg.optimizer is OptimizerType.TRON:
        return "tron"
    return "lbfgs"


def solve(
    objective: GlmObjective,
    w0,
    data,
    configuration: GlmOptimizationConfiguration,
    l2_weight=None,
    l1_weight=None,
    box=None,
) -> SolveResult:
    """Run the configured solver. The optimizer CHOICE is static (python
    branch, resolved at trace time); the regularization WEIGHTS are traced.

    l2_weight / l1_weight default to the values implied by the configuration
    but may be overridden (warm-started λ sweeps). An explicit ``l1_weight``
    is authoritative: a concrete 0 / 0.0 disables OWL-QN even if the
    configuration's own regularization_weight implies L1; a traced scalar
    selects OWL-QN (the choice must be static under jit).
    """
    cfg = configuration.optimizer_config
    l2 = jnp.asarray(configuration.l2_weight if l2_weight is None else l2_weight, dtype=w0.dtype)
    use_owlqn, l1_value = _resolve_l1(configuration, l1_weight)
    if use_owlqn:
        l1 = jnp.asarray(l1_value, dtype=w0.dtype)
        if cfg.optimizer is OptimizerType.TRON:
            raise ValueError("TRON does not support L1 regularization (use LBFGS/OWL-QN)")
        return owlqn_solve(objective, w0, data, l2, l1, cfg, box=box)
    if cfg.optimizer is OptimizerType.TRON:
        return tron_solve(objective, w0, data, l2, cfg, box=box)
    return lbfgs_solve(objective, w0, data, l2, cfg, box=box)


def solve_init(
    objective: GlmObjective,
    w0,
    data,
    configuration: GlmOptimizationConfiguration,
    l2_weight=None,
    l1_weight=None,
):
    """Build the resumable loop state for the configured solver."""
    cfg = configuration.optimizer_config
    l2 = jnp.asarray(configuration.l2_weight if l2_weight is None else l2_weight, dtype=w0.dtype)
    kind = solver_kind(configuration, l1_weight)
    if kind == "owlqn":
        _, l1_value = _resolve_l1(configuration, l1_weight)
        l1 = jnp.asarray(l1_value, dtype=w0.dtype)
        return owlqn_init(objective, w0, data, l2, l1, cfg)
    if kind == "tron":
        return tron_init(objective, w0, data, l2, cfg)
    return lbfgs_init(objective, w0, data, l2, cfg)


def solve_chunk(
    objective: GlmObjective,
    state,
    data,
    configuration: GlmOptimizationConfiguration,
    l2_weight=None,
    box=None,
    num_iters=None,
):
    """Advance a ``solve_init`` state by ≤ ``num_iters`` outer iterations
    (None = run to convergence / max_iterations). Dispatches on state type."""
    cfg = configuration.optimizer_config
    dtype = state.w.dtype
    l2 = jnp.asarray(configuration.l2_weight if l2_weight is None else l2_weight, dtype=dtype)
    if isinstance(state, _OwlqnState):
        return owlqn_chunk(objective, state, data, l2, cfg, box=box, num_iters=num_iters)
    if isinstance(state, _TronState):
        return tron_chunk(objective, state, data, l2, cfg, box=box, num_iters=num_iters)
    if isinstance(state, _LbfgsState):
        return lbfgs_chunk(objective, state, data, l2, cfg, box=box, num_iters=num_iters)
    raise TypeError(f"unknown solver state type {type(state).__name__}")


def solve_finalize(state, configuration: GlmOptimizationConfiguration) -> SolveResult:
    """Turn a loop state into the public ``SolveResult``."""
    cfg = configuration.optimizer_config
    if isinstance(state, _OwlqnState):
        return owlqn_finalize(state, cfg)
    if isinstance(state, _TronState):
        return tron_finalize(state, cfg)
    if isinstance(state, _LbfgsState):
        return lbfgs_finalize(state, cfg)
    raise TypeError(f"unknown solver state type {type(state).__name__}")


def block_on_result(result: SolveResult) -> SolveResult:
    """Block until every array in ``result`` is device-resident and
    computed. ``solve``/``solve_finalize`` return unblocked pytrees (XLA
    dispatch is async), which is what lets the overlapped CD schedule hide
    a solve behind other work; callers that need completed-by-now
    semantics — wall-clock measurement, reconciliation barriers — wait
    here instead of sprinkling ``block_until_ready`` over fields."""
    import jax

    jax.block_until_ready(
        [leaf for leaf in jax.tree_util.tree_leaves(result)
         if isinstance(leaf, jax.Array)]
    )
    return result
