"""Solver result containers and convergence bookkeeping.

Reference parity: optimization/Optimizer.scala (convergence checks :131-145,
abs tolerances derived from the initial state :68-71) and
OptimizationStatesTracker.scala:31 (per-iteration value history ring buffer,
surfaced in logs and ModelTracker). Device-side: the history is a fixed
[max_iterations+1] array padded with NaN, and the convergence reason is an
int32 code (types.ConvergenceReason).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from photon_ml_tpu.types import ConvergenceReason


@struct.dataclass
class SolveResult:
    """Outcome of one optimizer run. All fields are device arrays so the
    whole result can be vmap'd (one lane per random-effect entity)."""

    w: jax.Array              # [d] final coefficients
    value: jax.Array          # scalar final objective (incl. L2; incl. L1 for OWL-QN)
    grad_norm: jax.Array      # scalar ||grad|| (pseudo-gradient for OWL-QN)
    iterations: jax.Array     # int32 number of outer iterations performed
    reason: jax.Array         # int32 ConvergenceReason code
    value_history: jax.Array  # [max_iterations+1] objective per iteration, NaN-padded
    # [max_iterations+1, d] per-iteration coefficients, NaN-padded — only
    # when OptimizerConfig.track_coefficients (reference ModelTracker /
    # OptimizationStatesTracker keeps per-iteration coefficients)
    w_history: Optional[jax.Array] = None

    def converged(self) -> jax.Array:
        return self.reason != ConvergenceReason.NOT_CONVERGED.value

    def reason_enum(self) -> ConvergenceReason:
        return ConvergenceReason(int(self.reason))


def function_values_converged(f_prev: jax.Array, f: jax.Array, abs_tol: jax.Array) -> jax.Array:
    """|f_prev - f| <= abs_tol (reference Optimizer.scala:131-138)."""
    return jnp.abs(f_prev - f) <= abs_tol


def gradient_converged(grad_norm: jax.Array, abs_tol: jax.Array) -> jax.Array:
    """||g|| <= abs_tol (reference Optimizer.scala:140-145)."""
    return grad_norm <= abs_tol


def absolute_tolerances(f0: jax.Array, g0_norm: jax.Array, rel_tol: float):
    """Derive absolute tolerances from the initial state
    (reference Optimizer.scala:68-71: relative tolerance times the magnitude
    of the zero-model loss / gradient, floored to avoid degenerate zeros)."""
    abs_f_tol = rel_tol * jnp.maximum(jnp.abs(f0), 1e-15)
    abs_g_tol = rel_tol * jnp.maximum(g0_norm, 1e-15)
    return abs_f_tol, abs_g_tol
