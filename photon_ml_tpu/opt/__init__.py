from photon_ml_tpu.opt.config import (
    GlmOptimizationConfiguration,
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
)
from photon_ml_tpu.opt.lbfgs import lbfgs_solve
from photon_ml_tpu.opt.owlqn import owlqn_solve
from photon_ml_tpu.opt.solve import solve
from photon_ml_tpu.opt.state import SolveResult
from photon_ml_tpu.opt.tron import tron_solve

__all__ = [
    "GlmOptimizationConfiguration",
    "OptimizerConfig",
    "OptimizerType",
    "RegularizationContext",
    "lbfgs_solve",
    "owlqn_solve",
    "tron_solve",
    "solve",
    "SolveResult",
]
