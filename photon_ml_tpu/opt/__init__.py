from photon_ml_tpu.opt.config import (
    AdaptiveSolveConfig,
    GlmOptimizationConfiguration,
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
)
from photon_ml_tpu.opt.lbfgs import lbfgs_solve
from photon_ml_tpu.opt.owlqn import owlqn_solve
from photon_ml_tpu.opt.solve import (
    solve,
    solve_chunk,
    solve_finalize,
    solve_init,
    solver_kind,
)
from photon_ml_tpu.opt.state import SolveResult
from photon_ml_tpu.opt.tron import tron_solve

__all__ = [
    "AdaptiveSolveConfig",
    "GlmOptimizationConfiguration",
    "OptimizerConfig",
    "OptimizerType",
    "RegularizationContext",
    "lbfgs_solve",
    "owlqn_solve",
    "tron_solve",
    "solve",
    "solve_init",
    "solve_chunk",
    "solve_finalize",
    "solver_kind",
    "SolveResult",
]
