"""Strong-Wolfe line search as a single ``lax.while_loop`` state machine.

Replaces Breeze's StrongWolfeLineSearch (used by the reference's LBFGS,
LBFGS.scala:59-106). Standard bracket-then-zoom (Nocedal & Wright alg. 3.5/3.6)
with bisection zoom; c1=1e-4, c2=0.9. Each trial evaluates value-and-gradient
once; the gradient at the accepted point is carried out so the caller does not
re-evaluate.

The whole search is branch-free XLA control flow: one while_loop whose state
includes a ``stage`` flag (0 = bracketing, 1 = zoom) — safe under jit, vmap,
and shard_map.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp

C1 = 1e-4
C2 = 0.9


class LineSearchResult(NamedTuple):
    t: jax.Array        # accepted step
    f: jax.Array        # phi(t)
    g: jax.Array        # full gradient at w + t*d
    success: jax.Array  # bool: Wolfe conditions met


def strong_wolfe_search(
    eval_step: Callable[[jax.Array], Tuple[jax.Array, jax.Array, jax.Array]],
    f0: jax.Array,
    g0: jax.Array,
    dphi0: jax.Array,
    t_init: jax.Array,
    max_iters: int = 25,
) -> LineSearchResult:
    """eval_step(t) -> (phi(t), grad_at_point [d], dphi(t)).

    ``g0`` is the full gradient at t=0 (the caller already has it); it seeds
    the carried gradient buffers so no evaluation is spent on shape probing.
    Returns the accepted step with its value/gradient. When the search cannot
    satisfy Wolfe within ``max_iters`` evaluations it returns the best
    sufficient-decrease point seen (success=False if none found; the t=0
    point with its g0 is the last resort so the caller can detect a null step).
    """

    class _S(NamedTuple):
        stage: jax.Array    # 0 bracket, 1 zoom, 2 done
        i: jax.Array
        t: jax.Array        # current trial
        t_lo: jax.Array
        f_lo: jax.Array
        d_lo: jax.Array
        t_hi: jax.Array
        f_hi: jax.Array
        # best sufficient-decrease point seen (fallback)
        t_best: jax.Array
        f_best: jax.Array
        g_best: jax.Array
        has_best: jax.Array
        # accepted point
        t_acc: jax.Array
        f_acc: jax.Array
        g_acc: jax.Array
        success: jax.Array

    zero = jnp.zeros_like(t_init)
    init = _S(
        stage=jnp.int32(0),
        i=jnp.int32(0),
        t=t_init,
        t_lo=zero,
        f_lo=f0,
        d_lo=dphi0,
        t_hi=zero,
        f_hi=f0,
        t_best=zero,
        f_best=f0,
        g_best=g0,
        has_best=jnp.bool_(False),
        t_acc=zero,
        f_acc=f0,
        g_acc=g0,
        success=jnp.bool_(False),
    )

    def cond(s: _S):
        return (s.stage != 2) & (s.i < max_iters)

    def body(s: _S) -> _S:
        f_t, g_t, d_t = eval_step(s.t)
        armijo_fail = (f_t > f0 + C1 * s.t * dphi0) | ((s.i > 0) & (f_t >= s.f_lo) & (s.stage == 0))
        wolfe_ok = (~armijo_fail) & (jnp.abs(d_t) <= -C2 * dphi0)

        # track best sufficient-decrease point as a fallback
        suff = f_t <= f0 + C1 * s.t * dphi0
        better = suff & ((~s.has_best) | (f_t < s.f_best))
        t_best = jnp.where(better, s.t, s.t_best)
        f_best = jnp.where(better, f_t, s.f_best)
        g_best = jnp.where(better, g_t, s.g_best)
        has_best = s.has_best | suff

        def bracket_step():
            # returns (stage, t, t_lo, f_lo, d_lo, t_hi, f_hi, accept)
            enter_zoom_hi = armijo_fail
            enter_zoom_swap = (~armijo_fail) & (~wolfe_ok) & (d_t >= 0)
            stage = jnp.where(wolfe_ok, 2, jnp.where(enter_zoom_hi | enter_zoom_swap, 1, 0))
            # zoom brackets
            t_lo = jnp.where(enter_zoom_hi, s.t_lo, jnp.where(enter_zoom_swap, s.t, s.t))
            f_lo = jnp.where(enter_zoom_hi, s.f_lo, jnp.where(enter_zoom_swap, f_t, f_t))
            d_lo = jnp.where(enter_zoom_hi, s.d_lo, jnp.where(enter_zoom_swap, d_t, d_t))
            t_hi = jnp.where(enter_zoom_hi, s.t, jnp.where(enter_zoom_swap, s.t_lo, s.t_hi))
            f_hi = jnp.where(enter_zoom_hi, f_t, jnp.where(enter_zoom_swap, s.f_lo, s.f_hi))
            # next trial: midpoint if zooming, expand if still bracketing
            t_next = jnp.where(stage == 1, 0.5 * (t_lo + t_hi), s.t * 2.0)
            return stage, t_next, t_lo, f_lo, d_lo, t_hi, f_hi

        def zoom_step():
            shrink_hi = armijo_fail | (f_t >= s.f_lo)
            stage = jnp.where(wolfe_ok, 2, jnp.int32(1))
            # if new lo, possibly swap hi to old lo when derivative points past
            swap = (~shrink_hi) & (d_t * (s.t_hi - s.t_lo) >= 0)
            t_hi = jnp.where(shrink_hi, s.t, jnp.where(swap, s.t_lo, s.t_hi))
            f_hi = jnp.where(shrink_hi, f_t, jnp.where(swap, s.f_lo, s.f_hi))
            t_lo = jnp.where(shrink_hi, s.t_lo, s.t)
            f_lo = jnp.where(shrink_hi, s.f_lo, f_t)
            d_lo = jnp.where(shrink_hi, s.d_lo, d_t)
            t_next = 0.5 * (t_lo + t_hi)
            return stage, t_next, t_lo, f_lo, d_lo, t_hi, f_hi

        b = bracket_step()
        z = zoom_step()
        in_zoom = s.stage == 1
        stage = jnp.where(in_zoom, z[0], b[0])
        t_next = jnp.where(in_zoom, z[1], b[1])
        t_lo = jnp.where(in_zoom, z[2], b[2])
        f_lo = jnp.where(in_zoom, z[3], b[3])
        d_lo = jnp.where(in_zoom, z[4], b[4])
        t_hi = jnp.where(in_zoom, z[5], b[5])
        f_hi = jnp.where(in_zoom, z[6], b[6])

        accepted = stage == 2
        return _S(
            stage=stage,
            i=s.i + 1,
            t=t_next,
            t_lo=t_lo,
            f_lo=f_lo,
            d_lo=d_lo,
            t_hi=t_hi,
            f_hi=f_hi,
            t_best=t_best,
            f_best=f_best,
            g_best=g_best,
            has_best=has_best,
            t_acc=jnp.where(accepted, s.t, s.t_acc),
            f_acc=jnp.where(accepted, f_t, s.f_acc),
            g_acc=jnp.where(accepted, g_t, s.g_acc),
            success=s.success | accepted,
        )

    o = jax.lax.while_loop(cond, body, init)

    # Fallback: best sufficient-decrease point seen (t=0 state if none).
    use_acc = o.success
    return LineSearchResult(
        t=jnp.where(use_acc, o.t_acc, jnp.where(o.has_best, o.t_best, 0.0)),
        f=jnp.where(use_acc, o.f_acc, jnp.where(o.has_best, o.f_best, f0)),
        g=jnp.where(use_acc, o.g_acc, o.g_best),
        success=use_acc | o.has_best,
    )
