"""L-BFGS as a fully on-device ``lax.while_loop`` program.

Reference parity: optimization/LBFGS.scala:39 — which delegated to
``breeze.optimize.LBFGS`` on the Spark driver, with one cluster job per
objective evaluation. Here the whole solve (two-loop recursion, strong-Wolfe
line search, convergence checks) is one XLA program: no host round-trips,
vmap-able so millions of per-entity random-effect solves batch into one
kernel launch.

Defaults match the reference (maxIter=100, m=10, tol=1e-7,
LBFGS.scala:147-152). Box constraints are applied by projection after each
accepted step (LBFGS.scala:72).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from photon_ml_tpu.losses.objective import GlmObjective
from photon_ml_tpu.opt.config import OptimizerConfig
from photon_ml_tpu.opt.linesearch import strong_wolfe_search
from photon_ml_tpu.opt.state import (
    SolveResult,
    absolute_tolerances,
    function_values_converged,
    gradient_converged,
)
from photon_ml_tpu.types import ConvergenceReason


class _LbfgsState(NamedTuple):
    """Resumable L-BFGS loop state: everything the next outer iteration
    needs, including the absolute tolerances derived from the initial point
    (so a solve can be split into chunks — ``lbfgs_chunk`` — and each chunk
    continues exactly where the previous one stopped)."""

    w: jax.Array          # [d]
    f: jax.Array
    g: jax.Array          # [d]
    s_hist: jax.Array     # [m, d] steps ring buffer
    y_hist: jax.Array     # [m, d] gradient-diff ring buffer
    rho: jax.Array        # [m] 1/(s.y)
    count: jax.Array      # int32 number of valid history pairs
    it: jax.Array         # int32 outer iteration
    reason: jax.Array     # int32 ConvergenceReason
    history: jax.Array    # [max_iter+1] objective values
    w_hist: jax.Array     # [max_iter+1, d] coefficients (or [0] when off)
    abs_f_tol: jax.Array  # scalar, derived from f0 at init
    abs_g_tol: jax.Array  # scalar, derived from ||g0|| at init


def two_loop_direction(
    g: jax.Array, s_hist: jax.Array, y_hist: jax.Array, rho: jax.Array, count: jax.Array
) -> jax.Array:
    """Two-loop recursion over a masked ring buffer.

    History slots are ordered oldest→newest modulo m; slot i is valid iff
    i < count. Invalid slots have rho=0 so their updates are algebraic no-ops
    (alpha = rho*(s.q) = 0), which keeps the loop branch-free.
    """
    m = rho.shape[0]

    # History may be stored bf16 (config.history_dtype); rows are cast to
    # the working dtype on read so every dot/axpy accumulates full precision.
    wd = g.dtype

    def bwd(i, carry):
        q, alphas = carry
        idx = jnp.mod(count - 1 - i, m)  # newest first
        valid = i < count
        r = jnp.where(valid, rho[idx], 0.0)
        a = r * jnp.dot(s_hist[idx].astype(wd), q)
        q = q - a * y_hist[idx].astype(wd)
        alphas = alphas.at[idx].set(a)
        return q, alphas

    q, alphas = jax.lax.fori_loop(0, m, bwd, (g, jnp.zeros_like(rho)))

    # initial Hessian scaling gamma = (s.y)/(y.y) of the newest valid pair
    newest = jnp.mod(count - 1, m)
    have = count > 0
    s_new = s_hist[newest].astype(wd)
    y_new = y_hist[newest].astype(wd)
    sy = jnp.dot(s_new, y_new)
    yy = jnp.dot(y_new, y_new)
    gamma = jnp.where(have & (yy > 0), sy / jnp.maximum(yy, 1e-30), 1.0)
    r_vec = gamma * q

    def fwd(i, r_vec):
        idx = jnp.mod(count - m + i, m)  # oldest first among the last m
        valid = i >= (m - jnp.minimum(count, m))
        r = jnp.where(valid, rho[idx], 0.0)
        beta = r * jnp.dot(y_hist[idx].astype(wd), r_vec)
        return r_vec + jnp.where(valid, (alphas[idx] - beta), 0.0) * s_hist[idx].astype(wd)

    r_vec = jax.lax.fori_loop(0, m, fwd, r_vec)
    return -r_vec


def resolve_history_dtype(config: OptimizerConfig, working_dtype) -> jnp.dtype:
    """The storage dtype for s/y ring buffers (config.history_dtype or the
    working dtype) — shared by L-BFGS and OWL-QN."""
    return jnp.dtype(config.history_dtype) if config.history_dtype else working_dtype


def update_history(
    s_hist, y_hist, rho, count, s_vec, y_vec
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Curvature-guarded ring-buffer insert (skip when s.y too small),
    casting the pair to the buffers' storage dtype — shared by L-BFGS and
    OWL-QN so their history handling cannot diverge."""
    m = rho.shape[0]
    sy = jnp.dot(s_vec, y_vec)
    good_pair = sy > 1e-10 * jnp.maximum(jnp.dot(y_vec, y_vec), 1e-30)
    slot = jnp.mod(count, m)
    hdtype = s_hist.dtype
    s_hist = jnp.where(
        good_pair, s_hist.at[slot].set(s_vec.astype(hdtype)), s_hist
    )
    y_hist = jnp.where(
        good_pair, y_hist.at[slot].set(y_vec.astype(hdtype)), y_hist
    )
    rho = jnp.where(
        good_pair, rho.at[slot].set(1.0 / jnp.maximum(sy, 1e-30)), rho
    )
    count = jnp.where(good_pair, count + 1, count)
    return s_hist, y_hist, rho, count


def _project_box(w: jax.Array, lower, upper) -> jax.Array:
    if lower is not None:
        w = jnp.maximum(w, lower)
    if upper is not None:
        w = jnp.minimum(w, upper)
    return w


def resolve_box(box, config: OptimizerConfig):
    """(lower, upper, has_box) from a per-coefficient ``box`` override or
    the config's scalar bounds — shared by all three solvers."""
    lo, hi = box if box is not None else (
        config.constraint_lower, config.constraint_upper
    )
    return lo, hi, lo is not None or hi is not None


def lbfgs_init(
    objective: GlmObjective,
    w0: jax.Array,
    data,
    l2_weight: jax.Array,
    config: OptimizerConfig = OptimizerConfig(),
) -> _LbfgsState:
    """Evaluate the initial point and build the resumable loop state
    (absolute tolerances included — reference Optimizer.scala:68-71)."""
    m = config.history_length
    max_iter = config.max_iterations
    dim = w0.shape[-1]
    dtype = w0.dtype

    f0, g0 = objective.value_and_grad(w0, data, l2_weight)
    g0_norm = jnp.linalg.norm(g0)
    abs_f_tol, abs_g_tol = absolute_tolerances(f0, g0_norm, config.tolerance)

    hdtype = resolve_history_dtype(config, dtype)
    history0 = jnp.full((max_iter + 1,), jnp.nan, dtype=dtype).at[0].set(f0)
    w_hist0 = (
        jnp.full((max_iter + 1, dim), jnp.nan, dtype=dtype).at[0].set(w0)
        if config.track_coefficients
        else jnp.zeros((0,), dtype=dtype)
    )
    return _LbfgsState(
        w=w0,
        f=f0,
        g=g0,
        s_hist=jnp.zeros((m, dim), dtype=hdtype),
        y_hist=jnp.zeros((m, dim), dtype=hdtype),
        rho=jnp.zeros((m,), dtype=dtype),
        count=jnp.int32(0),
        it=jnp.int32(0),
        reason=jnp.int32(ConvergenceReason.NOT_CONVERGED.value),
        history=history0,
        w_hist=w_hist0,
        abs_f_tol=abs_f_tol,
        abs_g_tol=abs_g_tol,
    )


def lbfgs_chunk(
    objective: GlmObjective,
    state: _LbfgsState,
    data,
    l2_weight: jax.Array,
    config: OptimizerConfig = OptimizerConfig(),
    box: Optional[Tuple] = None,
    num_iters: Optional[int] = None,
) -> _LbfgsState:
    """Advance the solve by at most ``num_iters`` outer iterations (None =
    run to convergence/max_iterations). The full solver state — curvature
    ring buffers, step counts, tolerances — is carried in ``state``, so
    chunked execution follows EXACTLY the same per-iterate trajectory as one
    uninterrupted ``while_loop``; only the program boundaries differ. This
    is what lets the random-effect driver pull converged lanes out of a
    vmapped batch every K iterations (estimators/random_effect.py)."""
    max_iter = config.max_iterations
    dtype = state.w.dtype
    box_lo, box_hi, has_box = resolve_box(box, config)
    it_stop = None if num_iters is None else state.it + jnp.int32(num_iters)

    def cond(s: _LbfgsState):
        c = (s.reason == ConvergenceReason.NOT_CONVERGED.value) & (s.it < max_iter)
        if it_stop is not None:
            c = c & (s.it < it_stop)
        return c

    def body(s: _LbfgsState) -> _LbfgsState:
        d = two_loop_direction(s.g, s.s_hist, s.y_hist, s.rho, s.count)
        dphi0 = jnp.dot(d, s.g)
        # Safeguard: if not a descent direction (can happen after box
        # projection perturbs the quasi-Newton pairs), restart with -g.
        bad = dphi0 >= 0
        d = jnp.where(bad, -s.g, d)
        dphi0 = jnp.where(bad, -jnp.dot(s.g, s.g), dphi0)

        def eval_step(t):
            w_t = s.w + t * d
            f_t, g_t = objective.value_and_grad(w_t, data, l2_weight)
            return f_t, g_t, jnp.dot(g_t, d)

        # First iteration: t ~ 1/||g|| (Breeze's firstStepSize heuristic);
        # afterwards the natural quasi-Newton step t=1.
        t_init = jnp.where(
            s.count == 0, 1.0 / jnp.maximum(jnp.linalg.norm(d), 1e-12), 1.0
        ).astype(dtype)
        ls = strong_wolfe_search(
            eval_step, s.f, s.g, dphi0, t_init, config.max_line_search_iterations
        )

        w_new = s.w + ls.t * d
        w_new = _project_box(w_new, box_lo, box_hi)
        # Projection may have changed the point; recompute f/g only if a box
        # is configured (static branch — no cost otherwise).
        if has_box:
            f_new, g_new = objective.value_and_grad(w_new, data, l2_weight)
        else:
            f_new, g_new = ls.f, ls.g

        s_vec = w_new - s.w
        y_vec = g_new - s.g
        s_hist, y_hist, rho, count = update_history(
            s.s_hist, s.y_hist, s.rho, s.count, s_vec, y_vec
        )

        it = s.it + 1
        # Convergence checks (reference Optimizer.scala:131-145). A failed
        # line search that produced no movement terminates with
        # OBJECTIVE_NOT_IMPROVING — f_conv is gated on success so a stalled
        # search is never misreported as converged.
        no_step = (~ls.success) | (ls.t <= 0)
        f_conv = ls.success & function_values_converged(s.f, f_new, s.abs_f_tol)
        g_conv = gradient_converged(jnp.linalg.norm(g_new), s.abs_g_tol)
        reason = jnp.where(
            g_conv,
            ConvergenceReason.GRADIENT_CONVERGED.value,
            jnp.where(
                no_step,
                ConvergenceReason.OBJECTIVE_NOT_IMPROVING.value,
                jnp.where(
                    f_conv,
                    ConvergenceReason.FUNCTION_VALUES_CONVERGED.value,
                    jnp.where(
                        it >= max_iter,
                        ConvergenceReason.MAX_ITERATIONS.value,
                        ConvergenceReason.NOT_CONVERGED.value,
                    ),
                ),
            ),
        ).astype(jnp.int32)

        return _LbfgsState(
            w=w_new,
            f=f_new,
            g=g_new,
            s_hist=s_hist,
            y_hist=y_hist,
            rho=rho,
            count=count,
            it=it,
            reason=reason,
            history=s.history.at[it].set(f_new),
            w_hist=(
                s.w_hist.at[it].set(w_new)
                if config.track_coefficients
                else s.w_hist
            ),
            abs_f_tol=s.abs_f_tol,
            abs_g_tol=s.abs_g_tol,
        )

    return jax.lax.while_loop(cond, body, state)


def lbfgs_finalize(
    state: _LbfgsState, config: OptimizerConfig = OptimizerConfig()
) -> SolveResult:
    """Turn a finished (or exhausted) loop state into a SolveResult. A state
    still marked NOT_CONVERGED is reported as MAX_ITERATIONS — callers only
    finalize once the iteration budget is spent."""
    reason = jnp.where(
        state.reason == ConvergenceReason.NOT_CONVERGED.value,
        jnp.int32(ConvergenceReason.MAX_ITERATIONS.value),
        state.reason,
    )
    return SolveResult(
        w=state.w,
        value=state.f,
        grad_norm=jnp.linalg.norm(state.g),
        iterations=state.it,
        reason=reason,
        value_history=state.history,
        w_history=state.w_hist if config.track_coefficients else None,
    )


def lbfgs_solve(
    objective: GlmObjective,
    w0: jax.Array,
    data,
    l2_weight: jax.Array,
    config: OptimizerConfig = OptimizerConfig(),
    box: Optional[Tuple] = None,
) -> SolveResult:
    """Minimize objective over w starting from w0. Pure function of its
    inputs; jit/vmap/shard_map-safe.

    ``box`` = (lower, upper) per-coefficient arrays (either side may be
    None) — the reference's per-feature constraint map
    (GLMSuite.createConstraintFeatureMap); scalar bounds come from the
    config."""
    state = lbfgs_init(objective, w0, data, l2_weight, config)
    state = lbfgs_chunk(objective, state, data, l2_weight, config, box=box)
    return lbfgs_finalize(state, config)
