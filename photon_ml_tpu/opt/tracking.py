"""Host-side optimization telemetry: per-solve and per-coordinate trackers.

Reference parity: OptimizationStatesTracker.scala:31 (per-iteration
(loss, time) ring buffer surfaced in logs/ModelTracker),
FixedEffectOptimizationTracker.scala and RandomEffectOptimizationTracker.scala
(statistics over millions of per-entity solves: convergence-reason counts and
iteration/loss distributions).

Device-side history already lives in opt.state.SolveResult (NaN-padded
``value_history``); these classes are the host-side view that turns one
SolveResult — or a vmap'd batch of them — into loggable summaries.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.opt.state import SolveResult
from photon_ml_tpu.types import ConvergenceReason


@dataclasses.dataclass(frozen=True)
class OptimizationStatesTracker:
    """History of one optimizer run (OptimizationStatesTracker.scala:31)."""

    values: np.ndarray  # [iterations+1] objective per iteration (trimmed)
    iterations: int
    convergence_reason: ConvergenceReason
    elapsed_seconds: Optional[float] = None
    # final-iterate gradient norm (the convergence plane's stationarity
    # signal; None for trackers built before the solve finished)
    grad_norm: Optional[float] = None

    @classmethod
    def from_result(
        cls, result: SolveResult, elapsed_seconds: Optional[float] = None
    ) -> "OptimizationStatesTracker":
        history = np.asarray(result.value_history)
        iters = int(result.iterations)
        return cls(
            values=history[: iters + 1],
            iterations=iters,
            convergence_reason=result.reason_enum(),
            elapsed_seconds=elapsed_seconds,
            grad_norm=float(result.grad_norm),
        )

    @property
    def converged(self) -> bool:
        return self.convergence_reason is not ConvergenceReason.NOT_CONVERGED

    def to_summary_string(self) -> str:
        head = (
            f"{self.iterations} iterations, reason={self.convergence_reason.name}"
        )
        if self.values.size:
            head += f", f0={self.values[0]:.6g}, f*={self.values[-1]:.6g}"
        if self.elapsed_seconds is not None:
            head += f", {self.elapsed_seconds:.3f}s"
        return head


@dataclasses.dataclass(frozen=True)
class FixedEffectOptimizationTracker:
    """One tracker per fixed-effect update (FixedEffectOptimizationTracker.scala)."""

    states: OptimizationStatesTracker

    def to_summary_string(self) -> str:
        return f"fixed-effect solve: {self.states.to_summary_string()}"


@dataclasses.dataclass(frozen=True)
class RandomEffectOptimizationTracker:
    """Aggregate convergence telemetry over per-entity solves
    (RandomEffectOptimizationTracker.scala): reason counts + iteration and
    final-loss distributions across all (unpadded) entities."""

    num_entities: int
    reason_counts: Dict[ConvergenceReason, int]
    iteration_stats: Dict[str, float]  # min/max/mean/p50/p90
    value_stats: Dict[str, float]

    @classmethod
    def from_results(
        cls,
        results: List[SolveResult],
        real_counts: "Optional[List[int]]" = None,
    ) -> "RandomEffectOptimizationTracker":
        """``results`` are vmap'd SolveResults (leading entity axis), one per
        bucket. ``real_counts`` (per bucket) excludes mesh-padding entity
        lanes from the telemetry; None means every lane is a real entity."""
        from photon_ml_tpu.parallel.mesh import fetch_global

        if real_counts is None:
            real_counts = [res.reason.shape[0] for res in results]
        reasons = [
            fetch_global(res.reason)[:k] for res, k in zip(results, real_counts)
        ]
        iters = [
            fetch_global(res.iterations)[:k] for res, k in zip(results, real_counts)
        ]
        finals = [
            fetch_global(res.value)[:k] for res, k in zip(results, real_counts)
        ]
        reason_all = np.concatenate(reasons) if reasons else np.zeros(0, np.int32)
        iter_all = np.concatenate(iters) if iters else np.zeros(0, np.int32)
        value_all = np.concatenate(finals) if finals else np.zeros(0, np.float32)

        counts = {
            r: int(np.sum(reason_all == r.value))
            for r in ConvergenceReason
            if np.any(reason_all == r.value)
        }
        return cls(
            num_entities=int(reason_all.size),
            reason_counts=counts,
            iteration_stats=_stats(iter_all.astype(np.float64)),
            value_stats=_stats(value_all.astype(np.float64)),
        )

    def to_summary_string(self) -> str:
        reason_part = ", ".join(
            f"{r.name}={c}" for r, c in sorted(self.reason_counts.items(), key=lambda kv: kv[0].value)
        )
        it = self.iteration_stats
        return (
            f"random-effect solves over {self.num_entities} entities: "
            f"[{reason_part}] iterations(mean={it.get('mean', 0):.1f}, "
            f"p50={it.get('p50', 0):.0f}, p90={it.get('p90', 0):.0f}, "
            f"max={it.get('max', 0):.0f})"
        )


@dataclasses.dataclass(frozen=True)
class SolverStats:
    """Per-bucket telemetry from the convergence-adaptive RE driver.

    ``executed_lane_iterations`` counts iterations actually dispatched
    (Σ over rounds of width × chunk-advance); ``lockstep_lane_iterations``
    is what the one-shot vmap would have executed (num_entities × slowest
    entity's iteration count) — their ratio is the adaptive win.
    """

    bucket: int
    optimizer: str                 # 'lbfgs' | 'owlqn' | 'tron'
    num_entities: int
    rounds: int
    chunk_iters: int
    dispatch_widths: tuple         # lane count per round (pow2 ladder)
    iterations_p50: float
    iterations_p99: float
    iterations_max: int
    sum_entity_iterations: int     # Σ per-entity final iteration counts
    executed_lane_iterations: int
    lockstep_lane_iterations: int
    converged: int                 # entities with reason != NOT_CONVERGED
    chunk_retraces: int            # jit trace count for chunk programs

    @property
    def wasted_lane_fraction(self) -> float:
        """Fraction of executed lane-iterations spent on already-converged
        or padding lanes (0 = perfect packing)."""
        if self.executed_lane_iterations == 0:
            return 0.0
        return 1.0 - self.sum_entity_iterations / self.executed_lane_iterations

    @property
    def lane_iteration_savings(self) -> float:
        """lockstep / executed — ≥1; ≥2 on skewed-convergence workloads."""
        if self.executed_lane_iterations == 0:
            return 1.0
        return self.lockstep_lane_iterations / self.executed_lane_iterations

    def to_summary_string(self) -> str:
        return (
            f"bucket {self.bucket} ({self.optimizer}, {self.num_entities} entities): "
            f"{self.rounds} rounds of K={self.chunk_iters} at widths "
            f"{list(self.dispatch_widths)}, iterations(p50={self.iterations_p50:.0f}, "
            f"p99={self.iterations_p99:.0f}, max={self.iterations_max}), "
            f"lane-iters executed={self.executed_lane_iterations} vs "
            f"lockstep={self.lockstep_lane_iterations} "
            f"({self.lane_iteration_savings:.2f}x saved, "
            f"wasted={self.wasted_lane_fraction:.1%}), "
            f"converged={self.converged}/{self.num_entities}"
        )


@dataclasses.dataclass
class TransferStats:
    """Score-plane transfer accounting for one coordinate-descent run.

    The CD driver owns one instance per ``run`` and counts every row-length
    (``num_rows``) score array that crosses the host/device boundary, plus
    the full host score-plane re-sums the legacy host plane performs. On the
    device plane the steady state is zero row transfers and zero host sums —
    tests and the ``bench.py --cd-scores`` contract gate on exactly that.
    """

    score_plane: str               # 'host' | 'device'
    num_rows: int
    bytes_per_row_array: int = 0   # num_rows * 4 (f32), set in __post_init__
    coordinate_updates: int = 0
    outer_iterations: int = 0
    host_score_sums: int = 0       # full C-way score-plane re-sums on host
    device_plane_updates: int = 0  # incremental total += new - old updates
    row_transfers_h2d: int = 0     # row-length arrays pushed host -> device
    row_transfers_d2h: int = 0     # row-length arrays pulled device -> host

    def __post_init__(self) -> None:
        self.bytes_per_row_array = int(self.num_rows) * 4

    def record_h2d(self, arrays: int = 1) -> None:
        self.row_transfers_h2d += int(arrays)

    def record_d2h(self, arrays: int = 1) -> None:
        self.row_transfers_d2h += int(arrays)

    @property
    def row_bytes_h2d(self) -> int:
        return self.row_transfers_h2d * self.bytes_per_row_array

    @property
    def row_bytes_d2h(self) -> int:
        return self.row_transfers_d2h * self.bytes_per_row_array

    @property
    def row_bytes_total(self) -> int:
        return self.row_bytes_h2d + self.row_bytes_d2h

    def per_outer_iteration(self) -> Dict[str, float]:
        """Steady-state rates: row arrays / bytes / sums per outer iteration."""
        it = max(self.outer_iterations, 1)
        return {
            "row_transfers_per_iter": (
                (self.row_transfers_h2d + self.row_transfers_d2h) / it
            ),
            "row_bytes_per_iter": self.row_bytes_total / it,
            "host_score_sums_per_iter": self.host_score_sums / it,
        }

    def snapshot(self) -> Dict[str, object]:
        out = {
            "score_plane": self.score_plane,
            "num_rows": self.num_rows,
            "coordinate_updates": self.coordinate_updates,
            "outer_iterations": self.outer_iterations,
            "host_score_sums": self.host_score_sums,
            "device_plane_updates": self.device_plane_updates,
            "row_transfers_h2d": self.row_transfers_h2d,
            "row_transfers_d2h": self.row_transfers_d2h,
            "row_bytes_h2d": self.row_bytes_h2d,
            "row_bytes_d2h": self.row_bytes_d2h,
        }
        out.update(self.per_outer_iteration())
        return out

    def to_summary_string(self) -> str:
        return (
            f"score plane '{self.score_plane}' over {self.num_rows} rows: "
            f"{self.coordinate_updates} updates in {self.outer_iterations} "
            f"outer iterations, {self.host_score_sums} host score sums, "
            f"{self.device_plane_updates} device plane updates, "
            f"row transfers h2d={self.row_transfers_h2d} "
            f"d2h={self.row_transfers_d2h} "
            f"({self.row_bytes_total / 1e6:.3f} MB)"
        )


def _stats(x: np.ndarray) -> Dict[str, float]:
    if x.size == 0:
        return {}
    return {
        "min": float(np.min(x)),
        "max": float(np.max(x)),
        "mean": float(np.mean(x)),
        "p50": float(np.percentile(x, 50)),
        "p90": float(np.percentile(x, 90)),
    }
