"""Optimizer and regularization configuration.

Reference parity: optimization/OptimizerConfig.scala:23,
RegularizationContext.scala:35 (elastic-net α split :55-76),
GLMOptimizationConfiguration.scala:28, OptimizerFactory.scala:27 (OWL-QN is
selected automatically whenever the L1 component is positive). The reference's
string mini-language (``maxIter,tol,λ,downSampleRate,optimizer,regType``) is
replaced by typed dataclasses; cli/ provides parsing from structured config.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Tuple

from photon_ml_tpu.types import RegularizationType


class OptimizerType(enum.Enum):
    LBFGS = "lbfgs"
    TRON = "tron"
    # OWL-QN is not user-selectable in the reference either; it is LBFGS's
    # L1 mode, chosen by the factory when l1_weight > 0.


@dataclasses.dataclass(frozen=True)
class RegularizationContext:
    """Splits a single regularization weight λ into (l1, l2) parts.

    ELASTIC_NET with mixing α: l1 = α·λ, l2 = (1-α)·λ
    (reference RegularizationContext.scala:55-76).
    """

    reg_type: RegularizationType = RegularizationType.NONE
    alpha: Optional[float] = None  # elastic-net mixing, required for ELASTIC_NET

    def __post_init__(self) -> None:
        if self.reg_type is RegularizationType.ELASTIC_NET:
            a = self.alpha if self.alpha is not None else 0.5
            if not (0.0 <= a <= 1.0):
                raise ValueError(f"elastic net alpha must be in [0,1], got {a}")
        elif self.alpha is not None:
            raise ValueError(f"alpha is only valid for ELASTIC_NET, got {self.reg_type}")

    def l1_weight(self, reg_weight: float) -> float:
        if self.reg_type is RegularizationType.L1:
            return reg_weight
        if self.reg_type is RegularizationType.ELASTIC_NET:
            return (self.alpha if self.alpha is not None else 0.5) * reg_weight
        return 0.0

    def l2_weight(self, reg_weight: float) -> float:
        if self.reg_type is RegularizationType.L2:
            return reg_weight
        if self.reg_type is RegularizationType.ELASTIC_NET:
            return (1.0 - (self.alpha if self.alpha is not None else 0.5)) * reg_weight
        return 0.0


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Static solver knobs (hashable; passed as a jit static argument).

    Defaults mirror the reference: LBFGS maxIter=100, m=10, tol=1e-7
    (LBFGS.scala:147-152); TRON maxIter=15, ≤20 CG iterations, tol=1e-5
    (TRON.scala:253-259).
    """

    optimizer: OptimizerType = OptimizerType.LBFGS
    max_iterations: int = 100
    tolerance: float = 1e-7
    # LBFGS
    history_length: int = 10
    max_line_search_iterations: int = 25
    # Storage dtype for the [m, d] s/y history ring buffers — "bfloat16"
    # halves the dominant memory term of huge-d solves (SCALING.md: at 1e9
    # coefficients the m=10 history is 10 GB/chip in f32); all dot products
    # still accumulate in the working dtype. None = same dtype as w.
    history_dtype: Optional[str] = None
    # TRON
    max_cg_iterations: int = 20
    cg_tolerance: float = 0.1
    max_improvement_failures: int = 5  # TRON.scala maxNumImprovementFailures
    # Box constraints: (lower, upper) scalars or None. Per-coefficient boxes
    # are passed at solve time as arrays (reference parses a per-feature
    # constraint map; see estimators).
    constraint_lower: Optional[float] = None
    constraint_upper: Optional[float] = None
    # Record per-iteration coefficients in SolveResult.w_history
    # ([max_iterations+1, d] — the reference's ModelTracker). Costs a
    # max_iter x d buffer; off by default.
    track_coefficients: bool = False

    def __post_init__(self) -> None:
        if self.history_dtype not in (None, "float32", "bfloat16"):
            raise ValueError(
                f"history_dtype must be None/float32/bfloat16, "
                f"got {self.history_dtype!r}"
            )

    @classmethod
    def lbfgs(cls, **kw) -> "OptimizerConfig":
        return cls(optimizer=OptimizerType.LBFGS, **kw)

    @classmethod
    def tron(cls, **kw) -> "OptimizerConfig":
        kw.setdefault("max_iterations", 15)
        kw.setdefault("tolerance", 1e-5)
        return cls(optimizer=OptimizerType.TRON, **kw)


@dataclasses.dataclass(frozen=True)
class AdaptiveSolveConfig:
    """Knobs for the convergence-adaptive random-effect driver (hashable;
    part of the jit program cache key).

    The driver runs the vmap'd per-entity solve in chunks of ``chunk_iters``
    outer iterations, pulls the per-lane converged mask after each chunk,
    compacts unconverged entities into a dense prefix, and re-dispatches at
    the next smaller power-of-two lane count. Compiled-program count per
    (optimizer, bucket shape) is therefore bounded by the pow2 ladder.
    ``enabled=False`` restores the one-shot lockstep dispatch exactly.
    """

    enabled: bool = True
    # Outer solver iterations per chunk. Small K pulls the converged mask
    # often (more savings on skewed workloads) at the cost of more dispatches.
    chunk_iters: int = 8
    # Stop shrinking below this lane count: tiny dispatches are dominated by
    # launch overhead, so the tail just runs lockstep at this width.
    min_lanes: int = 8

    def __post_init__(self) -> None:
        if self.chunk_iters < 1:
            raise ValueError(f"chunk_iters must be >= 1, got {self.chunk_iters}")
        if self.min_lanes < 1:
            raise ValueError(f"min_lanes must be >= 1, got {self.min_lanes}")


@dataclasses.dataclass(frozen=True)
class GlmOptimizationConfiguration:
    """Per-problem bundle: solver + regularization + λ + down-sampling rate
    (reference GLMOptimizationConfiguration.scala:28)."""

    optimizer_config: OptimizerConfig = OptimizerConfig()
    regularization: RegularizationContext = RegularizationContext()
    regularization_weight: float = 0.0
    down_sampling_rate: float = 1.0
    # Convergence-adaptive random-effect solving (chunked rounds + lane
    # compaction); only consulted by train_random_effects.
    adaptive: AdaptiveSolveConfig = AdaptiveSolveConfig()

    def __post_init__(self) -> None:
        if not (0.0 < self.down_sampling_rate <= 1.0):
            raise ValueError(f"down_sampling_rate in (0,1], got {self.down_sampling_rate}")
        if self.regularization_weight < 0:
            raise ValueError("regularization_weight must be >= 0")

    @property
    def l1_weight(self) -> float:
        return self.regularization.l1_weight(self.regularization_weight)

    @property
    def l2_weight(self) -> float:
        return self.regularization.l2_weight(self.regularization_weight)
