"""Evaluators: AUC, RMSE/MSE/MAE, per-task losses, grouped metrics, P@k.

Reference parity: evaluation/Evaluator.scala:23 (evaluate(scores) joined with
label/offset/weight, `betterThan` direction :62), EvaluatorType.scala:21,
AreaUnderROCCurveLocalEvaluator.scala:25 (single-pass rank-sum AUC with tie
averaging :33), RMSEEvaluator and the loss evaluators, MultiEvaluator.scala:39
(group scores by an id tag, one metric per group, unweighted mean :49-64),
PrecisionAtK{Local,Multi}Evaluator, EvaluatorFactory.scala:22.

The core metrics are jit-compiled sort/segment programs (AUC = one sort +
cumulative sums — the TPU replacement for the reference's per-partition
rank-sum); grouped evaluation reuses them per group via a stable host-side
group partition (evaluation is off the training hot path).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.losses.pointwise import (
    LogisticLoss,
    PoissonLoss,
    SmoothedHingeLoss,
    SquaredLoss,
)
from photon_ml_tpu.types import TaskType


class EvaluatorType(enum.Enum):
    AUC = "AUC"
    RMSE = "RMSE"
    MSE = "MSE"
    MAE = "MAE"
    LOGISTIC_LOSS = "LOGISTIC_LOSS"
    POISSON_LOSS = "POISSON_LOSS"
    SQUARED_LOSS = "SQUARED_LOSS"
    SMOOTHED_HINGE_LOSS = "SMOOTHED_HINGE_LOSS"
    PRECISION_AT_K = "PRECISION_AT_K"


@jax.jit
def area_under_roc_curve(scores: jax.Array, labels: jax.Array, weights=None) -> jax.Array:
    """Rank-sum (Mann-Whitney) AUC with tie averaging, one sort.

    Matches reference AreaUnderROCCurveLocalEvaluator.scala:33-77 (which
    sorts by score and averages ranks across tied groups). Weighted variant:
    ranks become cumulative weights; reduces to the classic formula when all
    weights are 1. Returns NaN when only one class is present (reference
    returns NaN/undefined there too).
    """
    n = scores.shape[0]
    if weights is None:
        weights = jnp.ones_like(scores)
    pos_w = jnp.where(labels > 0.5, weights, 0.0)
    neg_w = jnp.where(labels > 0.5, 0.0, weights)

    order = jnp.argsort(scores)
    s_sorted = scores[order]
    pw = pos_w[order]
    nw = neg_w[order]

    # AUC = P(score_pos > score_neg) + 0.5*P(tie), weighted:
    # sum_i pw_i * (negweight strictly below i + 0.5 * negweight tied with i)
    # over W_pos * W_neg. Tie groups found after one sort.
    is_new = jnp.concatenate([jnp.array([True]), s_sorted[1:] != s_sorted[:-1]])
    seg = jnp.cumsum(is_new.astype(jnp.int32)) - 1  # tie-group id per element
    neg_cum = jnp.cumsum(nw)
    seg_neg_w = jnp.zeros((n,), dtype=nw.dtype).at[seg].add(nw)  # neg weight per group
    seg_neg_end = jnp.zeros((n,), dtype=nw.dtype).at[seg].max(neg_cum)
    neg_below = seg_neg_end[seg] - seg_neg_w[seg]  # strictly-lower neg weight
    u = jnp.sum(pw * (neg_below + 0.5 * seg_neg_w[seg]))
    w_pos = jnp.sum(pw)
    w_neg = jnp.sum(nw)
    auc = u / (w_pos * w_neg)
    return jnp.where((w_pos > 0) & (w_neg > 0), auc, jnp.nan)


def _weighted_mean(terms: jax.Array, weights: jax.Array) -> jax.Array:
    return jnp.sum(jnp.where(weights > 0, weights * terms, 0.0)) / jnp.maximum(
        jnp.sum(weights), 1e-30
    )


def _np_auc(s: np.ndarray, y: np.ndarray, w: np.ndarray) -> float:
    """Numpy twin of area_under_roc_curve (identical tie/weight semantics)."""
    pos_w = np.where(y > 0.5, w, 0.0)
    neg_w = np.where(y > 0.5, 0.0, w)
    order = np.argsort(s, kind="stable")
    ss, pw, nw = s[order], pos_w[order], neg_w[order]
    is_new = np.concatenate([[True], ss[1:] != ss[:-1]])
    seg = np.cumsum(is_new) - 1
    seg_neg = np.bincount(seg, weights=nw)
    neg_below = np.cumsum(seg_neg)[seg] - seg_neg[seg]
    u = float(np.sum(pw * (neg_below + 0.5 * seg_neg[seg])))
    w_pos, w_neg = float(pw.sum()), float(nw.sum())
    return u / (w_pos * w_neg) if w_pos > 0 and w_neg > 0 else float("nan")


def _np_wmean(terms: np.ndarray, w: np.ndarray) -> float:
    return float(np.sum(np.where(w > 0, w * terms, 0.0)) / max(np.sum(w), 1e-30))


def _np_logistic(s, y, w):
    return _np_wmean(np.logaddexp(0.0, s) - y * s, w)


def _np_poisson(s, y, w):
    return _np_wmean(np.exp(s) - y * s, w)


def _np_smoothed_hinge(s, y, w):
    u = np.where(y > 0.5, 1.0, -1.0) * s
    terms = np.where(u >= 1, 0.0, np.where(u <= 0, 0.5 - u, 0.5 * (1 - u) ** 2))
    return _np_wmean(terms, w)


def nan_aware_better_than(a: float, b: float, larger_is_better: bool = True) -> bool:
    """Is metric a better than b; any value beats NaN, NaN beats nothing
    (reference Evaluator.betterThan semantics)."""
    if b != b:
        return True
    if a != a:
        return False
    return a > b if larger_is_better else a < b


@dataclasses.dataclass(frozen=True)
class Evaluator:
    """Metric with an ordering (is higher better?). ``host_fn`` is a numpy
    twin used for per-group evaluation, where calling the jit'd ``fn`` would
    recompile for every distinct group size."""

    name: str
    fn: Callable  # (scores, labels, weights) -> scalar
    larger_is_better: bool
    host_fn: Optional[Callable] = None

    def evaluate(self, scores, labels, weights=None) -> float:
        scores = jnp.asarray(scores)
        labels = jnp.asarray(labels)
        weights = jnp.ones_like(scores) if weights is None else jnp.asarray(weights)
        return float(self.fn(scores, labels, weights))

    def evaluate_host(self, scores, labels, weights) -> float:
        if self.host_fn is not None:
            return float(self.host_fn(scores, labels, weights))
        return self.evaluate(scores, labels, weights)

    def better_than(self, a: float, b: float) -> bool:
        """Is metric value a better than b (reference Evaluator.betterThan)."""
        return nan_aware_better_than(a, b, self.larger_is_better)


AUC = Evaluator("AUC", area_under_roc_curve, larger_is_better=True, host_fn=_np_auc)
RMSE = Evaluator(
    "RMSE",
    jax.jit(lambda s, y, w: jnp.sqrt(_weighted_mean((s - y) ** 2, w))),
    larger_is_better=False,
    host_fn=lambda s, y, w: np.sqrt(_np_wmean((s - y) ** 2, w)),
)
MSE = Evaluator(
    "MSE",
    jax.jit(lambda s, y, w: _weighted_mean((s - y) ** 2, w)),
    larger_is_better=False,
    host_fn=lambda s, y, w: _np_wmean((s - y) ** 2, w),
)
MAE = Evaluator(
    "MAE",
    jax.jit(lambda s, y, w: _weighted_mean(jnp.abs(s - y), w)),
    larger_is_better=False,
    host_fn=lambda s, y, w: _np_wmean(np.abs(s - y), w),
)
LogisticLossEvaluator = Evaluator(
    "LOGISTIC_LOSS",
    jax.jit(lambda s, y, w: _weighted_mean(LogisticLoss.value(s, y), w)),
    larger_is_better=False,
    host_fn=_np_logistic,
)
PoissonLossEvaluator = Evaluator(
    "POISSON_LOSS",
    jax.jit(lambda s, y, w: _weighted_mean(PoissonLoss.value(s, y), w)),
    larger_is_better=False,
    host_fn=_np_poisson,
)
SquaredLossEvaluator = Evaluator(
    "SQUARED_LOSS",
    jax.jit(lambda s, y, w: _weighted_mean(SquaredLoss.value(s, y), w)),
    larger_is_better=False,
    host_fn=lambda s, y, w: _np_wmean(0.5 * (s - y) ** 2, w),
)
SmoothedHingeLossEvaluator = Evaluator(
    "SMOOTHED_HINGE_LOSS",
    jax.jit(lambda s, y, w: _weighted_mean(SmoothedHingeLoss.value(s, y), w)),
    larger_is_better=False,
    host_fn=_np_smoothed_hinge,
)


def PrecisionAtK(k: int) -> Evaluator:
    """Precision@k: fraction of positives among the k highest scores
    (reference PrecisionAtKLocalEvaluator; typically used per-group)."""

    def fn(scores, labels, weights):
        kk = min(k, scores.shape[0])
        top = jnp.argsort(-scores)[:kk]
        return jnp.mean((labels[top] > 0.5).astype(jnp.float32))

    def host_fn(scores, labels, weights):
        kk = min(k, len(scores))
        top = np.argsort(-scores, kind="stable")[:kk]
        return float(np.mean(labels[top] > 0.5))

    return Evaluator(f"PRECISION@{k}", jax.jit(fn), larger_is_better=True, host_fn=host_fn)


@dataclasses.dataclass(frozen=True)
class MultiEvaluator:
    """Grouped ("sharded") metric: apply ``base`` per id-tag group, average
    the per-group values, skipping groups where the metric is undefined
    (reference MultiEvaluator.scala:49-64, e.g. single-class AUC groups)."""

    base: Evaluator
    group_ids: tuple  # hashable snapshot of per-row group keys
    tag: Optional[str] = None  # the id-tag name, for log/metric labels

    @property
    def name(self) -> str:
        return f"{self.base.name}:{self.tag or 'grouped'}"

    @property
    def larger_is_better(self) -> bool:
        return self.base.larger_is_better

    def better_than(self, a: float, b: float) -> bool:
        return self.base.better_than(a, b)

    def evaluate(self, scores, labels, weights=None) -> float:
        scores = np.asarray(scores)
        labels = np.asarray(labels)
        weights = np.ones_like(scores) if weights is None else np.asarray(weights)
        gids = np.asarray(self.group_ids)
        # one sort partitions all groups; per-group metric runs on the host
        # numpy twin (the jit'd fn would recompile per distinct group size)
        order = np.argsort(gids, kind="stable")
        sorted_gids = gids[order]
        starts = np.flatnonzero(
            np.concatenate([[True], sorted_gids[1:] != sorted_gids[:-1]])
        )
        ends = np.append(starts[1:], len(gids))
        vals = []
        for s, e in zip(starts, ends):
            idx = order[s:e]
            v = self.base.evaluate_host(scores[idx], labels[idx], weights[idx])
            if v == v:  # skip NaN groups
                vals.append(v)
        return float(np.mean(vals)) if vals else float("nan")


def evaluator_for(etype: EvaluatorType, k: int = 10) -> Evaluator:
    """EvaluatorType -> implementation (reference EvaluatorFactory.scala:22)."""
    table = {
        EvaluatorType.AUC: AUC,
        EvaluatorType.RMSE: RMSE,
        EvaluatorType.MSE: MSE,
        EvaluatorType.MAE: MAE,
        EvaluatorType.LOGISTIC_LOSS: LogisticLossEvaluator,
        EvaluatorType.POISSON_LOSS: PoissonLossEvaluator,
        EvaluatorType.SQUARED_LOSS: SquaredLossEvaluator,
        EvaluatorType.SMOOTHED_HINGE_LOSS: SmoothedHingeLossEvaluator,
    }
    if etype is EvaluatorType.PRECISION_AT_K:
        return PrecisionAtK(k)
    return table[etype]


def default_evaluator(task: TaskType) -> Evaluator:
    """Task -> default validation metric (reference GameEstimator default
    evaluators: AUC for logistic, RMSE for linear, Poisson loss for Poisson)."""
    return {
        TaskType.LOGISTIC_REGRESSION: AUC,
        TaskType.LINEAR_REGRESSION: RMSE,
        TaskType.POISSON_REGRESSION: PoissonLossEvaluator,
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: AUC,
    }[task]
