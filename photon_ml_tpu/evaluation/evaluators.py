"""Evaluators: AUC, RMSE/MSE/MAE, per-task losses, grouped metrics, P@k.

Reference parity: evaluation/Evaluator.scala:23 (evaluate(scores) joined with
label/offset/weight, `betterThan` direction :62), EvaluatorType.scala:21,
AreaUnderROCCurveLocalEvaluator.scala:25 (single-pass rank-sum AUC with tie
averaging :33), RMSEEvaluator and the loss evaluators, MultiEvaluator.scala:39
(group scores by an id tag, one metric per group, unweighted mean :49-64),
PrecisionAtK{Local,Multi}Evaluator, EvaluatorFactory.scala:22.

The core metrics are jit-compiled sort/segment programs (AUC = one sort +
cumulative sums — the TPU replacement for the reference's per-partition
rank-sum); grouped evaluation reuses them per group via a stable host-side
group partition (evaluation is off the training hot path).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.losses.pointwise import (
    LogisticLoss,
    PoissonLoss,
    SmoothedHingeLoss,
    SquaredLoss,
)
from photon_ml_tpu.types import TaskType


class EvaluatorType(enum.Enum):
    AUC = "AUC"
    RMSE = "RMSE"
    MSE = "MSE"
    MAE = "MAE"
    LOGISTIC_LOSS = "LOGISTIC_LOSS"
    POISSON_LOSS = "POISSON_LOSS"
    SQUARED_LOSS = "SQUARED_LOSS"
    SMOOTHED_HINGE_LOSS = "SMOOTHED_HINGE_LOSS"
    PRECISION_AT_K = "PRECISION_AT_K"


@jax.jit
def area_under_roc_curve(scores: jax.Array, labels: jax.Array, weights=None) -> jax.Array:
    """Rank-sum (Mann-Whitney) AUC with tie averaging, one sort.

    Matches reference AreaUnderROCCurveLocalEvaluator.scala:33-77 (which
    sorts by score and averages ranks across tied groups). Weighted variant:
    ranks become cumulative weights; reduces to the classic formula when all
    weights are 1. Returns NaN when only one class is present (reference
    returns NaN/undefined there too).
    """
    n = scores.shape[0]
    if weights is None:
        weights = jnp.ones_like(scores)
    pos_w = jnp.where(labels > 0.5, weights, 0.0)
    neg_w = jnp.where(labels > 0.5, 0.0, weights)

    order = jnp.argsort(scores)
    s_sorted = scores[order]
    pw = pos_w[order]
    nw = neg_w[order]

    # AUC = P(score_pos > score_neg) + 0.5*P(tie), weighted:
    # sum_i pw_i * (negweight strictly below i + 0.5 * negweight tied with i)
    # over W_pos * W_neg. Tie groups found after one sort.
    is_new = jnp.concatenate([jnp.array([True]), s_sorted[1:] != s_sorted[:-1]])
    seg = jnp.cumsum(is_new.astype(jnp.int32)) - 1  # tie-group id per element
    neg_cum = jnp.cumsum(nw)
    seg_neg_w = jnp.zeros((n,), dtype=nw.dtype).at[seg].add(nw)  # neg weight per group
    seg_neg_end = jnp.zeros((n,), dtype=nw.dtype).at[seg].max(neg_cum)
    neg_below = seg_neg_end[seg] - seg_neg_w[seg]  # strictly-lower neg weight
    u = jnp.sum(pw * (neg_below + 0.5 * seg_neg_w[seg]))
    w_pos = jnp.sum(pw)
    w_neg = jnp.sum(nw)
    auc = u / (w_pos * w_neg)
    return jnp.where((w_pos > 0) & (w_neg > 0), auc, jnp.nan)


def _weighted_mean(terms: jax.Array, weights: jax.Array) -> jax.Array:
    return jnp.sum(jnp.where(weights > 0, weights * terms, 0.0)) / jnp.maximum(
        jnp.sum(weights), 1e-30
    )


@dataclasses.dataclass(frozen=True)
class Evaluator:
    """Metric with an ordering (is higher better?)."""

    name: str
    fn: Callable  # (scores, labels, weights) -> scalar
    larger_is_better: bool

    def evaluate(self, scores, labels, weights=None) -> float:
        scores = jnp.asarray(scores)
        labels = jnp.asarray(labels)
        weights = jnp.ones_like(scores) if weights is None else jnp.asarray(weights)
        return float(self.fn(scores, labels, weights))

    def better_than(self, a: float, b: float) -> bool:
        """Is metric value a better than b (reference Evaluator.betterThan)."""
        if b != b:  # b is NaN
            return True
        if a != a:
            return False
        return a > b if self.larger_is_better else a < b


AUC = Evaluator("AUC", area_under_roc_curve, larger_is_better=True)
RMSE = Evaluator(
    "RMSE",
    jax.jit(lambda s, y, w: jnp.sqrt(_weighted_mean((s - y) ** 2, w))),
    larger_is_better=False,
)
MSE = Evaluator(
    "MSE", jax.jit(lambda s, y, w: _weighted_mean((s - y) ** 2, w)), larger_is_better=False
)
MAE = Evaluator(
    "MAE", jax.jit(lambda s, y, w: _weighted_mean(jnp.abs(s - y), w)), larger_is_better=False
)
LogisticLossEvaluator = Evaluator(
    "LOGISTIC_LOSS",
    jax.jit(lambda s, y, w: _weighted_mean(LogisticLoss.value(s, y), w)),
    larger_is_better=False,
)
PoissonLossEvaluator = Evaluator(
    "POISSON_LOSS",
    jax.jit(lambda s, y, w: _weighted_mean(PoissonLoss.value(s, y), w)),
    larger_is_better=False,
)
SquaredLossEvaluator = Evaluator(
    "SQUARED_LOSS",
    jax.jit(lambda s, y, w: _weighted_mean(SquaredLoss.value(s, y), w)),
    larger_is_better=False,
)
SmoothedHingeLossEvaluator = Evaluator(
    "SMOOTHED_HINGE_LOSS",
    jax.jit(lambda s, y, w: _weighted_mean(SmoothedHingeLoss.value(s, y), w)),
    larger_is_better=False,
)


def PrecisionAtK(k: int) -> Evaluator:
    """Precision@k: fraction of positives among the k highest scores
    (reference PrecisionAtKLocalEvaluator; typically used per-group)."""

    def fn(scores, labels, weights):
        kk = min(k, scores.shape[0])
        top = jnp.argsort(-scores)[:kk]
        return jnp.mean((labels[top] > 0.5).astype(jnp.float32))

    return Evaluator(f"PRECISION@{k}", jax.jit(fn), larger_is_better=True)


@dataclasses.dataclass(frozen=True)
class MultiEvaluator:
    """Grouped ("sharded") metric: apply ``base`` per id-tag group, average
    the per-group values, skipping groups where the metric is undefined
    (reference MultiEvaluator.scala:49-64, e.g. single-class AUC groups)."""

    base: Evaluator
    group_ids: tuple  # hashable snapshot of per-row group keys

    @property
    def name(self) -> str:
        return f"{self.base.name}:grouped"

    @property
    def larger_is_better(self) -> bool:
        return self.base.larger_is_better

    def better_than(self, a: float, b: float) -> bool:
        return self.base.better_than(a, b)

    def evaluate(self, scores, labels, weights=None) -> float:
        scores = np.asarray(scores)
        labels = np.asarray(labels)
        weights = np.ones_like(scores) if weights is None else np.asarray(weights)
        gids = np.asarray(self.group_ids)
        vals = []
        for g in np.unique(gids):
            m = gids == g
            v = self.base.evaluate(scores[m], labels[m], weights[m])
            if v == v:  # skip NaN groups
                vals.append(v)
        return float(np.mean(vals)) if vals else float("nan")


def evaluator_for(etype: EvaluatorType, k: int = 10) -> Evaluator:
    """EvaluatorType -> implementation (reference EvaluatorFactory.scala:22)."""
    table = {
        EvaluatorType.AUC: AUC,
        EvaluatorType.RMSE: RMSE,
        EvaluatorType.MSE: MSE,
        EvaluatorType.MAE: MAE,
        EvaluatorType.LOGISTIC_LOSS: LogisticLossEvaluator,
        EvaluatorType.POISSON_LOSS: PoissonLossEvaluator,
        EvaluatorType.SQUARED_LOSS: SquaredLossEvaluator,
        EvaluatorType.SMOOTHED_HINGE_LOSS: SmoothedHingeLossEvaluator,
    }
    if etype is EvaluatorType.PRECISION_AT_K:
        return PrecisionAtK(k)
    return table[etype]


def default_evaluator(task: TaskType) -> Evaluator:
    """Task -> default validation metric (reference GameEstimator default
    evaluators: AUC for logistic, RMSE for linear, Poisson loss for Poisson)."""
    return {
        TaskType.LOGISTIC_REGRESSION: AUC,
        TaskType.LINEAR_REGRESSION: RMSE,
        TaskType.POISSON_REGRESSION: PoissonLossEvaluator,
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: AUC,
    }[task]
