"""Feature normalization folded into the objective algebraically.

Top-level module (not under losses/) because both ops.data and losses.objective
depend on it: the context is a flax pytree that travels WITH the data batch so
jit treats factor/shift as traced arguments, never as baked-in constants.

Reference parity: normalization/NormalizationContext.scala:39 — the transform
x -> (x - shift) .* factor is NEVER materialized on the data; instead the
objective uses effective coefficients ``ew = factor .* w`` and a scalar margin
correction ``- dot(shift, ew)`` (ValueAndGradientAggregator.scala:35-79), so
sparse feature batches stay sparse. ``transform_model_coefficients`` maps the
trained coefficients back to the original feature space
(NormalizationContext.scala:71-82).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from photon_ml_tpu.types import NormalizationType


@struct.dataclass
class NormalizationContext:
    """factor/shift are [d] arrays or None (no-op). When shift is present an
    intercept must exist; the intercept's slot has factor 1, shift 0
    (enforced by the factory, reference NormalizationContext.scala:95-145)."""

    factor: Optional[jax.Array] = None
    shift: Optional[jax.Array] = None

    @property
    def is_identity(self) -> bool:
        return self.factor is None and self.shift is None

    def effective_coefficients(self, w: jax.Array) -> jax.Array:
        return w * self.factor if self.factor is not None else w

    def margin_shift(self, ew: jax.Array) -> jax.Array:
        """Scalar correction subtracted from every margin."""
        if self.shift is None:
            return jnp.zeros((), dtype=ew.dtype)
        return jnp.dot(self.shift, ew)

    def apply_to_gradient(self, raw: jax.Array, csum: jax.Array) -> jax.Array:
        """Map d(loss)/d(ew) pieces to d(loss)/dw.

        raw = X^T c, csum = sum(c); grad_j = factor_j * (raw_j - shift_j*csum).
        """
        g = raw
        if self.shift is not None:
            g = g - self.shift * csum
        if self.factor is not None:
            g = g * self.factor
        return g

    def transform_model_coefficients(self, w: jax.Array, intercept_index: Optional[int]) -> jax.Array:
        """Trained-in-normalized-space w -> original-space coefficients
        (reference NormalizationContext.scala:71-82): w_orig = factor .* w,
        intercept_orig = intercept - dot(shift, factor .* w)."""
        w_orig = self.effective_coefficients(w)
        if self.shift is not None:
            if intercept_index is None:
                raise ValueError("shift normalization requires an intercept")
            correction = jnp.dot(self.shift, w_orig)
            w_orig = w_orig.at[intercept_index].add(-correction)
        return w_orig

    def inverse_transform_model_coefficients(
        self, w_orig: jax.Array, intercept_index: Optional[int]
    ) -> jax.Array:
        """Original-space coefficients -> normalized-space (exact inverse of
        ``transform_model_coefficients``; used to warm-start a normalized
        solve from a saved original-space model)."""
        w = w_orig
        if self.shift is not None:
            if intercept_index is None:
                raise ValueError("shift normalization requires an intercept")
            correction = jnp.dot(self.shift, w_orig)
            w = w.at[intercept_index].add(correction)
        if self.factor is not None:
            w = w / self.factor
        return w

    def transform_model_variances(
        self, v: jax.Array, intercept_index: Optional[int]
    ) -> jax.Array:
        """Normalized-space coefficient variances -> original space.

        Delta method on the linear map w_orig = factor .* w (and the
        intercept's shift correction, treating coefficients as independent):
        var_orig = factor^2 .* var; var_intercept += sum((shift*factor)^2 var).
        (The reference pushes variances through the same transform as means —
        GeneralizedLinearOptimizationProblem.scala:94-95 — which drops the
        square; this is the mathematically consistent version.)
        """
        v_orig = v * self.factor * self.factor if self.factor is not None else v
        if self.shift is not None:
            if intercept_index is None:
                raise ValueError("shift normalization requires an intercept")
            extra = jnp.sum((self.shift * self.shift) * v_orig) - (
                self.shift[intercept_index] ** 2
            ) * v_orig[intercept_index]
            v_orig = v_orig.at[intercept_index].add(extra)
        return v_orig


def build_normalization_context(
    norm_type: NormalizationType,
    mean: jax.Array,
    variance: jax.Array,
    max_magnitude: jax.Array,
    intercept_index: Optional[int],
) -> NormalizationContext:
    """Factory from feature summary statistics (reference
    NormalizationContext.scala:95-145).

    - SCALE_WITH_STANDARD_DEVIATION: factor = 1/std
    - SCALE_WITH_MAX_MAGNITUDE:      factor = 1/max|x|
    - STANDARDIZATION:               factor = 1/std, shift = mean (needs intercept)
    """
    if norm_type is NormalizationType.NONE:
        return NormalizationContext()

    std = jnp.sqrt(variance)
    inv_std = jnp.where(std > 0, 1.0 / jnp.maximum(std, 1e-30), 1.0)
    if norm_type is NormalizationType.SCALE_WITH_STANDARD_DEVIATION:
        factor, shift = inv_std, None
    elif norm_type is NormalizationType.SCALE_WITH_MAX_MAGNITUDE:
        mm = jnp.abs(max_magnitude)
        factor = jnp.where(mm > 0, 1.0 / jnp.maximum(mm, 1e-30), 1.0)
        shift = None
    elif norm_type is NormalizationType.STANDARDIZATION:
        if intercept_index is None:
            raise ValueError("STANDARDIZATION requires an intercept feature")
        factor, shift = inv_std, mean
    else:
        raise ValueError(f"unknown normalization type {norm_type}")

    if intercept_index is not None:
        factor = factor.at[intercept_index].set(1.0)
        if shift is not None:
            shift = shift.at[intercept_index].set(0.0)
    return NormalizationContext(factor=factor, shift=shift)
