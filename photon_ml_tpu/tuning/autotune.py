"""A/B trial harness for ``--auto-tune``: MetricsRegistry as the judge.

:func:`run_ab_trials` runs each candidate config through a caller-supplied
trial function and picks the winner by a named metric read from a **fresh**
:class:`~photon_ml_tpu.telemetry.metrics.MetricsRegistry` per trial — never
the process-global registry, so (a) trial A's counters cannot leak into
trial B's judgment and (b) the surrounding run's telemetry is not polluted
by trial traffic. The lifecycle tests in ``tests/test_telemetry.py`` pin
this isolation contract.

The trial function does the real work (an iteration-0 fit, a warmup
replay) and records whatever it wants into the registry it is handed; if
it records nothing under the judge metric, the harness falls back to the
trial's wall-clock (recorded as ``autotune.wall_s``).
"""
from __future__ import annotations

import dataclasses
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Sequence

from photon_ml_tpu.telemetry.metrics import MetricsRegistry

__all__ = ["TrialResult", "ABResult", "judge_from_snapshot", "run_ab_trials"]

DEFAULT_JUDGE_METRIC = "autotune.wall_s"


def judge_from_snapshot(snapshot: Dict[str, Any], metric: str) -> Optional[float]:
    """Read a judge metric from a registry snapshot: counters first, then
    gauge last-values, then histogram means."""
    counters = snapshot.get("counters") or {}
    if metric in counters:
        return float(counters[metric])
    gauges = snapshot.get("gauges") or {}
    if metric in gauges:
        return float(gauges[metric]["last"])
    hists = snapshot.get("histograms") or {}
    if metric in hists:
        return float(hists[metric].get("mean", 0.0))
    return None


@dataclasses.dataclass
class TrialResult:
    index: int
    config: Dict[str, Any]
    score: Optional[float]
    wall_s: float
    snapshot: Dict[str, Any]
    error: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d.pop("snapshot", None)  # snapshots are bulky; keep results portable
        return d


@dataclasses.dataclass
class ABResult:
    judge_metric: str
    minimize: bool
    trials: List[TrialResult]
    winner_index: int

    @property
    def winner(self) -> TrialResult:
        return self.trials[self.winner_index]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "judge_metric": self.judge_metric,
            "minimize": self.minimize,
            "winner_index": self.winner_index,
            "winner_config": self.winner.config,
            "trials": [t.to_dict() for t in self.trials],
        }


def run_ab_trials(
    candidates: Sequence[Dict[str, Any]],
    run_trial: Callable[[Dict[str, Any], MetricsRegistry], None],
    judge_metric: str = DEFAULT_JUDGE_METRIC,
    minimize: bool = True,
    logger=None,
) -> ABResult:
    """Run every candidate, judge by ``judge_metric``, return the bracket.

    A trial that raises is recorded with its error and an infinitely-bad
    score rather than aborting the bracket — auto-tune must never make a
    run fail that would have succeeded untuned. Candidate 0 (the control)
    wins ties, so the incumbent config is only displaced by a strict win.
    """
    if not candidates:
        raise ValueError("run_ab_trials needs at least one candidate")
    trials: List[TrialResult] = []
    for i, config in enumerate(candidates):
        registry = MetricsRegistry()  # fresh per trial: no cross-trial leaks
        start = time.perf_counter()
        error = None
        try:
            run_trial(dict(config), registry)
        except Exception:
            error = traceback.format_exc(limit=8)
        wall = time.perf_counter() - start
        registry.gauge("autotune.wall_s", wall)
        snapshot = registry.snapshot()
        score = None if error else judge_from_snapshot(snapshot, judge_metric)
        if score is None and not error:
            score = judge_from_snapshot(snapshot, DEFAULT_JUDGE_METRIC)
        trials.append(
            TrialResult(
                index=i,
                config=dict(config),
                score=score,
                wall_s=wall,
                snapshot=snapshot,
                error=error,
            )
        )
        if logger is not None:
            logger.info(
                "auto-tune trial %d/%d: %s=%s wall=%.3fs config=%s%s",
                i + 1,
                len(candidates),
                judge_metric,
                f"{score:.6g}" if score is not None else "n/a",
                wall,
                config,
                " (FAILED)" if error else "",
            )

    def _key(t: TrialResult) -> float:
        if t.score is None:
            return float("inf")
        return t.score if minimize else -t.score

    best = min(range(len(trials)), key=lambda i: (_key(trials[i]), i))
    return ABResult(
        judge_metric=judge_metric,
        minimize=minimize,
        trials=trials,
        winner_index=best,
    )
