"""Declared knob space: every tunable config surface, registered once.

The registration mechanism is the contract that keeps future knobs
observable: a knob is not tunable until it declares *which report metrics
its decision depends on* (``metric_deps``) and *which phase it moves*
(``phase``). The offline tuner refuses to reason about config surfaces
that are not in this table, so adding a knob forces you to say what
evidence would justify changing it.

Knobs are identified by dotted names mirroring where they act:
``adaptive.*`` feed :class:`photon_ml_tpu.opt.config.AdaptiveSolveConfig`,
``serving.*`` are ``serve_game`` CLI surfaces, ``train.*`` are
``train_game``/engine surfaces.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

__all__ = ["KnobSpec", "register_knob", "get_knob", "all_knobs", "KNOBS"]


@dataclasses.dataclass(frozen=True)
class KnobSpec:
    """One tunable knob.

    ``metric_deps`` names the :class:`RunReport` evidence the tuner reads
    when proposing a value — phase fractions (``phase:<name>``), solver
    join fields (``solver:<field>``), registry metrics (``metric:<name>``)
    or jit counters (``jit:<key>``). ``candidates`` is the discrete ladder
    the A/B layer may trial; continuous knobs enumerate a sensible grid.
    """

    name: str
    kind: str  # "int" | "float" | "str" | "bool" | "csv_ints"
    default: Any
    applies_to: str  # "train" | "serve" | "both"
    phase: str  # RunReport phase bucket this knob chiefly moves
    metric_deps: Tuple[str, ...]
    candidates: Tuple[Any, ...]
    description: str

    def parse(self, value: Any) -> Any:
        if self.kind == "int":
            return int(value)
        if self.kind == "bool":
            if isinstance(value, str):
                return value.strip().lower() in ("1", "true", "yes", "on")
            return bool(value)
        if self.kind == "float":
            return float(value)
        if self.kind == "csv_ints":
            if isinstance(value, str):
                return tuple(int(v) for v in value.split(",") if v.strip())
            return tuple(int(v) for v in value)
        return str(value)


KNOBS: Dict[str, KnobSpec] = {}


def register_knob(spec: KnobSpec) -> KnobSpec:
    if spec.name in KNOBS:
        raise ValueError(f"knob {spec.name!r} registered twice")
    KNOBS[spec.name] = spec
    return spec


def get_knob(name: str) -> KnobSpec:
    try:
        return KNOBS[name]
    except KeyError:
        raise KeyError(
            f"unknown knob {name!r}; registered: {sorted(KNOBS)}"
        ) from None


def all_knobs() -> Tuple[KnobSpec, ...]:
    return tuple(KNOBS[name] for name in sorted(KNOBS))


# ------------------------------------------------------------------ table

register_knob(KnobSpec(
    name="adaptive.chunk_iters",
    kind="int",
    default=8,
    applies_to="train",
    phase="re_solve",
    metric_deps=(
        "phase:re_solve",
        "solver:lane_iteration_savings",
        "solver:chunk_retraces",
        "jit:re_bucket_chunk",
    ),
    candidates=(4, 8, 16, 32),
    description=(
        "Iterations per adaptive-RE device chunk. Larger chunks amortize "
        "dispatch overhead but waste lane iterations past convergence; "
        "smaller chunks re-check convergence more often at more dispatches."
    ),
))

register_knob(KnobSpec(
    name="adaptive.min_lanes",
    kind="int",
    default=8,
    applies_to="train",
    phase="re_solve",
    metric_deps=(
        "phase:re_solve",
        "solver:lane_iteration_savings",
        "solver:rounds",
    ),
    candidates=(4, 8, 16, 32),
    description=(
        "Smallest compacted lane count an adaptive round may shrink to. "
        "Lower values squeeze out more wasted lanes per round but add "
        "compaction rounds (and retraces for new lane shapes)."
    ),
))

register_knob(KnobSpec(
    name="serving.bucket_sizes",
    kind="csv_ints",
    default=(1, 2, 4, 8, 16, 32),
    applies_to="serve",
    phase="serving",
    metric_deps=(
        "phase:serving",
        "metric:serving.latency_p99_ms",
        "metric:serving.batch_fill",
        "metric:serving.compile_count",
    ),
    candidates=(
        (1, 2, 4, 8, 16, 32),
        (1, 4, 16, 64),
        (1, 2, 4, 8, 16, 32, 64),
        (1, 8, 64),
    ),
    description=(
        "Microbatch padding ladder. A denser ladder improves batch fill "
        "(less padding waste) at the cost of more compiled programs; a "
        "sparser one compiles less but pads more."
    ),
))

register_knob(KnobSpec(
    name="serving.cache_capacity",
    kind="int",
    default=4096,
    applies_to="serve",
    phase="serving",
    metric_deps=(
        "phase:serving",
        "metric:serving.cache_hit_rate",
        "metric:serving.latency_p50_ms",
    ),
    candidates=(1024, 4096, 16384, 65536),
    description=(
        "Per-coordinate device row-cache capacity. Bigger caches lift the "
        "hit rate on skewed entity traffic at the cost of device memory."
    ),
))

register_knob(KnobSpec(
    name="serving.max_nnz",
    kind="int",
    default=0,  # 0 = derive from the replayed requests (max_nnz_of)
    applies_to="serve",
    phase="serving",
    metric_deps=(
        "phase:serving",
        "metric:serving.latency_p99_ms",
        "metric:serving.compile_count",
    ),
    candidates=(0,),
    description=(
        "Padded nonzeros per request row (0 = derive pow2 from traffic). "
        "Overriding trades truncation risk for smaller padded programs."
    ),
))

register_knob(KnobSpec(
    name="serving.shards",
    kind="int",
    default=4,
    applies_to="serve",
    phase="serving",
    metric_deps=(
        "phase:serving",
        "metric:serving.device_resident_rate",
        "metric:serving.latency_p99_ms",
        "metric:serving.requests_per_s",
    ),
    candidates=(1, 2, 4, 8),
    description=(
        "Device shards per random-effect table in sharded serving mode. "
        "More shards spread rows (and gather traffic) across more devices "
        "at one extra gather per shard per batch; on a single device the "
        "count only shapes the stacked table layout."
    ),
))

register_knob(KnobSpec(
    name="serving.admit_batch",
    kind="int",
    default=64,
    applies_to="serve",
    phase="serving",
    metric_deps=(
        "phase:serving",
        "metric:serving.deferred_rate",
        "metric:serving.admission_dropped_total",
        "metric:serving.admission_queue_depth",
    ),
    candidates=(16, 64, 256, 1024),
    description=(
        "Rows copied host→device per async admission step (one fixed-shape "
        "scatter). Bigger batches drain a cold-start burst faster but hold "
        "the routing lock longer per step and stage more bytes at once."
    ),
))

register_knob(KnobSpec(
    name="serving.batch_deadline_ms",
    kind="float",
    default=2.0,
    applies_to="serve",
    phase="serving",
    metric_deps=(
        "phase:serving",
        "metric:serving.latency_p99_ms",
        "metric:serving.batch_fill",
        "metric:serving.requests_per_s",
    ),
    candidates=(0.5, 1.0, 2.0, 5.0),
    description=(
        "Continuous-batching deadline: a forming bucket is scored once its "
        "oldest request has waited this long. Longer deadlines fill buckets "
        "(throughput) at the cost of added tail latency under light load."
    ),
))

register_knob(KnobSpec(
    name="train.schedule",
    kind="str",
    default="sync",
    applies_to="train",
    phase="cd_driver",
    metric_deps=(
        "phase:fe_solve",
        "phase:re_solve",
        "overlap:fe_solve",
        "overlap:re_solve",
    ),
    candidates=("sync", "async"),
    description=(
        "Coordinate-descent schedule. 'async' pipelines FE/RE solves with "
        "bounded staleness on the device score plane (plus RE bucket "
        "overlap); worth trying when FE and RE both hold material "
        "wall-clock and the ledger shows no overlap yet. 'sync' is the "
        "bitwise-reproducible default and required under multi-controller."
    ),
))

register_knob(KnobSpec(
    name="train.staleness",
    kind="int",
    default=1,
    applies_to="train",
    phase="cd_driver",
    metric_deps=(
        "overlap:fe_solve",
        "overlap:re_solve",
        "phase:cd_driver",
    ),
    candidates=(0, 1, 2),
    description=(
        "Max unreconciled coordinate updates an async dispatch may ignore. "
        "0 serializes (bitwise equal to sync), higher values overlap more "
        "solves per iteration at the cost of staler residuals (slower "
        "per-iteration convergence). Ignored under schedule='sync'."
    ),
))

register_knob(KnobSpec(
    name="stream.block_rows",
    kind="int",
    default=65536,
    applies_to="train",
    phase="io",
    metric_deps=(
        "phase:io",
        "metric:stream.stall_s",
        "metric:stream.prefetch_hide_ratio",
        "metric:stream.decode_s",
        "jit:stream_vg",
    ),
    candidates=(4096, 16384, 65536, 262144),
    description=(
        "Rows per streamed example block (train_game --block-rows). Bigger "
        "blocks amortize per-block dispatch and decode overhead and raise "
        "the prefetch hide ratio, but cost O(block_rows x max_nnz) host "
        "staging and device memory per buffered block; every value is one "
        "fixed compiled shape, so retuning retraces once."
    ),
))

register_knob(KnobSpec(
    name="stream.prefetch_depth",
    kind="int",
    default=2,
    applies_to="train",
    phase="io",
    metric_deps=(
        "metric:stream.stall_s",
        "metric:stream.prefetch_hide_ratio",
        "metric:stream.transfer_s",
        "phase:io",
    ),
    candidates=(0, 1, 2, 4),
    description=(
        "Staged blocks the background decode thread may run ahead "
        "(train_game --prefetch-depth). 0 is synchronous decode (every "
        "decode second surfaces as a stall); deeper staging hides decode "
        "behind solver compute until decode itself is the bottleneck, at "
        "prefetch_depth x block bytes of host staging memory."
    ),
))

register_knob(KnobSpec(
    name="stream.decode_workers",
    kind="int",
    default=-1,
    applies_to="train",
    phase="io",
    metric_deps=(
        "metric:stream.stall_s",
        "metric:stream.decode_s",
        "metric:stream.decode_work_s",
        "metric:stream.prefetch_hide_ratio",
        "phase:io",
    ),
    candidates=(-1, 0, 1, 2, 4, 8),
    description=(
        "Decode pool threads (train_game --decode-workers). -1 = auto "
        "(cpu_count-1 capped at 16; 0 on a single-core host). Each worker "
        "decodes one part file per GIL-released native call, so workers "
        "genuinely overlap; more workers shorten decode wall-clock "
        "(stream.decode_s) while stream.decode_work_s stays constant — "
        "their ratio is the pool's achieved parallelism."
    ),
))

register_knob(KnobSpec(
    name="stream.block_cache",
    kind="bool",
    default=True,
    applies_to="train",
    phase="io",
    metric_deps=(
        "metric:stream.stall_s",
        "metric:stream.decode_s",
        "metric:stream.cache_hit_blocks",
        "metric:stream.prefetch_hide_ratio",
        "phase:io",
    ),
    candidates=(False, True),
    description=(
        "Spill decoded blocks to the mmap-backed on-disk cache "
        "(train_game --block-cache-dir / --no-block-cache). Epoch 1 pays "
        "decode once and writes entries; every later block visit reloads "
        "zero-copy at page-cache speed with zero Avro work, so "
        "stream.decode_s collapses on warm epochs. Costs one padded-block "
        "footprint of disk per (block, shard-subset)."
    ),
))

register_knob(KnobSpec(
    name="stream.gap_schedule",
    kind="bool",
    default=False,
    applies_to="train",
    phase="io",
    metric_deps=(
        "metric:stream.gap_sched.visited_blocks",
        "metric:stream.gap_sched.visit_fraction",
        "metric:stream.block_gap_max",
        "metric:stream.blocks",
        "phase:io",
    ),
    candidates=(False, True),
    description=(
        "Gap-guided block scheduling in stochastic streaming mode "
        "(train_game --gap-schedule). Epochs visit the blocks with the "
        "largest staleness-decayed duality-gap estimates (DuHL, arxiv "
        "1702.07005) instead of a blind shuffle, cutting block visits to "
        "a target metric when per-block gaps are skewed; off is bitwise-"
        "identical to the historical shuffle. Not worth turning on when "
        "block gaps are near-uniform (IID data) — the scheduler then "
        "pays exploration for no visit savings."
    ),
))

register_knob(KnobSpec(
    name="stream.resident_blocks",
    kind="int",
    default=0,
    applies_to="train",
    phase="io",
    metric_deps=(
        "metric:stream.h2d_bytes",
        "metric:stream.transfer_s",
        "metric:stream.upload_hidden_s",
        "metric:stream.residency.h2d_saved_bytes",
        "metric:stream.residency.hbm_hit_blocks",
        "phase:transfers",
    ),
    candidates=(0, 2, 4, 8, 16),
    description=(
        "Device-resident block budget for streamed training (train_game "
        "--resident-blocks; 0 = off, bitwise-identical streaming). The "
        "top-gap blocks' uploads persist across passes (DuHL, arxiv "
        "1702.07005), so warm passes re-upload only the non-resident "
        "remainder — stream.h2d_bytes drops by resident/total per pass. "
        "Worth proposing when stream.transfer_s is material and device "
        "memory has headroom of resident_blocks x block upload bytes; "
        "pointless when the solve is decode- or compute-bound."
    ),
))

register_knob(KnobSpec(
    name="serve.eviction_policy",
    kind="str",
    default="oldest",
    applies_to="serve",
    phase="serving",
    metric_deps=(
        "metric:serving.device_resident_rate",
        "metric:serving.eviction.importance",
        "metric:serving.eviction.oldest",
        "metric:serving.importance.mean",
        "metric:serving.deferred_rate",
    ),
    candidates=("oldest", "importance"),
    description=(
        "Admission-victim selection for device-resident RE rows "
        "(serve_game --eviction-policy). 'oldest' is the historical FIFO; "
        "'importance' evicts the lowest EWMA-request-frequency x "
        "coefficient-norm row, keeping hot long-tail entities resident "
        "under churn — worth trying when traffic is skewed and "
        "serving.device_resident_rate sits below ~0.95 at the configured "
        "device budget."
    ),
))

register_knob(KnobSpec(
    name="serve.overload_burn_high",
    kind="float",
    default=1.0,
    applies_to="serve",
    phase="serving",
    metric_deps=(
        "metric:serving.overload.burn_rate",
        "metric:serving.overload.active",
        "metric:serving.slo.burn_rate",
        "metric:serving.latency_p99_ms",
    ),
    candidates=(0.8, 1.0, 1.5, 2.0),
    description=(
        "SLO burn rate at which closed-loop overload control engages "
        "(serve_game --overload-burn-high): batch deadlines shrink and "
        "FE-only-able requests are answered on the host without "
        "queueing. 1.0 means the error budget burns exactly as fast as "
        "it accrues; lower engages earlier (more shedding, tighter "
        "tail), higher tolerates short bursts before actuating."
    ),
))

register_knob(KnobSpec(
    name="serve.overload_shrink",
    kind="float",
    default=0.5,
    applies_to="serve",
    phase="serving",
    metric_deps=(
        "metric:serving.overload.deadline_scale",
        "metric:serving.batch_fill_ratio",
        "metric:serving.latency_p99_ms",
    ),
    candidates=(0.25, 0.5, 0.75),
    description=(
        "Batch-deadline multiplier applied while overloaded (serve_game "
        "--overload-shrink): smaller buckets dispatch sooner, trading "
        "batch fill for queue wait exactly when queue wait is burning "
        "the latency budget. Too small wastes device dispatches on "
        "near-empty buckets; 0.5 halves the deadline."
    ),
))

register_knob(KnobSpec(
    name="serve.score_delta_importance",
    kind="bool",
    default=True,
    applies_to="serve",
    phase="serving",
    metric_deps=(
        "metric:serving.device_resident_rate",
        "metric:serving.eviction.importance",
        "metric:serving.importance.mean",
    ),
    candidates=(False, True),
    description=(
        "Fold each entity's observed |score - FE-only score| EWMA into "
        "the importance eviction score (with serve.eviction_policy="
        "importance): rows whose random-effect correction actually "
        "moves scores stay resident even at modest request frequency. "
        "Off reverts to frequency x coefficient-norm alone. No effect "
        "under the 'oldest' policy (the delta pass never runs there)."
    ),
))

register_knob(KnobSpec(
    name="train.engine",
    kind="str",
    default="auto",
    applies_to="train",
    phase="fe_solve",
    metric_deps=(
        "phase:fe_solve",
        "phase:transfers",
        "jit:fe_solve",
    ),
    candidates=("auto", "ell", "benes", "fused"),
    description=(
        "Fixed-effect matvec engine. BENCH_LASTGOOD.json records a 19x "
        "spread across engines on the same shard shape, so this is the "
        "single highest-leverage train-side knob."
    ),
))
