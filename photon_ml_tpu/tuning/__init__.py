"""Offline tuning: declared knob space, report-driven proposals, A/B trials.

The loop this package closes: a run writes a RunLedger (PR 5) →
:func:`photon_ml_tpu.telemetry.analyze_ledger` replays it into a
:class:`RunReport` → :func:`propose` turns the report's occupancy and
solver evidence into a config proposal over the registered
:class:`KnobSpec` table → ``--auto-tune`` on ``train_game``/``serve_game``
A/Bs the proposal against the incumbent via :func:`run_ab_trials` (judged
by a fresh MetricsRegistry per trial) → the winner persists into the
serving artifact's ``tuned_config`` so the next boot starts tuned.

See docs/OBSERVABILITY.md ("The knob registry" and "--auto-tune").
"""
from photon_ml_tpu.tuning.knobs import (
    KNOBS,
    KnobSpec,
    all_knobs,
    get_knob,
    register_knob,
)
from photon_ml_tpu.tuning.tuner import (
    KnobProposal,
    TuningProposal,
    ab_candidates,
    propose,
    resolve_dep,
)
from photon_ml_tpu.tuning.autotune import (
    ABResult,
    TrialResult,
    judge_from_snapshot,
    run_ab_trials,
)

__all__ = [
    "KNOBS",
    "KnobSpec",
    "all_knobs",
    "get_knob",
    "register_knob",
    "KnobProposal",
    "TuningProposal",
    "ab_candidates",
    "propose",
    "resolve_dep",
    "ABResult",
    "TrialResult",
    "judge_from_snapshot",
    "run_ab_trials",
]
