"""Offline tuner: RunReport evidence → proposed config over the knob table.

:func:`propose` walks every registered :class:`KnobSpec`, resolves the
knob's declared ``metric_deps`` against the report, and applies a small
deterministic heuristic per knob. The output is a
:class:`TuningProposal` that records, for each knob, the proposed value,
whether it differs from the default, the rationale, and the resolved
evidence — so a proposal is auditable, not an oracle.

Proposals are *hypotheses*: :mod:`photon_ml_tpu.tuning.autotune` A/Bs
them against the incumbent config and lets the MetricsRegistry judge.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from photon_ml_tpu.telemetry.analyze import RunReport
from photon_ml_tpu.tuning.knobs import KnobSpec, all_knobs

__all__ = ["KnobProposal", "TuningProposal", "propose", "resolve_dep", "ab_candidates"]


def resolve_dep(report: RunReport, dep: str) -> Optional[float]:
    """Resolve one ``metric_deps`` entry against a report.

    ``phase:<name>`` → phase wall-clock fraction; ``overlap:<name>`` →
    phase overlap seconds (concurrent span time — the async schedule's
    observable); ``solver:<field>`` → solver-join field; ``metric:<name>``
    → registry snapshot lookup; ``jit:<key>`` → retrace count. Missing
    evidence resolves to None — a knob with no evidence keeps its
    default."""
    kind, _, key = dep.partition(":")
    if kind == "phase":
        return report.phase_fraction(key)
    if kind == "overlap":
        return report.phase_overlap(key)
    if kind == "solver":
        value = (report.solver or {}).get(key)
        return float(value) if value is not None else None
    if kind == "metric":
        return report.metric(key)
    if kind == "jit":
        value = (report.jit_traces or {}).get(key)
        if value is None:
            total = sum(report.jit_traces.values()) if report.jit_traces else None
            return float(total) if total is not None else None
        return float(value)
    return None


@dataclasses.dataclass
class KnobProposal:
    name: str
    value: Any
    default: Any
    changed: bool
    rationale: str
    evidence: Dict[str, Optional[float]]

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class TuningProposal:
    report_label: str
    source_path: Optional[str]
    knobs: Dict[str, KnobProposal]

    def changed(self) -> Dict[str, Any]:
        return {k: p.value for k, p in self.knobs.items() if p.changed}

    def values(self) -> Dict[str, Any]:
        return {k: p.value for k, p in self.knobs.items()}

    def to_dict(self) -> Dict[str, Any]:
        return {
            "report_label": self.report_label,
            "source_path": self.source_path,
            "knobs": {k: p.to_dict() for k, p in sorted(self.knobs.items())},
        }


def _propose_one(spec: KnobSpec, report: RunReport) -> KnobProposal:
    ev = {dep: resolve_dep(report, dep) for dep in spec.metric_deps}
    value: Any = spec.default
    why = "no evidence moves this knob; keeping the default"

    def _f(dep: str, default: float = 0.0) -> float:
        v = ev.get(dep)
        return float(v) if v is not None else default

    if spec.name == "adaptive.chunk_iters":
        share = _f("phase:re_solve")
        savings = ev.get("solver:lane_iteration_savings")
        retraces = _f("solver:chunk_retraces")
        if share >= 0.15 and savings is not None:
            ladder = list(spec.candidates)
            idx = ladder.index(spec.default) if spec.default in ladder else 1
            if savings < 1.2 and idx > 0:
                value = ladder[idx - 1]
                why = (
                    f"RE solve holds {share:.0%} of wall-clock but lockstep/"
                    f"executed savings is only {savings:.2f}x — smaller chunks "
                    "re-check convergence sooner and cut wasted lane iterations"
                )
            elif savings >= 2.0 and retraces <= 2 and idx + 1 < len(ladder):
                value = ladder[idx + 1]
                why = (
                    f"adaptive rounds already save {savings:.2f}x with few "
                    "chunk retraces; larger chunks amortize more dispatch "
                    "overhead without new compiles"
                )
            else:
                why = (
                    f"RE share {share:.0%}, savings {savings:.2f}x sit in the "
                    "default's sweet spot"
                )
        elif share:
            why = f"RE solve is only {share:.0%} of wall-clock; not worth moving"

    elif spec.name == "adaptive.min_lanes":
        share = _f("phase:re_solve")
        savings = ev.get("solver:lane_iteration_savings")
        rounds = _f("solver:rounds")
        if share >= 0.15 and savings is not None:
            ladder = list(spec.candidates)
            idx = ladder.index(spec.default) if spec.default in ladder else 1
            if savings < 1.2 and idx > 0:
                value = ladder[idx - 1]
                why = (
                    "low lane-iteration savings — allow compaction to shrink "
                    "further so converged lanes stop burning device time"
                )
            elif rounds > 0 and savings >= 2.0 and idx + 1 < len(ladder):
                value = ladder[idx + 1]
                why = (
                    f"{int(rounds)} compaction rounds for {savings:.2f}x "
                    "savings — a higher floor trades a little lane waste for "
                    "fewer rounds and retraced shapes"
                )
            else:
                why = "compaction cadence looks balanced at the default floor"
        elif share:
            why = f"RE solve is only {share:.0%} of wall-clock; not worth moving"

    elif spec.name == "serving.bucket_sizes":
        fill = ev.get("metric:serving.batch_fill")
        compiles = _f("metric:serving.compile_count")
        if fill is not None:
            if fill < 0.6:
                value = max(spec.candidates, key=len)
                why = (
                    f"batch fill is {fill:.0%} — padding waste dominates; a "
                    "denser ladder cuts padding at the cost of more programs"
                )
            elif fill > 0.85 and compiles > 2 * len(spec.default):
                value = min(spec.candidates, key=len)
                why = (
                    f"fill already {fill:.0%} with {int(compiles)} compiles — "
                    "a sparser ladder drops compile pressure cheaply"
                )
            else:
                why = f"batch fill {fill:.0%} is healthy on the default ladder"

    elif spec.name == "serving.cache_capacity":
        hit = ev.get("metric:serving.cache_hit_rate")
        if hit is not None:
            ladder = list(spec.candidates)
            idx = ladder.index(spec.default) if spec.default in ladder else 1
            if hit < 0.8 and idx + 1 < len(ladder):
                value = ladder[idx + 1]
                why = (
                    f"cache hit rate {hit:.0%} — entity traffic overflows the "
                    "row cache; step capacity up the ladder"
                )
            elif hit > 0.98 and idx > 0:
                value = ladder[idx - 1]
                why = (
                    f"hit rate {hit:.0%} — the cache is oversized; reclaim "
                    "device memory"
                )
            else:
                why = f"cache hit rate {hit:.0%} is fine at current capacity"

    elif spec.name == "serving.shards":
        resident = ev.get("metric:serving.device_resident_rate")
        if resident is not None:
            why = (
                f"device residency {resident:.0%}; shard count trades gather "
                "fan-out for per-device rows — move it only via A/B on the "
                "target mesh"
            )

    elif spec.name == "serving.admit_batch":
        deferred = ev.get("metric:serving.deferred_rate")
        dropped = _f("metric:serving.admission_dropped_total")
        if deferred is not None:
            ladder = list(spec.candidates)
            idx = ladder.index(spec.default) if spec.default in ladder else 1
            if dropped > 0 and idx + 1 < len(ladder):
                value = ladder[idx + 1]
                why = (
                    f"admission dropped {int(dropped)} queued rows — the "
                    "drain can't keep up with the deferred stream; bigger "
                    "steps move more rows per scatter"
                )
            elif deferred < 0.01 and idx > 0:
                value = ladder[idx - 1]
                why = (
                    f"deferred rate {deferred:.1%} — the cold tail is thin; "
                    "smaller steps shorten the routing-lock hold for free"
                )
            else:
                why = (
                    f"deferred rate {deferred:.1%} with no drops — admission "
                    "keeps up at the default step size"
                )

    elif spec.name == "serving.batch_deadline_ms":
        fill = ev.get("metric:serving.batch_fill")
        p99 = ev.get("metric:serving.latency_p99_ms")
        if fill is not None and p99 is not None:
            ladder = list(spec.candidates)
            idx = ladder.index(spec.default) if spec.default in ladder else 1
            if fill < 0.5 and idx + 1 < len(ladder):
                value = ladder[idx + 1]
                why = (
                    f"batch fill {fill:.0%} at p99 {p99:.2f}ms — buckets "
                    "score half-empty; a longer deadline lets them fill"
                )
            elif fill > 0.9 and idx > 0:
                value = ladder[idx - 1]
                why = (
                    f"buckets already fill ({fill:.0%}) before the deadline; "
                    "a shorter one trims queueing from the tail"
                )
            else:
                why = (
                    f"fill {fill:.0%} / p99 {p99:.2f}ms balance at the "
                    "default deadline"
                )
        elif fill is not None:
            why = (
                f"batch fill {fill:.0%} but no latency evidence — the "
                "deadline trades the two, keep the default until both are "
                "measured"
            )

    elif spec.name == "serving.max_nnz":
        p99 = ev.get("metric:serving.latency_p99_ms")
        why = (
            "keep deriving the pow2 pad from traffic"
            + (f" (p99 {p99:.2f}ms)" if p99 is not None else "")
            + "; overriding only pays off with a fixed upstream schema"
        )

    elif spec.name == "train.schedule":
        fe = _f("phase:fe_solve")
        re_ = _f("phase:re_solve")
        overlap = _f("overlap:fe_solve") + _f("overlap:re_solve")
        if overlap > 0:
            why = (
                f"ledger already shows {overlap:.2f}s of FE/RE overlap — the "
                "async schedule is active and pulling its weight"
            )
        elif fe >= 0.2 and re_ >= 0.2:
            value = "async"
            why = (
                f"FE ({fe:.0%}) and RE ({re_:.0%}) both hold material "
                "wall-clock with zero measured overlap — pipelining them "
                "with bounded staleness can hide one behind the other"
            )
        elif fe or re_:
            why = (
                f"one side dominates (FE {fe:.0%}, RE {re_:.0%}); "
                "overlapping buys little, keep the reproducible sync loop"
            )

    elif spec.name == "train.staleness":
        overlap = _f("overlap:fe_solve") + _f("overlap:re_solve")
        share = _f("phase:cd_driver")
        if overlap > 0:
            why = (
                f"async overlap measured at {overlap:.2f}s — staleness "
                f"{spec.default} is doing its job; step it only via A/B"
            )
        elif share:
            why = (
                "no overlap evidence yet (sync run?); staleness only acts "
                "under schedule='async'"
            )

    elif spec.name == "train.engine":
        share = _f("phase:fe_solve")
        if share >= 0.3:
            why = (
                f"FE solve holds {share:.0%} of wall-clock and engines span a "
                "19x spread — worth an A/B across candidate engines"
            )
        elif share:
            why = f"FE solve is only {share:.0%} of wall-clock; engine stays auto"

    return KnobProposal(
        name=spec.name,
        value=value,
        default=spec.default,
        changed=value != spec.default,
        rationale=why,
        evidence=ev,
    )


def propose(report: RunReport) -> TuningProposal:
    """Propose a value (with rationale + evidence) for EVERY registered
    knob. Knobs without supporting evidence keep their defaults, but still
    appear — the proposal doubles as an audit of what was observable."""
    return TuningProposal(
        report_label=report.label,
        source_path=report.source_path,
        knobs={spec.name: _propose_one(spec, report) for spec in all_knobs()},
    )


def ab_candidates(
    proposal: TuningProposal,
    applies_to: str,
    max_candidates: int = 2,
) -> List[Dict[str, Any]]:
    """Flatten a proposal into candidate config dicts for the A/B layer.

    Candidate 0 is always the incumbent defaults (the control). Changed
    knobs scoped to ``applies_to`` are applied together as candidate 1;
    if nothing changed, the first non-default ladder step of the most
    evidence-backed knob is trialed so ``--auto-tune`` always has a B arm.
    """
    scoped = [
        p for name, p in sorted(proposal.knobs.items())
        if _spec(name).applies_to in (applies_to, "both")
    ]
    control = {p.name: p.default for p in scoped}
    changed = {p.name: p.value for p in scoped if p.changed}
    candidates: List[Dict[str, Any]] = [dict(control)]
    if changed:
        trial = dict(control)
        trial.update(changed)
        candidates.append(trial)
    else:
        backed = [
            p for p in scoped
            if any(v is not None for v in p.evidence.values())
            and len(_spec(p.name).candidates) > 1
        ]
        if backed:
            p = backed[0]
            alt = next(
                (c for c in _spec(p.name).candidates if c != p.default), None
            )
            if alt is not None:
                trial = dict(control)
                trial[p.name] = alt
                candidates.append(trial)
    return candidates[: max_candidates + 1]


def _spec(name: str) -> KnobSpec:
    from photon_ml_tpu.tuning.knobs import get_knob

    return get_knob(name)
