"""Multi-device fixed-effect sparse features: Benes engine under shard_map.

Reference parity: the reference's distributed gradient is per-partition
sparse axpy + ``treeAggregate`` to the driver (ValueAndGradientAggregator
.scala:243-247, depth heuristic GameEstimator.scala:499-503). Here each
device owns a contiguous block of examples and runs the permutation-routed
sparse engine (ops/sparse_perm.py) on its block; the only collective is one
``psum`` over the data axis inside ``rmatvec`` — the treeAggregate
replacement, riding ICI instead of the Spark driver network.

Why shard_map and not GSPMD propagation: the engine's shuffle stages are
Pallas kernels, which have no SPMD partitioning rule — under plain jit XLA
would replicate them. shard_map pins each device to its own shard and its
own (stacked) shuffle plan.

Layout: every array leaf of the per-device ``BenesSparseFeatures`` is
stacked with a leading device axis of size ``mesh.shape[axis]``; all shards
are routed with identical paddings (K, KP, network size S) so one compiled
program serves every device. Rows are padded with zero-entry examples to a
multiple of the device count (padding rows carry weight 0 downstream).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map as _shard_map_impl

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_impl(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_impl(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )

from photon_ml_tpu.ops import routing
from photon_ml_tpu.ops.sparse_perm import BenesSparseFeatures, _assemble
from photon_ml_tpu.parallel.mesh import DATA_AXIS


@struct.dataclass
class ShardedBenesFeatures:
    """Data-parallel [n, d] sparse matrix: one Benes-routed shard per device.

    Implements the FeatureMatrix protocol (matvec/rmatvec/rmatvec_sq/
    row_norms_sq) over globally-shaped arrays: ``matvec`` maps a replicated
    ``w`` to margins sharded over the data axis; ``rmatvec`` reduces local
    gradients with one psum and returns a replicated [d] vector.
    """

    shards: BenesSparseFeatures  # every array leaf: [n_dev, ...]
    mesh: Mesh = struct.field(pytree_node=False)
    axis: str = struct.field(pytree_node=False)
    num_rows_: int = struct.field(pytree_node=False)  # global rows (padded)
    num_cols_: int = struct.field(pytree_node=False)

    @property
    def num_rows(self) -> int:
        return self.num_rows_

    @property
    def dim(self) -> int:
        return self.num_cols_

    def matvec(self, w: jax.Array) -> jax.Array:
        def local_mv(shards, w):
            z = jax.tree.map(lambda a: a[0], shards).matvec(w)
            return z[None]

        out = shard_map(
            local_mv,
            mesh=self.mesh,
            in_specs=(P(self.axis), P()),
            out_specs=P(self.axis),
        )(self.shards, w)
        return out.reshape(-1)

    def rmatvec(self, c: jax.Array) -> jax.Array:
        return self._rmatvec_shardmap(c, squared=False)

    def rmatvec_sq(self, c: jax.Array) -> jax.Array:
        return self._rmatvec_shardmap(c, squared=True)

    def _rmatvec_shardmap(self, c: jax.Array, squared: bool) -> jax.Array:
        n_dev = self.mesh.shape[self.axis]
        c2 = c.reshape(n_dev, -1)

        def local_rmv(shards, c_blk):
            local = jax.tree.map(lambda a: a[0], shards)
            g = local.rmatvec_sq(c_blk[0]) if squared else local.rmatvec(c_blk[0])
            return jax.lax.psum(g, self.axis)

        return shard_map(
            local_rmv,
            mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis)),
            out_specs=P(),
        )(self.shards, c2)

    def row_norms_sq(self) -> jax.Array:
        def local_rn(shards):
            return jax.tree.map(lambda a: a[0], shards).row_norms_sq()[None]

        out = shard_map(
            local_rn,
            mesh=self.mesh,
            in_specs=(P(self.axis),),
            out_specs=P(self.axis),
        )(self.shards)
        return out.reshape(-1)


def sharded_from_coo(
    rows,
    cols,
    vals,
    shape: Tuple[int, int],
    mesh: Mesh,
    axis: str = DATA_AXIS,
    plan_cache: Optional[str] = None,
    hot_col_threshold: Optional[int] = None,
    max_hot_cols: int = 128,
) -> ShardedBenesFeatures:
    """Split COO rows into per-device blocks and route each identically.

    The hot-column set is chosen once from GLOBAL column degrees and applied
    to every shard (so shard pytrees stack). Returns features whose
    ``num_rows`` is the padded global row count (multiple of the device
    count); callers padding labels/offsets/weights must give padding rows
    weight 0.
    """
    from photon_ml_tpu.ops.sparse_perm import coalesce_coo, select_hot_cols

    n, d = shape
    n_dev = mesh.shape[axis]
    rows, cols, vals = coalesce_coo(rows, cols, vals, n, d)

    n_loc = -(-n // n_dev)
    n_pad = n_loc * n_dev
    nnz = rows.size

    # Global hot-column selection (same rule as from_coo; the dense side is
    # per-shard [n_loc, H], hence the local row count in the gate).
    hot_ids = select_hot_cols(rows, cols, n_loc, d, hot_col_threshold, max_hot_cols)

    hot_pos = None
    if hot_ids is not None:
        hot_pos = np.full(d, -1, dtype=np.int64)
        hot_pos[hot_ids] = np.arange(hot_ids.size)
        is_hot = hot_pos[cols] >= 0
        hot_rows, hot_cols_e, hot_vals = rows[is_hot], cols[is_hot], vals[is_hot]
        rows, cols, vals = rows[~is_hot], cols[~is_hot], vals[~is_hot]
        nnz = rows.size

    # Common paddings across shards: K/KP from global maxima of per-shard
    # local degree counts (row degrees are shard-local by construction; col
    # degrees must be measured per shard).
    dev_of = rows // n_loc if nnz else np.zeros(0, np.int64)
    K = 1
    KP = 1
    for dev in range(n_dev):
        sel = dev_of == dev
        if not sel.any():
            continue
        K = max(K, int(np.bincount(rows[sel] - dev * n_loc).max()))
        KP = max(KP, int(np.bincount(cols[sel]).max()))
    S = routing.valid_size(max(n_loc * K, d * KP, 1))

    shard_structs = []
    for dev in range(n_dev):
        sel = dev_of == dev
        hm = None
        if hot_ids is not None:
            hm = np.zeros((n_loc, hot_ids.size), dtype=np.float32)
            h_sel = (hot_rows // n_loc) == dev
            hm[hot_rows[h_sel] - dev * n_loc, hot_pos[hot_cols_e[h_sel]]] = (
                hot_vals[h_sel]
            )
        shard_structs.append(
            _assemble(
                rows[sel] - dev * n_loc,
                cols[sel],
                vals[sel],
                n_loc,
                d,
                K,
                KP,
                hm,
                hot_ids,
                plan_cache,
                size_floor=S,
            )
        )

    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *shard_structs)
    # place each stacked leaf with its device axis sharded over the mesh
    stacked = jax.tree.map(
        lambda a: jax.device_put(
            a, NamedSharding(mesh, P(*([axis] + [None] * (a.ndim - 1))))
        ),
        stacked,
    )
    return ShardedBenesFeatures(
        shards=stacked,
        mesh=mesh,
        axis=axis,
        num_rows_=int(n_pad),
        num_cols_=int(d),
    )
