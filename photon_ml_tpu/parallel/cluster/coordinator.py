"""Coordinator side of the cluster plane.

One coordinator process (the trainer) drives N worker processes. Each
full-batch pass is: partition the live blocks across live hosts
(:class:`~photon_ml_tpu.parallel.cluster.assigner.BlockAssigner`), send
each host its ``pass`` message with the current weights, sum the partial
``(f, g)`` replies — the allreduce — and hand the sum back to the solver,
which finalizes regularization on the coordinator exactly as the
single-host path does. The reply sum is mathematically the same full-batch
value/gradient as one host streaming every block; only floating-point
summation order differs, so parity with single-host is gated on held-out
AUC (≤ 1e-3), not bitwise trajectories.

Failure protocol (rides PR 14's resilience plane):

* a worker that DIES closes its socket — the reader thread sees EOF and
  enqueues a death sentinel;
* a worker that WEDGES stops heartbeating — the pass loop notices
  ``last_seen`` exceeding the heartbeat timeout;
* either way the coordinator calls ``assigner.mark_host_failed``, records
  ``record_failure("cluster_host_lost", ...)`` into the failure ring (and
  through the attached sink into the progress ledger), and re-sends the
  dead host's unfinished blocks to the survivors as a fresh fragment of
  the SAME pass — the pass completes, the epoch barrier holds, nothing
  aborts. Only when zero hosts survive does the pass raise
  :class:`ClusterError`.

Observability (off by default — ``enable_telemetry()``): when enabled the
coordinator stamps per-fragment dispatch/arrival times, asks workers to
piggyback their recv→decode→solve→reply timings onto each ``partial``
reply (a ``"telemetry"`` dict — the wire protocol is otherwise unchanged,
and with telemetry off the messages are byte-identical to the plain
plane), and folds each pass into a skew profile: per-host busy seconds,
allreduce wait (last arrival minus first arrival), the coordinator's own
fold/update bubble, a straggler index, and measured per-host work shares
against the assigner's LPT-predicted gap shares. Profiles drain through
:meth:`ClusterCoordinator.drain_pass_profiles` into the progress ledger
as ``cluster_pass``/``host_pass`` records (see docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import os
import queue
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...resilience.failures import record_failure
from ...telemetry.metrics import get_registry
from .assigner import BlockAssigner
from .protocol import MessageSocket, recv_msg, send_msg

HEARTBEAT_TIMEOUT_ENV = "PHOTON_CLUSTER_HEARTBEAT_TIMEOUT_S"
_DEFAULT_HEARTBEAT_TIMEOUT_S = 30.0


class ClusterError(RuntimeError):
    """The cluster cannot make progress (no live hosts, bad handshake)."""


class _WorkerHandle:
    def __init__(self, host: int, msock: MessageSocket):
        self.host = host
        self.msock = msock
        self.alive = True
        self.last_seen = time.monotonic()
        # Heartbeat inter-arrival tracking (timeout tuning): last beat time
        # and a bounded window of deltas for the p99 gauge.
        self.last_beat: Optional[float] = None
        self.beat_deltas: List[float] = []


class ClusterCoordinator:
    """Accepts worker connections, drives distributed passes, survives
    worker death mid-pass."""

    def __init__(
        self,
        num_hosts: int,
        num_blocks: int,
        decay: float = 0.6,
        heartbeat_timeout_s: Optional[float] = None,
        bind_host: str = "127.0.0.1",
    ):
        if num_hosts < 1:
            raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
        self.num_hosts = int(num_hosts)
        self.num_blocks = int(num_blocks)
        self.assigner = BlockAssigner(
            num_blocks, hosts=range(self.num_hosts), decay=decay
        )
        if heartbeat_timeout_s is None:
            heartbeat_timeout_s = float(
                os.environ.get(
                    HEARTBEAT_TIMEOUT_ENV, _DEFAULT_HEARTBEAT_TIMEOUT_S
                )
            )
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        # Bind in __init__ so the port is known before workers spawn.
        self._server = socket.create_server((bind_host, 0))
        self.address: Tuple[str, int] = self._server.getsockname()[:2]
        self.workers: Dict[int, _WorkerHandle] = {}
        self._inbox: "queue.Queue[Tuple[int, Optional[dict]]]" = queue.Queue()
        self._reader_threads: List[threading.Thread] = []
        self._pass_id = 0
        self._next_frag = 0
        self._events: List[dict] = []
        self._closed = False
        # Telemetry (off by default; the wire protocol is unchanged and
        # byte-identical until enable_telemetry() is called).
        self.telemetry_enabled = False
        self._pass_profiles: List[dict] = []
        self._frag_meta: Dict[Tuple[int, int], dict] = {}
        self._pass_t0 = 0.0
        self._pass_requeued = 0

    # -- membership --------------------------------------------------------

    def wait_for_workers(self, timeout_s: float = 300.0) -> None:
        """Accept ``num_hosts`` hellos; reject config-skewed workers whose
        locally planned block count disagrees with ours."""
        deadline = time.monotonic() + timeout_s
        self._server.settimeout(5.0)
        while len(self.workers) < self.num_hosts:
            if time.monotonic() > deadline:
                raise ClusterError(
                    f"only {len(self.workers)}/{self.num_hosts} workers "
                    f"connected within {timeout_s:.0f}s"
                )
            try:
                sock, _ = self._server.accept()
            except socket.timeout:
                continue
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            hello = recv_msg(sock)
            if hello.get("type") != "hello":
                sock.close()
                raise ClusterError(f"expected hello, got {hello!r}")
            host = int(hello["host"])
            if hello.get("num_blocks") != self.num_blocks:
                send_msg(sock, {"type": "stop"})
                sock.close()
                raise ClusterError(
                    f"host {host} planned {hello.get('num_blocks')} blocks, "
                    f"coordinator planned {self.num_blocks}: the workers "
                    "must see the same files and --block-rows"
                )
            if host in self.workers:
                sock.close()
                raise ClusterError(f"duplicate hello from host {host}")
            handle = _WorkerHandle(host, MessageSocket(sock))
            self.workers[host] = handle
            t = threading.Thread(
                target=self._reader, args=(handle,), daemon=True,
                name=f"cluster-reader-{host}",
            )
            t.start()
            self._reader_threads.append(t)

    def _reader(self, handle: _WorkerHandle) -> None:
        try:
            while True:
                msg = handle.msock.recv()
                handle.last_seen = time.monotonic()
                if msg.get("type") == "heartbeat":
                    self._note_heartbeat(handle)
                    continue
                self._inbox.put((handle.host, msg))
        except (EOFError, OSError):
            self._inbox.put((handle.host, None))

    def _note_heartbeat(self, handle: _WorkerHandle) -> None:
        """Track per-host heartbeat inter-arrival so the timeout can be
        tuned from data: ``cluster.heartbeat_interarrival_p99_s{host=h}``
        far below the timeout means the timeout has headroom; near it
        means false host-lost verdicts are imminent."""
        now = time.monotonic()
        if handle.last_beat is not None:
            delta = now - handle.last_beat
            deltas = handle.beat_deltas
            deltas.append(delta)
            if len(deltas) > 256:
                del deltas[: len(deltas) - 256]
            scoped = get_registry().scoped({"host": str(handle.host)})
            scoped.observe("cluster.heartbeat_interarrival_s", delta)
            scoped.gauge(
                "cluster.heartbeat_interarrival_p99_s",
                float(np.percentile(deltas, 99)),
            )
        handle.last_beat = now

    # -- failure -----------------------------------------------------------

    def _lose_host(self, host: int, why: str) -> None:
        handle = self.workers.get(host)
        if handle is None or not handle.alive:
            return
        handle.alive = False
        handle.msock.close()
        self.assigner.mark_host_failed(host)
        record_failure(
            "cluster_host_lost",
            site=f"cluster.host{host}",
            detail=why,
            host=host,
        )
        get_registry().count("cluster.host_failures")
        self._events.append({"event": "host_lost", "host": host, "why": why})

    def _live(self) -> List[_WorkerHandle]:
        return [h for h in self.workers.values() if h.alive]

    def _check_heartbeats(self) -> List[int]:
        now = time.monotonic()
        stale = [
            h.host
            for h in self._live()
            if now - h.last_seen > self.heartbeat_timeout_s
        ]
        for host in stale:
            self._lose_host(host, "heartbeat timeout")
        return stale

    # -- control plane -----------------------------------------------------

    def _send(self, handle: _WorkerHandle, msg: dict) -> bool:
        try:
            handle.msock.send(msg)
            return True
        except OSError:
            self._lose_host(handle.host, "send failed")
            return False

    def set_residual(self, residual: Optional[np.ndarray]) -> None:
        """Broadcast the CD residual plane for the next solve (once per
        outer iteration, not per pass)."""
        payload = None if residual is None else np.asarray(residual)
        for handle in list(self._live()):
            self._send(handle, {"type": "residual", "residual": payload})

    # -- the distributed pass ----------------------------------------------

    def distributed_pass(
        self, w: np.ndarray
    ) -> Tuple[float, np.ndarray, Dict[int, float], List[dict]]:
        """One full-batch pass over every live block, data-parallel.

        Returns ``(f_sum, g_sum, gaps, block_stats)`` — the UNregularized
        sums; the solver's ``finalize`` adds the L2 term on the
        coordinator, exactly as the single-host path does.
        """
        self._pass_id += 1
        pass_id = self._pass_id
        if not self._live():
            raise ClusterError("no live hosts")
        assignment = self.assigner.assign()
        w = np.asarray(w)
        self._next_frag = 0
        tele = self.telemetry_enabled
        self._pass_t0 = time.monotonic()
        self._frag_meta = {}
        self._pass_requeued = 0
        start_unix = time.time() if tele else 0.0
        predicted = self.assigner.predicted_shares(assignment) if tele else {}
        # pending: (host, frag) -> blocks in flight
        pending: Dict[Tuple[int, int], List[int]] = {}
        dropped: List[int] = []
        for host, blocks in assignment.items():
            if not blocks:
                continue
            handle = self.workers[host]
            frag = self._next_frag
            if self._send_fragment(handle, pass_id, frag, w, blocks):
                pending[(host, frag)] = blocks
                self._next_frag += 1
            else:
                # died on send; requeue once the healthy sends are out
                dropped.extend(blocks)
        if dropped:
            self._requeue(pass_id, dropped, pending, w)
        f_sum = 0.0
        g_sum = np.zeros_like(w, dtype=np.float64)
        gaps: Dict[int, float] = {}
        block_stats: List[dict] = []
        arrivals: List[dict] = []
        stray = 0
        # Check heartbeats on a monotonic interval even when the inbox is
        # busy — a chatty inbox must not defer dead-host detection.
        hb_interval = min(1.0, self.heartbeat_timeout_s / 4.0)
        last_hb_check = time.monotonic()
        while pending:
            now = time.monotonic()
            if now - last_hb_check >= hb_interval:
                last_hb_check = now
                for dead in self._check_heartbeats():
                    self._recover(dead, pass_id, pending, w)
                if not pending:
                    break
            try:
                host, msg = self._inbox.get(timeout=hb_interval)
            except queue.Empty:
                continue  # heartbeat check runs at the top of the loop
            if msg is None:
                self._lose_host(host, "connection closed")
                self._recover(host, pass_id, pending, w)
                continue
            if msg.get("type") != "partial" or msg.get("pass_id") != pass_id:
                # stray reply from an abandoned fragment
                get_registry().count("cluster.stray_partials")
                stray += 1
                continue
            key = (host, msg["frag"])
            if key not in pending:
                get_registry().count("cluster.stray_partials")
                stray += 1
                continue
            del pending[key]
            if tele:
                meta = self._frag_meta.pop(key, None) or {
                    "host": host,
                    "frag": int(msg["frag"]),
                    "blocks": 0,
                    "dispatch_s": 0.0,
                }
                meta["arrival_s"] = time.monotonic() - self._pass_t0
                meta["worker"] = dict(msg.get("telemetry") or {})
                arrivals.append(meta)
            f_sum += float(msg["f"])
            g_sum += np.asarray(msg["g"], dtype=np.float64)
            for st in msg.get("block_stats", ()):
                gaps[int(st["block"])] = float(st.get("gap", 0.0))
                block_stats.append(dict(st, host=host))
        self.assigner.update(gaps)
        if tele:
            self._profile_pass(pass_id, start_unix, arrivals, predicted, stray)
        return f_sum, g_sum, gaps, block_stats

    def _send_fragment(
        self,
        handle: _WorkerHandle,
        pass_id: int,
        frag: int,
        w: np.ndarray,
        blocks: List[int],
    ) -> bool:
        """Send one ``pass`` fragment, stamping dispatch time when
        telemetry is on. With telemetry off the message is byte-identical
        to the plain plane (no extra keys)."""
        msg = {
            "type": "pass",
            "pass_id": pass_id,
            "frag": frag,
            "w": w,
            "blocks": blocks,
        }
        if self.telemetry_enabled:
            msg["telemetry"] = True
        if not self._send(handle, msg):
            return False
        if self.telemetry_enabled:
            self._frag_meta[(handle.host, frag)] = {
                "host": handle.host,
                "frag": frag,
                "blocks": len(blocks),
                "dispatch_s": time.monotonic() - self._pass_t0,
            }
        return True

    def _profile_pass(
        self,
        pass_id: int,
        start_unix: float,
        arrivals: List[dict],
        predicted: Dict[int, float],
        stray: int,
    ) -> None:
        """Fold one pass's fragment timeline into a skew profile.

        The decomposition is exact by construction: ``busy_s`` (start →
        first arrival, the fully overlapped compute window) +
        ``allreduce_wait_s`` (first → last arrival, the skew window where
        the coordinator waits on stragglers) + ``bubble_s`` (last arrival
        → end, the coordinator's own fold + assigner update) == wall.
        """
        t_end = time.monotonic()
        wall = max(t_end - self._pass_t0, 1e-12)
        if arrivals:
            first = min(a["arrival_s"] for a in arrivals)
            last = max(a["arrival_s"] for a in arrivals)
        else:
            first = last = wall
        hosts: Dict[int, dict] = {}
        fragments: List[dict] = []
        for a in arrivals:
            worker = a.get("worker") or {}
            h = hosts.setdefault(
                int(a["host"]),
                {
                    "busy_s": 0.0,
                    "wall_s": 0.0,
                    "blocks": 0,
                    "frags": 0,
                    "decode_s": 0.0,
                    "solve_s": 0.0,
                    "reply_s": 0.0,
                    "h2d_bytes": 0,
                },
            )
            h["frags"] += 1
            h["blocks"] += int(worker.get("blocks", a.get("blocks", 0)))
            h["wall_s"] = max(h["wall_s"], float(a["arrival_s"]))
            h["busy_s"] += float(worker.get("busy_s", 0.0))
            h["decode_s"] += float(worker.get("decode_s", 0.0))
            h["solve_s"] += float(worker.get("solve_s", 0.0))
            h["reply_s"] += float(worker.get("reply_s", 0.0))
            h["h2d_bytes"] += int(worker.get("h2d_bytes", 0))
            fragments.append(
                {
                    "host": int(a["host"]),
                    "frag": int(a["frag"]),
                    "blocks": int(a.get("blocks", 0)),
                    "dispatch_s": float(a.get("dispatch_s", 0.0)),
                    "arrival_s": float(a["arrival_s"]),
                    "busy_s": float(worker.get("busy_s", 0.0)),
                }
            )
        total_busy = sum(h["busy_s"] for h in hosts.values())
        for host, h in hosts.items():
            if host in predicted:
                h["predicted_share"] = float(predicted[host])
            if total_busy > 0:
                h["actual_share"] = h["busy_s"] / total_busy
        walls = [h["wall_s"] for h in hosts.values()]
        straggler_index = (
            max(walls) / max(sum(walls) / len(walls), 1e-12) if walls else 1.0
        )
        straggler_host = (
            max(hosts, key=lambda k: hosts[k]["wall_s"]) if hosts else -1
        )
        profile = {
            "pass_id": pass_id,
            "start_unix": start_unix,
            "wall_s": wall,
            "busy_s": first,
            "allreduce_wait_s": max(last - first, 0.0),
            "bubble_s": max(wall - last, 0.0),
            "straggler_index": float(straggler_index),
            "straggler_host": int(straggler_host),
            "blocks": sum(h["blocks"] for h in hosts.values()),
            "hosts": hosts,
            "fragments": fragments,
            "stray_partials": stray,
            "requeued_blocks": self._pass_requeued,
        }
        self._pass_profiles.append(profile)
        get_registry().record_cluster_pass(profile)

    def _recover(
        self,
        dead_host: int,
        pass_id: int,
        pending: Dict[Tuple[int, int], List[int]],
        w: np.ndarray,
    ) -> None:
        """Re-send a dead host's unfinished blocks to the survivors as new
        fragments of the same pass."""
        lost: List[int] = []
        for key in [k for k in pending if k[0] == dead_host]:
            lost.extend(pending.pop(key))
        if not lost:
            return
        if not self._live():
            raise ClusterError(
                f"host {dead_host} died and no hosts survive to take over "
                f"blocks {lost}"
            )
        self._requeue(pass_id, lost, pending, w)

    def _requeue(
        self,
        pass_id: int,
        blocks: List[int],
        pending: Dict[Tuple[int, int], List[int]],
        w: np.ndarray,
    ) -> None:
        if not self._live():
            raise ClusterError("no live hosts to requeue blocks on")
        targets = self.assigner.reassign(blocks)
        get_registry().count("cluster.blocks_reassigned", len(blocks))
        get_registry().count("cluster.requeued_blocks", len(blocks))
        self._pass_requeued += len(blocks)
        self._events.append(
            {
                "event": "blocks_reassigned",
                "blocks": sorted(blocks),
                "targets": {str(h): b for h, b in targets.items()},
            }
        )
        for host, blks in targets.items():
            handle = self.workers[host]
            frag = self._next_frag
            if self._send_fragment(handle, pass_id, frag, np.asarray(w), blks):
                pending[(host, frag)] = blks
                self._next_frag += 1
            else:
                # that survivor died too; recurse onto whoever is left
                self._requeue(pass_id, blks, pending, w)

    # -- bookkeeping -------------------------------------------------------

    def enable_telemetry(self, enabled: bool = True) -> None:
        """Turn on per-pass skew profiling and worker timing piggyback.
        Off by default: the disabled path sends byte-identical messages
        and builds no profiles."""
        self.telemetry_enabled = bool(enabled)

    def drain_pass_profiles(self) -> List[dict]:
        """Return and clear the skew profiles accumulated since the last
        drain (one per :meth:`distributed_pass` with telemetry on)."""
        out = self._pass_profiles
        self._pass_profiles = []
        return out

    def drain_events(self) -> List[dict]:
        out = self._events + self.assigner.drain_decisions()
        self._events = []
        return out

    def shutdown(self) -> None:
        if self._closed:
            return
        self._closed = True
        for handle in self.workers.values():
            if handle.alive:
                try:
                    handle.msock.send({"type": "stop"})
                except OSError:
                    pass
                handle.msock.close()
        try:
            self._server.close()
        except OSError:
            pass
