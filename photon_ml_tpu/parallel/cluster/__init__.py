"""Cluster plane: multi-host streaming data-parallel coordinate descent.

The composition ROADMAP item 1 asks for: PR 10's block-sharded streaming
solver run data-parallel across hosts, PR 13's gap ledger generalized into
cross-host block assignment, and PR 14's failure plane extended with a
host-failure protocol (heartbeat + socket-EOF detection, block
reassignment instead of job abort). See docs/SCALING.md "Multi-host
cluster plane" for the allreduce semantics and the staleness bound.
"""

from .assigner import BlockAssigner
from .coordinator import ClusterCoordinator, ClusterError
from .launcher import ClusterPlane
from .protocol import MessageSocket, ProtocolError, connect, recv_msg, send_msg
from .worker import ClusterWorker, serve_worker_in_thread

__all__ = [
    "BlockAssigner",
    "ClusterCoordinator",
    "ClusterError",
    "ClusterPlane",
    "ClusterWorker",
    "MessageSocket",
    "ProtocolError",
    "connect",
    "recv_msg",
    "send_msg",
    "serve_worker_in_thread",
]
