"""Gap-balanced cross-host block assignment.

PR 13's :class:`~photon_ml_tpu.streaming.gapsched.GapScheduler` orders
one host's visits by staleness-decayed duality-gap importance; this is the
same ledger generalized CROSS-host: every full-batch pass must visit every
block exactly once (exactness), so the only scheduling freedom is *which
host streams which blocks*. The assigner partitions blocks so each host's
share of the total gap mass — the first-order estimate of how much
objective movement its slice carries, hence how much numerical work the
line-search passes over it do — stays balanced, using the classic LPT
greedy (sort by score, give each block to the lightest host; with uniform
scores this degenerates to balanced counts).

Staleness bookkeeping matches the gap scheduler: a block's score decays by
``decay**age`` where ``age`` counts passes since the block's gap was last
measured. Because the distributed pass is synchronous (the coordinator's
allreduce is the epoch barrier), gradient staleness is zero; the only
stale quantity in the system is this assignment signal — at most one pass
old, and used purely for load balance, never for the math
(docs/SCALING.md documents the bound).

Host failure: ``mark_host_failed`` removes the host from the rotation and
``reassign`` splits its in-flight blocks over the survivors — the cluster
analog of the scheduler's ``mark_failed``, except blocks are never
excluded (another host CAN stream them; only the host is gone).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np


class BlockAssigner:
    """Partition ``num_blocks`` streamed blocks across hosts, rebalanced
    per pass from the shared gap ledger."""

    def __init__(
        self,
        num_blocks: int,
        hosts: Sequence[int],
        decay: float = 0.6,
    ):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if not list(hosts):
            raise ValueError("need at least one host")
        if not (0.0 < decay <= 1.0):
            raise ValueError(f"decay must be in (0, 1], got {decay}")
        self.num_blocks = int(num_blocks)
        self.live_hosts: List[int] = sorted(int(h) for h in hosts)
        self.failed_hosts: List[int] = []
        self.decay = float(decay)
        # uniform bootstrap: before any gap is measured LPT reduces to
        # balanced block counts, which is the right prior for equal-cost
        # blocks
        self.scores = np.ones(self.num_blocks, dtype=np.float64)
        self.age = np.zeros(self.num_blocks, dtype=np.int64)
        self.excluded = np.zeros(self.num_blocks, dtype=bool)
        self._decisions: List[dict] = []
        self._last_assignment: Optional[Dict[int, List[int]]] = None

    # -- ledger ------------------------------------------------------------

    def effective_scores(self) -> np.ndarray:
        return self.scores * np.power(self.decay, self.age)

    def update(self, gaps: Dict[int, float]) -> None:
        """Fold one pass's measured per-block gaps into the ledger."""
        self.age += 1
        for block, gap in gaps.items():
            b = int(block)
            if 0 <= b < self.num_blocks:
                self.scores[b] = abs(float(gap))
                self.age[b] = 0

    def mark_blocks_failed(self, blocks: Iterable[int]) -> None:
        """Permanently failed blocks (bad bytes on every host) leave the
        rotation entirely — mirrors GapScheduler.mark_failed."""
        for b in blocks:
            if 0 <= int(b) < self.num_blocks:
                self.excluded[int(b)] = True

    # -- partition ---------------------------------------------------------

    def _lpt(
        self, blocks: np.ndarray, hosts: Sequence[int]
    ) -> Dict[int, List[int]]:
        """Longest-processing-time greedy over effective gap scores:
        deterministic (stable sort, host order fixed), near-balanced in
        both score mass and count."""
        eff = self.effective_scores()
        order = blocks[np.argsort(-eff[blocks], kind="stable")]
        load = {h: 0.0 for h in hosts}
        count = {h: 0 for h in hosts}
        out: Dict[int, List[int]] = {h: [] for h in hosts}
        for b in order:
            # lightest score load first; ties (uniform bootstrap) break by
            # count then host id, so the bootstrap is a clean round-robin
            h = min(hosts, key=lambda x: (load[x], count[x], x))
            out[h].append(int(b))
            load[h] += float(eff[b])
            count[h] += 1
        # blocks stream in index order per host: consecutive blocks share
        # part files, so the worker's decode LRU actually gets hits
        for h in out:
            out[h].sort()
        return out

    def predicted_shares(
        self, assignment: Dict[int, List[int]]
    ) -> Dict[int, float]:
        """Each host's share of the total effective gap mass its slice
        carries — the LPT objective, i.e. the assigner's implicit
        prediction of relative per-host work. The coordinator's skew
        profile compares this against measured per-host busy time
        (assignment-quality feedback: a future skew-aware assigner
        actuates on the gap between the two)."""
        eff = self.effective_scores()
        assigned = [b for blks in assignment.values() for b in blks]
        total = max(float(eff[assigned].sum()), 1e-30) if assigned else 1e-30
        return {
            int(h): float(eff[blks].sum()) / total if blks else 0.0
            for h, blks in assignment.items()
        }

    def assign(self) -> Dict[int, List[int]]:
        """The per-pass partition of every non-excluded block over the
        live hosts."""
        if not self.live_hosts:
            raise RuntimeError("no live hosts left to assign blocks to")
        blocks = np.flatnonzero(~self.excluded)
        assignment = self._lpt(blocks, self.live_hosts)
        if assignment != self._last_assignment:
            # a line-searching solve runs many passes per iteration; only
            # partition CHANGES are ledger-worthy
            self._last_assignment = assignment
            shares = self.predicted_shares(assignment)
            self._decisions.append({
                "event": "rebalance",
                "hosts": {
                    str(h): len(blks) for h, blks in assignment.items()
                },
                "score_share": {
                    str(h): round(shares[h], 4) for h in assignment
                },
            })
        return assignment

    # -- failure -----------------------------------------------------------

    def mark_host_failed(self, host: int) -> None:
        host = int(host)
        if host in self.live_hosts:
            self.live_hosts.remove(host)
            self.failed_hosts.append(host)
        self._decisions.append({"event": "host_failed", "host": host})

    def reassign(self, blocks: Sequence[int]) -> Dict[int, List[int]]:
        """Split a dead host's unfinished blocks over the survivors."""
        if not self.live_hosts:
            raise RuntimeError(
                "every host failed; nothing left to reassign to"
            )
        targets = self._lpt(
            np.asarray(sorted(int(b) for b in blocks), dtype=np.int64),
            self.live_hosts,
        )
        targets = {h: blks for h, blks in targets.items() if blks}
        self._decisions.append({
            "event": "reassign",
            "blocks": sorted(int(b) for b in blocks),
            "targets": {str(h): blks for h, blks in targets.items()},
        })
        return targets

    def drain_decisions(self) -> List[dict]:
        out, self._decisions = self._decisions, []
        return out
