"""Cluster launcher: spawn and supervise the emulated multi-host mesh.

``ClusterPlane.launch`` starts one coordinator (in-process — the trainer
IS the coordinator, like the reference's Spark driver) plus ``num_hosts``
worker subprocesses running ``python -m photon_ml_tpu.parallel.cluster.worker``
pinned to CPU. Worker stdout/stderr go to per-host log FILES, not pipes —
an unread pipe's backpressure can wedge a worker mid-print (same lesson as
tests/test_multiprocess.py).

The same object shape (``set_residual`` / ``distributed_pass`` /
``drain_events``) is what :class:`StreamingFixedEffectCoordinate` accepts
as its ``cluster``, and a bare :class:`ClusterCoordinator` with
thread-hosted workers satisfies it too — tests use that form to exercise
the full wire protocol without subprocess startup cost. On a real pod,
``dev-scripts/run_multihost.py`` starts the same worker module once per
controller instead of this launcher spawning locally.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .coordinator import ClusterCoordinator

STARTUP_TIMEOUT_ENV = "PHOTON_CLUSTER_STARTUP_TIMEOUT_S"
_DEFAULT_STARTUP_TIMEOUT_S = 300.0


class ClusterPlane:
    """A live cluster: in-process coordinator + spawned worker processes."""

    def __init__(
        self,
        coordinator: ClusterCoordinator,
        procs: Sequence[subprocess.Popen],
        log_paths: Sequence[str],
    ):
        self.coordinator = coordinator
        self.procs = list(procs)
        self.log_paths = list(log_paths)
        self._closed = False

    # -- construction ------------------------------------------------------

    @classmethod
    def launch(
        cls,
        num_hosts: int,
        num_blocks: int,
        train_dirs: Sequence[str],
        coordinate_config: str,
        task: str,
        feature_shard: str,
        block_rows: int,
        input_columns_names: Optional[str] = None,
        on_block_error: str = "fail",
        prefetch_depth: int = 2,
        block_cache_dir: Optional[str] = None,
        block_latency_s: Optional[float] = None,
        kill_host: Optional[Tuple[int, int]] = None,
        heartbeat_timeout_s: Optional[float] = None,
        startup_timeout_s: Optional[float] = None,
        log_dir: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        telemetry_dir: Optional[str] = None,
    ) -> "ClusterPlane":
        """Spawn ``num_hosts`` workers over the same training files and
        block plan; ``kill_host=(h, n)`` arms host ``h`` to chaos-die after
        streaming ``n`` blocks (the killed-host-mid-epoch drill).
        ``telemetry_dir`` federates observability across the mesh: the
        coordinator profiles every pass (skew/straggler attribution) and
        each worker writes its own ledger to
        ``{telemetry_dir}/worker-{host}-ledger.jsonl``."""
        coordinator = ClusterCoordinator(
            num_hosts, num_blocks, heartbeat_timeout_s=heartbeat_timeout_s
        )
        if telemetry_dir is not None:
            os.makedirs(telemetry_dir, exist_ok=True)
            coordinator.enable_telemetry()
        if log_dir is None:
            log_dir = tempfile.mkdtemp(prefix="photon-cluster-")
        os.makedirs(log_dir, exist_ok=True)
        worker_env = dict(os.environ)
        worker_env.setdefault("JAX_PLATFORMS", "cpu")
        # the emulated mesh shares one box: keep each worker's BLAS pool
        # from oversubscribing it
        worker_env.setdefault("OPENBLAS_NUM_THREADS", "1")
        if env:
            worker_env.update(env)
        addr = f"{coordinator.address[0]}:{coordinator.address[1]}"
        procs: List[subprocess.Popen] = []
        log_paths: List[str] = []
        try:
            for host in range(num_hosts):
                cmd = [
                    sys.executable, "-m",
                    "photon_ml_tpu.parallel.cluster.worker",
                    "--coordinator-address", addr,
                    "--host-id", str(host),
                    "--train-data-dirs", *list(train_dirs),
                    "--coordinate-config", coordinate_config,
                    "--task", task,
                    "--feature-shard", feature_shard,
                    "--block-rows", str(block_rows),
                    "--prefetch-depth", str(prefetch_depth),
                    "--on-block-error", on_block_error,
                ]
                if input_columns_names:
                    cmd += ["--input-columns-names", input_columns_names]
                if block_cache_dir:
                    # per-host subdirs: the decoded entries are identical
                    # but concurrent writers should not share files
                    cmd += [
                        "--block-cache-dir",
                        os.path.join(block_cache_dir, f"host-{host}"),
                    ]
                if block_latency_s is not None:
                    cmd += ["--block-latency-s", str(block_latency_s)]
                if kill_host is not None and kill_host[0] == host:
                    cmd += ["--chaos-kill-after", str(kill_host[1])]
                if telemetry_dir is not None:
                    cmd += [
                        "--telemetry-out",
                        os.path.join(
                            telemetry_dir, f"worker-{host}-ledger.jsonl"
                        ),
                    ]
                log_path = os.path.join(log_dir, f"worker-{host}.log")
                log_paths.append(log_path)
                log_f = open(log_path, "wb")
                try:
                    procs.append(
                        subprocess.Popen(
                            cmd, stdout=log_f, stderr=subprocess.STDOUT,
                            env=worker_env,
                        )
                    )
                finally:
                    log_f.close()
            if startup_timeout_s is None:
                startup_timeout_s = float(
                    os.environ.get(
                        STARTUP_TIMEOUT_ENV, _DEFAULT_STARTUP_TIMEOUT_S
                    )
                )
            coordinator.wait_for_workers(timeout_s=startup_timeout_s)
        except BaseException:
            for p in procs:
                p.kill()
            coordinator.shutdown()
            raise
        return cls(coordinator, procs, log_paths)

    # -- training-plane interface (what the coordinate calls) --------------

    @property
    def num_blocks(self) -> int:
        return self.coordinator.num_blocks

    def set_residual(self, residual: Optional[np.ndarray]) -> None:
        self.coordinator.set_residual(residual)

    def distributed_pass(self, w: np.ndarray):
        return self.coordinator.distributed_pass(w)

    def drain_events(self) -> List[dict]:
        return self.coordinator.drain_events()

    def drain_pass_profiles(self) -> List[dict]:
        return self.coordinator.drain_pass_profiles()

    # -- lifecycle ---------------------------------------------------------

    def worker_logs(self) -> Dict[int, str]:
        out = {}
        for host, path in enumerate(self.log_paths):
            try:
                with open(path, "r", errors="replace") as f:
                    out[host] = f.read()
            except OSError:
                out[host] = ""
        return out

    def close(self, reap_timeout_s: float = 30.0) -> None:
        if self._closed:
            return
        self._closed = True
        self.coordinator.shutdown()
        for p in self.procs:
            try:
                p.wait(timeout=reap_timeout_s)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def __enter__(self) -> "ClusterPlane":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
