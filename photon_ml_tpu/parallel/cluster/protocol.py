"""Length-prefixed message framing for the cluster plane.

Why not ``jax.distributed`` collectives: a psum wedges forever when one
participant dies, and the cluster plane's whole point is to SURVIVE a
killed host mid-epoch (the reference inherited this from Spark — a lost
executor's partitions are recomputed, the treeAggregate just re-runs).
So the allreduce/control plane is a small coordinator/worker TCP protocol
carrying numpy payloads: the same driver-aggregate-broadcast shape as the
reference's ``treeAggregate`` + broadcast, with sockets as the failure
detector (a killed process closes its socket; a wedged one stops
heartbeating).

Framing is an 8-byte big-endian length prefix followed by a pickled
payload. Pickle is acceptable here because both ends are processes WE
spawned on a trusted interconnect (localhost for the emulated mesh, the
pod's DCN for a real one) — never expose these sockets to untrusted
peers.

Message vocabulary (dicts keyed by ``"type"``):

* ``hello``      worker -> coordinator: ``host`` id, ``num_blocks`` of its
                 locally planned stream (coordinator verifies the plans
                 agree — a config-skewed worker is rejected at the door).
* ``residual``   coordinator -> workers: the CD residual plane for the
                 next solve (per outer iteration, not per pass).
* ``pass``       coordinator -> worker: ``pass_id``, ``frag``, ``w``, and
                 the ``blocks`` this host streams for this pass. With
                 coordinator telemetry enabled the message carries
                 ``telemetry: True``, asking the worker to time itself.
* ``partial``    worker -> coordinator: echo of ``pass_id``/``frag`` plus
                 the host's partial ``f``/``g`` sums and per-block stats.
                 When the ``pass`` asked for telemetry, also a
                 ``telemetry`` dict piggybacking the fragment timings —
                 ``busy_s``/``decode_s``/``solve_s``/``reply_s``,
                 ``blocks`` visited, ``h2d_bytes`` moved — so the skew
                 profile needs no second transport. With telemetry off
                 (the default) both messages are byte-identical to the
                 plain plane: zero extra keys, zero extra messages.
* ``heartbeat``  worker -> coordinator: liveness, sent from a dedicated
                 thread so a long jit compile never reads as death.
* ``stop``       coordinator -> workers: drain and exit 0.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Any, Optional, Tuple

_HEADER = struct.Struct("!Q")
# Guard against a corrupt/hostile length prefix allocating the world.
MAX_MESSAGE_BYTES = 1 << 33


class ProtocolError(RuntimeError):
    """A malformed frame (bad length, truncated payload)."""


def send_msg(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_HEADER.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        read = sock.recv_into(view[got:], n - got)
        if read == 0:
            raise EOFError("peer closed the connection")
        got += read
    return bytes(buf)


def recv_msg(sock: socket.socket) -> Any:
    (length,) = _HEADER.unpack(_recv_exact(sock, _HEADER.size))
    if length > MAX_MESSAGE_BYTES:
        raise ProtocolError(f"frame of {length} bytes exceeds cap")
    return pickle.loads(_recv_exact(sock, length))


class MessageSocket:
    """A framed socket with a send lock, so the heartbeat thread and the
    main loop can interleave sends without tearing frames."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._send_lock = threading.Lock()

    def send(self, obj: Any) -> None:
        with self._send_lock:
            send_msg(self.sock, obj)

    def recv(self) -> Any:
        return recv_msg(self.sock)

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


def connect(address: Tuple[str, int], timeout: Optional[float] = None) -> MessageSocket:
    sock = socket.create_connection(address, timeout=timeout)
    sock.settimeout(None)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return MessageSocket(sock)
