"""Worker side of the cluster plane.

Each worker owns a full :class:`StreamingSource` over the SAME training
files as every other host (the plan is rebuilt deterministically from a
sorted file scan, and the hello handshake verifies the block counts
agree), but per pass it streams only the block subset the coordinator
assigned — the ``order=`` seam of :class:`BlockPrefetcher`. For its
blocks it accumulates the donated per-block ``value_and_grad`` exactly
like the single-host solver's ``_full_pass`` (l2=0 — regularization is
finalized once, on the coordinator) and replies with the partial
``(f, g)`` sums plus per-block stats feeding the shared gap ledger.

Failure semantics are deliberately coarse: ANY exception while streaming
a pass (including an armed ``cluster.worker_block`` fault) kills the
worker, whose closed socket is the coordinator's failure signal. Recovery
lives at the CLUSTER level — the dead host's blocks are reassigned, the
pass completes on the survivors — not at the block level, so a worker
never needs its own retry machinery beyond what StreamingSource already
does for IO.

Run as a module for subprocess workers::

    python -m photon_ml_tpu.parallel.cluster.worker \
        --coordinator-address 127.0.0.1:PORT --host-id 0 \
        --train-data-dirs DIR --coordinate-config CFG.json \
        --task LOGISTIC_REGRESSION --feature-shard global --block-rows 4096

or in-thread for tests via :func:`serve_worker_in_thread`.
"""

from __future__ import annotations

import argparse
import logging
import os
import threading
import time
from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ...resilience.faultpoints import FatalInjectedFault, fault_point, register_fault_site
from ...telemetry.metrics import get_registry
from ...telemetry.span import span
from ...streaming.blocks import StreamingSource
from ...streaming.coordinate import (
    _fuse_block_offsets,
    _objective_for_task,
    _pad_residual,
)
from ...streaming.prefetch import BlockPrefetcher
from ...streaming.solver import StreamPrograms
from ...types import TaskType
from .protocol import connect

logger = logging.getLogger(__name__)

FAULT_SITE = "cluster.worker_block"
register_fault_site(
    FAULT_SITE,
    "cluster worker, before streaming each assigned block: an armed fault "
    "kills the worker mid-pass, exercising host-loss reassignment",
)

BLOCK_LATENCY_ENV = "PHOTON_CLUSTER_BLOCK_LATENCY_S"
HEARTBEAT_INTERVAL_S = 2.0


class ClusterWorker:
    """One host's streaming + partial-accumulation loop."""

    def __init__(
        self,
        host_id: int,
        source: StreamingSource,
        shard_id: str,
        task: TaskType,
        prefetch_depth: int = 2,
        block_latency_s: Optional[float] = None,
        chaos_kill_after: Optional[int] = None,
    ):
        self.host_id = int(host_id)
        self.source = source
        self.shard_id = shard_id
        self.objective = _objective_for_task(task)
        self.programs = StreamPrograms.for_objective(self.objective)
        self.prefetch_depth = int(prefetch_depth)
        if block_latency_s is None:
            block_latency_s = float(os.environ.get(BLOCK_LATENCY_ENV, "0"))
        # emulated per-block device latency for scaling benchmarks on a
        # 1-CPU box: sleeps in separate worker processes genuinely overlap,
        # so throughput scales with hosts the way real device time would
        self.block_latency_s = float(block_latency_s)
        self.chaos_kill_after = (
            None if chaos_kill_after is None else int(chaos_kill_after)
        )
        self._blocks_done = 0
        self._residual_padded = None
        self._dim = source.plan.shard_dims[shard_id]

    # -- one pass fragment -------------------------------------------------

    def _partial(
        self, w: np.ndarray, blocks: List[int], telemetry: bool = False
    ) -> dict:
        t0 = time.perf_counter() if telemetry else 0.0
        w_dev = jnp.asarray(w, dtype=jnp.float32)
        f = jnp.zeros((), dtype=w_dev.dtype)
        g = jnp.zeros((self._dim,), dtype=w_dev.dtype)
        stats: List[Tuple[int, object, object, object]] = []
        prefetcher = BlockPrefetcher(
            self.source,
            shards=(self.shard_id,),
            depth=self.prefetch_depth,
            order=[int(b) for b in blocks],
        )
        t_decode = time.perf_counter() if telemetry else 0.0
        for blk in prefetcher:
            fault_point(FAULT_SITE)
            if (
                self.chaos_kill_after is not None
                and self._blocks_done >= self.chaos_kill_after
            ):
                raise FatalInjectedFault(
                    f"chaos: host {self.host_id} killed after "
                    f"{self._blocks_done} blocks"
                )
            data = blk.data[self.shard_id]
            if self._residual_padded is not None:
                data = data.replace(
                    offsets=_fuse_block_offsets(
                        data.offsets,
                        self._residual_padded,
                        jnp.int32(blk.start),
                    )
                )
            f, g, bf, bg, bgap = self.programs.acc_vg_probe(w_dev, data, f, g)
            stats.append((int(blk.index), bf, bg, bgap))
            self._blocks_done += 1
            if self.block_latency_s > 0:
                time.sleep(self.block_latency_s)
        reply = {
            "f": float(f),
            "g": np.asarray(g, dtype=np.float64),
            "block_stats": [
                {
                    "block": idx,
                    "partial_loss": float(bf),
                    "partial_grad_norm": float(bg),
                    "gap": float(bgap),
                }
                for idx, bf, bg, bgap in stats
            ],
        }
        if telemetry:
            # Piggybacked fragment timing: decode (weight upload +
            # prefetcher setup), solve (the block loop), plus blocks
            # visited and H2D bytes moved. busy_s/reply_s are stamped by
            # run() just before send, where the reply cost is known.
            reply["telemetry"] = {
                "decode_s": t_decode - t0,
                "solve_s": time.perf_counter() - t_decode,
                "blocks": len(stats),
                "h2d_bytes": int(prefetcher.stats.h2d_bytes),
            }
        return reply

    # -- protocol loop -----------------------------------------------------

    def run(self, address: Tuple[str, int], connect_timeout_s: float = 60.0) -> None:
        msock = connect(address, timeout=connect_timeout_s)
        stop_beat = threading.Event()

        def _heartbeat():
            while not stop_beat.wait(HEARTBEAT_INTERVAL_S):
                try:
                    msock.send({"type": "heartbeat", "host": self.host_id})
                except OSError:
                    return

        try:
            msock.send(
                {
                    "type": "hello",
                    "host": self.host_id,
                    "num_blocks": self.source.plan.num_blocks,
                }
            )
            threading.Thread(
                target=_heartbeat, daemon=True,
                name=f"cluster-heartbeat-{self.host_id}",
            ).start()
            while True:
                msg = msock.recv()
                kind = msg.get("type")
                if kind == "stop":
                    break
                if kind == "residual":
                    residual = msg["residual"]
                    self._residual_padded = (
                        None
                        if residual is None
                        else _pad_residual(
                            jnp.asarray(residual, dtype=jnp.float32),
                            self.source.plan.padded_rows,
                        )
                    )
                elif kind == "pass":
                    # The coordinator only sets "telemetry" when its own
                    # telemetry is enabled; without it the reply is
                    # byte-identical to the plain plane.
                    want_tele = bool(msg.get("telemetry"))
                    t_recv = time.perf_counter() if want_tele else 0.0
                    with span(
                        "cluster/fragment",
                        host=self.host_id,
                        pass_id=int(msg["pass_id"]),
                        frag=int(msg["frag"]),
                        blocks=len(msg["blocks"]),
                    ):
                        reply = self._partial(
                            msg["w"], msg["blocks"], telemetry=want_tele
                        )
                    reply.update(
                        type="partial",
                        pass_id=msg["pass_id"],
                        frag=msg["frag"],
                        host=self.host_id,
                    )
                    if want_tele:
                        wt = reply["telemetry"]
                        t_send = time.perf_counter()
                        wt["reply_s"] = max(
                            0.0,
                            t_send - t_recv - wt["decode_s"] - wt["solve_s"],
                        )
                        wt["busy_s"] = t_send - t_recv
                        reg = get_registry()
                        reg.count("cluster.worker.fragments")
                        reg.count("cluster.worker.blocks", wt["blocks"])
                        reg.count("cluster.worker.h2d_bytes", wt["h2d_bytes"])
                        reg.observe("cluster.worker.solve_s", wt["solve_s"])
                    msock.send(reply)
        except EOFError:
            logger.info("host %d: coordinator closed connection", self.host_id)
        finally:
            stop_beat.set()
            msock.close()


def serve_worker_in_thread(
    worker: ClusterWorker, address: Tuple[str, int]
) -> threading.Thread:
    """Run a worker's protocol loop on a daemon thread (tests: exercises
    the full wire protocol without subprocess startup cost). A fatal
    injected fault ends the thread and closes the socket — the same
    death signal a killed process gives."""

    def _run():
        try:
            worker.run(address)
        except FatalInjectedFault as exc:
            logger.info("host %d chaos-killed: %s", worker.host_id, exc)
        except Exception:
            logger.exception("host %d worker died", worker.host_id)

    t = threading.Thread(
        target=_run, daemon=True, name=f"cluster-worker-{worker.host_id}"
    )
    t.start()
    return t


# -- subprocess entry ------------------------------------------------------


def _parse_address(spec: str) -> Tuple[str, int]:
    host, _, port = spec.rpartition(":")
    return host or "127.0.0.1", int(port)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="photon-ml-tpu cluster worker (spawned by the launcher)"
    )
    p.add_argument("--coordinator-address", required=True)
    p.add_argument("--host-id", type=int, required=True)
    p.add_argument("--train-data-dirs", nargs="+", required=True)
    p.add_argument("--coordinate-config", required=True)
    p.add_argument("--task", required=True)
    p.add_argument("--feature-shard", required=True)
    p.add_argument("--block-rows", type=int, default=4096)
    p.add_argument("--input-columns-names", default=None)
    p.add_argument("--prefetch-depth", type=int, default=2)
    p.add_argument("--on-block-error", default="fail")
    p.add_argument("--block-cache-dir", default=None)
    p.add_argument("--block-latency-s", type=float, default=None)
    p.add_argument("--chaos-kill-after", type=int, default=None)
    p.add_argument(
        "--telemetry-out",
        default=None,
        metavar="LEDGER.jsonl",
        help="write this worker's own run ledger (fragment spans, "
        "cluster.worker.* counters) to this path; enables span tracing "
        "in the worker process",
    )
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format=f"[host {args.host_id}] %(levelname)s %(message)s",
    )
    from ...cli.common import (
        expand_data_dirs,
        id_tags_needed,
        load_game_config,
        parse_input_columns,
    )

    shard_configs, coordinates, _, _ = load_game_config(args.coordinate_config)
    col_names = parse_input_columns(args.input_columns_names)
    train_dirs = expand_data_dirs(args.train_data_dirs, None, None)
    # index_maps=None: the maps rebuild deterministically from the sorted
    # file scan, so every host (and the coordinator) plans identical blocks
    source = StreamingSource.open(
        train_dirs,
        shard_configs,
        index_maps=None,
        block_rows=args.block_rows,
        id_tags=id_tags_needed(coordinates),
        cache_dir=args.block_cache_dir,
        **col_names,
    )
    source.on_block_error = args.on_block_error
    worker = ClusterWorker(
        host_id=args.host_id,
        source=source,
        shard_id=args.feature_shard,
        task=TaskType[args.task],
        prefetch_depth=args.prefetch_depth,
        block_latency_s=args.block_latency_s,
        chaos_kill_after=args.chaos_kill_after,
    )
    run = None
    if args.telemetry_out:
        from ...telemetry import start_run

        run = start_run(
            f"cluster-worker-{args.host_id}", ledger_path=args.telemetry_out
        )
    try:
        worker.run(_parse_address(args.coordinator_address))
    except FatalInjectedFault as exc:
        logger.error("chaos-killed: %s", exc)
        return 17
    finally:
        if run is not None:
            try:
                run.finish()
            except Exception:
                logger.exception("worker telemetry finish failed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
