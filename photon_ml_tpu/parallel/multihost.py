"""Multi-host (DCN) runtime helpers: process init + host-sharded input.

Reference parity: the reference's multi-node story is Spark/YARN — executors
pull partitions over the network, the driver coordinates (SURVEY.md §2.6).
The TPU-pod analog: one python process per host, `jax.distributed`
establishes the global device view, training-step collectives ride ICI
inside jit'd programs, and DCN carries only the input pipeline and
checkpoint IO.

These are the runtime seams, called from the CLIs (initialize) and usable
by multi-host input pipelines (file sharding, global batch assembly). They
degrade to the identity in single-process runs — which is also all the
in-repo tests can exercise; the multi-process branches follow the
documented jax.distributed contracts.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Sequence

import jax
import numpy as np

logger = logging.getLogger("photon_ml_tpu")


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Bring this process into the cluster. Returns True when a multi-process
    cluster is (or already was) established.

    MUST run before anything initializes an XLA backend (first jnp op,
    ``jax.devices()``, …) — the CLIs call it first thing. With no arguments
    jax auto-detects cluster environments (TPU pod metadata, Slurm, MPI); a
    plain single machine is not a cluster and stays single-process.

    Also points JAX's persistent compilation cache at the per-uid cache dir
    (every CLI funnels through here, so repeat runs skip first-compile cost;
    PHOTON_ML_TPU_COMPILE_CACHE overrides, "" disables), and re-asserts a
    JAX_PLATFORMS env request via jax.config — some accelerator plugins
    override the env var at import time, which would otherwise ignore an
    explicit platform choice (and hang on a dead device tunnel).
    """
    import os as _os

    env_platform = _os.environ.get("JAX_PLATFORMS", "").strip()
    if env_platform:
        try:
            jax.config.update("jax_platforms", env_platform)
        except Exception:  # pragma: no cover - very old jax
            pass
    from photon_ml_tpu.utils.cachedir import enable_compilation_cache

    enable_compilation_cache()
    try:
        if jax.distributed.is_initialized():
            return jax.process_count() > 1
    except AttributeError:  # pragma: no cover - very old jax
        pass
    try:
        import jax._src.xla_bridge as _xb

        backends_up = _xb.backends_are_initialized()
    except (ImportError, AttributeError):  # pragma: no cover - jax internals moved
        backends_up = False
    if backends_up:
        # Too late to join a cluster in this process. Fine for single-process
        # runs; loud for anything that looks like a real cluster request.
        if coordinator_address is not None:
            raise RuntimeError(
                "initialize_distributed(coordinator_address=...) must run "
                "before any JAX call that initializes the XLA backend"
            )
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError) as e:
        if coordinator_address is not None or num_processes is not None:
            raise  # explicit cluster request must not fail silently
        # no cluster environment auto-detected: single-process run
        logger.debug("no distributed environment detected (%s)", e)
        return False
    return jax.process_count() > 1


def barrier(name: str = "photon-ml-tpu-barrier") -> None:
    """Block until every process reaches this point (no-op single-process).

    Use after single-writer persistence (process 0 writes, everyone then
    reads) and before tearing down shared resources.
    """
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def add_distributed_args(parser) -> None:
    """CLI flags for an explicit cluster launch (torchrun-style): every
    process of the job runs the same command with its own --process-id.
    Omit all three on TPU pods/Slurm, where jax auto-detects the cluster."""
    parser.add_argument(
        "--coordinator-address", default=None,
        help="host:port of process 0 (explicit multi-host launch)",
    )
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)


def initialize_from_args(args) -> bool:
    """``initialize_distributed`` from parsed CLI args (the CLIs call this
    first thing, before any jax device use)."""
    return initialize_distributed(
        coordinator_address=getattr(args, "coordinator_address", None),
        num_processes=getattr(args, "num_processes", None),
        process_id=getattr(args, "process_id", None),
    )


def host_shard_files(paths: Sequence[str]) -> List[str]:
    """This host's slice of the input files (deterministic round-robin over
    the sorted list, so every host computes the same assignment)."""
    ordered = sorted(paths)
    n = jax.process_count()
    if n <= 1:
        return ordered
    i = jax.process_index()
    return [p for k, p in enumerate(ordered) if k % n == i]


def global_batch_from_host_rows(
    rows: np.ndarray, mesh, spec, global_rows: Optional[int] = None
):
    """Assemble a globally-sharded batch array from this host's row block.

    ``rows`` is the process-local data; ``spec`` a PartitionSpec placing the
    global batch over ``mesh``. Each process's block must be exactly the
    slice its own devices address — ``global_rows * local_devices /
    global_devices`` rows (devices cannot hold rows another host has, and
    this helper never moves data between hosts). File sharding
    (:func:`host_shard_files`) generally produces unequal row counts, so
    input pipelines equalize first: fixed-size per-host batches, with
    zero-weight padding rows for the remainder (weight-0 rows are exact
    no-ops in every objective). A too-small/too-large block raises with
    that instruction rather than tripping deep inside jax. On one process
    this is a plain device_put.
    """
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    if jax.process_count() <= 1:
        return jax.device_put(rows, sharding)
    global_shape = None
    if global_rows is not None:
        global_shape = (int(global_rows),) + tuple(rows.shape[1:])
    try:
        return jax.make_array_from_process_local_data(
            sharding, rows, global_shape=global_shape
        )
    except ValueError as e:
        # jax's shard-shape validation covers every spec (sharded over any
        # axis subset, partially sharded, replicated); we add the remedy
        raise ValueError(
            f"{e}\nEach host must supply exactly the rows its own devices "
            "address under the given spec (or the full global batch when "
            "the batch dimension is replicated); this helper never moves "
            "rows between hosts. Equalize per-host batches first — pad "
            "with zero-weight rows (exact no-ops in every objective) or "
            "trim to the share."
        ) from None
