"""2-D (data x feature) sharded fixed-effect features: the 1B-coefficient path.

Reference parity: the reference scales the fixed effect by partitioning
examples across executors and broadcasting the full coefficient vector to
every task each evaluation (DistributedObjectiveFunction convertFromVector;
treeAggregate ValueAndGradientAggregator.scala:243-247). That caps the
model at driver/executor heap. Here BOTH axes shard: the example axis over
a "data" mesh axis and the coefficient axis over a "feat" mesh axis, so a
1e9-coefficient vector lives as n_feat-way shards (w, grad, and the L-BFGS
history never materialize on one chip — SURVEY.md §7 hard part (d)).

Collectives per objective evaluation (all ICI, inserted here or by GSPMD):
- matvec:  psum of partial margins over "feat" (each device owns a column
  range; z_tile = X_tile @ w_local).
- rmatvec: psum of partial gradients over "data" (each device reduces its
  row block; output stays feat-sharded — no device ever holds full grad).
- loss sums / w dot products: GSPMD inserts the psums (sharded operands).

Each (data, feat) mesh tile holds its own sparse engine instance — the
permutation-routed Benes engine (TPU) or the ELL gather layout (CPU tests)
— routed with identical paddings so one compiled program serves the grid.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax.sharding import Mesh, PartitionSpec as P

from photon_ml_tpu.ops import routing
from photon_ml_tpu.utils.nativesort import lexsort_pairs
from photon_ml_tpu.ops.features import EllFeatures
from photon_ml_tpu.ops.sparse_perm import (
    _assemble,
    coalesce_coo,
    select_hot_cols,
    split_hot_entries,
)
from photon_ml_tpu.parallel.mesh import place as place_global, shard_map

DATA_AXIS = "data"
FEAT_AXIS = "feat"


def grid_mesh(
    n_data: int, n_feat: int, devices=None
) -> Mesh:
    """(n_data x n_feat) mesh over the flat device list."""
    if devices is None:
        devices = jax.devices()
    need = n_data * n_feat
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    grid = np.asarray(devices[:need]).reshape(n_data, n_feat)
    return Mesh(grid, (DATA_AXIS, FEAT_AXIS))


@struct.dataclass
class GridShardedFeatures:
    """[n, d] sparse matrix tiled over a (data, feat) mesh.

    FeatureMatrix protocol over GLOBAL logical shapes with sharded layouts:
    ``matvec`` maps a feat-sharded ``w`` [d_pad] to data-sharded margins
    [n_pad]; ``rmatvec`` maps data-sharded coefficients to a feat-sharded
    gradient. Use :func:`shard_vector_feat` / :func:`shard_vector_data` to
    place vectors accordingly.
    """

    shards: object  # per-tile engine pytree; array leaves [n_dd, n_df, ...]
    mesh: Mesh = struct.field(pytree_node=False)
    num_rows_: int = struct.field(pytree_node=False)  # padded global rows
    num_cols_: int = struct.field(pytree_node=False)  # padded global cols

    @property
    def num_rows(self) -> int:
        return self.num_rows_

    @property
    def dim(self) -> int:
        return self.num_cols_

    def _n_dd(self) -> int:
        return self.mesh.shape[DATA_AXIS]

    def _n_df(self) -> int:
        return self.mesh.shape[FEAT_AXIS]

    def matvec(self, w: jax.Array) -> jax.Array:
        w2 = w.reshape(self._n_df(), -1)

        def local_mv(shards, w_blk):
            tile = jax.tree.map(lambda a: a[0, 0], shards)
            z = tile.matvec(w_blk[0])
            return jax.lax.psum(z, FEAT_AXIS)[None]

        out = shard_map(
            local_mv,
            mesh=self.mesh,
            in_specs=(P(DATA_AXIS, FEAT_AXIS), P(FEAT_AXIS)),
            out_specs=P(DATA_AXIS),
        )(self.shards, w2)
        return out.reshape(-1)

    def rmatvec(self, c: jax.Array) -> jax.Array:
        return self._rmatvec(c, squared=False)

    def rmatvec_sq(self, c: jax.Array) -> jax.Array:
        return self._rmatvec(c, squared=True)

    def _rmatvec(self, c: jax.Array, squared: bool) -> jax.Array:
        c2 = c.reshape(self._n_dd(), -1)

        def local_rmv(shards, c_blk):
            tile = jax.tree.map(lambda a: a[0, 0], shards)
            g = tile.rmatvec_sq(c_blk[0]) if squared else tile.rmatvec(c_blk[0])
            return jax.lax.psum(g, DATA_AXIS)[None]

        out = shard_map(
            local_rmv,
            mesh=self.mesh,
            in_specs=(P(DATA_AXIS, FEAT_AXIS), P(DATA_AXIS)),
            out_specs=P(FEAT_AXIS),
        )(self.shards, c2)
        return out.reshape(-1)

    def row_norms_sq(self) -> jax.Array:
        def local_rn(shards):
            tile = jax.tree.map(lambda a: a[0, 0], shards)
            return jax.lax.psum(tile.row_norms_sq(), FEAT_AXIS)[None]

        out = shard_map(
            local_rn,
            mesh=self.mesh,
            in_specs=(P(DATA_AXIS, FEAT_AXIS),),
            out_specs=P(DATA_AXIS),
        )(self.shards)
        return out.reshape(-1)


def shard_vector_feat(x: jax.Array, mesh: Mesh) -> jax.Array:
    """Place a [d_pad] vector sharded over the feat axis (replicated over
    data) — the layout for w, grad, and optimizer history rows."""
    return place_global(x, mesh, P(FEAT_AXIS))


def shard_vector_data(x: jax.Array, mesh: Mesh) -> jax.Array:
    """Place an [n_pad] vector sharded over the data axis (labels, offsets,
    weights, margins)."""
    return place_global(x, mesh, P(DATA_AXIS))


def grid_from_coo(
    rows,
    cols,
    vals,
    shape: Tuple[int, int],
    mesh: Mesh,
    engine: str = "benes",
    plan_cache: Optional[str] = None,
    hot_col_threshold: Optional[int] = None,
    max_hot_cols: int = 128,
    kp_cap="auto",
    col_split="auto",
    payload_dtype: str = "float32",
) -> GridShardedFeatures:
    """Tile COO entries over the (data, feat) mesh and route each tile
    identically.

    Rows pad to a multiple of the data-axis size, columns to a multiple of
    the feat-axis size; callers padding labels/weights must give padding
    rows weight 0 (padded columns are simply never touched).
    """
    if engine not in ("benes", "ell", "fused"):
        raise ValueError(f"unknown engine {engine!r}; expected benes/ell/fused")
    if payload_dtype != "float32" and engine != "fused":
        raise ValueError(
            "payload_dtype applies to the fused engine only (the stage-by-"
            "stage and ELL engines have no half-width payload path)"
        )
    n, d = shape
    n_dd = mesh.shape[DATA_AXIS]
    n_df = mesh.shape[FEAT_AXIS]
    rows, cols, vals = coalesce_coo(rows, cols, vals, n, d)

    if n_dd == 1 and n_df == 1 and engine in ("benes", "fused"):
        # Single-tile grid: delegate to the full single-device builder so
        # the automatic KP-cap + column-split layout planner applies (the
        # 1B-coef chip tile's d*KP would otherwise overshoot the valid-size
        # ladder by up to 16x). Multi-tile grids pin shapes across tiles
        # and keep the flat layout below.
        if engine == "benes":
            from photon_ml_tpu.ops.sparse_perm import from_coo as _single
        else:
            from photon_ml_tpu.ops.fused_perm import from_coo as _single

        single_kw = (
            {"payload_dtype": payload_dtype} if engine == "fused" else {}
        )
        tile = _single(
            rows, cols, vals, (n, d), plan_cache=plan_cache,
            hot_col_threshold=hot_col_threshold, max_hot_cols=max_hot_cols,
            kp_cap=kp_cap, col_split=col_split, **single_kw,
        )
        stacked = jax.tree.map(
            lambda a: place_global(
                np.asarray(a)[None, None], mesh,
                P(DATA_AXIS, FEAT_AXIS, *([None] * np.asarray(a).ndim)),
            ),
            tile,
        )
        return GridShardedFeatures(
            shards=stacked, mesh=mesh, num_rows_=int(n), num_cols_=int(d)
        )

    n_loc = -(-n // n_dd)
    d_loc = -(-d // n_df)
    dd_of = rows // n_loc
    df_of = cols // d_loc

    # One sort by (tile id) then slice: O(nnz log nnz) once instead of one
    # full boolean-mask pass per tile (matters at 1e8+ nnz on big grids).
    tile_id = dd_of * n_df + df_of
    order = lexsort_pairs(tile_id)
    rows, cols, vals, tile_id = (
        rows[order], cols[order], vals[order], tile_id[order]
    )
    bounds = np.searchsorted(tile_id, np.arange(n_dd * n_df + 1))

    # Per-tile hot sets must stack: find each tile's hot columns, then pad
    # every tile to the common H with repeats of its first id and an
    # all-zero dense column (an exact no-op in every linear map).
    tile_entries = {}
    tile_hot = {}
    h_common = 0
    for dd in range(n_dd):
        for df in range(n_df):
            lo, hi = bounds[dd * n_df + df], bounds[dd * n_df + df + 1]
            tr = rows[lo:hi] - dd * n_loc
            tc = cols[lo:hi] - df * d_loc
            tv = vals[lo:hi]
            hot = select_hot_cols(
                tr, tc, n_loc, d_loc, hot_col_threshold, max_hot_cols
            )
            tile_entries[dd, df] = (tr, tc, tv)
            tile_hot[dd, df] = hot
            if hot is not None:
                h_common = max(h_common, hot.size)

    # Common paddings across tiles.
    K = 1
    KP = 1
    tiles_cold = {}
    tile_col_counts = {}
    for key, (tr, tc, tv) in tile_entries.items():
        hot = tile_hot[key]
        hm = None
        if h_common:
            if hot is None:
                hot = np.zeros(0, dtype=np.int64)
            tr, tc, tv, hm_real = (
                split_hot_entries(tr, tc, tv, n_loc, d_loc, hot)
                if hot.size
                else (tr, tc, tv, np.zeros((n_loc, 0), np.float32))
            )
            hm = np.zeros((n_loc, h_common), dtype=np.float32)
            hm[:, : hm_real.shape[1]] = hm_real
            pad_id = int(hot[0]) if hot.size else 0
            hot_full = np.full(h_common, pad_id, dtype=np.int64)
            hot_full[: hot.size] = hot
            tile_hot[key] = hot_full
        tiles_cold[key] = (tr, tc, tv, hm)
        tile_col_counts[key] = (
            np.bincount(tc, minlength=d_loc) if tr.size
            else np.zeros(d_loc, np.int64)
        )
        if tr.size:
            K = max(K, int(np.bincount(tr).max()))
            KP = max(KP, int(tile_col_counts[key].max()))

    if engine == "fused":
        # fused kernels need power-of-two slot groups
        from photon_ml_tpu.ops.fused_perm import _next_pow2

        K = _next_pow2(K)
        KP = _next_pow2(KP)

    # Layout planning (sparse_perm.plan_column_layout) evaluated over the
    # WHOLE grid's degree distribution so every tile keeps pinned shapes:
    # thin column-degree tails — the 1B-coef layout's ~1 nnz/col shards —
    # would otherwise pad every tile's network by max/mean degree AND the
    # valid-size ladder. A KP cap spills per-tile over-cap entries; a
    # column split turns each tile into a ColumnSplitFeatures of
    # identically-shaped sub-blocks.
    tile_spill = {key: (None, None, None) for key in tiles_cold}
    col_blocks = 1
    k_blk = K  # per-block pinned ELL width when the columns split
    block_spill: dict = {}
    if engine in ("benes", "fused") and (kp_cap or col_split != 1):
        from photon_ml_tpu.ops.sparse_perm import (
            resolve_layout,
            split_spill_entries,
        )

        all_counts = np.concatenate(
            [tile_col_counts[key] for key in sorted(tile_col_counts)]
        )

        def _grid_row_block_k(t: int) -> int:
            """Pinned per-block ELL width for a t-way column split: the max
            nnz any tile-local row holds within one column block, over ALL
            tiles (blocks stack across tiles, so the pin is the global
            max). Same refinement as sparse_perm.make_row_block_k."""
            d_bb_t = -(-d_loc // t)
            k_max = 1
            for tr, tc, _tv, _hm in tiles_cold.values():
                if not tr.size:
                    continue
                key2 = tr.astype(np.int64) * t + tc // d_bb_t
                _, cnts = np.unique(key2, return_counts=True)
                k_max = max(k_max, int(cnts.max()))
            if engine == "fused":
                k_max = 1 << max(k_max - 1, 0).bit_length()
            return k_max

        # all_counts spans every tile while n_loc/d_loc describe one tile:
        # scale the spill cost to per-tile units to match the network size
        cap, col_blocks = resolve_layout(
            kp_cap, col_split, all_counts, n_loc, d_loc, K, KP,
            row_block_k=_grid_row_block_k,
            spill_scale=1.0 / max(len(tiles_cold), 1),
        )
        if col_blocks > 1:
            k_blk = _grid_row_block_k(col_blocks)
        if col_blocks > 1:
            # partition each tile's cold entries into column blocks; apply
            # the cap per (tile, block); pad spills to ONE stackable length
            d_bb = -(-d_loc // col_blocks)
            m_max = 0
            tile_blocks = {}
            for key, (tr, tc, tv, hm) in tiles_cold.items():
                blocks = []
                blk_of = tc // d_bb
                for b in range(col_blocks):
                    m = blk_of == b
                    btr, btc, btv = tr[m], tc[m] - b * d_bb, tv[m]
                    counts_b = (
                        np.bincount(btc, minlength=d_bb) if btr.size
                        else np.zeros(d_bb, np.int64)
                    )
                    if cap is not None and btr.size and counts_b.max() > cap:
                        btr, btc, btv, sr, sc, sv = split_spill_entries(
                            btr, btc, btv, counts_b, cap
                        )
                    else:
                        sr = np.zeros(0, np.int64)
                        sc = np.zeros(0, np.int64)
                        sv = np.zeros(0, np.float32)
                    blocks.append((btr, btc, btv, sr, sc, sv))
                    m_max = max(m_max, sr.size)
                tile_blocks[key] = blocks
            for key, blocks in tile_blocks.items():
                block_spill[key] = []
                for b, (btr, btc, btv, sr, sc, sv) in enumerate(blocks):
                    pad = m_max - sr.size
                    spill = (
                        (np.pad(sr, (0, pad)), np.pad(sc, (0, pad)),
                         np.pad(sv, (0, pad)))
                        if m_max else (None, None, None)
                    )
                    block_spill[key].append((btr, btc, btv, spill))
            if cap is not None:
                KP = cap
        elif cap is not None:
            m_max = 0
            for key, (tr, tc, tv, hm) in tiles_cold.items():
                counts = tile_col_counts[key]
                if tr.size and counts.max() > cap:
                    tr, tc, tv, sr, sc, sv = split_spill_entries(
                        tr, tc, tv, counts, cap
                    )
                    tiles_cold[key] = (tr, tc, tv, hm)
                else:
                    sr = np.zeros(0, np.int64)
                    sc = np.zeros(0, np.int64)
                    sv = np.zeros(0, np.float32)
                tile_spill[key] = (sr, sc, sv)
                m_max = max(m_max, sr.size)
            KP = cap
            if m_max:
                # pad every tile's spill to one stackable length; padding
                # entries carry value 0 at (row 0, col 0) — exact no-ops
                for key, (sr, sc, sv) in tile_spill.items():
                    pad = m_max - sr.size
                    tile_spill[key] = (
                        np.pad(sr, (0, pad)),
                        np.pad(sc, (0, pad)),
                        np.pad(sv, (0, pad)),
                    )
            else:
                tile_spill = {key: (None, None, None) for key in tiles_cold}

    # In a multi-process cluster, only build (route!) the tiles whose device
    # belongs to this process — the expensive per-tile routing is O(local
    # share), not O(global). Non-addressable grid positions reuse one built
    # tile as a shape template: their content never reaches any device (the
    # placement callback only reads addressable blocks). K/KP/h_common come
    # from the GLOBAL degree loop above, so all processes agree on shapes.
    multiproc = jax.process_count() > 1
    if multiproc:
        pidx = jax.process_index()
        addressable = {
            (dd, df)
            for dd in range(n_dd)
            for df in range(n_df)
            if mesh.devices[dd, df].process_index == pidx
        }
        if not addressable:
            addressable = {(0, 0)}  # off-mesh process: one template tile
    else:
        addressable = None  # build everything

    def _build_tile(dd, df):
        tr, tc, tv, hm = tiles_cold[dd, df]
        hot_ids = tile_hot[dd, df] if h_common else None
        if engine in ("benes", "fused"):
            assembler = _assemble
            asm_kw = {}
            if engine == "fused":
                from photon_ml_tpu.ops import fused_perm

                assembler = fused_perm.assemble
                asm_kw = {"payload_dtype": payload_dtype}
            if col_blocks > 1:
                # pinned per-block layout: every (tile, block) shares
                # (k_blk, KP, S_b, spill length), so tiles stack
                # leaf-by-leaf; k_blk is the per-block ELL width (each
                # block holds only its columns' entries, so it is smaller
                # than the full-tile K — the planner priced it this way)
                from photon_ml_tpu.ops.sparse_perm import ColumnSplitFeatures

                d_bb = -(-d_loc // col_blocks)
                S_b = routing.valid_size(max(n_loc * k_blk, d_bb * KP, 1))
                blocks = []
                for b, (btr, btc, btv, spill) in enumerate(
                    block_spill[dd, df]
                ):
                    blocks.append(assembler(
                        btr, btc, btv, n_loc, d_bb, k_blk, KP, None, None,
                        plan_cache, size_floor=S_b, spill=spill, **asm_kw,
                    ))
                return ColumnSplitFeatures(
                    blocks=tuple(blocks),
                    hot_matrix=None if hm is None else jnp.asarray(hm),
                    hot_cols=(
                        None if hot_ids is None
                        else jnp.asarray(hot_ids, dtype=jnp.int32)
                    ),
                    col_bounds=tuple(
                        min(b * d_bb, d_loc) for b in range(col_blocks + 1)
                    ),
                    num_rows_=int(n_loc),
                    num_cols_=int(d_loc),
                )
            S = routing.valid_size(max(n_loc * K, d_loc * KP, 1))
            return assembler(
                tr, tc, tv, n_loc, d_loc, K, KP, hm, hot_ids,
                plan_cache, size_floor=S, spill=tile_spill[dd, df], **asm_kw,
            )
        ell = _ell_tile(tr, tc, tv, n_loc, d_loc, K)
        if h_common:
            return _EllWithHot(
                ell=ell,
                hot_matrix=jnp.asarray(hm),
                hot_cols=jnp.asarray(hot_ids, dtype=jnp.int32),
            )
        return ell

    built = {}
    if addressable is not None:
        for pos in sorted(addressable):
            built[pos] = _build_tile(*pos)
        template = built[min(built)]
    structs = []
    for dd in range(n_dd):
        row_structs = []
        for df in range(n_df):
            if addressable is None:
                row_structs.append(_build_tile(dd, df))
            else:
                row_structs.append(built.get((dd, df), template))
        structs.append(row_structs)

    # Stack on HOST (np) so the full global array never materializes on any
    # device; placement uploads only each process's addressable shards.
    stacked = jax.tree.map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]),
        *[
            jax.tree.map(lambda *ys: np.stack([np.asarray(y) for y in ys]), *row)
            for row in structs
        ],
    )
    stacked = jax.tree.map(
        lambda a: place_global(
            a, mesh, P(DATA_AXIS, FEAT_AXIS, *([None] * (a.ndim - 2)))
        ),
        stacked,
    )
    return GridShardedFeatures(
        shards=stacked,
        mesh=mesh,
        num_rows_=int(n_loc * n_dd),
        num_cols_=int(d_loc * n_df),
    )


def _ell_tile(tr, tc, tv, n_loc: int, d_loc: int, K: int) -> EllFeatures:
    """One tile in padded ELL layout with pinned row width K."""
    order = np.argsort(tr, kind="stable")
    tr, tc, tv = tr[order], tc[order], tv[order]
    counts = np.bincount(tr, minlength=n_loc)
    starts = np.zeros(n_loc + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    slots = np.arange(tr.size, dtype=np.int64) - starts[tr]
    values = np.zeros((n_loc, K), dtype=np.float32)
    indices = np.zeros((n_loc, K), dtype=np.int32)
    values[tr, slots] = tv
    indices[tr, slots] = tc
    return EllFeatures(
        values=jnp.asarray(values), indices=jnp.asarray(indices), num_cols=d_loc
    )


@struct.dataclass
class _EllWithHot:
    """ELL tile + dense hot side (mirrors BenesSparseFeatures hot-split
    semantics for the CPU/test engine)."""

    ell: EllFeatures
    hot_matrix: jax.Array
    hot_cols: jax.Array

    def matvec(self, w: jax.Array) -> jax.Array:
        return self.ell.matvec(w) + self.hot_matrix @ w[self.hot_cols]

    def rmatvec(self, c: jax.Array) -> jax.Array:
        g = self.ell.rmatvec(c)
        return g.at[self.hot_cols].add(self.hot_matrix.T @ c)

    def rmatvec_sq(self, c: jax.Array) -> jax.Array:
        g = self.ell.rmatvec_sq(c)
        hm2 = self.hot_matrix * self.hot_matrix
        return g.at[self.hot_cols].add(hm2.T @ c)

    def row_norms_sq(self) -> jax.Array:
        return self.ell.row_norms_sq() + jnp.sum(
            self.hot_matrix * self.hot_matrix, axis=-1
        )
