"""Device mesh + sharding helpers: the communication layer.

Reference parity: §2.6 of the survey — the reference's "distributed backend"
is Spark (treeAggregate all-reduce-to-driver + broadcast of coefficients per
evaluation, ValueAndGradientAggregator.scala:243-247,
DistributedObjectiveFunction.scala). The TPU-native replacement is sharding
annotations over a ``jax.sharding.Mesh``: batches are sharded over the "data"
axis, coefficients are replicated, and XLA inserts the all-reduces (psum over
ICI) inside the jit'd solver program wherever ``rmatvec``/loss-sum reductions
cross the batch axis. There is no per-step broadcast — coefficients live
resident on device.

Multi-host: the same annotations scale to DCN-attached slices via
jax.distributed; data loading feeds per-host shards (io/ pipeline).
"""

from __future__ import annotations

from typing import Optional, Sequence

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.ops.features import DenseFeatures, EllFeatures

DATA_AXIS = "data"

try:
    from jax import shard_map as _shard_map_impl

    def shard_map(f, mesh, in_specs, out_specs):
        """shard_map across jax versions (replication checking off: the
        feature engines mix Pallas calls and psums the checker can't type)."""
        return _shard_map_impl(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_impl(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


def data_parallel_mesh(
    num_devices: Optional[int] = None, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """1-D mesh over the batch ("data") axis."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def pad_batch_to_multiple(data: LabeledData, multiple: int) -> LabeledData:
    """Pad the batch with weight-0 rows so it divides evenly across devices.

    Padding rows have features=0, label=0, offset=0, weight=0 — exact
    algebraic no-ops in the objective (see losses/objective.py _wmask).
    """
    n = data.num_rows
    rem = n % multiple
    if rem == 0:
        return data
    pad = multiple - rem

    def pad0(a):
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths)

    feats = data.features
    if isinstance(feats, DenseFeatures):
        feats = DenseFeatures(matrix=pad0(feats.matrix))
    else:
        feats = EllFeatures(
            values=pad0(feats.values),
            indices=pad0(feats.indices),
            num_cols=feats.num_cols,
        )
    return LabeledData(
        features=feats,
        labels=pad0(data.labels),
        offsets=pad0(data.offsets),
        weights=pad0(data.weights),
        norm=data.norm,
    )


def place(x, mesh: Mesh, spec: P):
    """Place a host-global array onto a mesh sharding, working in BOTH
    runtime models: plain device_put under a single controller, and
    per-process addressable-shard placement in a multi-process cluster
    (device_put cannot reach other hosts' devices there). Every process
    must hold the same GLOBAL value of ``x``."""
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() <= 1:
        return jax.device_put(x, sharding)
    if isinstance(x, jax.Array):
        try:
            if x.sharding.is_equivalent_to(sharding, x.ndim):
                return x  # already placed (re-placing buckets is common)
        except Exception:
            pass
        x = fetch_global(x)  # may itself span processes
    else:
        x = np.asarray(x)
    return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])


def shard_batch(data: LabeledData, mesh: Mesh) -> LabeledData:
    """Place batch-axis arrays sharded over the mesh's data axis; the
    normalization context (feature-axis arrays) is replicated."""
    n_dev = mesh.shape[DATA_AXIS]
    data = pad_batch_to_multiple(data, n_dev)

    def put_rows(a):
        return place(a, mesh, P(DATA_AXIS))

    def put_mat(a):
        return place(a, mesh, P(DATA_AXIS, None))

    feats = data.features
    if isinstance(feats, DenseFeatures):
        feats = DenseFeatures(matrix=put_mat(feats.matrix))
    else:
        feats = EllFeatures(
            values=put_mat(feats.values),
            indices=put_mat(feats.indices),
            num_cols=feats.num_cols,
        )
    norm = data.norm
    if norm is not None:
        norm = replicate(norm, mesh)
    return LabeledData(
        features=feats,
        labels=put_rows(data.labels),
        offsets=put_rows(data.offsets),
        weights=put_rows(data.weights),
        norm=norm,
    )


def replicate(x, mesh: Mesh):
    """Fully replicate a pytree over the mesh."""
    return jax.tree.map(lambda a: place(a, mesh, P()), x)


@functools.lru_cache(maxsize=64)
def _gather_fn(sharding: NamedSharding):
    """One cached all-gather program per target sharding (a fresh jit per
    call would retrace + recompile on every fetch)."""
    return jax.jit(lambda x: x, out_shardings=sharding)


# Device->host fetch observers: callbacks invoked with the byte size of
# every array fetch_global materializes on host. The zero-row-transfer
# steady-state tests of the device score plane install one to prove no code
# path (driver OR coordinate internals) silently pulls a row-length score
# array; fetches of genuinely-host numpy inputs are not device transfers and
# are only observed when the input was a jax.Array.
_FETCH_OBSERVERS: list = []


def add_fetch_observer(callback) -> None:
    """Register ``callback(nbytes)`` to fire on every device->host fetch."""
    _FETCH_OBSERVERS.append(callback)


def remove_fetch_observer(callback) -> None:
    _FETCH_OBSERVERS.remove(callback)


def fetch_global(a):
    """``np.asarray`` for device arrays that may span processes: a sharded
    global array is all-gathered to a replicated layout first (every shard
    becomes addressable), then fetched. A plain no-op fetch everywhere else
    — host numpy code (the coordinate-descent driver's residual algebra)
    calls this instead of np.asarray.

    In a multi-host run this is a cross-process COLLECTIVE: every process
    must call it in the same order (never behind data-dependent branches).
    """
    was_device = isinstance(a, jax.Array)
    if (
        was_device
        and jax.process_count() > 1
        and not a.is_fully_addressable
    ):
        a = _gather_fn(NamedSharding(a.sharding.mesh, P()))(a)
    out = np.asarray(a)
    if was_device and _FETCH_OBSERVERS:
        for cb in list(_FETCH_OBSERVERS):
            cb(out.nbytes)
    return out
