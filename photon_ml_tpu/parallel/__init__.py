from photon_ml_tpu.parallel.mesh import (
    data_parallel_mesh,
    pad_batch_to_multiple,
    replicate,
    shard_batch,
)

__all__ = [
    "data_parallel_mesh",
    "pad_batch_to_multiple",
    "replicate",
    "shard_batch",
]
