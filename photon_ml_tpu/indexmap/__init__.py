"""Feature index maps: feature name <-> dense int index.

Reference parity: photon-api util/IndexMap.scala:22 (the name->index
contract), DefaultIndexMap.scala:27 (in-heap map built by
distinct+zipWithIndex :78), DefaultIndexMapLoader.scala, and the PalDB
off-heap path (PalDBIndexMap.scala:43) whose TPU-native equivalent is the
mmap'd PHIX store in :mod:`photon_ml_tpu.indexmap.offheap`.

Feature names follow the reference's ``name + INTERCEPT_DELIMITER + term``
convention (Constants.scala): a feature is identified by a single string key.
"""

from __future__ import annotations

import abc
import hashlib
from itertools import repeat
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

# reference Constants.scala: the intercept pseudo-feature's key
INTERCEPT_KEY = "(INTERCEPT)"
NAME_TERM_DELIMITER = "\x01"


def feature_key(name: str, term: str = "") -> str:
    """name/term pair -> single map key (reference NameAndTerm semantics)."""
    return name if not term else f"{name}{NAME_TERM_DELIMITER}{term}"


class IndexMap(abc.ABC):
    """name -> dense index contract (reference util/IndexMap.scala:22)."""

    @abc.abstractmethod
    def get_index(self, name: str) -> int:
        """Dense index of a feature name, or -1 when unmapped."""

    @abc.abstractmethod
    def get_feature_name(self, index: int) -> Optional[str]:
        """Inverse lookup; None when the index is absent."""

    @abc.abstractmethod
    def __len__(self) -> int:
        ...

    def get_indices(self, names: Sequence[str]) -> np.ndarray:
        """Vectorized lookup; -1 for unmapped names."""
        return np.fromiter(
            (self.get_index(n) for n in names), dtype=np.int64, count=len(names)
        )

    def __contains__(self, name: str) -> bool:
        return self.get_index(name) >= 0

    def content_digest(self) -> str:
        """Hex digest committing to the full name->index assignment.

        Decoded feature columns are a function of this mapping, so anything
        caching decoded data (the streaming block cache) must include it in
        its fingerprint — two same-size maps with permuted assignments
        otherwise collide. The generic implementation walks the dense index
        space; subclasses override with cheaper equivalents."""
        h = hashlib.sha256()
        for i in range(len(self)):
            h.update(f"{self.get_feature_name(i)}\x00{i}\x01".encode("utf-8"))
        return h.hexdigest()


class DefaultIndexMap(IndexMap):
    """In-heap dict map (reference DefaultIndexMap.scala:27)."""

    def __init__(self, name_to_index: Dict[str, int]):
        self._forward = dict(name_to_index)
        self._reverse = {i: n for n, i in self._forward.items()}
        if len(self._reverse) != len(self._forward):
            raise ValueError("index map has duplicate indices")

    @classmethod
    def from_names(
        cls, names: Iterable[str], add_intercept: bool = False
    ) -> "DefaultIndexMap":
        """distinct + sort + enumerate (the deterministic analog of the
        reference's distinct().sort().zipWithIndex(), DefaultIndexMap.scala:78)."""
        uniq: List[str] = sorted(set(names))
        if add_intercept and INTERCEPT_KEY not in uniq:
            uniq.append(INTERCEPT_KEY)
        return cls({n: i for i, n in enumerate(uniq)})

    def get_index(self, name: str) -> int:
        return self._forward.get(name, -1)

    def get_indices(self, names: Sequence[str]) -> np.ndarray:
        # hot on the serving route path: map(dict.get, names, repeat(-1))
        # stays entirely in C, vs one Python frame per name via get_index
        return np.fromiter(
            map(self._forward.get, names, repeat(-1)),
            dtype=np.int64,
            count=len(names),
        )

    def get_feature_name(self, index: int) -> Optional[str]:
        return self._reverse.get(int(index))

    def __len__(self) -> int:
        return len(self._forward)

    def content_digest(self) -> str:
        # index order, matching the base implementation byte-for-byte
        h = hashlib.sha256()
        for name, idx in sorted(self._forward.items(), key=lambda kv: kv[1]):
            h.update(f"{name}\x00{idx}\x01".encode("utf-8"))
        return h.hexdigest()

    def items(self):
        return self._forward.items()
