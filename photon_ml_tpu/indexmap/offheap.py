"""Off-heap partitioned feature index map: the PalDB-equivalent native store.

Reference parity: util/PalDBIndexMap.scala:43 (partitioned read-only mmap
stores, name->index and index->name in one store :69-103),
PalDBIndexMapBuilder.scala:27 (per-partition store build) and
FeatureIndexingJob.scala:56 (hash-partitioned distinct features -> one store
per partition). The store format ("PHIX") and its C++ reader/builder live in
photon_ml_tpu/native/indexstore.cpp; this module compiles that file on demand
(g++ -O2 -shared), binds it via ctypes, and falls back to a pure-Python mmap
reader/writer of the SAME format when no compiler is available — files are
interchangeable between both implementations.

Partitioning: key -> partition by fnv1a64(key) % num_partitions (stable
across Python/C++). Global indices are assigned contiguously per partition;
``partition_offsets`` in metadata.json lets reverse lookup binary-search the
owning partition.
"""

from __future__ import annotations

import ctypes
import hashlib
import json
import mmap
import os
import pathlib
import struct
import subprocess
import threading
from typing import Iterable, List, Optional, Sequence

import numpy as np

from photon_ml_tpu.indexmap import IndexMap

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native"
_SRC = _NATIVE_DIR / "indexstore.cpp"
_LIB = _NATIVE_DIR / "_indexstore.so"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False

_FNV_OFFSET = np.uint64(14695981039346656037)
_FNV_PRIME = np.uint64(1099511628211)

METADATA_FILE = "metadata.json"
PARTITION_FILE = "partition-{i}.bin"

_HEADER = struct.Struct("<4sIQQQQQQ")  # magic, version, slots, entries, fwd, rev, keys_off, keys_len
_MAGIC = b"PHIX"
_EMPTY = np.uint64(0xFFFFFFFFFFFFFFFF)


def _load_native() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the native store; None if unavailable."""
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            from photon_ml_tpu.utils.nativelib import build_and_load

            lib = build_and_load(_SRC, _LIB)
            if lib is None:
                raise RuntimeError("native index store unavailable")
            lib.phix_build.restype = ctypes.c_int
            lib.phix_build.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
            ]
            lib.phix_open.restype = ctypes.c_void_p
            lib.phix_open.argtypes = [ctypes.c_char_p]
            lib.phix_get.restype = ctypes.c_int64
            lib.phix_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
            lib.phix_get_batch.restype = None
            lib.phix_get_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
            ]
            lib.phix_name_at.restype = ctypes.c_int64
            lib.phix_name_at.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint32,
            ]
            lib.phix_num_entries.restype = ctypes.c_uint64
            lib.phix_num_entries.argtypes = [ctypes.c_void_p]
            lib.phix_close.restype = None
            lib.phix_close.argtypes = [ctypes.c_void_p]
            _lib = lib
        except Exception:
            _lib_failed = True
        return _lib


def native_available() -> bool:
    return _load_native() is not None


def _pack_keys(names: Sequence[bytes]):
    """Concatenate byte keys -> (blob, offsets u64, lens u32)."""
    lens = np.fromiter((len(n) for n in names), dtype=np.uint32, count=len(names))
    offs = np.zeros(len(names), dtype=np.uint64)
    if len(names) > 1:
        offs[1:] = np.cumsum(lens[:-1], dtype=np.uint64)
    return b"".join(names), offs, lens


def fnv1a_hashes(names: Sequence[bytes]) -> np.ndarray:
    """Vectorized FNV-1a 64 over byte keys (partition routing; identical to
    the C++ fnv1a in indexstore.cpp)."""
    if not len(names):
        return np.zeros(0, dtype=np.uint64)
    lens = np.fromiter((len(n) for n in names), dtype=np.int64, count=len(names))
    max_len = int(lens.max()) if len(lens) else 0
    buf = np.zeros((len(names), max_len), dtype=np.uint8)
    for i, n in enumerate(names):
        buf[i, : len(n)] = np.frombuffer(n, dtype=np.uint8)
    h = np.full(len(names), _FNV_OFFSET, dtype=np.uint64)
    with np.errstate(over="ignore"):
        for j in range(max_len):
            live = j < lens
            h[live] = (h[live] ^ buf[live, j].astype(np.uint64)) * _FNV_PRIME
    return h


def _splitmix64(x: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def _pow2_slots(n: int) -> int:
    want = (n * 10) // 7 + 1
    s = 16
    while s < want:
        s <<= 1
    return s


def _build_partition_python(
    path: str, names: Sequence[bytes], indices: np.ndarray
) -> None:
    """Pure-Python writer of the PHIX format (fallback; file-identical
    semantics to phix_build)."""
    n = len(names)
    slots = _pow2_slots(n)
    mask = np.uint64(slots - 1)
    blob, offs, lens = _pack_keys(names)

    fwd_off = np.full(slots, _EMPTY, dtype=np.uint64)
    fwd_len = np.zeros(slots, dtype=np.uint32)
    fwd_idx = np.zeros(slots, dtype=np.uint32)
    rev_ip1 = np.zeros(slots, dtype=np.uint64)
    rev_off = np.zeros(slots, dtype=np.uint64)
    rev_len = np.zeros(slots, dtype=np.uint32)

    hashes = fnv1a_hashes(names)
    rhashes = _splitmix64(np.asarray(indices, dtype=np.uint64))
    for i in range(n):
        slot = int(hashes[i] & mask)
        while fwd_off[slot] != _EMPTY:
            if fwd_len[slot] == lens[i] and blob[
                int(fwd_off[slot]) : int(fwd_off[slot]) + int(lens[i])
            ] == names[i]:
                raise ValueError(f"duplicate key {names[i]!r}")
            slot = (slot + 1) % slots
        fwd_off[slot] = offs[i]
        fwd_len[slot] = lens[i]
        fwd_idx[slot] = indices[i]
        rslot = int(rhashes[i] & mask)
        while rev_ip1[rslot] != 0:
            rslot = (rslot + 1) % slots
        rev_ip1[rslot] = np.uint64(int(indices[i]) + 1)
        rev_off[rslot] = offs[i]
        rev_len[rslot] = lens[i]

    fwd = np.zeros(slots, dtype=[("off", "<u8"), ("len", "<u4"), ("idx", "<u4")])
    fwd["off"], fwd["len"], fwd["idx"] = fwd_off, fwd_len, fwd_idx
    rev = np.zeros(
        slots, dtype=[("ip1", "<u8"), ("off", "<u8"), ("len", "<u4"), ("pad", "<u4")]
    )
    rev["ip1"], rev["off"], rev["len"] = rev_ip1, rev_off, rev_len

    header_size = _HEADER.size
    fwd_bytes = fwd.tobytes()
    rev_bytes = rev.tobytes()
    header = _HEADER.pack(
        _MAGIC, 1, slots, n,
        header_size,
        header_size + len(fwd_bytes),
        header_size + len(fwd_bytes) + len(rev_bytes),
        len(blob),
    )
    with open(path, "wb") as f:
        f.write(header)
        f.write(fwd_bytes)
        f.write(rev_bytes)
        f.write(blob)


def _build_partition(path: str, names: Sequence[bytes], indices: np.ndarray) -> None:
    lib = _load_native()
    if lib is None:
        _build_partition_python(path, names, indices)
        return
    blob, offs, lens = _pack_keys(names)
    idx = np.ascontiguousarray(indices, dtype=np.uint32)
    rc = lib.phix_build(
        str(path).encode(), blob,
        offs.ctypes.data_as(ctypes.c_void_p),
        np.ascontiguousarray(lens).ctypes.data_as(ctypes.c_void_p),
        idx.ctypes.data_as(ctypes.c_void_p),
        len(names),
    )
    if rc != 0:
        raise OSError(f"phix_build failed with code {rc} for {path}")


class _PythonPartition:
    """mmap reader of one PHIX partition (fallback)."""

    def __init__(self, path: str):
        self._f = open(path, "rb")
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        magic, version, slots, entries, fwd_off, rev_off, keys_off, keys_len = (
            _HEADER.unpack_from(self._mm, 0)
        )
        if magic != _MAGIC or version != 1:
            raise ValueError(f"not a PHIX v1 store: {path}")
        self.num_entries = entries
        self._slots = slots
        self._buf = memoryview(self._mm)
        self._fwd = np.frombuffer(
            self._buf, dtype=[("off", "<u8"), ("len", "<u4"), ("idx", "<u4")],
            count=slots, offset=fwd_off,
        )
        self._rev = np.frombuffer(
            self._buf,
            dtype=[("ip1", "<u8"), ("off", "<u8"), ("len", "<u4"), ("pad", "<u4")],
            count=slots, offset=rev_off,
        )
        self._keys_off = keys_off

    def get(self, key: bytes, h: int) -> int:
        mask = self._slots - 1
        slot = int(h) & mask
        mm, ko = self._mm, self._keys_off
        while self._fwd["off"][slot] != _EMPTY:
            off = int(self._fwd["off"][slot])
            ln = int(self._fwd["len"][slot])
            if ln == len(key) and mm[ko + off : ko + off + ln] == key:
                return int(self._fwd["idx"][slot])
            slot = (slot + 1) & mask
        return -1

    def name_at(self, index: int) -> Optional[bytes]:
        mask = self._slots - 1
        slot = int(_splitmix64(np.asarray([index], dtype=np.uint64))[0]) & mask
        want = index + 1
        while self._rev["ip1"][slot] != 0:
            if int(self._rev["ip1"][slot]) == want:
                off = self._keys_off + int(self._rev["off"][slot])
                return self._mm[off : off + int(self._rev["len"][slot])]
            slot = (slot + 1) & mask
        return None

    def close(self) -> None:
        # numpy views over the mmap must be dropped before closing it
        self._fwd = None
        self._rev = None
        self._buf.release()
        self._mm.close()
        self._f.close()


class _NativePartition:
    def __init__(self, path: str, lib: ctypes.CDLL):
        self._lib = lib
        self._h = lib.phix_open(str(path).encode())
        if not self._h:
            raise OSError(f"phix_open failed for {path}")
        self.num_entries = int(lib.phix_num_entries(self._h))

    def get(self, key: bytes, h: int) -> int:
        return int(self._lib.phix_get(self._h, key, len(key)))

    def get_batch(self, blob: bytes, offs: np.ndarray, lens: np.ndarray) -> np.ndarray:
        out = np.empty(len(lens), dtype=np.int64)
        self._lib.phix_get_batch(
            self._h, blob,
            np.ascontiguousarray(offs, dtype=np.uint64).ctypes.data_as(ctypes.c_void_p),
            np.ascontiguousarray(lens, dtype=np.uint32).ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p),
            len(lens),
        )
        return out

    def name_at(self, index: int) -> Optional[bytes]:
        buf = ctypes.create_string_buffer(4096)
        n = self._lib.phix_name_at(self._h, index, buf, 4096)
        if n < 0:
            return None
        if n > 4096:  # rare: longer than the buffer, retry exact
            buf = ctypes.create_string_buffer(n)
            self._lib.phix_name_at(self._h, index, buf, n)
        return buf.raw[: min(n, len(buf.raw))]

    def close(self) -> None:
        if self._h:
            self._lib.phix_close(self._h)
            self._h = None


def build_offheap_index_map(
    names: Iterable[str],
    output_dir: str,
    num_partitions: int = 1,
) -> "OffHeapIndexMap":
    """Distinct, hash-partition, and store feature names; assign contiguous
    global indices per partition (reference FeatureIndexingJob.scala:92-179).
    Returns the opened map."""
    out = pathlib.Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    uniq = sorted(set(names))
    keys = [n.encode("utf-8") for n in uniq]
    part_of = (
        (fnv1a_hashes(keys) % np.uint64(num_partitions)).astype(np.int64)
        if keys
        else np.zeros(0, dtype=np.int64)
    )

    offsets: List[int] = []
    next_index = 0
    for p in range(num_partitions):
        members = [i for i in range(len(keys)) if part_of[i] == p]
        offsets.append(next_index)
        indices = np.arange(next_index, next_index + len(members), dtype=np.uint32)
        _build_partition(
            str(out / PARTITION_FILE.format(i=p)),
            [keys[i] for i in members],
            indices,
        )
        next_index += len(members)

    (out / METADATA_FILE).write_text(
        json.dumps(
            {
                "format": "PHIX",
                "version": 1,
                "num_partitions": num_partitions,
                "num_entries": len(uniq),
                "partition_offsets": offsets,
            }
        )
    )
    return OffHeapIndexMap(output_dir)


class OffHeapIndexMap(IndexMap):
    """Partitioned mmap'd feature index map (reference PalDBIndexMap.scala:43).

    Opens every partition store (native if possible, pure-Python otherwise).
    Forward lookup routes by fnv1a(key) % P; reverse lookup binary-searches
    ``partition_offsets`` (indices are contiguous per partition).
    """

    def __init__(self, directory: str):
        meta = json.loads((pathlib.Path(directory) / METADATA_FILE).read_text())
        if meta.get("format") != "PHIX":
            raise ValueError(f"{directory} is not a PHIX index map directory")
        self._dir = str(directory)
        self._num_partitions = int(meta["num_partitions"])
        self._num_entries = int(meta["num_entries"])
        self._offsets = np.asarray(meta["partition_offsets"], dtype=np.int64)
        lib = _load_native()
        self._parts = []
        for p in range(self._num_partitions):
            path = str(pathlib.Path(directory) / PARTITION_FILE.format(i=p))
            self._parts.append(
                _NativePartition(path, lib) if lib else _PythonPartition(path)
            )

    def get_index(self, name: str) -> int:
        key = name.encode("utf-8")
        h = int(fnv1a_hashes([key])[0])
        return self._parts[h % self._num_partitions].get(key, h)

    def get_indices(self, names: Sequence[str]) -> np.ndarray:
        keys = [n.encode("utf-8") for n in names]
        if not keys:
            return np.zeros(0, dtype=np.int64)
        hashes = fnv1a_hashes(keys)
        parts = (hashes % np.uint64(self._num_partitions)).astype(np.int64)
        out = np.empty(len(keys), dtype=np.int64)
        for p in range(self._num_partitions):
            sel = np.nonzero(parts == p)[0]
            if not len(sel):
                continue
            part = self._parts[p]
            if isinstance(part, _NativePartition):
                blob, offs, lens = _pack_keys([keys[i] for i in sel])
                out[sel] = part.get_batch(blob, offs, lens)
            else:
                for i in sel:
                    out[i] = part.get(keys[i], int(hashes[i]))
        return out

    def get_feature_name(self, index: int) -> Optional[str]:
        if index < 0 or index >= self._num_entries:
            return None
        p = int(np.searchsorted(self._offsets, index, side="right")) - 1
        raw = self._parts[p].name_at(int(index))
        return raw.decode("utf-8") if raw is not None else None

    def __len__(self) -> int:
        return self._num_entries

    def content_digest(self) -> str:
        """Digest of the store directory's file identities — (name, size,
        mtime_ns) of metadata + every partition — instead of the base
        class's O(entries) reverse scan. PHIX stores are immutable once
        built, so file identity IS content identity; a rebuilt store (even
        with identical entries) digests differently, which can only cause
        a spurious cache miss, never a stale hit."""
        h = hashlib.sha256()
        for name in sorted(os.listdir(self._dir)):
            st = os.stat(os.path.join(self._dir, name))
            h.update(
                f"{name}\x00{st.st_size}\x00{st.st_mtime_ns}\x01".encode("utf-8")
            )
        return h.hexdigest()

    def close(self) -> None:
        for p in self._parts:
            p.close()
        self._parts = []

    def __enter__(self) -> "OffHeapIndexMap":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
