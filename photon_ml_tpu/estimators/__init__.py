from photon_ml_tpu.estimators.model_training import GlmFit, train_glm

__all__ = ["GlmFit", "train_glm"]
