"""GameEstimator: sklearn-style fit() for GAME/GLMix models.

Reference parity: estimators/GameEstimator.scala:52 — fit(data, validation,
configs) builds per-coordinate datasets (prepareTrainingDataSets :292-343),
loss/optimizer per coordinate, runs CoordinateDescent, and evaluates
validation data per update; one fit per optimization configuration, best
model selected by the first validation evaluator.

TPU-native notes: dataset preparation (entity grouping, projection, ELL
building) happens once here — the analog of the reference's one-time
shuffles — producing device-resident blocks reused across configurations.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.coordinate import (
    Coordinate,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.algorithm.coordinate_descent import (
    SCORE_PLANES,
    CoordinateDescent,
)
from photon_ml_tpu.algorithm.schedule import SCHEDULES
from photon_ml_tpu.algorithm.factored_random_effect import (
    FactoredRandomEffectCoordinate,
    MFOptimizationConfiguration,
)
from photon_ml_tpu.data.game_data import FeatureShard, GameData
from photon_ml_tpu.data.random_effect import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_ml_tpu.evaluation.evaluators import Evaluator, default_evaluator
from photon_ml_tpu.losses.objective import make_glm_objective
from photon_ml_tpu.losses.pointwise import loss_for_task
from photon_ml_tpu.models.game import CoordinateMeta, GameModel
from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.opt.config import GlmOptimizationConfiguration
from photon_ml_tpu.telemetry import span
from photon_ml_tpu.types import TaskType

logger = logging.getLogger("photon_ml_tpu")


def _coordinate_regularization(model, coord) -> float:
    """One coordinate's regularization term 0.5*l2*||w||^2 + l1*||w||_1
    over its current model (reference getRegularizationTermValue). The
    weights come from the COORDINATE object (which carries any sweep/tuning
    overrides), not the estimator's base configs. All reductions run on
    device (sharded arrays reduce with XLA-inserted collectives); exactly
    one scalar reaches the host per call."""
    from photon_ml_tpu.algorithm.factored_random_effect import (
        FactoredRandomEffectCoordinate,
        FactoredRandomEffectModel,
    )
    from photon_ml_tpu.models.glm import GeneralizedLinearModel
    from photon_ml_tpu.models.random_effect import RandomEffectModel

    def term(a, opt):
        return 0.5 * opt.l2_weight * jnp.sum(a * a) + opt.l1_weight * jnp.sum(
            jnp.abs(a)
        )

    if isinstance(model, FactoredRandomEffectModel):
        assert isinstance(coord, FactoredRandomEffectCoordinate)
        total = sum(
            term(c, coord.re_configuration)
            for c in model.latent.coefficients
        )
        total = total + term(model.projection_matrix, coord.matrix_configuration)
        return float(total)
    opt = getattr(coord, "configuration", None)
    if opt is None:
        return 0.0
    if isinstance(model, GeneralizedLinearModel):
        return float(term(model.coefficients.means, opt))
    if isinstance(model, RandomEffectModel):
        return float(sum(term(c, opt) for c in model.coefficients))
    return 0.0


def _describe_config(cfg: GlmOptimizationConfiguration) -> str:
    return (
        f"{cfg.optimizer_config.optimizer.name}"
        f"(λ={cfg.regularization_weight}, {cfg.regularization.reg_type.name})"
    )


def _config_digest(overrides: Dict[str, GlmOptimizationConfiguration]) -> str:
    """Stable 8-hex fingerprint of a per-coordinate override map; part of
    the per-config checkpoint path so an edited sweep list cannot resume
    from a checkpoint trained under different settings."""
    import hashlib

    key = repr(sorted((cid, cfg) for cid, cfg in overrides.items()))
    return hashlib.sha1(key.encode()).hexdigest()[:8]


@dataclasses.dataclass(frozen=True)
class ParallelConfiguration:
    """Multi-chip layout for GAME training over a (data x feat) device grid.

    - Fixed-effect coordinates train through the grid-sharded sparse engine
      (parallel/grid_features.py): examples sharded over ``n_data`` devices,
      coefficients over ``n_feat`` (margins psum over feat, gradients over
      data) — the reference's treeAggregate+broadcast replaced by ICI
      collectives, with no chip ever holding the full coefficient vector.
    - Random-effect coordinates shard their entity blocks over ALL
      n_data*n_feat devices (independent per-entity solves, no collectives).

    The reference has no analog: Spark parallelism is implicit in the RDD
    runtime (GameEstimator.scala treeAggregateDepth is its only knob).
    """

    n_data: int
    n_feat: int = 1
    engine: str = "benes"  # grid tile engine: "benes" | "ell" | "fused"

    def build_mesh(self):
        from photon_ml_tpu.parallel.grid_features import grid_mesh

        return grid_mesh(self.n_data, self.n_feat)


@dataclasses.dataclass(frozen=True)
class FixedEffectCoordinateConfiguration:
    """Reference FixedEffectDataConfiguration + per-coordinate optimizer
    config (GameEstimator builds both from the CLI mini-languages)."""

    feature_shard: str
    optimizer: GlmOptimizationConfiguration = GlmOptimizationConfiguration()
    # sparse engine for the global problem: "auto" | "ell" | "benes" | "fused"
    # (GameData.sparse_features; "auto" routes large TPU problems through
    # the permutation engine)
    sparse_engine: str = "auto"


@dataclasses.dataclass(frozen=True)
class RandomEffectCoordinateConfiguration:
    feature_shard: str
    data: RandomEffectDataConfiguration
    optimizer: GlmOptimizationConfiguration = GlmOptimizationConfiguration()


@dataclasses.dataclass(frozen=True)
class FactoredRandomEffectCoordinateConfiguration:
    """Reference FactoredRandomEffectOptimizationProblem.scala:42: a latent
    RE problem + projection-matrix problem pair plus MF config."""

    feature_shard: str
    data: RandomEffectDataConfiguration
    mf: MFOptimizationConfiguration
    optimizer: GlmOptimizationConfiguration = GlmOptimizationConfiguration()
    matrix_optimizer: Optional[GlmOptimizationConfiguration] = None


CoordinateConfiguration = Union[
    FixedEffectCoordinateConfiguration,
    RandomEffectCoordinateConfiguration,
    FactoredRandomEffectCoordinateConfiguration,
]


@dataclasses.dataclass
class GameFit:
    model: GameModel
    validation_metric: Optional[float]
    objective_history: List[Tuple[str, float]]
    validation_history: List[Tuple[str, float]]


class GameEstimator:
    def __init__(
        self,
        task: TaskType,
        coordinates: Dict[str, CoordinateConfiguration],
        update_order: Optional[Sequence[str]] = None,
        num_outer_iterations: int = 1,
        evaluator: Optional[Evaluator] = None,
        normalization: Optional[Dict[str, NormalizationContext]] = None,
        intercept_indices: Optional[Dict[str, int]] = None,
        parallel: Optional[ParallelConfiguration] = None,
        extra_evaluators: Sequence[Evaluator] = (),
        compute_variance: bool = False,
        emitter: Optional[object] = None,
        score_plane: str = "device",
        schedule: str = "sync",
        staleness: int = 1,
    ) -> None:
        """``normalization``/``intercept_indices`` are per-feature-shard;
        they apply to fixed-effect coordinates (training runs in normalized
        space, coefficients are mapped back after each solve — reference
        prepareNormalizationContexts, GameEstimator.scala). Random-effect
        locals are index-map projected and train unnormalized.

        ``evaluator`` selects best models; ``extra_evaluators`` are
        additionally computed and logged per coordinate per CD iteration
        (the reference logs EVERY configured evaluator there,
        CoordinateDescent.scala:283-293) without affecting selection."""
        if not coordinates:
            raise ValueError("need at least one coordinate configuration")
        self.task = task
        self.coordinate_configs = dict(coordinates)
        self.update_order = list(update_order) if update_order else list(coordinates)
        self.num_outer_iterations = num_outer_iterations
        self.evaluator = evaluator or default_evaluator(task)
        self.extra_evaluators = list(extra_evaluators)
        self.normalization = dict(normalization or {})
        self.intercept_indices = dict(intercept_indices or {})
        self.parallel = parallel
        self._mesh = parallel.build_mesh() if parallel is not None else None
        # reference COMPUTE_VARIANCE (GameTrainingParams): attach 1/(H_jj+eps)
        # coefficient variances to FE and RE models (not the factored/MF
        # coordinate — random-projection variances don't back-project)
        self.compute_variance = compute_variance
        # optional event.EventEmitter for SolverStatsEvent telemetry from the
        # CD driver (adaptive random-effect lane efficiency)
        self.emitter = emitter
        # where the CD score plane lives: "device" keeps per-coordinate score
        # arrays resident on the training mesh with scalar-only host
        # transfers; "host" is the legacy numpy plane. Multi-controller runs
        # always use the host plane — its fetch_global collectives are the
        # proven cross-process ordering.
        if score_plane not in SCORE_PLANES:
            raise ValueError(
                f"score_plane must be one of {SCORE_PLANES}, got {score_plane!r}"
            )
        self.score_plane = score_plane
        # CD schedule: "sync" (default, bitwise-identical trajectories) or
        # "async" (bounded-staleness pipelined solves + RE bucket overlap on
        # the device plane). Multi-controller runs force sync, exactly like
        # they force the host score plane.
        if schedule not in SCHEDULES:
            raise ValueError(
                f"schedule must be one of {SCHEDULES}, got {schedule!r}"
            )
        if int(staleness) < 0:
            raise ValueError(f"staleness must be >= 0, got {staleness}")
        self.schedule = schedule
        self.staleness = int(staleness)
        # per-bucket SolverStats from the most recent resolve_coordinate call
        self.last_resolve_stats: list = []
        # TransferStats from the most recent _run_fit / resolve_coordinate
        self.last_transfer_stats = None
        self.last_resolve_transfers = None

    def _effective_score_plane(self) -> str:
        """Device plane requires fully-addressable score arrays; under a
        multi-controller runtime eager per-row ops on globally-sharded
        arrays are not safe, so fall back to the host plane (whose
        fetch_global collectives run in identical order on every process)."""
        if jax.process_count() > 1:
            return "host"
        return self.score_plane

    def _effective_schedule(self) -> str:
        """The async schedule pipelines eager per-row updates on the device
        score plane; under multi-controller (or whenever the effective
        plane is the host one) the sync loop's single global dispatch order
        is required, so async falls back to sync."""
        if self.schedule == "async" and self._effective_score_plane() != "device":
            return "sync"
        return self.schedule

    def _build_coordinate(
        self, cid: str, cfg: CoordinateConfiguration, data: GameData
    ) -> Coordinate:
        with span(
            "game/build_coordinate", coordinate=cid, kind=type(cfg).__name__
        ):
            return self._build_coordinate_impl(cid, cfg, data)

    def _build_coordinate_impl(
        self, cid: str, cfg: CoordinateConfiguration, data: GameData
    ) -> Coordinate:
        shard = data.feature_shards[cfg.feature_shard]
        if isinstance(cfg, FixedEffectCoordinateConfiguration):
            if self.parallel is not None:
                return self._build_grid_fixed_effect(cfg, data)
            labeled = LabeledData.create(
                data.sparse_features(cfg.feature_shard, engine=cfg.sparse_engine),
                jnp.asarray(data.labels),
                offsets=jnp.asarray(data.offsets),
                weights=jnp.asarray(data.weights),
                norm=self.normalization.get(cfg.feature_shard),
            )
            return FixedEffectCoordinate(
                data=labeled,
                task=self.task,
                configuration=cfg.optimizer,
                intercept_index=self.intercept_indices.get(cfg.feature_shard),
                compute_variances=self.compute_variance,
            )
        re_ds = build_random_effect_dataset(
            data.id_tags[cfg.data.random_effect_type],
            shard.rows,
            shard.cols,
            shard.vals,
            shard.dim,
            data.labels,
            cfg.data,
            offsets=data.offsets,
            weights=data.weights,
        )
        # computed unconditionally: the summary's device reductions are
        # collectives on sharded buckets, so they must run on every process
        # regardless of per-process log levels
        logger.info("[%s] %s", cid, re_ds.to_summary_string())
        mesh = None
        mesh_axes = None
        if self.parallel is not None:
            from photon_ml_tpu.data.random_effect import (
                pad_entities_to_multiple,
                place_dataset,
            )
            from photon_ml_tpu.parallel.grid_features import DATA_AXIS, FEAT_AXIS

            n_dev = self.parallel.n_data * self.parallel.n_feat
            mesh = self._mesh
            mesh_axes = (DATA_AXIS, FEAT_AXIS)
            # entity-axis sharding over every device of the grid — for the
            # factored coordinate too (its latent datasets derive from these
            # arrays, so the per-entity solves inherit the placement)
            re_ds = place_dataset(
                pad_entities_to_multiple(re_ds, n_dev), mesh, mesh_axes
            )
        if isinstance(cfg, FactoredRandomEffectCoordinateConfiguration):
            return FactoredRandomEffectCoordinate(
                dataset=re_ds,
                task=self.task,
                re_configuration=cfg.optimizer,
                matrix_configuration=cfg.matrix_optimizer or cfg.optimizer,
                mf_configuration=cfg.mf,
                base_offsets=data.offsets,
                mesh=mesh,
                mesh_axes=mesh_axes,
            )
        return RandomEffectCoordinate(
            dataset=re_ds,
            task=self.task,
            configuration=cfg.optimizer,
            base_offsets=data.offsets,
            mesh=mesh,
            mesh_axes=mesh_axes,
            compute_variances=self.compute_variance,
        )

    def _build_grid_fixed_effect(
        self, cfg: "FixedEffectCoordinateConfiguration", data: GameData
    ) -> FixedEffectCoordinate:
        """Fixed effect over the (data x feat) device grid: features tiled
        through the grid engine, batch arrays padded + data-sharded, the
        normalization context padded on the feature axis. The coordinate
        trims back to real shapes at its boundary."""
        from photon_ml_tpu.parallel.grid_features import (
            grid_from_coo,
            shard_vector_data,
        )

        shard = data.feature_shards[cfg.feature_shard]
        n, d = data.num_rows, shard.dim
        gf = grid_from_coo(
            shard.rows, shard.cols, shard.vals, (n, d), self._mesh,
            engine=self.parallel.engine,
        )

        def pad_rows(a):
            out = np.zeros(gf.num_rows, dtype=np.float32)
            out[:n] = np.asarray(a, dtype=np.float32)
            return shard_vector_data(jnp.asarray(out), self._mesh)

        norm = self.normalization.get(cfg.feature_shard)
        if norm is not None and gf.dim != d:
            factor = norm.factor
            shift = norm.shift
            if factor is not None:
                factor = jnp.pad(
                    jnp.asarray(factor), (0, gf.dim - d), constant_values=1.0
                )
            if shift is not None:
                shift = jnp.pad(jnp.asarray(shift), (0, gf.dim - d))
            norm = norm.replace(factor=factor, shift=shift)

        labeled = LabeledData(
            features=gf,
            labels=pad_rows(data.labels),
            offsets=pad_rows(data.offsets),
            weights=pad_rows(data.weights),
            norm=norm,
        )
        return FixedEffectCoordinate(
            data=labeled,
            task=self.task,
            configuration=cfg.optimizer,
            intercept_index=self.intercept_indices.get(cfg.feature_shard),
            num_real_rows=n,
            num_real_cols=d,
            compute_variances=self.compute_variance,
        )

    def _meta(self) -> Dict[str, CoordinateMeta]:
        meta = {}
        for cid, cfg in self.coordinate_configs.items():
            if isinstance(cfg, FixedEffectCoordinateConfiguration):
                meta[cid] = CoordinateMeta(
                    feature_shard=cfg.feature_shard,
                    sparse_engine=cfg.sparse_engine,
                )
            else:
                meta[cid] = CoordinateMeta(
                    feature_shard=cfg.feature_shard,
                    random_effect_type=cfg.data.random_effect_type,
                )
        return meta

    @staticmethod
    def _check_resume_compatible(
        models: Dict[str, object],
        coordinates: Dict[str, Coordinate],
        require_all: bool = True,
    ) -> None:
        """Fail fast (with a clear message) when a checkpoint's layout does
        not match the datasets rebuilt from the current data/config."""
        from photon_ml_tpu.models.glm import GeneralizedLinearModel
        from photon_ml_tpu.models.random_effect import RandomEffectModel

        problems = []
        for cid, model in models.items():
            coord = coordinates.get(cid)
            if coord is None:
                problems.append(f"{cid}: not in current configuration")
                continue
            if isinstance(model, GeneralizedLinearModel):
                from photon_ml_tpu.streaming.coordinate import (
                    StreamingFixedEffectCoordinate,
                )

                if not isinstance(
                    coord,
                    (FixedEffectCoordinate, StreamingFixedEffectCoordinate),
                ):
                    problems.append(
                        f"{cid}: checkpoint holds a fixed-effect model but "
                        "the coordinate is now configured as "
                        f"{type(coord).__name__}"
                    )
                    continue
                # parallel layouts pad the coordinate's feature axis;
                # checkpoints carry real-dim models (streaming coordinates
                # always speak real dims)
                if isinstance(coord, StreamingFixedEffectCoordinate):
                    want = coord.dim
                else:
                    want = coord.num_real_cols or coord.data.dim
                if model.dim != want:
                    problems.append(
                        f"{cid}: checkpoint dim {model.dim} != data dim {want}"
                    )
            else:
                latent = getattr(model, "latent", model)
                if not isinstance(latent, RandomEffectModel):
                    continue
                ds = coord.dataset
                if latent.entity_ids != ds.entity_ids:
                    problems.append(
                        f"{cid}: checkpoint entity layout differs from the "
                        "dataset rebuilt from the current data/config"
                    )
        if require_all and set(coordinates) - set(models):
            missing = sorted(set(coordinates) - set(models))
            problems.append(f"coordinates missing from checkpoint: {missing}")
        if problems:
            raise ValueError(
                "checkpoint is incompatible with this run — it was written "
                "for different data or configuration:\n  "
                + "\n  ".join(problems)
            )

    def resolve_coordinate(
        self,
        cid: str,
        data: GameData,
        models: Dict[str, object],
        initial_model: object = "auto",
    ):
        """Warm-started re-solve of ONE coordinate against ``data`` — the
        single-coordinate slice of a CD outer iteration, exposed for the
        nearline incremental trainer.

        Builds only this coordinate's dataset over ``data``, scores every
        OTHER coordinate's current model as the residual offset (standard CD
        residual algebra), and runs one ``update_model``. For a random-effect
        coordinate the warm start is re-aligned onto the fresh dataset's
        entity layout by id (``align_warm_start``) — entities absent from
        ``data`` are untouched by construction because the dataset only
        contains the entities present in it; entities absent from the old
        model start from zero. Returns the re-solved sub-model in the new
        dataset's layout.
        """
        cfg = self.coordinate_configs.get(cid)
        if cfg is None:
            raise ValueError(
                f"unknown coordinate {cid!r}; have {sorted(self.coordinate_configs)}"
            )
        if isinstance(cfg, FactoredRandomEffectCoordinateConfiguration):
            raise ValueError(
                f"coordinate {cid!r} is factored — single-coordinate re-solve "
                "supports fixed-effect and plain random-effect coordinates"
            )
        with span(
            "game/resolve_coordinate", coordinate=cid, num_rows=data.num_rows
        ):
            return self._resolve_coordinate_impl(
                cid, cfg, data, models, initial_model
            )

    def _resolve_coordinate_impl(self, cid, cfg, data, models, initial_model):
        coord = self._build_coordinate(cid, cfg, data)
        meta = self._meta()
        others = {
            c: m for c, m in models.items() if c != cid and m is not None
        }
        if others:
            gm = GameModel(
                models=others,
                meta={c: meta[c] for c in others},
                task=self.task,
            )
            residual = np.asarray(gm.score(data), dtype=np.float32)
        else:
            residual = np.zeros(data.num_rows, dtype=np.float32)
        model0 = models.get(cid) if initial_model == "auto" else initial_model
        if isinstance(coord, RandomEffectCoordinate) and model0 is not None:
            from photon_ml_tpu.estimators.random_effect import align_warm_start

            model0 = align_warm_start(model0, coord.dataset)
        from photon_ml_tpu.opt.tracking import TransferStats

        effective_plane = self._effective_score_plane()
        transfers = TransferStats(
            score_plane=effective_plane, num_rows=data.num_rows
        )
        transfers.coordinate_updates = 1
        if effective_plane == "device" and coord.supports_device_plane:
            # one residual upload; the offset regroup onto the coordinate's
            # padded blocks happens on device (no further row transfers)
            transfers.record_h2d()
            transfers.device_plane_updates = 1
            updated = coord.update_model_device(model0, jnp.asarray(residual))
        else:
            transfers.record_h2d()
            updated = coord.update_model(model0, residual)
        self.last_resolve_transfers = transfers
        # warm-started nearline re-solves have the largest iteration skew —
        # surface the adaptive driver's lane telemetry to the caller
        self.last_resolve_stats = list(getattr(coord, "last_solver_stats", []))
        if self.emitter is not None and self.last_resolve_stats:
            from photon_ml_tpu.event import SolverStatsEvent

            for s in self.last_resolve_stats:
                self.emitter.send_event(SolverStatsEvent.from_stats(cid, s))
        return updated

    def fit(
        self,
        data: GameData,
        validation_data: Optional[GameData] = None,
        checkpoint_dir: Optional[str] = None,
        initial_models: Optional[Dict[str, object]] = None,
        progress: Optional[object] = None,
    ) -> GameFit:
        """With ``checkpoint_dir``, training state is written atomically
        after every outer CD iteration and an existing checkpoint there is
        resumed automatically (skipping completed iterations) — see
        photon_ml_tpu.checkpoint. ``initial_models`` warm-starts coordinates
        (reference warmStartModels across tuning trials,
        cli/game/training/Driver.scala:484-501); a resumed checkpoint takes
        precedence. ``progress`` is an optional
        :class:`~photon_ml_tpu.telemetry.progress.ConvergenceTracker`; None
        (the default) leaves training bitwise-identical."""
        coordinates = {
            cid: self._build_coordinate(cid, cfg, data)
            for cid, cfg in self.coordinate_configs.items()
        }
        return self._run_fit(
            coordinates, data, validation_data, checkpoint_dir, initial_models,
            progress=progress,
        )

    def fit_streaming(
        self,
        source,
        validation_data: Optional[GameData] = None,
        checkpoint_dir: Optional[str] = None,
        initial_models: Optional[Dict[str, object]] = None,
        prefetch_depth: int = 2,
        mode: str = "full",
        stochastic_epochs: int = 5,
        stochastic_chunk_iters: int = 4,
        blocks_per_update: int = 1,
        seed: int = 0,
        gap_schedule: bool = False,
        resident_blocks: int = 0,
        resident_bytes: Optional[int] = None,
        progress: Optional[object] = None,
        cluster: Optional[object] = None,
    ) -> GameFit:
        """Out-of-core ``fit``: fixed-effect coordinates stream fixed-shape
        blocks from a :class:`~photon_ml_tpu.streaming.StreamingSource`
        instead of holding the design matrix in memory.

        One streamed setup pass accumulates the per-row scalar planes
        (labels/offsets/weights/id tags — O(n) scalars, not features) and
        the per-entity COO of random-effect shards, so RE coordinates run
        through the existing cost-sorted bucket packing unchanged. The FE
        feature payload — the memory-dominant term — never materializes:
        each CD update/score re-streams it, with host staging bounded by
        ``prefetch_depth × block bytes``.

        ``mode='full'`` is the exact full-batch streamed solve (same
        optimum as in-memory, the default); ``mode='stochastic'`` visits
        shuffled block groups per epoch on the resumable solver seam —
        gate it on held-out metric parity before trusting it.
        ``gap_schedule=True`` (stochastic only) replaces the blind shuffle
        with duality-gap-guided block selection (docs/SCALING.md).

        ``resident_blocks``/``resident_bytes`` cap a device-resident set of
        top-gap blocks whose uploads persist across streamed passes — the
        HBM level of the residency hierarchy (docs/SCALING.md "Residency
        hierarchy"). Warm passes then re-upload only the non-resident
        remainder; the solve trajectory is unchanged (identical visit
        order, only transfer volume drops). Requires ``mode='full'`` or
        ``gap_schedule=True``, and no ``cluster``.

        ``cluster`` (a ``parallel.cluster.ClusterPlane`` or bare
        ``ClusterCoordinator``) runs the fixed-effect solve data-parallel
        across hosts: every streamed pass becomes a distributed allreduce
        over the workers' assigned block shares, while random-effect
        coordinates stay entity-partitioned on this host (per-entity
        solves never cross hosts — the GAME structure makes RE
        embarrassingly parallel). Requires ``mode='full'`` and exactly one
        fixed-effect coordinate (one cluster drives one block plan).
        """
        from photon_ml_tpu.streaming.coordinate import (
            StreamingFixedEffectCoordinate,
        )

        if self.parallel is not None:
            raise ValueError(
                "streaming training does not compose with the device-grid "
                "parallel layout yet (multi-host streaming is roadmap work)"
            )
        if self.compute_variance:
            raise ValueError(
                "streaming training cannot compute coefficient variances "
                "(needs a second Hessian-diagonal pass; train in-memory)"
            )
        fe_cfgs = {
            cid: cfg
            for cid, cfg in self.coordinate_configs.items()
            if isinstance(cfg, FixedEffectCoordinateConfiguration)
        }
        for cid, cfg in fe_cfgs.items():
            if self.normalization.get(cfg.feature_shard) is not None:
                raise ValueError(
                    f"streaming coordinate {cid!r}: normalization requires "
                    "a streamed feature-stats pass (not implemented); use "
                    "--normalization-type NONE or train in-memory"
                )
        if cluster is not None:
            if mode != "full":
                raise ValueError(
                    "cluster training requires mode='full' (the distributed "
                    "pass sums exact per-host partials)"
                )
            if len(fe_cfgs) != 1:
                raise ValueError(
                    "cluster training requires exactly one fixed-effect "
                    f"coordinate, config has {sorted(fe_cfgs) or 'none'}"
                )
        re_shards = sorted({
            cfg.feature_shard
            for cid, cfg in self.coordinate_configs.items()
            if cid not in fe_cfgs
        })
        planes = source.row_planes(coo_shards=re_shards)
        data = GameData(
            labels=planes.labels,
            feature_shards={
                sid: FeatureShard(rows=r, cols=c, vals=v, dim=d)
                for sid, (r, c, v, d) in planes.shard_coo.items()
            },
            id_tags=planes.id_tags,
            offsets=planes.offsets,
            weights=planes.weights,
        )
        coordinates: Dict[str, Coordinate] = {}
        for cid, cfg in self.coordinate_configs.items():
            if cid in fe_cfgs:
                coordinates[cid] = StreamingFixedEffectCoordinate(
                    source=source,
                    shard_id=cfg.feature_shard,
                    task=self.task,
                    configuration=cfg.optimizer,
                    prefetch_depth=prefetch_depth,
                    mode=mode,
                    epochs=stochastic_epochs,
                    chunk_iters=stochastic_chunk_iters,
                    blocks_per_update=blocks_per_update,
                    seed=seed,
                    gap_schedule=gap_schedule,
                    resident_blocks=resident_blocks,
                    resident_bytes=resident_bytes,
                    # convergence plane: per-block loss/grad/gap probes run
                    # only when a tracker is attached (bitwise contract)
                    collect_block_stats=progress is not None,
                    cluster=cluster,
                )
            else:
                coordinates[cid] = self._build_coordinate(cid, cfg, data)
        return self._run_fit(
            coordinates, data, validation_data, checkpoint_dir, initial_models,
            progress=progress,
        )

    def fit_multiple(
        self,
        data: GameData,
        validation_data: Optional[GameData] = None,
        configs: Sequence[Dict[str, GlmOptimizationConfiguration]] = (),
        warm_start: bool = True,
        checkpoint_dir: Optional[str] = None,
    ) -> List[GameFit]:
        """One fit per model configuration — the reference's
        ``fit(data, validation, Seq[GameModelOptimizationConfiguration])``
        (GameEstimator.scala:175-217), which trains one GAME model per swept
        configuration and leaves best-model selection to the caller
        (``select_best_fit`` = Driver.scala:356 selectBestModel).

        Each entry of ``configs`` maps coordinate id → per-coordinate
        optimizer configuration; coordinates absent from an entry keep the
        estimator's configured optimizer. The expensive dataset preparation
        (entity grouping, projection, routing) happens ONCE and is shared
        by every fit — only the solver configuration changes per run (the
        analog of the reference reusing prepared trainingDataSets across
        the config sequence). ``warm_start`` seeds each fit with the
        previous fit's models. ``checkpoint_dir`` gets one subdirectory per
        configuration, keyed by index AND a digest of the override map
        (``config-000-1a2b3c4d``) so a resume after the sweep list was
        edited retrains instead of silently returning a model trained
        under different settings.
        """
        base = {
            cid: self._build_coordinate(cid, cfg, data)
            for cid, cfg in self.coordinate_configs.items()
        }
        if not configs:
            configs = [{}]
        fits: List[GameFit] = []
        prev_models: Optional[Dict[str, object]] = None
        for i, overrides in enumerate(configs):
            unknown = set(overrides) - set(base)
            if unknown:
                raise ValueError(
                    f"config {i} names unknown coordinates: {sorted(unknown)}"
                )
            coords = {
                cid: (
                    self._replace_optimizer(coord, overrides[cid])
                    if cid in overrides
                    else coord
                )
                for cid, coord in base.items()
            }
            logger.info(
                "fit %d/%d with config overrides: %s", i + 1, len(configs),
                {c: _describe_config(v) for c, v in overrides.items()} or "(defaults)",
            )
            fit = self._run_fit(
                coords,
                data,
                validation_data,
                (
                    None
                    if checkpoint_dir is None
                    else f"{checkpoint_dir}/config-{i:03d}-{_config_digest(overrides)}"
                ),
                prev_models if warm_start else None,
            )
            fits.append(fit)
            if warm_start:
                prev_models = fit.model.models
        return fits

    def select_best_fit(self, fits: Sequence[GameFit]) -> Optional[int]:
        """Index of the fit the validation evaluator ranks best (reference
        Driver.scala:356 selectBestModel — reduce by the first evaluator's
        betterThan); None when no fit carries a validation metric, like the
        reference's reduceOption on an empty evaluation sequence."""
        best: Optional[int] = None
        for i, fit in enumerate(fits):
            if fit.validation_metric is None:
                continue
            if best is None or self.evaluator.better_than(
                fit.validation_metric, fits[best].validation_metric
            ):
                best = i
        return best

    @staticmethod
    def _replace_optimizer(
        coord: Coordinate, opt: GlmOptimizationConfiguration
    ) -> Coordinate:
        """A coordinate with the same (device-resident) dataset but a new
        optimizer configuration. For factored coordinates the projection-
        matrix solve follows the sweep only when it was sharing the RE
        configuration; a separately-configured matrix_optimizer is kept."""
        if isinstance(coord, FactoredRandomEffectCoordinate):
            shared = coord.matrix_configuration == coord.re_configuration
            return dataclasses.replace(
                coord,
                re_configuration=opt,
                matrix_configuration=(
                    opt if shared else coord.matrix_configuration
                ),
            )
        return dataclasses.replace(coord, configuration=opt)

    def _run_fit(
        self,
        coordinates: Dict[str, Coordinate],
        data: GameData,
        validation_data: Optional[GameData],
        checkpoint_dir: Optional[str],
        initial_models: Optional[Dict[str, object]],
        progress: Optional[object] = None,
    ) -> GameFit:
        meta = self._meta()

        loss = loss_for_task(self.task)
        labels = jnp.asarray(data.labels)
        weights = jnp.asarray(data.weights)
        offsets = jnp.asarray(data.offsets)

        def training_objective(total_scores) -> float:
            # accepts the device plane's running total (jax.Array) or the
            # host plane's numpy sum; exactly ONE scalar crosses to the host
            z = offsets + jnp.asarray(total_scores)
            terms = loss.value(z, labels)
            return float(jnp.sum(jnp.where(weights > 0, weights * terms, 0.0)))

        # per-coordinate cache keyed by model identity (strong ref, so an id
        # is never reused while cached): only the coordinate that just
        # updated recomputes its term
        reg_cache: Dict[str, Tuple[object, float]] = {}

        def regularization_term(models: Dict[str, object]) -> float:
            """Σ per-coordinate 0.5*l2*||w||^2 + l1*||w||_1 over the current
            models (reference getRegularizationTermValue, logged per update
            CoordinateDescent.scala:247-258). Weights come from the built
            Coordinate objects, which carry sweep/tuning overrides."""
            total = 0.0
            for cid, m in models.items():
                coord = coordinates.get(cid)
                if coord is None:
                    continue
                cached = reg_cache.get(cid)
                if cached is None or cached[0] is not m:
                    reg_cache[cid] = (m, _coordinate_regularization(m, coord))
                total += reg_cache[cid][1]
            return total

        validate = None
        if validation_data is not None:
            def validate(models: Dict[str, object]) -> float:
                gm = GameModel(models=dict(models), meta=meta, task=self.task)
                scores = gm.score(validation_data) + validation_data.offsets
                primary = self.evaluator.evaluate(
                    scores, validation_data.labels, validation_data.weights
                )
                if self.extra_evaluators:
                    # reference CoordinateDescent.scala:283-293: every
                    # configured evaluator is computed and logged per
                    # coordinate update; only the first drives selection
                    extras = {
                        ev.name: ev.evaluate(
                            scores,
                            validation_data.labels,
                            validation_data.weights,
                        )
                        for ev in self.extra_evaluators
                    }
                    logger.info(
                        "validation metrics: %s=%.6f %s",
                        self.evaluator.name, primary,
                        " ".join(f"{k}={v:.6f}" for k, v in extras.items()),
                    )
                return primary

        schedule = self._effective_schedule()
        # the async schedule's RE leg: overlap bucket solves inside each
        # random-effect coordinate (0 restores the sequential, bitwise-
        # identical path — set every run so shared built coordinates are
        # correct for whichever schedule this fit uses)
        for coord in coordinates.values():
            if hasattr(coord, "overlap_buckets"):
                coord.overlap_buckets = 2 if schedule == "async" else 0

        cd = CoordinateDescent(
            coordinates,
            num_rows=data.num_rows,
            update_order=self.update_order,
            training_objective=training_objective,
            regularization_term=regularization_term,
            validate=validate,
            validation_better_than=self.evaluator.better_than,
            emitter=self.emitter,
            score_plane=self._effective_score_plane(),
            schedule=schedule,
            staleness=self.staleness,
            progress=progress,
        )

        start_iteration = 0
        initial_best = None
        on_iteration_end = None
        prior_objective_history: List[Tuple[str, float]] = []
        prior_validation_history: List[Tuple[str, float]] = []
        if initial_models is not None:
            # warm start may cover a subset of coordinates
            self._check_resume_compatible(
                initial_models, coordinates, require_all=False
            )
        if checkpoint_dir is not None:
            from photon_ml_tpu import checkpoint as ckpt

            if ckpt.has_checkpoint(checkpoint_dir):
                initial_models, state, best = ckpt.load_training_checkpoint(
                    checkpoint_dir
                )
                self._check_resume_compatible(initial_models, coordinates)
                start_iteration = int(state["completed_iterations"])
                if best is not None and state.get("best_metric") is not None:
                    initial_best = (best, float(state["best_metric"]))
                prior_objective_history = [
                    tuple(x) for x in state.get("objective_history", [])
                ]
                prior_validation_history = [
                    tuple(x) for x in state.get("validation_history", [])
                ]
                logger.info(
                    "resuming from checkpoint %s at outer iteration %d",
                    checkpoint_dir, start_iteration,
                )

            def on_iteration_end(outer: int, running) -> None:
                ckpt.save_training_checkpoint(
                    checkpoint_dir,
                    running.models,
                    state={
                        "completed_iterations": outer + 1,
                        "best_metric": running.best_metric,
                        # full histories so a second resume stays complete
                        "objective_history": prior_objective_history
                        + running.objective_history,
                        "validation_history": prior_validation_history
                        + running.validation_history,
                    },
                    best_models=(
                        running.best_models if validate is not None else None
                    ),
                )

        with span(
            "game/fit",
            coordinates=len(coordinates),
            num_rows=data.num_rows,
            score_plane=cd.score_plane,
        ):
            result = cd.run(
                self.num_outer_iterations,
                initial_models=initial_models,
                start_iteration=start_iteration,
                initial_best=initial_best,
                on_iteration_end=on_iteration_end,
            )
        self.last_transfer_stats = cd.transfer_stats
        model = GameModel(models=result.best_models, meta=meta, task=self.task)
        return GameFit(
            model=model,
            validation_metric=result.best_metric,
            objective_history=prior_objective_history + result.objective_history,
            validation_history=prior_validation_history + result.validation_history,
        )
