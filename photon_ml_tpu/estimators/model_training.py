"""Single-GLM training entry: warm-started regularization sweep.

Reference parity: ModelTraining.trainGeneralizedLinearModel
(ModelTraining.scala:106-213): one optimization problem is reused across a
λ sweep sorted high→low, warm-starting each fit from the previous optimum
(:160-206). Optional per-coefficient variances from the inverse Hessian
diagonal (DistributedOptimizationProblem.scala:80-94).

TPU notes: the solver program is compiled once (λ is a traced scalar); when
``data`` is sharded over a mesh's batch axis the same code runs data-parallel
with XLA-inserted psums — there is no separate "distributed trainer".
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from photon_ml_tpu.losses.objective import GlmObjective, make_glm_objective
from photon_ml_tpu.losses.pointwise import loss_for_task
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.opt.config import GlmOptimizationConfiguration
from photon_ml_tpu.opt.solve import solve
from photon_ml_tpu.opt.state import SolveResult
from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.types import TaskType


@dataclasses.dataclass
class GlmFit:
    """One trained model of a sweep."""

    regularization_weight: float
    model: GeneralizedLinearModel
    result: SolveResult
    # per-iteration models (original feature space) when track_models was
    # requested — the reference's ModelTracker (ModelTracker.scala,
    # DistributedOptimizationProblem per-iteration tracking)
    tracked_models: Optional[List[GeneralizedLinearModel]] = None


def block_on_fit(fit: GlmFit) -> GlmFit:
    """Block until the fit's arrays are computed. ``train_glm`` returns
    unblocked pytrees (the async CD schedule relies on that to overlap the
    FE solve with RE work); timing and reconciliation code that needs the
    solve to have actually finished waits here."""
    import jax

    jax.block_until_ready(
        [leaf for leaf in jax.tree_util.tree_leaves(
            (fit.model, fit.result)
        ) if isinstance(leaf, jax.Array)]
    )
    return fit


def train_glm(
    data: LabeledData,
    task: TaskType,
    configuration: GlmOptimizationConfiguration,
    regularization_weights: Optional[Sequence[float]] = None,
    initial_model: Optional[GeneralizedLinearModel] = None,
    warm_start: bool = True,
    compute_variances: bool = False,
    track_models: bool = False,
    intercept_index: Optional[int] = None,
    box_constraints=None,
) -> List[GlmFit]:
    """Train one GLM per regularization weight, warm-starting down the sorted
    sweep. Returns fits in the caller's requested order.

    Coefficients are returned in the ORIGINAL feature space: when ``data.norm``
    is set, training runs in normalized space and the optimum is mapped back
    (reference NormalizationContext.transformModelCoefficients / Driver flow).
    """
    objective = make_glm_objective(loss_for_task(task))
    if regularization_weights is None:
        regularization_weights = [configuration.regularization_weight]
    if track_models:
        configuration = dataclasses.replace(
            configuration,
            optimizer_config=dataclasses.replace(
                configuration.optimizer_config, track_coefficients=True
            ),
        )

    dim = data.dim
    if initial_model is not None:
        # initial_model carries ORIGINAL-space coefficients; map into the
        # normalized training space before warm-starting.
        w = initial_model.coefficients.means
        if data.norm is not None:
            w = data.norm.inverse_transform_model_coefficients(w, intercept_index)
    else:
        w = jnp.zeros((dim,), dtype=jnp.float32)

    reg = configuration.regularization
    use_l1 = any(reg.l1_weight(lw) > 0 for lw in regularization_weights)

    # An explicit 0.0 l1_weight pins the solver to LBFGS/TRON even when the
    # configuration's own regularization_weight would imply L1 (the sweep
    # weights are authoritative).
    # box_constraints arrive in the ORIGINAL feature space (the reference's
    # per-feature constraint map, GLMSuite); training may run in normalized
    # space, where w_orig = factor .* w_norm (componentwise, factor > 0), so
    # the bounds map by the same positive diagonal. Shift normalization
    # mixes the intercept non-componentwise — an explicitly-bounded
    # intercept cannot be honored there and is rejected.
    if box_constraints is not None and data.norm is not None:
        lo, hi = box_constraints
        if data.norm.shift is not None and intercept_index is not None:
            import numpy as np

            if (np.isfinite(np.asarray(lo)[intercept_index])
                    or np.isfinite(np.asarray(hi)[intercept_index])):
                raise ValueError(
                    "an intercept box constraint cannot be combined with "
                    "shift normalization (the intercept mixes all "
                    "coefficients there); constrain only non-intercept "
                    "features or use a factor-only normalization"
                )
        factor = data.norm.factor
        if factor is not None:
            lo = jnp.asarray(lo) / factor
            hi = jnp.asarray(hi) / factor
        box_constraints = (lo, hi)
    solver = jax.jit(
        lambda w0, dd, l2, l1: solve(
            objective,
            w0,
            dd,
            configuration,
            l2_weight=l2,
            l1_weight=l1 if use_l1 else 0.0,
            box=box_constraints,
        )
    )
    hess_diag = jax.jit(objective.hessian_diag) if compute_variances else None

    # high -> low so each warm start begins from a smoother problem
    # (reference ModelTraining.scala:160-206)
    sweep = sorted(regularization_weights, reverse=True)
    fits: dict[float, GlmFit] = {}
    for lam in sweep:
        l2 = jnp.float32(reg.l2_weight(lam))
        l1 = jnp.float32(reg.l1_weight(lam))
        result = solver(w, data, l2, l1)
        if warm_start:
            w = result.w

        variances = None
        if compute_variances:
            # var_j ~= 1 / (H_jj + eps) (reference
            # DistributedOptimizationProblem.scala:80-94)
            diag = hess_diag(result.w, data, l2)
            variances = 1.0 / (diag + 1e-12)

        w_out = result.w
        if data.norm is not None:
            w_out = data.norm.transform_model_coefficients(w_out, intercept_index)
            if variances is not None:
                variances = data.norm.transform_model_variances(variances, intercept_index)
        model = GeneralizedLinearModel(
            coefficients=Coefficients(means=w_out, variances=variances), task=task
        )

        tracked = None
        if track_models and result.w_history is not None:
            tracked = []
            iters = int(result.iterations)
            for w_i in result.w_history[: iters + 1]:
                if data.norm is not None:
                    w_i = data.norm.transform_model_coefficients(
                        w_i, intercept_index
                    )
                tracked.append(
                    GeneralizedLinearModel(
                        coefficients=Coefficients(means=w_i), task=task
                    )
                )
        fits[lam] = GlmFit(
            regularization_weight=lam, model=model, result=result,
            tracked_models=tracked,
        )

    return [fits[lam] for lam in regularization_weights]
