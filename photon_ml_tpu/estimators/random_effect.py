"""Random-effect training and scoring: vmap'd local solves.

Reference parity: algorithm/RandomEffectCoordinate.scala:39 — updateModel
(:103-143) runs ``activeData.join(problems).join(models).mapValues{ local
Breeze solve }``, i.e. millions of independent optimizations inside executor
closures; score (:157-187) covers active + passive data. Here each dataset
bucket becomes ONE jit-compiled program: ``vmap(solver)`` over the entity
axis — every entity's full L-BFGS/TRON/OWL-QN while_loop runs in lockstep
lanes on the MXU with zero cross-entity communication. Sharding the entity
axis over a mesh scales this to a pod with no collectives in the solve.

Convergence-adaptive driver: a lockstep dispatch runs until its SLOWEST
entity converges, so on skewed workloads most lanes burn dead iterations.
When ``configuration.adaptive.enabled`` the per-bucket solve instead runs in
chunks of K outer iterations (full solver state — L-BFGS memory, OWL-QN
orthant state, TRON trust radius — carried across chunks, so the per-lane
trajectory is IDENTICAL to one-shot), pulls the converged mask after each
chunk, compacts unconverged entities into a dense prefix (stable argsort on
the mask + one gather program), and re-dispatches survivors at the next
smaller power-of-two lane count. Compiled programs per (optimizer, bucket
shape) are bounded by the pow2 ladder and verified by ``solver_trace_counts``.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.parallel.mesh import fetch_global

from photon_ml_tpu.data.random_effect import RandomEffectDataset, ReBucket
from photon_ml_tpu.losses.objective import make_glm_objective
from photon_ml_tpu.losses.pointwise import loss_for_task
from photon_ml_tpu.models.random_effect import RandomEffectModel
from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.opt.config import GlmOptimizationConfiguration
from photon_ml_tpu.opt.solve import (
    solve,
    solve_chunk,
    solve_finalize,
    solve_init,
    solver_kind,
)
from photon_ml_tpu.opt.state import SolveResult
from photon_ml_tpu.opt.tracking import SolverStats
from photon_ml_tpu.telemetry import note_jit_trace, span
from photon_ml_tpu.types import ConvergenceReason, TaskType

_NOT_CONVERGED = ConvergenceReason.NOT_CONVERGED.value

# Python-side jit-cache-miss counter: each key is (program, optimizer kind)
# and its count only grows when XLA actually (re)traces that program — the
# increment sits inside the traced body, which never executes on cache hits.
# Tests use this to assert the pow2 ladder bounds compilation.
_TRACE_COUNTS: "collections.Counter[Tuple[str, str]]" = collections.Counter()


def solver_trace_counts() -> Dict[Tuple[str, str], int]:
    """Snapshot of the RE solver jit trace counters (testing/telemetry)."""
    return dict(_TRACE_COUNTS)


def _note_trace(program: str, kind: str) -> None:
    """Trace-time side effect shared by every RE program: the local
    counter tests assert on, plus the global telemetry jit.traces.*
    counter (telemetry.metrics.note_jit_trace)."""
    _TRACE_COUNTS[(program, kind)] += 1
    note_jit_trace(program, kind)


def _bucket_data(bucket: ReBucket) -> LabeledData:
    return LabeledData(
        features=DenseFeatures(matrix=bucket.X),
        labels=bucket.labels,
        offsets=bucket.offsets,
        weights=bucket.weights,
        norm=None,
    )


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


def _is_multi_device(x) -> bool:
    sharding = getattr(x, "sharding", None)
    if sharding is None:
        return False
    try:
        return len(sharding.device_set) > 1
    except Exception:  # noqa: BLE001 - sharding APIs vary across jax versions
        return False


class _RePrograms(NamedTuple):
    """Jitted programs for one (task, configuration, compute_variances)
    combination. jax.jit specializes each per input shape, so the compiled
    program count is (#pow2 widths) per bucket shape — never per round."""

    kind: str
    chunk_iters: int
    oneshot: Callable    # (w0, data, pv, l2, l1) -> (SolveResult, w_masked, var|None)
    init: Callable       # (w0, data, l2, l1) -> batched solver state
    chunk: Callable      # (state, data, l2) -> state advanced by <= K iters
    extract: Callable    # (state, data, pv, l2) -> (SolveResult, w_masked, var|None)
    compact: Callable    # (tree, idx) -> tree gathered along the entity axis


@functools.lru_cache(maxsize=None)
def _re_programs(
    task: TaskType,
    configuration: GlmOptimizationConfiguration,
    compute_variances: bool,
) -> _RePrograms:
    objective = make_glm_objective(loss_for_task(task))
    use_l1 = configuration.l1_weight > 0
    kind = solver_kind(configuration, None if use_l1 else 0.0)
    K = configuration.adaptive.chunk_iters

    def _mask_and_var(res: SolveResult, data, pv, l2):
        # padding columns have all-zero features; L2 keeps them at 0, but be
        # explicit so exported models never leak junk. Fused into the same
        # program as the solve/finalize so there is no separate dispatch.
        w = jnp.where(pv, res.w, 0.0)
        if compute_variances:
            diag = objective.hessian_diag(res.w, data, l2)
            var = jnp.where(pv, 1.0 / (diag + 1e-12), 0.0)
        else:
            var = None
        return w, var

    def oneshot_one(w0, data, pv, l2, l1):
        res = solve(
            objective, w0, data, configuration,
            l2_weight=l2, l1_weight=l1 if use_l1 else 0.0,
        )
        w, var = _mask_and_var(res, data, pv, l2)
        return res, w, var

    def init_one(w0, data, l2, l1):
        return solve_init(
            objective, w0, data, configuration,
            l2_weight=l2, l1_weight=l1 if use_l1 else 0.0,
        )

    def chunk_one(state, data, l2):
        return solve_chunk(
            objective, state, data, configuration, l2_weight=l2, num_iters=K
        )

    def extract_one(state, data, pv, l2):
        res = solve_finalize(state, configuration)
        w, var = _mask_and_var(res, data, pv, l2)
        return res, w, var

    def _oneshot(w0, data, pv, l2, l1):
        _note_trace("re_oneshot", kind)
        return jax.vmap(oneshot_one, in_axes=(0, 0, 0, None, None))(w0, data, pv, l2, l1)

    def _init(w0, data, l2, l1):
        _note_trace("re_init", kind)
        return jax.vmap(init_one, in_axes=(0, 0, None, None))(w0, data, l2, l1)

    def _chunk(state, data, l2):
        _note_trace("re_chunk", kind)
        return jax.vmap(chunk_one, in_axes=(0, 0, None))(state, data, l2)

    def _extract(state, data, pv, l2):
        _note_trace("re_extract", kind)
        return jax.vmap(extract_one, in_axes=(0, 0, 0, None))(state, data, pv, l2)

    def _compact(tree, idx):
        _note_trace("re_compact", kind)
        return jax.tree.map(lambda a: a[idx], tree)

    # Donate the carried solver state so each round updates in place instead
    # of copying the (w, memory, history) buffers; CPU ignores donation (and
    # warns), so only request it on accelerators.
    donate = () if jax.default_backend() == "cpu" else (0,)
    return _RePrograms(
        kind=kind,
        chunk_iters=K,
        oneshot=jax.jit(_oneshot),
        init=jax.jit(_init),
        chunk=jax.jit(_chunk, donate_argnums=donate),
        extract=jax.jit(_extract),
        compact=jax.jit(_compact),
    )


def _scatter_extract(progs, state, data, pv, l2, live, buffers, num_entities):
    """Finalize the current lanes on device, then scatter every result leaf
    into host buffers at the original entity rows (``live``). Re-scattering a
    frozen lane later is idempotent: done lanes never advance."""
    res, w_m, var = jax.device_get(progs.extract(state, data, pv, l2))
    leaves = {"__w_masked": np.asarray(w_m)}
    if var is not None:
        leaves["__var"] = np.asarray(var)
    for f in dataclasses.fields(SolveResult):
        v = getattr(res, f.name)
        if v is not None:
            leaves[f.name] = np.asarray(v)
    for name, arr in leaves.items():
        if name not in buffers:
            buffers[name] = np.zeros((num_entities,) + arr.shape[1:], dtype=arr.dtype)
        buffers[name][live] = arr


def _solve_bucket_adaptive(
    progs: _RePrograms,
    bucket: ReBucket,
    w0: jax.Array,
    l2: jax.Array,
    l1: jax.Array,
    max_iterations: int,
    min_lanes: int,
    bucket_index: int,
):
    """Chunked rounds + lane compaction for one bucket. Returns
    (SolveResult over the ORIGINAL entity order, masked w, variances|None,
    SolverStats)."""
    E = bucket.num_entities
    K = progs.chunk_iters
    data = _bucket_data(bucket)
    pv = bucket.proj_valid
    retrace0 = _TRACE_COUNTS[("re_chunk", progs.kind)]

    state = progs.init(w0, data, l2, l1)
    live = np.arange(E)             # lane -> original entity row
    width = E
    its_before = np.zeros(E, dtype=np.int64)
    executed = 0
    widths: List[int] = []
    buffers: Dict[str, np.ndarray] = {}
    # ceil(max_iter/K) chunks always finish every lane; +1 slack for the
    # converged-at-init case where the first chunk advances nothing.
    max_rounds = -(-max_iterations // K) + 1

    for round_index in range(max_rounds):
        with span(
            "re/adaptive_round",
            bucket=bucket_index,
            round=round_index,
            width=width,
        ):
            state = progs.chunk(state, data, l2)
            widths.append(width)
            # host-side bookkeeping below overlaps the async device dispatch
            its_after = np.asarray(jax.device_get(state.it)).astype(np.int64)
            reasons = np.asarray(jax.device_get(state.reason))
            executed += width * int(np.max(its_after - its_before)) if width else 0
            done = (reasons != _NOT_CONVERGED) | (its_after >= max_iterations)
            n_live = int(np.sum(~done))
            if n_live == 0:
                _scatter_extract(progs, state, data, pv, l2, live, buffers, E)
                break
            new_width = _next_pow2(max(n_live, min_lanes))
            if new_width < width:
                # freeze current results, then compact survivors (+ filler done
                # lanes up to the pow2 width) into a dense prefix on device
                _scatter_extract(progs, state, data, pv, l2, live, buffers, E)
                keep = np.argsort(done, kind="stable")[:new_width]
                idx = jnp.asarray(keep, dtype=jnp.int32)
                state, data, pv = progs.compact((state, data, pv), idx)
                live = live[keep]
                its_before = its_after[keep]
                width = new_width
            else:
                its_before = its_after
    else:
        _scatter_extract(progs, state, data, pv, l2, live, buffers, E)

    sr_kwargs = {
        f.name: (jnp.asarray(buffers[f.name]) if f.name in buffers else None)
        for f in dataclasses.fields(SolveResult)
    }
    res_full = SolveResult(**sr_kwargs)
    w_full = jnp.asarray(buffers["__w_masked"])
    var_full = jnp.asarray(buffers["__var"]) if "__var" in buffers else None

    its = buffers["iterations"].astype(np.int64)
    reasons_full = buffers["reason"]
    max_its = int(its.max()) if its.size else 0
    stats = SolverStats(
        bucket=bucket_index,
        optimizer=progs.kind,
        num_entities=E,
        rounds=len(widths),
        chunk_iters=K,
        dispatch_widths=tuple(widths),
        iterations_p50=float(np.percentile(its, 50)) if its.size else 0.0,
        iterations_p99=float(np.percentile(its, 99)) if its.size else 0.0,
        iterations_max=max_its,
        sum_entity_iterations=int(its.sum()),
        executed_lane_iterations=int(executed),
        lockstep_lane_iterations=E * max_its,
        converged=int(np.sum(reasons_full != _NOT_CONVERGED)),
        chunk_retraces=_TRACE_COUNTS[("re_chunk", progs.kind)] - retrace0,
    )
    return res_full, w_full, var_full, stats


def _solve_bucket_oneshot(
    progs: _RePrograms,
    bucket: ReBucket,
    w0: jax.Array,
    l2: jax.Array,
    l1: jax.Array,
    bucket_index: int,
):
    """Classic lockstep dispatch (adaptive disabled / sharded / tiny bucket);
    masking and variances run inside the same jit program."""
    data = _bucket_data(bucket)
    res, w, var = progs.oneshot(w0, data, bucket.proj_valid, l2, l1)
    E = bucket.num_entities
    its = np.asarray(fetch_global(res.iterations)).astype(np.int64)
    reasons = np.asarray(fetch_global(res.reason))
    max_its = int(its.max()) if its.size else 0
    stats = SolverStats(
        bucket=bucket_index,
        optimizer=progs.kind,
        num_entities=E,
        rounds=1,
        chunk_iters=progs.chunk_iters,
        dispatch_widths=(E,),
        iterations_p50=float(np.percentile(its, 50)) if its.size else 0.0,
        iterations_p99=float(np.percentile(its, 99)) if its.size else 0.0,
        iterations_max=max_its,
        sum_entity_iterations=int(its.sum()),
        executed_lane_iterations=E * max_its,
        lockstep_lane_iterations=E * max_its,
        converged=int(np.sum(reasons != _NOT_CONVERGED)),
        chunk_retraces=0,
    )
    return res, w, var, stats


def train_random_effects(
    dataset: RandomEffectDataset,
    task: TaskType,
    configuration: GlmOptimizationConfiguration,
    initial_model: Optional[RandomEffectModel] = None,
    compute_variances: bool = False,
    stats_out: Optional[List[SolverStats]] = None,
    overlap_buckets: int = 0,
) -> tuple[RandomEffectModel, List[SolveResult]]:
    """Solve one GLM per entity (all buckets). Returns the model and the
    per-bucket vmap'd SolveResults (per-entity convergence telemetry — the
    RandomEffectOptimizationTracker equivalent).

    When ``configuration.adaptive.enabled`` each bucket runs through the
    convergence-adaptive driver (chunked rounds + pow2 lane compaction);
    sharded buckets and buckets at/below ``adaptive.min_lanes`` fall back to
    the one-shot lockstep dispatch, whose results are identical. If
    ``stats_out`` is given, one :class:`SolverStats` per bucket is appended.

    ``overlap_buckets >= 2`` overlaps that many bucket solves on worker
    threads (the async CD schedule's RE leg): while one bucket's adaptive
    driver blocks on its converged-mask pull or runs host-side lane
    compaction bookkeeping, another bucket's chunk dispatches keep the
    device busy. Bucket solves are mutually independent and the programs
    come from the same pow2 registry, so per-bucket results are
    bitwise-identical to the sequential path and no new retraces are
    introduced. Sharded (multi-device) buckets force the sequential path —
    collectives must be issued in one global order.
    """
    progs = _re_programs(task, configuration, compute_variances)
    adaptive = configuration.adaptive
    max_iter = configuration.optimizer_config.max_iterations

    l2 = jnp.float32(configuration.l2_weight)
    l1 = jnp.float32(configuration.l1_weight)

    def _warm_start(b, bucket):
        if initial_model is not None:
            return _fit_entity_axis(
                initial_model.coefficients[b], bucket.num_entities
            )
        return jnp.zeros(
            (bucket.num_entities, bucket.local_dim), dtype=jnp.float32
        )

    def _solve_one(b, bucket, w0, use_adaptive):
        if use_adaptive:
            return _solve_bucket_adaptive(
                progs, bucket, w0, l2, l1, max_iter, adaptive.min_lanes, b
            )
        return _solve_bucket_oneshot(progs, bucket, w0, l2, l1, b)

    use_adaptive_by_bucket = [
        adaptive.enabled
        and bucket.num_entities > adaptive.min_lanes
        and not _is_multi_device(bucket.X)
        for bucket in dataset.buckets
    ]
    overlap = (
        int(overlap_buckets) >= 2
        and len(dataset.buckets) > 1
        and not any(_is_multi_device(b.X) for b in dataset.buckets)
    )

    coeffs, variances, results = [], [], []
    if overlap:
        # lazy import: algorithm.coordinate imports this module at its top,
        # so a module-level import back into algorithm.* could deadlock the
        # partially-initialized package on first touch
        from photon_ml_tpu.algorithm.schedule import ScheduleExecutor

        solved = []
        with ScheduleExecutor(
            max_in_flight=min(int(overlap_buckets), len(dataset.buckets)),
            name="re-buckets",
        ) as executor:
            for b, bucket in enumerate(dataset.buckets):
                # warm-start layout on the driver; only the solve overlaps
                w0 = _warm_start(b, bucket)
                solved.append(
                    executor.submit(
                        b,
                        functools.partial(
                            _solve_one, b, bucket, w0, use_adaptive_by_bucket[b]
                        ),
                        span_name="re/solve_bucket",
                        bucket=b,
                        mode=(
                            "adaptive" if use_adaptive_by_bucket[b] else "oneshot"
                        ),
                        entities=bucket.num_entities,
                        optimizer=progs.kind,
                        overlap=True,
                    )
                )
            bucket_outs = [work.result() for work in solved]
        for res, w, var, stats in bucket_outs:
            coeffs.append(w)
            variances.append(var)
            results.append(res)
            if stats_out is not None:
                stats_out.append(stats)
    else:
        for b, bucket in enumerate(dataset.buckets):
            w0 = _warm_start(b, bucket)
            use_adaptive = use_adaptive_by_bucket[b]
            with span(
                "re/solve_bucket",
                device_sync=True,
                bucket=b,
                mode="adaptive" if use_adaptive else "oneshot",
                entities=bucket.num_entities,
                optimizer=progs.kind,
            ):
                res, w, var, stats = _solve_one(b, bucket, w0, use_adaptive)
            coeffs.append(w)
            variances.append(var)
            results.append(res)
            if stats_out is not None:
                stats_out.append(stats)

    model = RandomEffectModel(
        random_effect_type=dataset.config.random_effect_type,
        task=task,
        coefficients=coeffs,
        variances=variances,
        proj_indices=[b.proj_indices for b in dataset.buckets],
        proj_valid=[b.proj_valid for b in dataset.buckets],
        entity_ids=dataset.entity_ids,
        entity_to_loc=dataset.entity_to_loc,
        global_dim=dataset.global_dim,
        projector_type=dataset.config.projector,
        projection_seed=dataset.config.seed,
    )
    return model, results


def align_warm_start(
    model: RandomEffectModel, dataset: RandomEffectDataset
) -> RandomEffectModel:
    """Re-layout a trained RE model onto a DIFFERENT dataset's entity/bucket
    layout so it can warm-start ``train_random_effects`` there.

    ``train_random_effects`` consumes ``initial_model.coefficients[b]``
    positionally, which is only correct when the model was trained on the
    same dataset. The nearline path re-solves against a dataset built from a
    fresh events batch — different entities, different bucket packing,
    different local feature sets — so the old coefficients must be joined by
    entity id and re-scattered through the new dataset's projection indices.
    Entities the old model never saw start from zero (a fresh row).
    """
    from photon_ml_tpu.projector import ProjectorType

    if dataset.config.projector is ProjectorType.RANDOM:
        raise ValueError(
            "align_warm_start cannot re-scatter into a RANDOM-projected "
            "dataset: projected local spaces are seed/dim-dependent and "
            "global-space coefficients do not map back exactly"
        )
    coeffs = []
    for b, bucket in enumerate(dataset.buckets):
        idx_b = np.asarray(fetch_global(bucket.proj_indices))
        val_b = np.asarray(fetch_global(bucket.proj_valid))
        w = np.zeros(idx_b.shape, dtype=np.float32)
        for e, eid in enumerate(dataset.entity_ids[b]):
            old = model.coefficients_for(eid)
            if not old:
                continue
            row_idx, row_ok = idx_b[e], val_b[e]
            for j in range(len(row_idx)):
                if row_ok[j]:
                    w[e, j] = old.get(int(row_idx[j]), 0.0)
        coeffs.append(jnp.asarray(w))
    return RandomEffectModel(
        random_effect_type=dataset.config.random_effect_type,
        task=model.task,
        coefficients=coeffs,
        variances=[None] * len(coeffs),
        proj_indices=[b.proj_indices for b in dataset.buckets],
        proj_valid=[b.proj_valid for b in dataset.buckets],
        entity_ids=dataset.entity_ids,
        entity_to_loc=dataset.entity_to_loc,
        global_dim=dataset.global_dim,
        projector_type=dataset.config.projector,
        projection_seed=dataset.config.seed,
    )


@jax.jit
def _score_bucket(w: jax.Array, bucket: ReBucket) -> jax.Array:
    return jnp.einsum("esd,ed->es", bucket.X, w)


@jax.jit
def _score_passive(w: jax.Array, X: jax.Array, entity_index: jax.Array) -> jax.Array:
    return jnp.einsum("pd,pd->p", X, w[entity_index])


def _fit_entity_axis(w: jax.Array, num_entities: int) -> jax.Array:
    """Adapt a per-bucket coefficient block to the dataset's entity axis.

    Mesh padding grows the entity axis with trivial lanes; a model trained
    on a padded dataset carries the extra zero rows, a model from an
    unpadded (or differently-padded) run does not. Real entities always
    occupy the leading rows in build order, so pad with zeros / trim to
    align (reference analog: RandomEffectModel joins by REId and tolerates
    missing entities, RandomEffectModel.scala:~150).
    """
    e = w.shape[0]
    if e == num_entities:
        return w
    if e < num_entities:
        return jnp.pad(w, [(0, num_entities - e)] + [(0, 0)] * (w.ndim - 1))
    return w[:num_entities]


@jax.jit
def _gathered_scores(coeffs, buckets, passives, row_gather):
    """All buckets' active + passive scores assembled into the row-order
    plane with one gather through the precomputed row -> source-slot index
    (``RandomEffectDataset.row_gather``). XLA scatter-add serializes on CPU
    (and degrades on TPU); every row has exactly one source slot, so the
    gather is its fast dual and reproduces the scatter bitwise — padding
    slots and inactive lanes are simply never referenced."""
    parts = []
    for w, bucket, p in zip(coeffs, buckets, passives):
        w_b = _fit_entity_axis(w, bucket.num_entities)
        parts.append(jnp.einsum("esd,ed->es", bucket.X, w_b).reshape(-1))
        if p is not None:
            parts.append(jnp.einsum("pd,pd->p", p.X, w[p.entity_index]))
    flat = jnp.concatenate(parts + [jnp.zeros(1, dtype=jnp.float32)])
    return flat[row_gather]


def score_random_effects_device(
    model: RandomEffectModel, dataset: RandomEffectDataset
) -> jax.Array:
    """Device-plane :func:`score_random_effects`: the same active + passive
    scores, assembled into a device-resident [num_rows] plane — no host
    round trip. Numerically identical to the host path (each row has
    exactly one source bucket/slot)."""
    return _gathered_scores(
        list(model.coefficients),
        dataset.buckets,
        dataset.passive,
        dataset.gather_index(),
    )


def score_random_effects(
    model: RandomEffectModel, dataset: RandomEffectDataset
) -> np.ndarray:
    """Raw per-row scores x . w_entity aligned with the ORIGINAL row order
    (active + passive rows; reference RandomEffectCoordinate.score
    :157-187 = active join + passive broadcast scoring). Offsets are NOT
    included — score algebra composes them at the coordinate level."""
    out = np.zeros(dataset.num_rows, dtype=np.float32)
    for b, bucket in enumerate(dataset.buckets):
        w_b = _fit_entity_axis(model.coefficients[b], bucket.num_entities)
        z = fetch_global(_score_bucket(w_b, bucket))
        wt = fetch_global(bucket.weights)
        pos = fetch_global(bucket.sample_pos)
        mask = wt > 0
        out[pos[mask]] = z[mask]
        p = dataset.passive[b]
        if p is not None:
            zp = fetch_global(
                _score_passive(model.coefficients[b], p.X, p.entity_index)
            )
            out[np.asarray(p.sample_pos)] = zp
    return out
