"""Random-effect training and scoring: vmap'd local solves.

Reference parity: algorithm/RandomEffectCoordinate.scala:39 — updateModel
(:103-143) runs ``activeData.join(problems).join(models).mapValues{ local
Breeze solve }``, i.e. millions of independent optimizations inside executor
closures; score (:157-187) covers active + passive data. Here each dataset
bucket becomes ONE jit-compiled program: ``vmap(solver)`` over the entity
axis — every entity's full L-BFGS/TRON/OWL-QN while_loop runs in lockstep
lanes on the MXU with zero cross-entity communication. Sharding the entity
axis over a mesh scales this to a pod with no collectives in the solve.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.parallel.mesh import fetch_global

from photon_ml_tpu.data.random_effect import RandomEffectDataset, ReBucket
from photon_ml_tpu.losses.objective import make_glm_objective
from photon_ml_tpu.losses.pointwise import loss_for_task
from photon_ml_tpu.models.random_effect import RandomEffectModel
from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.opt.config import GlmOptimizationConfiguration
from photon_ml_tpu.opt.solve import solve
from photon_ml_tpu.opt.state import SolveResult
from photon_ml_tpu.types import TaskType


def _bucket_data(bucket: ReBucket) -> LabeledData:
    return LabeledData(
        features=DenseFeatures(matrix=bucket.X),
        labels=bucket.labels,
        offsets=bucket.offsets,
        weights=bucket.weights,
        norm=None,
    )


def train_random_effects(
    dataset: RandomEffectDataset,
    task: TaskType,
    configuration: GlmOptimizationConfiguration,
    initial_model: Optional[RandomEffectModel] = None,
    compute_variances: bool = False,
) -> tuple[RandomEffectModel, List[SolveResult]]:
    """Solve one GLM per entity (all buckets). Returns the model and the
    per-bucket vmap'd SolveResults (per-entity convergence telemetry — the
    RandomEffectOptimizationTracker equivalent)."""
    objective = make_glm_objective(loss_for_task(task))
    use_l1 = configuration.l1_weight > 0

    def solve_one(w0, data, l2, l1):
        return solve(
            objective, w0, data, configuration,
            l2_weight=l2, l1_weight=l1 if use_l1 else 0.0,
        )

    batched = jax.jit(jax.vmap(solve_one, in_axes=(0, 0, None, None)))
    hess_diag = (
        jax.jit(jax.vmap(objective.hessian_diag, in_axes=(0, 0, None)))
        if compute_variances
        else None
    )

    l2 = jnp.float32(configuration.l2_weight)
    l1 = jnp.float32(configuration.l1_weight)
    coeffs, variances, results = [], [], []
    for b, bucket in enumerate(dataset.buckets):
        data = _bucket_data(bucket)
        if initial_model is not None:
            w0 = _fit_entity_axis(
                initial_model.coefficients[b], bucket.num_entities
            )
        else:
            w0 = jnp.zeros((bucket.num_entities, bucket.local_dim), dtype=jnp.float32)
        res = batched(w0, data, l2, l1)
        # padding columns have all-zero features; L2 keeps them at 0, but be
        # explicit so exported models never leak junk
        w = jnp.where(bucket.proj_valid, res.w, 0.0)
        coeffs.append(w)
        if compute_variances:
            diag = hess_diag(res.w, data, l2)
            variances.append(jnp.where(bucket.proj_valid, 1.0 / (diag + 1e-12), 0.0))
        else:
            variances.append(None)
        results.append(res)

    model = RandomEffectModel(
        random_effect_type=dataset.config.random_effect_type,
        task=task,
        coefficients=coeffs,
        variances=variances,
        proj_indices=[b.proj_indices for b in dataset.buckets],
        proj_valid=[b.proj_valid for b in dataset.buckets],
        entity_ids=dataset.entity_ids,
        entity_to_loc=dataset.entity_to_loc,
        global_dim=dataset.global_dim,
        projector_type=dataset.config.projector,
        projection_seed=dataset.config.seed,
    )
    return model, results


def align_warm_start(
    model: RandomEffectModel, dataset: RandomEffectDataset
) -> RandomEffectModel:
    """Re-layout a trained RE model onto a DIFFERENT dataset's entity/bucket
    layout so it can warm-start ``train_random_effects`` there.

    ``train_random_effects`` consumes ``initial_model.coefficients[b]``
    positionally, which is only correct when the model was trained on the
    same dataset. The nearline path re-solves against a dataset built from a
    fresh events batch — different entities, different bucket packing,
    different local feature sets — so the old coefficients must be joined by
    entity id and re-scattered through the new dataset's projection indices.
    Entities the old model never saw start from zero (a fresh row).
    """
    from photon_ml_tpu.projector import ProjectorType

    if dataset.config.projector is ProjectorType.RANDOM:
        raise ValueError(
            "align_warm_start cannot re-scatter into a RANDOM-projected "
            "dataset: projected local spaces are seed/dim-dependent and "
            "global-space coefficients do not map back exactly"
        )
    coeffs = []
    for b, bucket in enumerate(dataset.buckets):
        idx_b = np.asarray(fetch_global(bucket.proj_indices))
        val_b = np.asarray(fetch_global(bucket.proj_valid))
        w = np.zeros(idx_b.shape, dtype=np.float32)
        for e, eid in enumerate(dataset.entity_ids[b]):
            old = model.coefficients_for(eid)
            if not old:
                continue
            row_idx, row_ok = idx_b[e], val_b[e]
            for j in range(len(row_idx)):
                if row_ok[j]:
                    w[e, j] = old.get(int(row_idx[j]), 0.0)
        coeffs.append(jnp.asarray(w))
    return RandomEffectModel(
        random_effect_type=dataset.config.random_effect_type,
        task=model.task,
        coefficients=coeffs,
        variances=[None] * len(coeffs),
        proj_indices=[b.proj_indices for b in dataset.buckets],
        proj_valid=[b.proj_valid for b in dataset.buckets],
        entity_ids=dataset.entity_ids,
        entity_to_loc=dataset.entity_to_loc,
        global_dim=dataset.global_dim,
        projector_type=dataset.config.projector,
        projection_seed=dataset.config.seed,
    )


@jax.jit
def _score_bucket(w: jax.Array, bucket: ReBucket) -> jax.Array:
    return jnp.einsum("esd,ed->es", bucket.X, w)


@jax.jit
def _score_passive(w: jax.Array, X: jax.Array, entity_index: jax.Array) -> jax.Array:
    return jnp.einsum("pd,pd->p", X, w[entity_index])


def _fit_entity_axis(w: jax.Array, num_entities: int) -> jax.Array:
    """Adapt a per-bucket coefficient block to the dataset's entity axis.

    Mesh padding grows the entity axis with trivial lanes; a model trained
    on a padded dataset carries the extra zero rows, a model from an
    unpadded (or differently-padded) run does not. Real entities always
    occupy the leading rows in build order, so pad with zeros / trim to
    align (reference analog: RandomEffectModel joins by REId and tolerates
    missing entities, RandomEffectModel.scala:~150).
    """
    e = w.shape[0]
    if e == num_entities:
        return w
    if e < num_entities:
        return jnp.pad(w, [(0, num_entities - e)] + [(0, 0)] * (w.ndim - 1))
    return w[:num_entities]


def score_random_effects(
    model: RandomEffectModel, dataset: RandomEffectDataset
) -> np.ndarray:
    """Raw per-row scores x . w_entity aligned with the ORIGINAL row order
    (active + passive rows; reference RandomEffectCoordinate.score
    :157-187 = active join + passive broadcast scoring). Offsets are NOT
    included — score algebra composes them at the coordinate level."""
    out = np.zeros(dataset.num_rows, dtype=np.float32)
    for b, bucket in enumerate(dataset.buckets):
        w_b = _fit_entity_axis(model.coefficients[b], bucket.num_entities)
        z = fetch_global(_score_bucket(w_b, bucket))
        wt = fetch_global(bucket.weights)
        pos = fetch_global(bucket.sample_pos)
        mask = wt > 0
        out[pos[mask]] = z[mask]
        p = dataset.passive[b]
        if p is not None:
            zp = fetch_global(
                _score_passive(model.coefficients[b], p.X, p.entity_index)
            )
            out[np.asarray(p.sample_pos)] = zp
    return out
