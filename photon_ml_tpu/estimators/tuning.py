"""Hyperparameter tuning adapter for GameEstimator.

Reference parity: estimators/GameEstimatorEvaluationFunction.scala:34 — packs
per-coordinate regularization weights into a vector (sorted coordinate order;
factored coordinates contribute two entries: RE weight then latent-matrix
weight), unpacks a candidate vector into a new optimization configuration,
refits, and reports the first validation evaluator's value; and
cli/game/training/Driver.scala:318-348 (runHyperparameterTuning wiring).

Deviation: the vector holds log10(λ) rather than raw λ — λ is scale-free, so
searching in log space is the standard improvement (SURVEY.md §5 config note).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.estimators.game import (
    CoordinateConfiguration,
    FactoredRandomEffectCoordinateConfiguration,
    GameEstimator,
    GameFit,
)
from photon_ml_tpu.hyperparameter.search import (
    GaussianProcessSearch,
    RandomSearch,
)


@dataclasses.dataclass
class TuningTrial:
    """One tuning evaluation: the fit, the hyperparameter vector that
    produced it, and the validation metric (the reference's GameResult)."""

    fit: GameFit
    hyperparameters: np.ndarray
    value: float


class GameEstimatorEvaluationFunction:
    def __init__(
        self,
        estimator: GameEstimator,
        data,
        validation_data,
        min_weight: float = 1e-8,
        warm_start: bool = True,
        initial_warm_models: Optional[Dict[str, object]] = None,
    ) -> None:
        self.estimator = estimator
        self.data = data
        self.validation_data = validation_data
        self.min_weight = min_weight
        # Each trial warm-starts from the previous trial's models (reference
        # warmStartModels, cli/game/training/Driver.scala:484-501);
        # ``initial_warm_models`` seeds the first trial.
        self.warm_start = warm_start
        self._warm_models: Optional[Dict[str, object]] = (
            dict(initial_warm_models) if initial_warm_models else None
        )
        # Sorted coordinate ids for a deterministic vector layout
        # (the reference uses SortedMap for the same reason).
        self._order = sorted(estimator.coordinate_configs)

    @property
    def num_params(self) -> int:
        return sum(
            2
            if isinstance(
                self.estimator.coordinate_configs[cid],
                FactoredRandomEffectCoordinateConfiguration,
            )
            else 1
            for cid in self._order
        )

    def configuration_to_vector(
        self, configs: Dict[str, CoordinateConfiguration]
    ) -> np.ndarray:
        vals: List[float] = []
        for cid in self._order:
            cfg = configs[cid]
            vals.append(cfg.optimizer.regularization_weight)
            if isinstance(cfg, FactoredRandomEffectCoordinateConfiguration):
                matrix = cfg.matrix_optimizer or cfg.optimizer
                vals.append(matrix.regularization_weight)
        return np.log10(np.maximum(np.asarray(vals), self.min_weight))

    def vector_to_configuration(
        self, hyperparameters: np.ndarray
    ) -> Dict[str, CoordinateConfiguration]:
        weights = [10.0 ** float(v) for v in np.asarray(hyperparameters)]
        if len(weights) != self.num_params:
            raise ValueError(
                f"expected {self.num_params} hyperparameters, got {len(weights)}"
            )
        it = iter(weights)
        out: Dict[str, CoordinateConfiguration] = {}
        for cid in self._order:
            cfg = self.estimator.coordinate_configs[cid]
            new_opt = dataclasses.replace(
                cfg.optimizer, regularization_weight=next(it)
            )
            if isinstance(cfg, FactoredRandomEffectCoordinateConfiguration):
                matrix = cfg.matrix_optimizer or cfg.optimizer
                new_matrix = dataclasses.replace(
                    matrix, regularization_weight=next(it)
                )
                out[cid] = dataclasses.replace(
                    cfg, optimizer=new_opt, matrix_optimizer=new_matrix
                )
            else:
                out[cid] = dataclasses.replace(cfg, optimizer=new_opt)
        return out

    def __call__(self, hyperparameters: np.ndarray) -> Tuple[float, TuningTrial]:
        configs = self.vector_to_configuration(hyperparameters)
        estimator = GameEstimator(
            task=self.estimator.task,
            coordinates=configs,
            update_order=self.estimator.update_order,
            num_outer_iterations=self.estimator.num_outer_iterations,
            evaluator=self.estimator.evaluator,
            extra_evaluators=self.estimator.extra_evaluators,
            normalization=self.estimator.normalization,
            intercept_indices=self.estimator.intercept_indices,
            parallel=self.estimator.parallel,
            compute_variance=self.estimator.compute_variance,
        )
        fit = estimator.fit(
            self.data,
            validation_data=self.validation_data,
            initial_models=self._warm_models if self.warm_start else None,
        )
        if self.warm_start:
            self._warm_models = dict(fit.model.models)
        if fit.validation_metric is None:
            raise ValueError("tuning requires validation data")
        value = float(fit.validation_metric)
        trial = TuningTrial(
            fit=fit,
            hyperparameters=np.asarray(hyperparameters, dtype=float),
            value=value,
        )
        return value, trial

    def vectorize_params(self, result: TuningTrial) -> np.ndarray:
        return result.hyperparameters

    def get_evaluation_value(self, result: TuningTrial) -> float:
        return result.value

    def trial_from_fit(self, fit: GameFit) -> TuningTrial:
        """Seed observation from a model trained before tuning started
        (the reference passes prior GameResults into ``find``)."""
        if fit.validation_metric is None:
            raise ValueError("seed fit has no validation metric")
        return TuningTrial(
            fit=fit,
            hyperparameters=self.configuration_to_vector(
                self.estimator.coordinate_configs
            ),
            value=float(fit.validation_metric),
        )


def run_hyperparameter_tuning(
    estimator: GameEstimator,
    data,
    validation_data,
    mode: str = "BAYESIAN",
    num_iterations: int = 10,
    log10_range: Tuple[float, float] = (-4.0, 4.0),
    prior_fits: Sequence[GameFit] = (),
    seed: int = 0,
    warm_start: bool = True,
) -> List[TuningTrial]:
    """Driver.runHyperparameterTuning equivalent. Returns all trials; callers
    select the best with ``estimator.evaluator.better_than``."""
    mode = mode.upper()
    if mode == "NONE" or num_iterations <= 0:
        return []
    fn = GameEstimatorEvaluationFunction(
        estimator, data, validation_data,
        warm_start=warm_start,
        initial_warm_models=(
            dict(prior_fits[-1].model.models) if prior_fits and warm_start
            else None
        ),
    )
    ranges = [log10_range] * fn.num_params
    if mode == "BAYESIAN":
        searcher: RandomSearch[TuningTrial] = GaussianProcessSearch(
            ranges,
            fn,
            larger_is_better=estimator.evaluator.larger_is_better,
            seed=seed,
        )
    elif mode == "RANDOM":
        searcher = RandomSearch(ranges, fn, seed=seed)
    else:
        raise ValueError(f"unknown tuning mode: {mode}")
    observations = [fn.trial_from_fit(f) for f in prior_fits]
    return searcher.find(num_iterations, observations)
