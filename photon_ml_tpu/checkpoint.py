"""Mid-training checkpoint/resume for GAME coordinate descent.

The reference has NO mid-training checkpointing — recovery is Spark lineage
recompute plus full model save/load between jobs (SURVEY.md §5). This module
improves on that: after every outer CD iteration the full training state
(per-coordinate models in their native padded-block layout, best-so-far
models, histories) is written atomically (tmp dir + rename), so a preempted
TPU job resumes exactly where it stopped — the TPU-era replacement for
lineage recovery.

Models are stored as .npz arrays + JSON sidecars (bucket structure included),
NOT the Avro export format: a resume must restore the exact padded layouts
the coordinates were built with. A layout fingerprint guards against
resuming with different data or configs.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.parallel.mesh import fetch_global

from photon_ml_tpu.resilience.faultpoints import fault_point, register_fault_site

from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.models.random_effect import RandomEffectModel
from photon_ml_tpu.projector import ProjectorType
from photon_ml_tpu.types import TaskType

STATE_FILE = "training-state.json"
_FORMAT_VERSION = 1
_TMP_PREFIX = ".ckpt-tmp-"
_OLD_PREFIX = ".ckpt-old-"

FAULT_PUBLISH = register_fault_site(
    "train.checkpoint.publish",
    "between the checkpoint tmp-dir fsync and the atomic rename — a fault "
    "here must leave the previous checkpoint loadable",
)


# ------------------------------------------------------------- serialization

def _save_glm(d: Optional[str], m: GeneralizedLinearModel) -> dict:
    arrays = {"means": fetch_global(m.coefficients.means)}
    if m.coefficients.variances is not None:
        arrays["variances"] = fetch_global(m.coefficients.variances)
    if d is not None:
        np.savez(os.path.join(d, "glm.npz"), **arrays)
    return {"kind": "glm", "task": m.task.name}


def _load_glm(d: str, meta: dict) -> GeneralizedLinearModel:
    z = np.load(os.path.join(d, "glm.npz"))
    return GeneralizedLinearModel(
        coefficients=Coefficients(
            means=jnp.asarray(z["means"]),
            variances=jnp.asarray(z["variances"]) if "variances" in z else None,
        ),
        task=TaskType[meta["task"]],
    )


def _save_re(d: Optional[str], m: RandomEffectModel) -> dict:
    arrays = {}
    for b in range(len(m.coefficients)):
        arrays[f"coef_{b}"] = fetch_global(m.coefficients[b])
        arrays[f"idx_{b}"] = fetch_global(m.proj_indices[b])
        arrays[f"valid_{b}"] = fetch_global(m.proj_valid[b])
        if m.variances[b] is not None:
            arrays[f"var_{b}"] = fetch_global(m.variances[b])
    if d is not None:
        np.savez(os.path.join(d, "re.npz"), **arrays)
    return {
        "kind": "random_effect",
        "task": m.task.name,
        "random_effect_type": m.random_effect_type,
        "entity_ids": m.entity_ids,
        "global_dim": m.global_dim,
        "projector_type": m.projector_type.name,
        "projection_seed": m.projection_seed,
        "num_buckets": len(m.coefficients),
    }


def _load_re(d: str, meta: dict) -> RandomEffectModel:
    z = np.load(os.path.join(d, "re.npz"))
    nb = meta["num_buckets"]
    entity_ids: List[List[str]] = [list(ids) for ids in meta["entity_ids"]]
    return RandomEffectModel(
        random_effect_type=meta["random_effect_type"],
        task=TaskType[meta["task"]],
        coefficients=[jnp.asarray(z[f"coef_{b}"]) for b in range(nb)],
        variances=[
            jnp.asarray(z[f"var_{b}"]) if f"var_{b}" in z else None
            for b in range(nb)
        ],
        proj_indices=[jnp.asarray(z[f"idx_{b}"]) for b in range(nb)],
        proj_valid=[jnp.asarray(z[f"valid_{b}"]) for b in range(nb)],
        entity_ids=entity_ids,
        entity_to_loc={
            eid: (b, e)
            for b, ids in enumerate(entity_ids)
            for e, eid in enumerate(ids)
        },
        global_dim=meta["global_dim"],
        projector_type=ProjectorType[meta["projector_type"]],
        projection_seed=meta.get("projection_seed", 0),
    )


def _save_factored(d: Optional[str], m) -> dict:
    latent_dir = None
    if d is not None:
        latent_dir = os.path.join(d, "latent")
        os.makedirs(latent_dir, exist_ok=True)
    latent_meta = _save_re(latent_dir, m.latent)
    B = fetch_global(m.projection_matrix)
    if d is not None:
        np.savez(os.path.join(d, "projection.npz"), projection_matrix=B)
    return {
        "kind": "factored_random_effect",
        "task": m.task.name,
        "random_effect_type": m.random_effect_type,
        "latent": latent_meta,
    }


def _load_factored(d: str, meta: dict):
    from photon_ml_tpu.algorithm.factored_random_effect import (
        FactoredRandomEffectModel,
    )

    latent = _load_re(os.path.join(d, "latent"), meta["latent"])
    z = np.load(os.path.join(d, "projection.npz"))
    return FactoredRandomEffectModel(
        random_effect_type=meta["random_effect_type"],
        task=TaskType[meta["task"]],
        latent=latent,
        projection_matrix=jnp.asarray(z["projection_matrix"]),
    )


def _save_submodel(d: Optional[str], model) -> dict:
    from photon_ml_tpu.algorithm.factored_random_effect import (
        FactoredRandomEffectModel,
    )

    if d is not None:
        os.makedirs(d, exist_ok=True)
    if isinstance(model, GeneralizedLinearModel):
        return _save_glm(d, model)
    if isinstance(model, RandomEffectModel):
        return _save_re(d, model)
    if isinstance(model, FactoredRandomEffectModel):
        return _save_factored(d, model)
    raise TypeError(f"cannot checkpoint sub-model type {type(model)}")


def _load_submodel(d: str, meta: dict):
    kind = meta["kind"]
    if kind == "glm":
        return _load_glm(d, meta)
    if kind == "random_effect":
        return _load_re(d, meta)
    if kind == "factored_random_effect":
        return _load_factored(d, meta)
    raise ValueError(f"unknown checkpoint sub-model kind: {kind}")


def model_fingerprint(models: Dict[str, object]) -> Dict[str, list]:
    """Shape signature per coordinate — resume sanity check (bucket counts,
    entity counts, local dims must match the rebuilt datasets)."""
    out = {}
    for cid, m in models.items():
        if isinstance(m, GeneralizedLinearModel):
            out[cid] = ["glm", int(m.dim)]
        elif isinstance(m, RandomEffectModel):
            out[cid] = ["re"] + [list(c.shape) for c in m.coefficients]
        else:
            out[cid] = [
                "fre",
                list(m.projection_matrix.shape),
            ] + [list(c.shape) for c in m.latent.coefficients]
    return out


# ------------------------------------------------------------------ save/load

def _sweep_orphans(parent: str, keep: str) -> None:
    """Delete leftover ``.ckpt-tmp-*`` / ``.ckpt-old-*`` sibling dirs — a
    kill between the two renames (or mid-build) leaks them forever, and a
    long training run saves every outer iteration. Runs after a SUCCESSFUL
    save, so any matching dir other than ``keep`` is an orphan (single
    writer per parent directory — the checkpointing contract)."""
    try:
        names = os.listdir(parent)
    except OSError:
        return
    for name in names:
        if not (name.startswith(_TMP_PREFIX) or name.startswith(_OLD_PREFIX)):
            continue
        full = os.path.join(parent, name)
        if full != keep and os.path.isdir(full):
            shutil.rmtree(full, ignore_errors=True)


def _prune_numbered_siblings(directory: str, keep_last_n: int) -> None:
    """Retention for iteration-numbered checkpoint dirs (``ckpt-000010``):
    keep the ``keep_last_n`` highest-numbered siblings sharing the same
    prefix, delete the rest. Only dirs that actually contain a checkpoint
    state file are eligible — anything else in the parent is left alone."""
    import re

    if keep_last_n < 1:
        raise ValueError(f"keep_last_n must be >= 1, got {keep_last_n}")
    base = os.path.basename(os.path.abspath(directory))
    m = re.match(r"^(.*?)(\d+)$", base)
    if m is None:
        raise ValueError(
            f"keep_last_n needs an iteration-numbered checkpoint directory "
            f"name (e.g. 'ckpt-000010'), got {base!r}"
        )
    prefix = m.group(1)
    parent = os.path.dirname(os.path.abspath(directory)) or "."
    numbered = []
    for name in os.listdir(parent):
        mm = re.match(rf"^{re.escape(prefix)}(\d+)$", name)
        full = os.path.join(parent, name)
        if mm and os.path.isfile(os.path.join(full, STATE_FILE)):
            numbered.append((int(mm.group(1)), full))
    numbered.sort()
    for _, full in numbered[:-keep_last_n]:
        shutil.rmtree(full, ignore_errors=True)


def save_training_checkpoint(
    directory: str,
    models: Dict[str, object],
    state: dict,
    best_models: Optional[Dict[str, object]] = None,
    keep_last_n: Optional[int] = None,
) -> None:
    """Atomically write a checkpoint: build in a tmp sibling dir, fsync the
    state file, then rename over the target (crash-safe). A successful save
    also sweeps orphaned tmp/old sibling dirs left by earlier crashes, and
    ``keep_last_n`` prunes older iteration-numbered sibling checkpoints
    (the directory name must end in digits, e.g. ``ckpt-000010``).

    Multi-host: sharded model arrays are gathered on EVERY process (the
    gathers are collectives), but only process 0 writes files; other
    processes return after the gathers."""
    import jax

    write = jax.process_index() == 0
    if not write:
        for model in models.values():
            _save_submodel(None, model)  # run the gather collectives only
        for model in (best_models or {}).values():
            _save_submodel(None, model)
        return
    parent = os.path.dirname(os.path.abspath(directory)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=_TMP_PREFIX, dir=parent)
    try:
        meta: Dict[str, dict] = {}
        for cid, model in models.items():
            meta[cid] = _save_submodel(os.path.join(tmp, "models", cid), model)
        best_meta: Optional[Dict[str, dict]] = None
        if best_models is not None:
            best_meta = {}
            for cid, model in best_models.items():
                best_meta[cid] = _save_submodel(
                    os.path.join(tmp, "best", cid), model
                )
        payload = {
            "version": _FORMAT_VERSION,
            "state": state,
            "models": meta,
            "best_models": best_meta,
            "fingerprint": model_fingerprint(models),
        }
        state_path = os.path.join(tmp, STATE_FILE)
        with open(state_path, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        fault_point(FAULT_PUBLISH)
        # crash-safe swap: move the old checkpoint ASIDE first so a kill at
        # any point leaves either the old or the new checkpoint loadable,
        # then delete the old one
        old = None
        if os.path.isdir(directory):
            old = tempfile.mkdtemp(prefix=_OLD_PREFIX, dir=parent)
            os.rmdir(old)
            os.replace(directory, old)
        os.replace(tmp, directory)
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    _sweep_orphans(parent, keep=tmp)
    if keep_last_n is not None:
        _prune_numbered_siblings(directory, keep_last_n)


def has_checkpoint(directory: str) -> bool:
    return os.path.isfile(os.path.join(directory, STATE_FILE))


def load_training_checkpoint(
    directory: str,
) -> Tuple[Dict[str, object], dict, Optional[Dict[str, object]]]:
    """→ (models, state, best_models or None).

    A successful load also sweeps orphaned ``.ckpt-tmp-*`` / ``.ckpt-old-*``
    sibling dirs: a job killed between the tmp-dir fsync and the atomic
    rename leaves its half-built tmp behind, and the NEXT save may be hours
    away — resume is the earliest safe point to reclaim the disk. The sweep
    runs after the checkpoint parses, so a corrupt state file never deletes
    material an operator might recover from."""
    directory = os.path.abspath(directory)
    with open(os.path.join(directory, STATE_FILE)) as f:
        payload = json.load(f)
    if payload.get("version") != _FORMAT_VERSION:
        raise ValueError(
            f"unsupported checkpoint version: {payload.get('version')}"
        )
    models = {
        cid: _load_submodel(os.path.join(directory, "models", cid), meta)
        for cid, meta in payload["models"].items()
    }
    best = None
    if payload.get("best_models") is not None:
        best = {
            cid: _load_submodel(os.path.join(directory, "best", cid), meta)
            for cid, meta in payload["best_models"].items()
        }
    _sweep_orphans(os.path.dirname(directory) or ".", keep=directory)
    return models, payload["state"], best
