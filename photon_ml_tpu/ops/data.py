"""Struct-of-arrays labeled data batch.

Reference parity: photon-lib data/LabeledPoint.scala:32 — (label, features,
offset, weight) — except batched: one pytree holds n examples. Padding rows
are encoded as weight 0 (an algebraic no-op in every objective term); there is
deliberately no separate mask field.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.ops.features import FeatureMatrix


@struct.dataclass
class LabeledData:
    """A batch of (label, features, offset, weight) examples.

    labels/offsets/weights: [n]; padding rows must have weight 0.

    ``norm`` is the NormalizationContext folded into any objective evaluated
    over this batch; it lives in the data pytree (traced jit argument) so
    factor/shift arrays are never baked into compiled programs as constants.
    """

    features: FeatureMatrix
    labels: jax.Array
    offsets: jax.Array
    weights: jax.Array
    norm: Optional[NormalizationContext] = None

    @classmethod
    def create(
        cls,
        features: FeatureMatrix,
        labels: jax.Array,
        offsets: Optional[jax.Array] = None,
        weights: Optional[jax.Array] = None,
        norm: Optional[NormalizationContext] = None,
    ) -> "LabeledData":
        labels = jnp.asarray(labels, dtype=jnp.float32)
        n = labels.shape[-1]
        offsets = (
            jnp.zeros((n,), dtype=jnp.float32)
            if offsets is None
            else jnp.asarray(offsets, dtype=jnp.float32)
        )
        weights = (
            jnp.ones((n,), dtype=jnp.float32)
            if weights is None
            else jnp.asarray(weights, dtype=jnp.float32)
        )
        return cls(
            features=features,
            labels=labels,
            offsets=offsets,
            weights=weights,
            norm=norm,
        )

    @property
    def num_rows(self) -> int:
        return self.labels.shape[-1]

    @property
    def dim(self) -> int:
        return self.features.dim

    def total_weight(self) -> jax.Array:
        return jnp.sum(self.weights)

    def with_offsets(self, offsets: jax.Array) -> "LabeledData":
        """Replace offsets (the residual trick: Coordinate.scala:59-62)."""
        return self.replace(offsets=offsets)

    def add_to_offsets(self, scores: jax.Array) -> "LabeledData":
        """addScoresToOffsets (reference FixedEffectDataSet.scala:44-54)."""
        return self.replace(offsets=self.offsets + scores)
