"""Device execution of static-permutation plans (see ops/routing.py).

A plan is a sequence of within-row 128-lane shuffles (``tpu.dynamic_gather``
via Pallas), within-tile sublane shuffles, and free XLA relayouts. All
stages are dense vector work — this is how the framework runs the sparse
GLM gather/scatter at vector speed instead of XLA's scalar ~10ns/element
loop (the TPU replacement for the reference's per-partition sparse axpy,
ValueAndGradientAggregator.scala:132-153).

Execution modes:
- TPU: Pallas kernels (one program launch amortized over the whole solve).
- CPU/tests: XLA ``take_along_axis`` fallback — identical semantics, used
  by the 8-virtual-device harness where Pallas TPU kernels can't run.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax.experimental import pallas as pl

from photon_ml_tpu.ops.pallas_kernels import pallas_available
from photon_ml_tpu.ops.routing import (
    LANES,
    Enter,
    LaneShuffle,
    Leave,
    PermPlan,
    SublaneShuffle,
)

try:  # pragma: no cover - absent on CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False


@struct.dataclass
class DevicePlan:
    """Jit-friendly plan: shuffle index arrays are pytree leaves (runtime
    inputs, not baked-in constants), stage structure is static metadata."""

    idx: Tuple[jax.Array, ...]
    kinds: Tuple[tuple, ...] = struct.field(pytree_node=False)
    size: int = struct.field(pytree_node=False)


def device_plan(plan: PermPlan) -> DevicePlan:
    idx = []
    kinds = []
    for st in plan.stages:
        if isinstance(st, LaneShuffle):
            # lane indices are < 128, sublane indices < 8: int8 on device
            # halves the plan's HBM footprint and per-pass index traffic
            # (kernels upcast in VMEM, which is free next to the loads)
            idx.append(jnp.asarray(st.idx, dtype=jnp.int8))
            kinds.append(("lane",))
        elif isinstance(st, SublaneShuffle):
            idx.append(jnp.asarray(st.idx, dtype=jnp.int8))
            kinds.append(("sublane", st.rows))
        elif isinstance(st, Enter):
            kinds.append(("enter", st.blocks, st.rows))
        elif isinstance(st, Leave):
            kinds.append(("leave", st.blocks, st.rows))
        else:  # pragma: no cover
            raise TypeError(st)
    return DevicePlan(idx=tuple(idx), kinds=tuple(kinds), size=plan.size)


def _row_block(m: int) -> int:
    for rb in (4096, 2048, 1024, 512, 256, 128, 64, 32, 16, 8):
        if m % rb == 0:
            return rb
    return m


# Test hook: run the Pallas kernels through the interpreter (CPU) so their
# semantics are covered by the 8-virtual-device harness, not just on TPU.
_INTERPRET = False


def _lane_shuffle_pallas(v: jax.Array, idx: jax.Array) -> jax.Array:
    m = v.shape[0]
    rb = _row_block(m)

    def kernel(x_ref, i_ref, o_ref):
        sel = i_ref[:].astype(jnp.int32)
        o_ref[:] = jnp.take_along_axis(x_ref[:], sel, axis=1)

    return pl.pallas_call(
        kernel,
        grid=(m // rb,),
        in_specs=[
            pl.BlockSpec((rb, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((rb, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rb, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, LANES), v.dtype),
        interpret=_INTERPRET,
    )(v, idx)


def _sublane_shuffle_pallas(v: jax.Array, idx: jax.Array, rows: int) -> jax.Array:
    m = v.shape[0]
    rb = _row_block(m)
    assert rb % rows == 0

    def kernel(x_ref, i_ref, o_ref):
        # Loop-free within-group row movement: rows <= 8 source rows per
        # group, so materialize each group-constant source row and select.
        # (A fori_loop of tiny dynamic slices compiles pathologically in
        # Mosaic at rb/rows ~ hundreds of steps; 'rows' selects vectorize.)
        x = x_ref[:].reshape(rb // rows, rows, LANES)
        sel = i_ref[:].astype(jnp.int32).reshape(rb // rows, rows, LANES)
        acc = jnp.zeros_like(x)
        for k in range(rows):
            src_row = jax.lax.broadcast_in_dim(
                x[:, k, :], x.shape, (0, 2)
            )
            acc = jnp.where(sel == k, src_row, acc)
        o_ref[:] = acc.reshape(rb, LANES)

    return pl.pallas_call(
        kernel,
        grid=(m // rb,),
        in_specs=[
            pl.BlockSpec((rb, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((rb, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rb, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, LANES), v.dtype),
        interpret=_INTERPRET,
    )(v, idx)


def _lane_shuffle_xla(v: jax.Array, idx: jax.Array) -> jax.Array:
    return jnp.take_along_axis(v, idx.astype(jnp.int32), axis=1)


def _sublane_shuffle_xla(v: jax.Array, idx: jax.Array, rows: int) -> jax.Array:
    m = v.shape[0]
    blk = v.reshape(m // rows, rows, LANES)
    sel = idx.astype(jnp.int32).reshape(m // rows, rows, LANES)
    return jnp.take_along_axis(blk, sel, axis=1).reshape(m, LANES)


def _use_pallas(m: int, rows: int | None = None) -> bool:
    if not (_HAS_PLTPU and pallas_available()):
        return False
    if m < 32:
        return False  # tiny plans: XLA handles them; int8 tiles need >=32 rows
    if _row_block(m) % 32 != 0:
        return False  # int8 index blocks must respect the (32, 128) tile
    if rows is not None and _row_block(m) % rows != 0:
        return False
    return True


def apply_plan(dplan: DevicePlan, x: jax.Array) -> jax.Array:
    """Apply the permutation plan to ``x`` (length must equal plan size).

    Returns the permuted array of the same length. Safe under jit/vmap-free
    contexts; all stage shapes are static.
    """
    assert x.shape[-1] == dplan.size, (x.shape, dplan.size)
    v = x.reshape(-1, LANES)
    ai = 0
    for kind in dplan.kinds:
        if kind[0] == "lane":
            idx = dplan.idx[ai]
            ai += 1
            if _use_pallas(v.shape[0]):
                v = _lane_shuffle_pallas(v, idx)
            else:
                v = _lane_shuffle_xla(v, idx)
        elif kind[0] == "sublane":
            idx = dplan.idx[ai]
            ai += 1
            rows = kind[1]
            if rows == 1:
                continue  # single-row groups: identity movement
            if _use_pallas(v.shape[0], rows):
                v = _sublane_shuffle_pallas(v, idx, rows)
            else:
                v = _sublane_shuffle_xla(v, idx, rows)
        elif kind[0] == "enter":
            _, b, r = kind
            v = v.reshape(b, r, LANES).transpose(0, 2, 1).reshape(-1, LANES)
        elif kind[0] == "leave":
            _, b, r = kind
            v = v.reshape(b, LANES, r).transpose(0, 2, 1).reshape(-1, LANES)
        else:  # pragma: no cover
            raise ValueError(kind)
    return v.reshape(-1)
