"""Static-permutation routing through a radix-128 Clos/Benes network.

Why this exists: the GLM hot loop is a sparse matvec/rmatvec pair
(reference: ValueAndGradientAggregator.scala:132-153 runs sparse axpy per
Spark partition). A TPU has no vectorized arbitrary gather/scatter — XLA
lowers both to a ~10ns/element scalar loop — but it *does* have a fast
within-row 128-lane shuffle (`tpu.dynamic_gather`), fast transposes, and
fast dense reductions. Any static permutation of an ``[R, 128]`` array
factors (Slepian–Duguid / Clos routing) into

    (within-row lane shuffle) o (per-lane row movement) o (within-row shuffle)

where the middle stage recurses with R -> R/128 until R <= 8, at which point
it is a sublane shuffle inside one hardware tile. Routing = proper
128-edge-coloring of the (source row, destination row) incidence multigraph,
computed once at data-prep time by Euler-split halving
(native/eulercolor.cpp). At run time a permutation of N elements costs
~2*log_128(N)-1 lane-shuffle passes — all dense vector work, no scalar core.

This module is host-side (numpy): it builds the stage plan and provides a
reference ``host_apply`` used by tests. Device execution lives in
``ops/permute_net.py``; the sparse-feature engine built on top lives in
``ops/sparse_perm.py``.
"""

from __future__ import annotations

import ctypes
import logging
import subprocess
from dataclasses import dataclass
from pathlib import Path
from typing import List, Optional, Sequence, Union

import numpy as np

logger = logging.getLogger(__name__)

LANES = 128
MAX_SUBLANES = 8  # hardware sublane-gather window (tpu.dynamic_gather dim 0)

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
_SRC = _NATIVE_DIR / "eulercolor.cpp"
_LIB = _NATIVE_DIR / "_eulercolor.so"

_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _load_native():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    from photon_ml_tpu.utils.nativelib import build_and_load

    lib = build_and_load(_SRC, _LIB)
    if lib is not None:
        lib.euler_color.restype = ctypes.c_int
        lib.euler_color.argtypes = [
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
    _lib = lib
    return _lib


def _euler_color_numpy(src: np.ndarray, dst: np.ndarray, deg: int,
                       n_src: int, n_dst: int) -> np.ndarray:
    """Fallback colorer: Euler-split halving with a sequential cycle walk.

    Pairings are built vectorized; the alternate 2-coloring walks each
    pairing cycle in Python. Correct at any size; used only when the native
    colorer (eulercolor.cpp) cannot be built, so speed is secondary.
    """
    n_edges = src.shape[0]
    color = np.zeros(n_edges, dtype=np.int32)
    levels = int(deg).bit_length() - 1

    def pair(subset: np.ndarray, key: np.ndarray) -> np.ndarray:
        order = subset[np.argsort(key[subset], kind="stable")]
        partner = np.empty(n_edges, dtype=np.int64)
        partner[order[0::2]] = order[1::2]
        partner[order[1::2]] = order[0::2]
        return partner

    classes = [np.arange(n_edges, dtype=np.int64)]
    for level in range(levels):
        next_classes = []
        for subset in classes:
            ps = pair(subset, src)
            pd = pair(subset, dst)
            visited = np.zeros(n_edges, dtype=bool)
            bit = np.zeros(n_edges, dtype=bool)
            for e0 in subset.tolist():
                if visited[e0]:
                    continue
                e, b, via_src = e0, False, True
                while True:
                    visited[e] = True
                    bit[e] = b
                    e = int(ps[e] if via_src else pd[e])
                    via_src = not via_src
                    b = not b
                    if e == e0:
                        break
            sel = bit[subset]
            color[subset[sel]] |= 1 << (levels - 1 - level)
            next_classes.append(subset[~sel])
            next_classes.append(subset[sel])
        classes = next_classes
    return color


def euler_color(src: np.ndarray, dst: np.ndarray, deg: int, n_src: int,
                n_dst: int) -> np.ndarray:
    """Proper ``deg``-edge-coloring of a regular bipartite multigraph.

    Every src node and dst node must have exactly ``deg`` incident edges;
    ``deg`` must be a power of two. Returns ``color[e] in [0, deg)`` with no
    two edges of equal color sharing a src node or a dst node.
    """
    src = np.ascontiguousarray(src, dtype=np.int32)
    dst = np.ascontiguousarray(dst, dtype=np.int32)
    n_edges = src.shape[0]
    assert deg > 0 and (deg & (deg - 1)) == 0, "deg must be a power of two"
    assert n_edges == n_src * deg == n_dst * deg
    lib = _load_native()
    if lib is not None:
        color = np.zeros(n_edges, dtype=np.int32)
        rc = lib.euler_color(
            ctypes.c_int64(n_edges),
            ctypes.c_int32(deg),
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int32(n_src),
            ctypes.c_int32(n_dst),
            color.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        if rc == 0:
            return color
        logger.warning("native euler_color rc=%d; numpy fallback", rc)
    return _euler_color_numpy(src, dst, deg, n_src, n_dst)


# --------------------------------------------------------------------------
# Stage types. All arrays are host numpy; permute_net converts to device.
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class LaneShuffle:
    """y[r, c] = x[r, idx[r, c]] — within-row 128-lane gather (form B)."""

    idx: np.ndarray  # [rows, 128] int32 in [0, 128)


@dataclass(frozen=True)
class SublaneShuffle:
    """Within consecutive blocks of ``rows`` rows (rows <= 8):
    y[g*rows + i, c] = x[g*rows + idx[g*rows + i, c], c] (form A)."""

    idx: np.ndarray  # [total_rows, 128] int32 in [0, rows)
    rows: int


@dataclass(frozen=True)
class Enter:
    """Relayout into the recursion: view [B, R, 128], transpose the last two
    axes, reshape to [B*128*(R//128), 128]. Pure XLA, ~free."""

    blocks: int
    rows: int


@dataclass(frozen=True)
class Leave:
    """Inverse of :class:`Enter` with the same (blocks, rows)."""

    blocks: int
    rows: int


Stage = Union[LaneShuffle, SublaneShuffle, Enter, Leave]


@dataclass
class PermPlan:
    """Executable decomposition of ``y = x[perm]`` into shuffle stages."""

    size: int  # padded network size (multiple of 128)
    stages: List[Stage]

    def invert(self) -> "PermPlan":
        """Plan for the inverse permutation (stages reversed + inverted)."""
        inv_stages: List[Stage] = []
        for st in reversed(self.stages):
            if isinstance(st, LaneShuffle):
                rows = st.idx.shape[0]
                inv = np.empty_like(st.idx)
                r = np.arange(rows)[:, None]
                inv[r, st.idx] = np.broadcast_to(
                    np.arange(LANES, dtype=st.idx.dtype), st.idx.shape
                )
                inv_stages.append(LaneShuffle(idx=inv))
            elif isinstance(st, SublaneShuffle):
                total, R = st.idx.shape[0], st.rows
                blk = st.idx.reshape(total // R, R, LANES)
                inv = np.empty_like(blk)
                g = np.arange(total // R)[:, None, None]
                c = np.arange(LANES)[None, None, :]
                i = np.broadcast_to(
                    np.arange(R, dtype=st.idx.dtype)[None, :, None], blk.shape
                )
                inv[g, blk, c] = i
                inv_stages.append(
                    SublaneShuffle(idx=inv.reshape(total, LANES), rows=R)
                )
            elif isinstance(st, Enter):
                inv_stages.append(Leave(blocks=st.blocks, rows=st.rows))
            elif isinstance(st, Leave):
                inv_stages.append(Enter(blocks=st.blocks, rows=st.rows))
            else:  # pragma: no cover
                raise TypeError(st)
        return PermPlan(size=self.size, stages=inv_stages)


def valid_size(n: int) -> int:
    """Smallest routable network size >= n: c * 128**(m+1), c in {1,2,4,8}.

    c is restricted to powers of two so the recursion base emits
    SublaneShuffle stages with rows in {1,2,4,8} — shapes the vectorized
    Pallas sublane kernel handles; a non-power-of-two c would force the
    scalar XLA gather fallback on TPU for that stage.
    """
    if n <= 0:
        raise ValueError("size must be positive")
    base = LANES
    while True:
        for c in (1, 2, 4, 8):
            if c * base >= n:
                return c * base
        base *= LANES


def _route(sigma: np.ndarray, B: int, R: int, stages: List[Stage]) -> None:
    """Emit stages for per-block permutations.

    sigma: [B, R, 128] int64 — for each block, destination position (r, c)
    holds the *source* flat position (rs*128 + cs) within the same block.
    """
    rs, cs = np.divmod(sigma, LANES)  # [B, R, 128]
    b_ids = np.arange(B, dtype=np.int64)[:, None, None]
    rd = np.broadcast_to(np.arange(R, dtype=np.int64)[None, :, None], sigma.shape)
    src_node = (b_ids * R + rs).ravel()
    dst_node = (b_ids * R + rd).ravel()
    color = euler_color(src_node, dst_node, LANES, B * R, B * R).astype(np.int64)

    # First lane shuffle: x1[rs, color] = x[rs, cs]
    la = np.empty(B * R * LANES, dtype=np.int32)
    la[src_node * LANES + color] = cs.ravel().astype(np.int32)
    stages.append(LaneShuffle(idx=la.reshape(B * R, LANES)))

    # Middle stage: per-lane row movement m[rd, color] = rs (block-local).
    m = np.empty(B * R * LANES, dtype=np.int64)
    m[dst_node * LANES + color] = rs.ravel()
    m = m.reshape(B, R, LANES)

    if R <= MAX_SUBLANES:
        stages.append(
            SublaneShuffle(idx=m.reshape(B * R, LANES).astype(np.int32), rows=R)
        )
    else:
        assert R % LANES == 0, f"unroutable row count {R}"
        R1 = R // LANES
        # Relayout: new block (b, lane c); new position (g, j) holds old
        # (b, g*128 + j, c). Element wanted at new (b, c, gd, jd) comes from
        # old row m[b, gd*128+jd, c] = gs*128 + js -> new (b, c, gs, js).
        stages.append(Enter(blocks=B, rows=R))
        m_t = np.transpose(m, (0, 2, 1))  # [B, 128, R] indexed by (b, c, rd)
        sigma2 = m_t.reshape(B * LANES, R1, LANES)  # values are rs = gs*128+js
        _route(sigma2, B * LANES, R1, stages)
        stages.append(Leave(blocks=B, rows=R))

    # Final lane shuffle: y[rd, cd] = x2[rd, color]
    lb = color.astype(np.int32).reshape(B * R, LANES)
    stages.append(LaneShuffle(idx=lb))


def build_plan(perm: Sequence[int] | np.ndarray, size: Optional[int] = None) -> PermPlan:
    """Build a plan computing ``y = x[perm]`` (gather convention).

    ``perm`` must be a bijection over [0, len(perm)). The network size is
    padded up to :func:`valid_size`; padded positions map identically.
    """
    perm = np.asarray(perm, dtype=np.int64)
    n = perm.shape[0]
    S = valid_size(max(n, 1) if size is None else size)
    if S < n:
        raise ValueError(f"requested size {size} < permutation length {n}")
    full = np.arange(S, dtype=np.int64)
    full[:n] = perm
    # sanity: bijection
    if np.unique(perm).shape[0] != n or (n and perm.max() >= n):
        raise ValueError("perm is not a bijection over its domain")
    stages: List[Stage] = []
    _route(full.reshape(1, S // LANES, LANES), 1, S // LANES, stages)
    return PermPlan(size=S, stages=stages)


def host_apply(plan: PermPlan, x: np.ndarray) -> np.ndarray:
    """Reference execution of a plan on host (numpy). Returns the full
    padded [size] result (input and output live in different layouts whose
    real lengths may differ; callers slice what they need). For tests."""
    S = plan.size
    v = np.zeros(S, dtype=x.dtype)
    v[: x.shape[0]] = x
    v = v.reshape(S // LANES, LANES)
    for st in plan.stages:
        if isinstance(st, LaneShuffle):
            v = np.take_along_axis(v, st.idx, axis=1)
        elif isinstance(st, SublaneShuffle):
            rows = v.shape[0]
            blk = v.reshape(rows // st.rows, st.rows, LANES)
            idx = st.idx.reshape(rows // st.rows, st.rows, LANES)
            v = np.take_along_axis(blk, idx, axis=1).reshape(rows, LANES)
        elif isinstance(st, Enter):
            B, R = st.blocks, st.rows
            v = (
                v.reshape(B, R, LANES)
                .transpose(0, 2, 1)
                .reshape(B * LANES * (R // LANES), LANES)
            )
        elif isinstance(st, Leave):
            B, R = st.blocks, st.rows
            v = (
                v.reshape(B, LANES, R)
                .transpose(0, 2, 1)
                .reshape(B * R, LANES)
            )
        else:  # pragma: no cover
            raise TypeError(st)
    return v.reshape(S)
