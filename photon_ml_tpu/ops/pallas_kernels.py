"""Pallas TPU kernels for the GLM hot path.

The reference's per-partition compute kernel (ValueAndGradientAggregator
.scala:33: one pass accumulating Σ w·l(z,y) and Σ w·l′·x) maps to TPU as a
fused MXU kernel: per row-block, z = X·w rides the MXU, the pointwise loss
and its derivative ride the VPU, and gradᵀ += dzᵀ·X rides the MXU again —
ONE pass over X in HBM instead of the two XLA makes for matvec + rmatvec.

Dense row-blocks only (the TPU has no efficient arbitrary gather/scatter, so
the ELL sparse path stays on XLA; per-entity random-effect blocks are dense
by construction via index-map projection). Grid iterations on TPU execute
sequentially, so the kernel accumulates into its output block across steps.

See /opt/skills/guides/pallas_guide.md for the programming model.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

ROW_BLOCK = 256
LANE = 128


def _loss_terms(kind, z, y):
    """(l(z,y), dl/dz) on the VPU. ``kind`` is a PointwiseLoss class (its
    value/d1 are pure elementwise jnp, valid inside a kernel) — one source
    of truth with the XLA objective."""
    return kind.value(z, y), kind.d1(z, y)


def _kernel(kind: str, x_ref, y_ref, off_ref, wt_ref, w_ref,
            val_ref, grad_ref, csum_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        val_ref[...] = jnp.zeros_like(val_ref)
        grad_ref[...] = jnp.zeros_like(grad_ref)
        csum_ref[...] = jnp.zeros_like(csum_ref)

    x = x_ref[...]                       # [BN, D]
    z = jax.lax.dot_general(
        x, w_ref[...],                   # [BN, D] x [1, D]
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )[:, 0] + off_ref[:, 0]              # [BN]
    y = y_ref[:, 0]
    wt = wt_ref[:, 0]
    l, d1 = _loss_terms(kind, z, y)
    # weight-0 padding rows must be exact no-ops even when the unweighted
    # term overflows (0 * inf -> NaN would poison the sums)
    lw = jnp.where(wt > 0, wt * l, 0.0)
    dz = jnp.where(wt > 0, wt * d1, 0.0)  # [BN]
    # Mosaic forbids scalar stores to VMEM: accumulate (1,1)-shaped arrays
    val_ref[...] += jnp.sum(lw)[None, None]
    csum_ref[...] += jnp.sum(dz)[None, None]
    grad_ref[...] += jax.lax.dot_general(
        dz[None, :], x,                  # [1, BN] x [BN, D]
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


def _pad_to(a: jax.Array, axis: int, multiple: int) -> jax.Array:
    size = a.shape[axis]
    rem = (-size) % multiple
    if rem == 0:
        return a
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, rem)
    return jnp.pad(a, pad)


@functools.partial(jax.jit, static_argnames=("kind", "interpret"))
def fused_value_grad(
    matrix: jax.Array,    # [n, d] dense features
    labels: jax.Array,    # [n]
    offsets: jax.Array,   # [n]
    weights: jax.Array,   # [n]
    w: jax.Array,         # [d]
    kind=None,  # PointwiseLoss class (static); required
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One-pass (Σ wᵢ·l, Σ wᵢ·l′·xᵢ, Σ wᵢ·l′) — loss sum, gradient, and the
    coefficient sum the normalization shift path needs."""
    if kind is None:
        raise ValueError("kind (a PointwiseLoss class) is required")
    n, d = matrix.shape
    x = _pad_to(_pad_to(matrix, 0, ROW_BLOCK), 1, LANE)
    np_, dp = x.shape
    nb = np_ // ROW_BLOCK
    # padding rows carry weight 0 (exact no-ops in every sum); vectors are
    # [np_, 1] columns so the (ROW_BLOCK, 1) blocks satisfy Mosaic's tile
    # rule (sublane divisible by 8, trailing dim equal to the array's)
    col = lambda v: _pad_to(v.astype(jnp.float32), 0, ROW_BLOCK)[:, None]
    yv, off, wt = col(labels), col(offsets), col(weights)
    wv = _pad_to(w.astype(jnp.float32)[None, :], 1, LANE)

    val, grad, csum = pl.pallas_call(
        functools.partial(_kernel, kind),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((ROW_BLOCK, dp), lambda i: (i, 0)),
            pl.BlockSpec((ROW_BLOCK, 1), lambda i: (i, 0)),
            pl.BlockSpec((ROW_BLOCK, 1), lambda i: (i, 0)),
            pl.BlockSpec((ROW_BLOCK, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, dp), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, dp), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, dp), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, yv, off, wt, wv)
    return val[0, 0], grad[0, :d], csum[0, 0]


def _single_kernel(kind: str, x_ref, y_ref, off_ref, wt_ref, w_ref,
                   val_ref, grad_ref, csum_ref):
    """Grid-free variant: whole problem in one VMEM block. No cross-step
    accumulation, so jax.vmap batches it cleanly (the batch axis becomes the
    grid) — this is the per-entity random-effect inner-loop kernel."""
    x = x_ref[...]                       # [S, D]
    z = jax.lax.dot_general(
        x, w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )[:, 0] + off_ref[0, :]
    y = y_ref[0, :]
    wt = wt_ref[0, :]
    l, d1 = _loss_terms(kind, z, y)
    lw = jnp.where(wt > 0, wt * l, 0.0)
    dz = jnp.where(wt > 0, wt * d1, 0.0)
    # Mosaic forbids scalar stores to VMEM: store (1,1)-shaped arrays
    val_ref[...] = jnp.sum(lw)[None, None]
    csum_ref[...] = jnp.sum(dz)[None, None]
    grad_ref[...] = jax.lax.dot_general(
        dz[None, :], x, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


@functools.partial(jax.jit, static_argnames=("kind", "interpret"))
def fused_value_grad_single(
    matrix: jax.Array,    # [s, d]
    labels: jax.Array,    # [s]
    offsets: jax.Array,   # [s]
    weights: jax.Array,   # [s]
    w: jax.Array,         # [d]
    kind=None,  # PointwiseLoss class (static); required
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Single-block fused pass; vmap-safe (use for per-entity solves)."""
    if kind is None:
        raise ValueError("kind (a PointwiseLoss class) is required")
    s, d = matrix.shape
    x = _pad_to(_pad_to(matrix, 0, 8), 1, LANE)
    sp, dp = x.shape
    yv = _pad_to(labels.astype(jnp.float32)[None, :], 1, 8)
    off = _pad_to(offsets.astype(jnp.float32)[None, :], 1, 8)
    wt = _pad_to(weights.astype(jnp.float32)[None, :], 1, 8)
    wv = _pad_to(w.astype(jnp.float32)[None, :], 1, LANE)
    val, grad, csum = pl.pallas_call(
        functools.partial(_single_kernel, kind),
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, dp), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, yv, off, wt, wv)
    return val[0, 0], grad[0, :d], csum[0, 0]


# At most this many elements go through the single-block kernel (must fit
# VMEM comfortably); larger dense problems use the blocked grid kernel.
SINGLE_BLOCK_MAX_ELEMENTS = 2_000_000


def fused_value_grad_auto(matrix, labels, offsets, weights, w, kind):
    """The objective's entry: ONLY the single-block (vmappable, chip-local)
    variant auto-routes — large dense problems return None and the caller
    stays on XLA, which GSPMD can partition (pallas_call has no partitioning
    rule, so routing a mesh-sharded FE matrix here would replicate it).
    Off-TPU (the 'force' debug mode) the interpreter runs the kernel."""
    s, d = matrix.shape
    if s * d > SINGLE_BLOCK_MAX_ELEMENTS:
        return None
    return fused_value_grad_single(
        matrix, labels, offsets, weights, w, kind=kind,
        interpret=not pallas_available(),
    )


def pallas_available() -> bool:
    """True when a TPU backend can run the kernels natively."""
    if not _HAS_PLTPU:
        return False
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False


@functools.cache
def enabled() -> bool:
    """Fused kernels are opt-in: PHOTON_ML_TPU_PALLAS=1 enables them (on a
    TPU backend), =0/unset disables, and =force enables even off-TPU via
    the pallas interpreter (slow; correctness drives only). The objective
    checks this once at trace time."""
    import os

    flag = os.environ.get("PHOTON_ML_TPU_PALLAS", "")
    if flag == "1":
        return pallas_available()
    if flag == "force":
        return _HAS_PLTPU
    return False
