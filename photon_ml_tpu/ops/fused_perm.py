"""Fused execution of Benes permutation plans: the large-d GLM fast path.

``ops/permute_net.py`` executes a routed plan stage by stage: every lane or
sublane shuffle and every enter/leave relayout is its own device pass, so one
permutation of S elements costs ~11 full HBM round-trips at production sizes
(7 shuffles + 4 relayouts), and the surrounding GLM algebra (broadcast w over
column slots, multiply by stored values, segment-reduce) adds several more.

This module fuses the same plan into ``2m+1`` Pallas kernels (m = recursion
depth, so 3 or 5 at realistic sizes) by folding each enter/leave transpose
into the adjacent lane shuffle's block layout, and folding the GLM prologue/
epilogue into the first/last kernel:

- descend kernel: lane-shuffle a [128u, 128] tile, transpose it, write it
  into the entered layout — the relayout becomes the kernel's output
  BlockSpec instead of a separate pass.
- base kernel: innermost (lane, sublane, lane) triple in one row-local pass.
- ascend kernel: read a tile from the entered layout (transposed read = the
  leave relayout), lane-shuffle, write.
- prologue (first descend): build the network input in-kernel from the
  small operand — broadcast w over each column's KP slots (matvec), or
  multiply the stored ELL values by the row-broadcast coefficient vector
  (rmatvec) — instead of materializing a [S] array first.
- epilogue (last ascend): reduce each row/column's slot group to the output
  vector (margins z or gradient g) in-kernel.

Per linear map this is ~3x less HBM traffic than the stage-by-stage path.
Reference parity: this implements the same per-example sparse axpy math as
ValueAndGradientAggregator.scala:132-153; only the execution strategy is
TPU-specific.

Slot-group sizes K (ELL, max nnz/row) and KP (CSC, max nnz/col) are rounded
up to powers of two so slot groups tile the 128-lane axis evenly (group <=
128) or span whole rows (group = 128q): both make the prologue/epilogue a
dense in-kernel reshape/matmul instead of a gather.

Off TPU the class runs an unfused XLA fallback (broadcast -> apply_plan ->
reduce) with identical semantics; the Pallas kernels themselves are covered
on CPU through the interpreter (tests set ``_INTERPRET``).
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from photon_ml_tpu.ops import routing
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.ops.pallas_kernels import pallas_available
from photon_ml_tpu.ops.permute_net import DevicePlan, apply_plan, device_plan
from photon_ml_tpu.ops.routing import LANES

from jax.experimental import pallas as pl

# Test hook: run the fused kernels through the Pallas interpreter (CPU).
_INTERPRET = False

_MAX_BASE_BLOCK = 1024  # rows per base-kernel block (VMEM budget)

# Largest slot group (K or KP) the fused prologue/epilogue can address. For
# group = 128*q the operand BlockSpec height is LANES*u//q with u as small as
# 1, so q > LANES would silently produce a zero-height block and an obscure
# Mosaic failure at production shapes (a row/column with more than
# LANES*LANES nonzeros after hot-column splitting). Guarded in ``assemble``.
MAX_FUSED_GROUP = LANES * LANES


class FusedGroupTooLarge(ValueError):
    """A slot group exceeds what the fused executor can tile. The
    stage-by-stage engine (``engine="benes"``) has no such limit."""


# --------------------------------------------------------------------------
# Plan parsing: recover the canonical (descend* base ascend*) shape that
# routing._route always emits.
# --------------------------------------------------------------------------


class ParsedPlan(NamedTuple):
    descents: Tuple[Tuple[int, int, int], ...]  # (idx slot, B, R) per level
    base: Tuple[int, Optional[int], int, int]   # (idx_a, idx_s or None, rows, idx_b)
    ascents: Tuple[Tuple[int, int, int], ...]   # (idx slot, B, R), outermost last


def parse_plan(dplan: DevicePlan) -> ParsedPlan:
    kinds = dplan.kinds
    pos = 0   # position in kinds
    ai = 0    # position in idx tuple
    descents = []
    while pos + 1 < len(kinds) and kinds[pos][0] == "lane" and kinds[pos + 1][0] == "enter":
        _, b, r = kinds[pos + 1]
        descents.append((ai, b, r))
        ai += 1
        pos += 2
    if not (
        pos + 2 < len(kinds)
        and kinds[pos][0] == "lane"
        and kinds[pos + 1][0] == "sublane"
        and kinds[pos + 2][0] == "lane"
    ):
        raise ValueError(f"unrecognized plan structure at {pos}: {kinds}")
    rows = kinds[pos + 1][1]
    base = (ai, ai + 1, rows, ai + 2)
    ai += 3
    pos += 3
    ascents = []
    for _ in range(len(descents)):
        if not (pos + 1 < len(kinds) and kinds[pos][0] == "leave" and kinds[pos + 1][0] == "lane"):
            raise ValueError(f"unrecognized plan structure at {pos}: {kinds}")
        _, b, r = kinds[pos]
        ascents.append((ai, b, r))
        ai += 1
        pos += 2
    if pos != len(kinds):
        raise ValueError(f"trailing plan stages at {pos}: {kinds}")
    return ParsedPlan(tuple(descents), base, tuple(ascents))


# --------------------------------------------------------------------------
# Prologue / epilogue specs (all group sizes are powers of two).
# --------------------------------------------------------------------------


class Broadcast(NamedTuple):
    """Network input[col*KP + k] = vec[col] — matvec's w expansion."""

    vec: jax.Array  # [S // group]
    group: int      # KP


class MulBroadcast(NamedTuple):
    """input[row*K + k] = t(values[row*K + k]) * vec[row] — rmatvec's c
    expansion. ``transform`` applies elementwise to the stored values in the
    kernel: "id", "sq" (Hessian diagonal), "abs" / "nnz" (summary stats)."""

    values: jax.Array  # [S] flat slot values (ELL layout)
    vec: jax.Array     # [S // group]
    group: int         # K
    transform: str = "id"


class MulReduce(NamedTuple):
    """out[row] = sum_k values[row*K+k] * permuted[row*K+k] — matvec's z."""

    values: jax.Array  # [S]
    group: int         # K


class Reduce(NamedTuple):
    """out[col] = sum_k permuted[col*KP+k] — rmatvec's g."""

    group: int  # KP


def _group_mats(group: int, dtype=jnp.float32):
    """(expand [g2, 128], reduce [128, g2]) 0/1 matrices for a slot group of
    ``group`` lanes, where g2 = 128 // group; built in-kernel via iota."""
    g2 = LANES // group
    lane = jax.lax.broadcasted_iota(jnp.int32, (g2, LANES), 1) // group
    slot = jax.lax.broadcasted_iota(jnp.int32, (g2, LANES), 0)
    expand = (lane == slot).astype(dtype)
    return expand, expand.T


def _apply_transform(vals: jax.Array, transform: str) -> jax.Array:
    if transform == "id":
        return vals
    if transform == "sq":
        return vals * vals
    if transform == "abs":
        return jnp.abs(vals)
    if transform == "nnz":
        return (vals != 0).astype(vals.dtype)
    raise ValueError(f"unknown value transform {transform!r}")


def _build_input_block(pro, w_ref, v_ref, rows: int):
    """Materialize a [rows, 128] network-input tile inside a kernel.

    ``w_ref`` is the small-operand block; ``v_ref`` the values block (or None).
    For group <= 128 the operand block is [rows, 128//group]; for group =
    128*q it is [rows//q, 1] and each operand element spans q rows.
    """
    group = pro.group
    if group <= LANES:
        wb = w_ref[...]  # [rows, 128//group]
        expand, _ = _group_mats(group, wb.dtype)
        x = jax.lax.dot_general(
            wb, expand, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # [rows, 128]
    else:
        q = group // LANES
        wb = w_ref[...]  # [rows//q, 1]
        # row r of the tile takes operand element r//q: select matrix
        r_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, rows // q), 0) // q
        s_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, rows // q), 1)
        sel = (r_ids == s_ids).astype(wb.dtype)
        col = jax.lax.dot_general(
            sel, wb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # [rows, 1]
        x = jnp.broadcast_to(col, (rows, LANES))
    if isinstance(pro, MulBroadcast):
        x = _apply_transform(v_ref[...], pro.transform) * x
    return x


def _pro_specs(pro, R1: int, u: int):
    """(extra inputs, extra in_specs) the prologue adds to a descend call."""
    group = pro.group
    if group <= LANES:
        g2 = LANES // group
        op = pro.vec.reshape(-1, g2)
        specs = [pl.BlockSpec((LANES * u, g2), lambda b, g: (b * R1 // u + g, 0))]
        inputs = [op]
    else:
        q = group // LANES
        op = pro.vec.reshape(-1, 1)
        specs = [pl.BlockSpec((LANES * u // q, 1), lambda b, g: (b * R1 // u + g, 0))]
        inputs = [op]
    if isinstance(pro, MulBroadcast):
        vals = pro.values.reshape(-1, LANES)
        specs.insert(
            0, pl.BlockSpec((LANES * u, LANES), lambda b, g: (b * R1 // u + g, 0))
        )
        inputs.insert(0, vals)
    return inputs, specs


# --------------------------------------------------------------------------
# Fused kernels.
# --------------------------------------------------------------------------


def _tile_cap() -> int:
    """Rows-of-128 per kernel block (the pipeline tile height).

    Default 8 → [1024, 128] f32 blocks (~0.5 MB payload). VMEM holds far
    larger tiles; PHOTON_FUSED_TILE_U raises the cap (power of two) so the
    hardware session can A/B whether per-grid-step overhead — not HBM
    bandwidth — is what binds the kernels (VERDICT r4 weak #3)."""
    try:
        cap = int(os.environ.get("PHOTON_FUSED_TILE_U", "8"))
    except ValueError:
        return 8
    if cap < 8 or cap & (cap - 1):
        return 8
    return cap


def _tile_rows(R1: int) -> int:
    """Sublane tile count u for the 3-D entered layout [B*128, R1, 128].

    Mosaic's lowering requires the middle block dim be divisible by 8 or
    equal to the full array dim R1, so u is the largest power-of-two
    divisor of R1 within the tile cap (>= 8 whenever 8 | R1), and u = R1
    below that (plans are power-of-two sized, making R1 < 8 exact)."""
    cap = _tile_cap()
    u = 8
    while R1 % u:
        u //= 2
    if u < 8 and u != R1:
        raise ValueError(
            f"R1={R1} admits no Mosaic-legal sublane tile (need 8 | u or "
            "u == R1); plan sizes must be powers of two"
        )
    while u * 2 <= cap and R1 % (u * 2) == 0:
        u *= 2
    return u


def _descend_call(
    v, idx, B: int, R: int, pro, interpret: bool, payload_dtype=jnp.float32
) -> jax.Array:
    """(lane shuffle; enter relayout) in one pass; optional input prologue.

    Input layout [B*R, 128]; output entered layout [B*128*R1, 128] returned
    as a 3-D [B*128, R1, 128] array (the caller treats it as opaque).
    ``payload_dtype`` is the storage dtype of the permuted intermediates:
    bfloat16 halves the network's HBM traffic at one entry rounding (the
    prologue math and the final reductions stay f32).
    """
    R1 = R // LANES
    u = _tile_rows(R1)
    if pro is not None and pro.group > LANES:
        # the q-path prologue builds an O(u^2) in-kernel selection matrix;
        # keep the default tile height there regardless of the A/B cap
        u = min(u, 8)

    def kernel(*refs):
        o_ref = refs[-1]
        i_ref = refs[-2]
        if pro is None:
            # shuffle in f32 regardless of the storage dtype: Mosaic's
            # dynamic_gather needs data/index bitwidths to match, and the
            # converts are VMEM-local (HBM load/store stay payload-width)
            x = refs[0][...].astype(jnp.float32)
        elif isinstance(pro, MulBroadcast):
            x = _build_input_block(pro, refs[1], refs[0], LANES * u)
        else:
            x = _build_input_block(pro, refs[0], None, LANES * u)
        sel = i_ref[...].astype(jnp.int32)
        y = jnp.take_along_axis(x, sel, axis=1)
        # y row (t*128 + j) lane c -> out[c, t, j]: a single 2-D transpose
        # ([128u,128] -> [128,128u]) then a minor-dim split — the rank-3
        # transpose equivalent, expressed in ops Mosaic lowers well
        o_ref[...] = y.T.reshape(LANES, u, LANES).astype(o_ref.dtype)

    if pro is None:
        inputs = [v.reshape(B * R, LANES)]
        specs = [pl.BlockSpec((LANES * u, LANES), lambda b, g: (b * R1 // u + g, 0))]
    else:
        inputs, specs = _pro_specs(pro, R1, u)
    inputs.append(idx)
    specs.append(pl.BlockSpec((LANES * u, LANES), lambda b, g: (b * R1 // u + g, 0)))

    return pl.pallas_call(
        kernel,
        grid=(B, R1 // u),
        in_specs=specs,
        out_specs=pl.BlockSpec((LANES, u, LANES), lambda b, g: (b, g, 0)),
        out_shape=jax.ShapeDtypeStruct((B * LANES, R1, LANES), payload_dtype),
        interpret=interpret,
    )(*inputs)


def _ascend_call(v3, idx, B: int, R: int, epi, interpret: bool):
    """(leave relayout; lane shuffle) in one pass; optional output epilogue.

    Input: entered layout as 3-D [B*128, R1, 128]. Output: [B*R, 128] plain
    rows, or the epilogue's reduced vector.
    """
    R1 = R // LANES
    u = _tile_rows(R1)
    if epi is not None and epi.group > LANES:
        # the q-path epilogue builds an O(u^2) selection matrix (see
        # _descend_call); keep the default tile height there
        u = min(u, 8)

    def _shuffled(x_ref, i_ref):
        # f32 in-VMEM shuffle (see _descend_call): converts are local, the
        # HBM read keeps the payload width
        t = x_ref[...].astype(jnp.float32)
        # t [128, u, 128]: t[c, t_, j] = row (g*u+t_)*128+j lane c;
        # minor-dim merge then one 2-D transpose: y[t_*128+j, c] = t[c, t_, j]
        y = t.reshape(LANES, u * LANES).T
        sel = i_ref[...].astype(jnp.int32)
        return jnp.take_along_axis(y, sel, axis=1)

    def _reduced(y):
        y = y.astype(jnp.float32)  # accumulate reductions in f32 always
        group = epi.group
        if group <= LANES:
            _, reduce = _group_mats(group, y.dtype)
            return jax.lax.dot_general(
                y, reduce, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )  # [128u, 128//group]
        q = group // LANES
        rowsum = jnp.sum(y, axis=1, keepdims=True)  # [128u, 1]
        nrow = LANES * u
        r_ids = jax.lax.broadcasted_iota(jnp.int32, (nrow // q, nrow), 1) // q
        s_ids = jax.lax.broadcasted_iota(jnp.int32, (nrow // q, nrow), 0)
        sel2 = (r_ids == s_ids).astype(y.dtype)
        return jax.lax.dot_general(
            sel2, rowsum, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # [128u//q, 1]

    def kernel_plain(x_ref, i_ref, o_ref):
        o_ref[...] = _shuffled(x_ref, i_ref).astype(o_ref.dtype)

    def kernel_reduce(x_ref, i_ref, o_ref):
        o_ref[...] = _reduced(_shuffled(x_ref, i_ref))

    def kernel_mul_reduce(x_ref, v_ref, i_ref, o_ref):
        o_ref[...] = _reduced(_shuffled(x_ref, i_ref) * v_ref[...])

    in_specs = [
        pl.BlockSpec((LANES, u, LANES), lambda b, g: (b, g, 0)),
        pl.BlockSpec((LANES * u, LANES), lambda b, g: (b * R1 // u + g, 0)),
    ]
    inputs = [v3, idx]
    if epi is None:
        body = kernel_plain
    elif isinstance(epi, MulReduce):
        in_specs.insert(
            1, pl.BlockSpec((LANES * u, LANES), lambda b, g: (b * R1 // u + g, 0))
        )
        inputs.insert(1, epi.values.reshape(-1, LANES))
        body = kernel_mul_reduce
    else:
        body = kernel_reduce

    if epi is None:
        out_specs = pl.BlockSpec((LANES * u, LANES), lambda b, g: (b * R1 // u + g, 0))
        out_shape = jax.ShapeDtypeStruct((B * R, LANES), v3.dtype)
    else:
        group = epi.group
        if group <= LANES:
            g2 = LANES // group
            out_specs = pl.BlockSpec((LANES * u, g2), lambda b, g: (b * R1 // u + g, 0))
            out_shape = jax.ShapeDtypeStruct((B * R, g2), jnp.float32)
        else:
            q = group // LANES
            out_specs = pl.BlockSpec(
                (LANES * u // q, 1), lambda b, g: (b * R1 // u + g, 0)
            )
            out_shape = jax.ShapeDtypeStruct((B * R // q, 1), jnp.float32)

    out = pl.pallas_call(
        body,
        grid=(B, R1 // u),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*inputs)
    if epi is None:
        return out
    return out.reshape(-1)


def _base_call(v, idx_a, idx_s, rows: int, idx_b, interpret: bool) -> jax.Array:
    """Innermost (lane, sublane, lane) triple, row-local, one pass."""
    M = v.shape[0]
    # base blocks grow with the tile cap but stay clamped at 4x: the
    # sublane stage materializes [rb/rows, rows, 128] accumulators per
    # step, and an oversized base kernel failing to compile would wipe the
    # whole engine's A/B (the descend/ascend knob is the experiment)
    rb = _MAX_BASE_BLOCK * min(_tile_cap() // 8, 4)
    while M % rb or rb % max(rows, 1):
        rb //= 2

    def kernel(x_ref, ia_ref, *rest):
        o_ref = rest[-1]
        # f32 in-VMEM shuffles (see _descend_call)
        x = x_ref[...].astype(jnp.float32)
        x = jnp.take_along_axis(x, ia_ref[...].astype(jnp.int32), axis=1)
        if rows > 1:
            is_ref, ib_ref = rest[0], rest[1]
            blk = x.reshape(rb // rows, rows, LANES)
            sel = is_ref[...].astype(jnp.int32).reshape(rb // rows, rows, LANES)
            acc = jnp.zeros_like(blk)
            for k in range(rows):
                src = jax.lax.broadcast_in_dim(blk[:, k, :], blk.shape, (0, 2))
                acc = jnp.where(sel == k, src, acc)
            x = acc.reshape(rb, LANES)
        else:
            ib_ref = rest[0]
        x = jnp.take_along_axis(x, ib_ref[...].astype(jnp.int32), axis=1)
        o_ref[...] = x.astype(o_ref.dtype)

    spec = pl.BlockSpec((rb, LANES), lambda i: (i, 0))
    inputs = [v, idx_a] + ([idx_s] if rows > 1 else []) + [idx_b]
    return pl.pallas_call(
        kernel,
        grid=(M // rb,),
        in_specs=[spec] * len(inputs),
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((M, LANES), v.dtype),
        interpret=interpret,
    )(*inputs)


def fused_execute(
    dplan: DevicePlan, pro, epi, interpret: Optional[bool] = None,
    payload_dtype=jnp.float32,
):
    """Run a full permutation plan with fused prologue/epilogue.

    pro: Broadcast | MulBroadcast — builds the [S]-layout network input.
    epi: MulReduce | Reduce — reduces the permuted output to a vector.
    Returns the epilogue's [S // epi.group] vector.

    ``payload_dtype=bfloat16`` stores the permuted intermediates half-size
    (one rounding at network entry; permutes are exact; reductions
    accumulate f32) — ~2x less HBM traffic through the network stages.
    """
    if interpret is None:
        interpret = _INTERPRET
    parsed = parse_plan(dplan)
    if not parsed.descents:
        raise ValueError("plan too small for fused execution (no recursion)")
    v = None
    for j, (ai, B, R) in enumerate(parsed.descents):
        v = _descend_call(
            v, dplan.idx[ai], B, R, pro if j == 0 else None, interpret,
            payload_dtype=payload_dtype,
        )
        v = v.reshape(B * LANES * (R // LANES), LANES)
    ia, isl, rows, ib = parsed.base
    idx_s = dplan.idx[isl] if rows > 1 else None
    v = _base_call(v, dplan.idx[ia], idx_s, rows, dplan.idx[ib], interpret)
    last = len(parsed.ascents) - 1
    for j, (ai, B, R) in enumerate(parsed.ascents):
        v3 = v.reshape(B * LANES, R // LANES, LANES)
        v = _ascend_call(v3, dplan.idx[ai], B, R, epi if j == last else None, interpret)
    return v


def unfused_execute(dplan: DevicePlan, pro, epi, payload_dtype=jnp.float32) -> jax.Array:
    """Same semantics via plain XLA (stage-by-stage apply_plan): the CPU /
    fallback path and the reference for the fused kernels (including the
    payload-dtype entry rounding)."""
    S = dplan.size
    if isinstance(pro, Broadcast):
        x = jnp.broadcast_to(
            pro.vec[:, None], (pro.vec.shape[0], pro.group)
        ).reshape(-1)
    else:
        vals = _apply_transform(pro.values, pro.transform)
        x = vals * jnp.repeat(pro.vec, pro.group, total_repeat_length=S)
    x = x.astype(payload_dtype)
    y = apply_plan(dplan, x).astype(jnp.float32)
    if isinstance(epi, MulReduce):
        y = y * epi.values
    return y.reshape(-1, epi.group).sum(axis=1)


# --------------------------------------------------------------------------
# The feature-matrix engine built on fused execution.
# --------------------------------------------------------------------------


def _next_pow2(x: int) -> int:
    return 1 << max(int(x) - 1, 0).bit_length()


def fused_engine_works() -> bool:
    """One-time probe (cached per process): compile and run a tiny fused
    matvec/rmatvec on the current backend and check it against dense math.
    The estimator's "auto" engine choice consults this so a Mosaic lowering
    regression degrades to the stage-by-stage engine instead of crashing."""
    global _PROBE_RESULT
    if _PROBE_RESULT is None:
        _PROBE_RESULT = _run_probe()
    return _PROBE_RESULT


_PROBE_RESULT: Optional[bool] = None


def _run_probe() -> bool:
    if not pallas_available():
        return False
    try:
        rng = np.random.default_rng(0)
        n, d, nnz = 256, 200, 2000
        rows = rng.integers(0, n, nnz)
        cols = rng.integers(0, d, nnz)
        vals = rng.standard_normal(nnz).astype(np.float32)
        dense = np.zeros((n, d), np.float32)
        np.add.at(dense, (rows, cols), vals)
        feats = from_coo(
            rows, cols, vals, (n, d), max_hot_cols=0,
            size_floor=LANES * LANES, plan_cache="",
        )
        w = rng.standard_normal(d).astype(np.float32)
        z = np.asarray(jax.jit(feats.matvec)(jnp.asarray(w)))
        c = rng.standard_normal(n).astype(np.float32)
        g = np.asarray(jax.jit(feats.rmatvec)(jnp.asarray(c)))
        # tight tolerance on purpose: the kernels force Precision.HIGHEST,
        # so anything beyond f32 accumulation noise (e.g. a lowering that
        # silently drops to one-pass bf16 MXU matmuls, ~1e-3 error here but
        # ~1e-2 at production scale) must fail the probe and fall back
        ok = np.allclose(z, dense @ w, atol=3e-4) and np.allclose(
            g, dense.T @ c, atol=3e-4
        )
        if not ok:
            import logging

            logging.getLogger(__name__).warning(
                "fused permutation engine probe produced wrong values; "
                "falling back to the stage-by-stage engine"
            )
        return ok
    except Exception as e:  # pragma: no cover - backend-specific lowering
        import logging

        logging.getLogger(__name__).warning(
            "fused permutation engine unavailable on this backend (%s); "
            "falling back to the stage-by-stage engine", e
        )
        return False


@struct.dataclass
class FusedBenesFeatures:
    """Sparse [n, d] matrix with fused Benes-routed linear maps.

    Same FeatureMatrix protocol as ``BenesSparseFeatures``; stores one flat
    [S] ELL-slot value array instead of separate ELL/CSC copies. K and KP
    are power-of-two slot-group sizes; hot columns split to a dense MXU side
    exactly as in the unfused engine.
    """

    ell_flat: jax.Array       # [S] float32, p = row*K + k layout, 0 in pads
    plan: DevicePlan          # ELL -> CSC direction
    plan_inv: DevicePlan      # CSC -> ELL direction
    hot_matrix: Optional[jax.Array]
    hot_cols: Optional[jax.Array]
    num_rows_: int = struct.field(pytree_node=False)
    num_cols_: int = struct.field(pytree_node=False)
    ell_k: int = struct.field(pytree_node=False)   # K
    csc_k: int = struct.field(pytree_node=False)   # KP
    # Spill side (KP cap, sparse_perm.auto_kp_cap): over-cap entries
    # evaluated by gather/scatter-add; bounded by max(nnz/128, 4096)
    spill_rows: Optional[jax.Array] = None   # [M] int32
    spill_cols: Optional[jax.Array] = None   # [M] int32
    spill_vals: Optional[jax.Array] = None   # [M] float32
    # Storage dtype of the permuted network intermediates: "bfloat16"
    # halves the network's HBM traffic at one entry rounding per map
    # (stored values / reductions stay f32). Opt-in; relative error per
    # margin/gradient component is ~2^-8/sqrt(K).
    payload_dtype: str = struct.field(pytree_node=False, default="float32")

    @property
    def num_rows(self) -> int:
        return self.num_rows_

    @property
    def dim(self) -> int:
        return self.num_cols_

    @property
    def size(self) -> int:
        return self.plan.size

    def _fused_ok(self) -> bool:
        if not parse_plan(self.plan).descents:
            return False  # plan too small to have a recursion level
        return _INTERPRET or pallas_available()

    def _run(self, dplan, pro, epi) -> jax.Array:
        pdt = jnp.dtype(self.payload_dtype)
        if self._fused_ok():
            return fused_execute(dplan, pro, epi, payload_dtype=pdt)
        return unfused_execute(dplan, pro, epi, payload_dtype=pdt)

    def matvec(self, w: jax.Array) -> jax.Array:
        S, KP, K = self.size, self.csc_k, self.ell_k
        wp = jnp.zeros((S // KP,), w.dtype).at[: self.num_cols_].set(w)
        z = self._run(
            self.plan_inv, Broadcast(wp, KP), MulReduce(self.ell_flat, K)
        )[: self.num_rows_]
        if self.hot_matrix is not None:
            z = z + self.hot_matrix @ w[self.hot_cols]
        if self.spill_rows is not None:
            z = z.at[self.spill_rows].add(self.spill_vals * w[self.spill_cols])
        return z

    def rmatvec(self, c: jax.Array) -> jax.Array:
        return self._rmatvec_impl(c, transform="id")

    def rmatvec_sq(self, c: jax.Array) -> jax.Array:
        return self._rmatvec_impl(c, transform="sq")

    def _rmatvec_impl(self, c: jax.Array, transform: str) -> jax.Array:
        """X^T c with the stored values elementwise-transformed first
        ("id" / "sq" / "abs" / "nnz" — the latter two feed summary stats)."""
        S, KP, K = self.size, self.csc_k, self.ell_k
        cp = jnp.zeros((S // K,), c.dtype).at[: self.num_rows_].set(c)
        g = self._run(
            self.plan,
            MulBroadcast(self.ell_flat, cp, K, transform=transform),
            Reduce(KP),
        )[: self.num_cols_]
        if self.hot_matrix is not None:
            hot = _apply_transform(self.hot_matrix, transform)
            g = g.at[self.hot_cols].add(hot.T @ c)
        if self.spill_rows is not None:
            sv = _apply_transform(self.spill_vals, transform)
            g = g.at[self.spill_cols].add(sv * c[self.spill_rows])
        return g

    def csc_view(self, flat_ell: jax.Array) -> jax.Array:
        """Route an [S] ELL-slot array to the column-grouped side and return
        it as [d, KP] (one row per column). Stats-path utility — executes
        the plain stage-by-stage permutation, not the fused kernels."""
        d, KP = self.num_cols_, self.csc_k
        return apply_plan(self.plan, flat_ell)[: d * KP].reshape(d, KP)

    def weights_to_slots(self, weights: jax.Array) -> jax.Array:
        """Broadcast per-row weights [n] to ELL slot order [S]."""
        S, K = self.size, self.ell_k
        wp = jnp.zeros((S // K,), weights.dtype).at[: self.num_rows_].set(weights)
        return jnp.repeat(wp, K, total_repeat_length=S)

    def row_norms_sq(self) -> jax.Array:
        sq = (self.ell_flat * self.ell_flat).reshape(-1, self.ell_k).sum(axis=1)
        sq = sq[: self.num_rows_]
        if self.hot_matrix is not None:
            sq = sq + jnp.sum(self.hot_matrix * self.hot_matrix, axis=-1)
        if self.spill_rows is not None:
            sq = sq.at[self.spill_rows].add(self.spill_vals * self.spill_vals)
        return sq

    def to_dense(self) -> DenseFeatures:
        eye = jnp.eye(self.num_cols_, dtype=self.ell_flat.dtype)
        cols = jax.vmap(self.matvec, in_axes=1, out_axes=1)(eye)
        return DenseFeatures(matrix=cols)


def from_coo(
    rows,
    cols,
    vals,
    shape,
    max_nnz_row: Optional[int] = None,
    plan_cache: Optional[str] = None,
    hot_col_threshold: Optional[int] = None,
    max_hot_cols: int = 128,
    size_floor: int = 0,
    pin_k: int = 0,
    pin_kp: int = 0,
    kp_cap="auto",
    col_split="auto",
    payload_dtype: str = "float32",
):
    """Build from COO triplets; same contract as ``sparse_perm.from_coo``
    (including the default per-uid routing-plan cache and the ``kp_cap``
    spill side — see that docstring).

    ``pin_k`` / ``pin_kp`` / ``size_floor`` force common paddings across
    shards of one dataset (the grid builder stacks tiles under one compiled
    program); pins must be powers of two and at least the shard's actual
    degree (a too-small pin raises rather than silently diverging from the
    sibling shards). An explicit ``pin_kp`` disables the auto cap.
    """
    from photon_ml_tpu.ops.sparse_perm import (
        build_column_split,
        make_row_block_k,
        prepare_cold_entries,
        resolve_layout,
        split_spill_entries,
    )

    if (pin_k or pin_kp) and (
        kp_cap not in ("auto", None, 0)
        or col_split not in ("auto", None, 0, 1)
    ):
        raise ValueError(
            "pin_k/pin_kp force the flat layout across sibling shards; an "
            "explicit kp_cap/col_split cannot be honored alongside them "
            "(drop the pins or the explicit layout)"
        )
    n, d = shape
    rows, cols, vals, hot_matrix, hot_ids, row_counts, col_counts = (
        prepare_cold_entries(
            rows, cols, vals, shape, max_nnz_row, hot_col_threshold, max_hot_cols
        )
    )
    nnz = rows.size
    K = max(
        _next_pow2(int(row_counts.max()) if nnz else 1),
        _next_pow2(int(max_nnz_row)) if max_nnz_row is not None else 1,
        1,
    )
    KP = max(_next_pow2(int(col_counts.max()) if nnz else 1), 1)
    spill = (None, None, None)
    # pinned paddings promise shape stability across sibling shards: the
    # layout planner must not replace the flat layout behind them
    if nnz and not pin_k and not pin_kp:
        cap, t = resolve_layout(
            kp_cap, col_split, col_counts, n, d, K, KP,
            size_floor=size_floor,
            row_block_k=make_row_block_k(rows, cols, n, d, pow2=True),
        )
        if t > 1:
            import functools

            return build_column_split(
                functools.partial(from_coo, payload_dtype=payload_dtype),
                rows, cols, vals, n, d, t, cap,
                hot_matrix, hot_ids, plan_cache,
            )
        if cap is not None:
            rows, cols, vals, sr, sc, sv = split_spill_entries(
                rows, cols, vals, col_counts, cap
            )
            spill = (sr, sc, sv)
            row_counts = np.bincount(rows, minlength=n)
            col_counts = np.minimum(col_counts, cap)
            KP = cap
    for name, pin, needed in (("pin_k", pin_k, K), ("pin_kp", pin_kp, KP)):
        if not pin:
            continue
        if pin & (pin - 1):
            raise ValueError(f"{name}={pin} must be a power of two")
        if pin < needed:
            raise ValueError(f"{name}={pin} below required group size {needed}")
    K = max(K, pin_k)
    KP = max(KP, pin_kp)
    return assemble(
        rows, cols, vals, n, d, K, KP, hot_matrix, hot_ids, plan_cache,
        size_floor=size_floor, row_counts=row_counts, col_counts=col_counts,
        spill=spill, payload_dtype=payload_dtype,
    )


def assemble(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n: int,
    d: int,
    K: int,
    KP: int,
    hot_matrix: Optional[np.ndarray],
    hot_ids: Optional[np.ndarray],
    plan_cache: Optional[str],
    size_floor: int = 0,
    row_counts: Optional[np.ndarray] = None,
    col_counts: Optional[np.ndarray] = None,
    spill=(None, None, None),
    payload_dtype: str = "float32",
) -> FusedBenesFeatures:
    """Route + lay out prepared cold entries with pinned power-of-two
    paddings — the fused twin of ``sparse_perm._assemble`` (the grid builder
    stacks identically-shaped tiles built through this)."""
    assert K & (K - 1) == 0 and KP & (KP - 1) == 0, "group sizes must be pow2"
    for name, group in (("K", K), ("KP", KP)):
        if group > MAX_FUSED_GROUP:
            raise FusedGroupTooLarge(
                f"slot group {name}={group} exceeds the fused executor's "
                f"limit of {MAX_FUSED_GROUP} (a row/column with more nonzeros "
                "than that after hot-column splitting, or a pin_k/pin_kp/"
                "cross-tile pad that large); use engine='benes' for this shard"
            )

    from photon_ml_tpu.ops.sparse_perm import route_layout

    ell_pos, _, plan, plan_inv, S = route_layout(
        rows, cols, n, d, K, KP, plan_cache, size_floor, row_counts, col_counts
    )

    ell_flat = np.zeros(S, dtype=np.float32)
    ell_flat[ell_pos] = vals

    from photon_ml_tpu.ops.sparse_perm import _spill_arrays

    sr, sc, sv = _spill_arrays(*spill)
    return FusedBenesFeatures(
        ell_flat=jnp.asarray(ell_flat),
        plan=device_plan(plan),
        plan_inv=device_plan(plan_inv),
        hot_matrix=None if hot_matrix is None else jnp.asarray(hot_matrix),
        hot_cols=None if hot_ids is None else jnp.asarray(hot_ids, dtype=jnp.int32),
        num_rows_=int(n),
        num_cols_=int(d),
        ell_k=int(K),
        csc_k=int(KP),
        spill_rows=sr,
        spill_cols=sc,
        spill_vals=sv,
        payload_dtype=payload_dtype,
    )
