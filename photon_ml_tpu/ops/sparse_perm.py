"""Benes-routed sparse feature matrix: TPU-native large-d GLM compute.

The fixed-effect problem multiplies a huge sparse matrix (n rows, up to 1e9
columns, ~constant nnz/row) by dense vectors in both directions every
optimizer iteration (reference hot loop: ValueAndGradientAggregator
.scala:132-153). XLA's gather/scatter lower to ~10ns/element scalar loops on
TPU, so instead both directions are expressed with only dense vector
primitives and ONE static data movement:

- ``matvec`` (z = X w): broadcast w over the column-grouped (CSC-ELL) slot
  grid — a free relayout — then apply the inverse Benes permutation to land
  each w value at its row-grouped (ELL) slot, multiply by the stored values
  and row-sum. No gather.
- ``rmatvec`` (g = X^T c): broadcast c over ELL slots (free), apply the
  forward permutation to column-grouped slots, row-sum per column. The
  scatter-add became a padded segmented sum.

The permutation is routed once at prep time (ops/routing.py) and executed as
~2*log_128(S)-1 lane-shuffle passes (ops/permute_net.py). Cost per linear
map is a handful of full passes over the nnz arrays at HBM speed — the same
asymptotics as the reference's per-partition sparse axpy, but vectorized.

Layouts (S = routed network size, a padded power-of-128 multiple):

- ELL side: flat [S] position p = row * K + k for p < n*K (row-major slots,
  K = padded max nnz/row); positions >= n*K are dead padding.
- CSC side: flat [S] position q = col * KP + k' for q < d*KP (column-major
  slots, KP = padded max nnz/col); q >= d*KP dead.
- ``plan`` maps CSC position q -> ELL position p for real entries and pads
  to pads (a bijection on [0, S)); ``plan_inv`` is its inverse.
"""

from __future__ import annotations

from typing import Optional

import os

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from photon_ml_tpu.ops import routing
from photon_ml_tpu.utils.nativesort import lexsort_pairs
from photon_ml_tpu.ops.permute_net import DevicePlan, apply_plan, device_plan


@struct.dataclass
class BenesSparseFeatures:
    """Sparse [n, d] feature matrix with Benes-routed linear maps.

    Drop-in sibling of ``ops.features.EllFeatures`` (same matvec/rmatvec/
    rmatvec_sq/row_norms_sq protocol) for the large-d fixed-effect path.

    High-degree ("hot") columns — intercept and frequent features, whose
    degree would otherwise set the CSC padding KP and blow up the routed
    network — are split out into a dense [n, H] side matrix that rides the
    MXU directly (z += X_hot @ w[hot_cols]; g[hot_cols] += X_hot^T c). The
    long tail stays in the permutation-routed sparse engine. The reference
    has no analog (Breeze sparse axpy is degree-oblivious); on TPU the
    split is what keeps both sides dense-regular.
    """

    ell_values: jax.Array     # [n, K] float32, 0 in padding slots
    csc_values: jax.Array     # [d, KP] float32, 0 in padding slots (= routed
                              # ell_values; stored to skip one permute)
    plan: DevicePlan          # CSC position q -> ELL position p
    plan_inv: DevicePlan      # ELL position p -> CSC position q
    hot_matrix: Optional[jax.Array]  # [n, H] dense hot columns (or None)
    hot_cols: Optional[jax.Array]    # [H] int32 original column ids
    num_rows_: int = struct.field(pytree_node=False)
    num_cols_: int = struct.field(pytree_node=False)
    # Spill side (KP cap, see plan_column_layout): entries beyond each
    # column's ``cap`` routed slots, evaluated by gather/scatter-add. The
    # auto planner prices each spilled entry at _spill_slot_cost() routed
    # slots and hard-bounds spill at max(nnz/8, 4096), so the scatter side
    # stays a small fraction of the network cost by construction.
    spill_rows: Optional[jax.Array] = None   # [M] int32
    spill_cols: Optional[jax.Array] = None   # [M] int32
    spill_vals: Optional[jax.Array] = None   # [M] float32

    @property
    def num_rows(self) -> int:
        return self.num_rows_

    @property
    def dim(self) -> int:
        return self.num_cols_

    @property
    def ell_k(self) -> int:
        return self.ell_values.shape[1]

    @property
    def csc_k(self) -> int:
        return self.csc_values.shape[1]

    def _to_ell(self, csc_flat: jax.Array) -> jax.Array:
        """Move a CSC-slot array into ELL slot order."""
        return apply_plan(self.plan_inv, csc_flat)

    def _to_csc(self, ell_flat: jax.Array) -> jax.Array:
        """Move an ELL-slot array into CSC slot order."""
        return apply_plan(self.plan, ell_flat)

    def _pad_ell(self, flat: jax.Array) -> jax.Array:
        return jnp.zeros(self.plan.size, flat.dtype).at[: flat.shape[0]].set(flat)

    def matvec(self, w: jax.Array) -> jax.Array:
        n, k = self.ell_values.shape
        d, kp = self.csc_values.shape
        wexp = jnp.broadcast_to(w[:, None], (d, kp)).reshape(-1)
        wexp = self._pad_ell(wexp) if wexp.shape[0] < self.plan.size else wexp
        w_ell = self._to_ell(wexp)[: n * k].reshape(n, k)
        z = jnp.sum(self.ell_values * w_ell, axis=-1)
        if self.hot_matrix is not None:
            z = z + self.hot_matrix @ w[self.hot_cols]
        if self.spill_rows is not None:
            z = z.at[self.spill_rows].add(self.spill_vals * w[self.spill_cols])
        return z

    def rmatvec(self, c: jax.Array) -> jax.Array:
        return self._rmatvec_impl(
            self.ell_values, self.hot_matrix, c, self.spill_vals
        )

    def rmatvec_sq(self, c: jax.Array) -> jax.Array:
        hot_sq = None if self.hot_matrix is None else self.hot_matrix * self.hot_matrix
        return self._rmatvec_impl(
            self.ell_values * self.ell_values, hot_sq, c,
            None if self.spill_vals is None
            else self.spill_vals * self.spill_vals,
        )

    def _rmatvec_impl(
        self,
        vals: jax.Array,
        hot: Optional[jax.Array],
        c: jax.Array,
        spill_vals: Optional[jax.Array] = None,
    ) -> jax.Array:
        n, k = vals.shape
        d, kp = self.csc_values.shape
        t = (vals * c[:, None]).reshape(-1)
        t = self._pad_ell(t) if t.shape[0] < self.plan.size else t
        t_csc = self._to_csc(t)[: d * kp].reshape(d, kp)
        g = jnp.sum(t_csc, axis=-1)
        if hot is not None:
            g = g.at[self.hot_cols].add(hot.T @ c)
        if spill_vals is not None:
            g = g.at[self.spill_cols].add(spill_vals * c[self.spill_rows])
        return g

    def row_norms_sq(self) -> jax.Array:
        sq = jnp.sum(self.ell_values * self.ell_values, axis=-1)
        if self.hot_matrix is not None:
            sq = sq + jnp.sum(self.hot_matrix * self.hot_matrix, axis=-1)
        if self.spill_rows is not None:
            sq = sq.at[self.spill_rows].add(self.spill_vals * self.spill_vals)
        return sq

    def to_dense(self):
        """Densify via one matvec per unit vector — test-scale only."""
        from photon_ml_tpu.ops.features import DenseFeatures

        eye = jnp.eye(self.num_cols_, dtype=self.ell_values.dtype)
        cols = jax.vmap(self.matvec, in_axes=1, out_axes=1)(eye)
        return DenseFeatures(matrix=cols)


@struct.dataclass
class ColumnSplitFeatures:
    """Sparse [n, d] matrix as independent column-block engines.

    The routed network's valid sizes step c*128^k with c in {1,2,4,8}
    (routing.valid_size), so a shard whose d*KP lands just past 8*128^k pays
    up to 16x slot padding (the 1B-coefficient layout's 2^24-column chip
    tile: d*KP = 2^26 rounds to 2^28). Splitting the column space into B
    blocks gives B networks of total size ~B * valid_size(d*KP/B) — back on
    the ladder — at the cost of B kernel dispatches per linear map inside
    one jit program. Every block is a full engine (own hot/spill sides);
    results are exact sums/concats of block results.
    """

    blocks: tuple                      # sub-engines (pytree node)
    # global hot-column dense side (ids in GLOBAL column space) — kept
    # outside the blocks so one [n, H] matmul serves the whole matrix
    hot_matrix: Optional[jax.Array]
    hot_cols: Optional[jax.Array]
    col_bounds: tuple = struct.field(pytree_node=False)  # len(blocks)+1 ints
    num_rows_: int = struct.field(pytree_node=False)
    num_cols_: int = struct.field(pytree_node=False)

    @property
    def num_rows(self) -> int:
        return self.num_rows_

    @property
    def dim(self) -> int:
        return self.num_cols_

    def _block_w(self, w: jax.Array, b: int) -> jax.Array:
        """w slice for block b, zero-padded to the block's width (pinned
        grid layouts give every block a uniform width that may overhang
        the true column count at the end)."""
        wb = w[self.col_bounds[b]: self.col_bounds[b + 1]]
        width = self.blocks[b].dim
        if wb.shape[0] < width:
            wb = jnp.pad(wb, (0, width - wb.shape[0]))
        return wb

    def matvec(self, w: jax.Array) -> jax.Array:
        z = None
        for b, blk in enumerate(self.blocks):
            zb = blk.matvec(self._block_w(w, b))
            z = zb if z is None else z + zb
        if self.hot_matrix is not None:
            z = z + self.hot_matrix @ w[self.hot_cols]
        return z

    def rmatvec(self, c: jax.Array) -> jax.Array:
        g = jnp.concatenate(
            [blk.rmatvec(c) for blk in self.blocks]
        )[: self.num_cols_]
        if self.hot_matrix is not None:
            g = g.at[self.hot_cols].add(self.hot_matrix.T @ c)
        return g

    def rmatvec_sq(self, c: jax.Array) -> jax.Array:
        g = jnp.concatenate(
            [blk.rmatvec_sq(c) for blk in self.blocks]
        )[: self.num_cols_]
        if self.hot_matrix is not None:
            hm2 = self.hot_matrix * self.hot_matrix
            g = g.at[self.hot_cols].add(hm2.T @ c)
        return g

    def row_norms_sq(self) -> jax.Array:
        sq = None
        for blk in self.blocks:
            sb = blk.row_norms_sq()
            sq = sb if sq is None else sq + sb
        if self.hot_matrix is not None:
            sq = sq + jnp.sum(self.hot_matrix * self.hot_matrix, axis=-1)
        return sq

    def to_dense(self):
        from photon_ml_tpu.ops.features import DenseFeatures

        mats = [np.asarray(blk.to_dense().matrix) for blk in self.blocks]
        # pinned grid layouts give uniform block widths that may overhang
        # the true column count; trim like rmatvec does
        dense = np.concatenate(mats, axis=1)[:, : self.num_cols_]
        if self.hot_matrix is not None:
            dense[:, np.asarray(self.hot_cols)] += np.asarray(self.hot_matrix)
        return DenseFeatures(matrix=jnp.asarray(dense))


@struct.dataclass
class _ZeroColumnsBlock:
    """A column block with no entries: all maps are exact zeros."""

    num_rows_: int = struct.field(pytree_node=False)
    num_cols_: int = struct.field(pytree_node=False)

    @property
    def num_rows(self) -> int:
        return self.num_rows_

    @property
    def dim(self) -> int:
        return self.num_cols_

    def matvec(self, w: jax.Array) -> jax.Array:
        return jnp.zeros((self.num_rows_,), dtype=w.dtype)

    def rmatvec(self, c: jax.Array) -> jax.Array:
        return jnp.zeros((self.num_cols_,), dtype=c.dtype)

    rmatvec_sq = rmatvec

    def row_norms_sq(self) -> jax.Array:
        return jnp.zeros((self.num_rows_,), dtype=jnp.float32)

    def to_dense(self):
        from photon_ml_tpu.ops.features import DenseFeatures

        return DenseFeatures(
            matrix=jnp.zeros((self.num_rows_, self.num_cols_), jnp.float32)
        )


# One spilled (over-cap) entry costs about this many routed slots. A COO
# gather + scatter-add runs ~7-10 ns/entry on TPU (SCALING.md measurement)
# while a routed slot moves ~45 B through ~2m+1 kernel passes — ~2 ns at
# the currently-achieved ~25 GB/s but ~0.06 ns at peak HBM, so the right
# ratio is bandwidth-dependent. The default 32 is conservative (prefers
# routing over spill when in doubt); PHOTON_SPILL_SLOT_COST lets the
# hardware measurement session calibrate it. Keeping this a COST (not a
# hard budget) is what lets a thin-tailed 2^26-column shard take a small
# cap + split instead of a 16x-padded flat network (the r5 planner fix).
def _spill_slot_cost() -> int:
    try:
        return max(int(os.environ.get("PHOTON_SPILL_SLOT_COST", "32")), 1)
    except ValueError:
        return 32


# Hard sanity bound: spill stays a small fraction of nnz so the device COO
# arrays and the scatter remain negligible next to the routed network.
_MAX_SPILL_FRACTION = 8  # spill <= nnz / 8


def plan_column_layout(
    col_counts: np.ndarray,
    n: int,
    d: int,
    K: int,
    kp_full: int,
    max_blocks: int = 16,
    size_floor: int = 0,
    row_block_k: Optional["callable"] = None,
    spill_scale: float = 1.0,
):
    """Jointly pick (kp_cap, n_col_blocks) minimizing total cost in routed
    slots, where over-cap (spilled) entries are priced at SPILL_SLOT_COST
    slots each.

    The levers interact through the coarse valid-size ladder (c*128^k,
    c in {1,2,4,8}): capping KP alone may not cross a ladder step, and
    splitting alone multiplies the uncapped d*KP. Candidates: every
    power-of-two cap whose spill stays under nnz/8, crossed with block
    counts {1,2,...,max_blocks}. ``row_block_k(t)`` optionally returns the
    true per-block row group size for a t-way column split (each block
    holds only its columns' entries, so its K is smaller than the global
    K); without it the global K bounds the row side. ``spill_scale``
    normalizes the spill cost to the network-size units: a multi-tile grid
    passes counts concatenated over all tiles while n/d describe ONE tile,
    so it passes 1/num_tiles to keep both sides per-tile. Returns
    ``(cap_or_None, n_blocks)``; a multi-block layout must beat the plain
    one by >= 2x in total cost to justify the extra dispatches.
    """
    nnz = int(col_counts.sum())
    s_plain = routing.valid_size(max(n * K, d * kp_full, size_floor, 1))
    if not nnz or (kp_full <= 1 and d <= 1):
        return None, 1
    max_spill = max(nnz // _MAX_SPILL_FRACTION, 4096)
    cands = []
    p = 1
    while p < kp_full:
        cands.append(p)
        p *= 2
    cands.append(kp_full)  # the uncapped candidate (spill 0), ALWAYS kept
    caps = []  # (cap, spill_cost)
    for p in cands:
        spill = (
            0 if p >= kp_full
            else int(np.maximum(col_counts - p, 0).sum())
        )
        if spill <= max_spill:
            caps.append((p, spill * _spill_slot_cost() * spill_scale))
    best = (None, 1, s_plain)
    for cap, spill_cost in caps:
        t = 1
        while t <= max_blocks:
            d_b = -(-d // t)
            k_t = row_block_k(t) if (row_block_k and t > 1) else K
            s_t = t * routing.valid_size(
                max(n * k_t, d_b * cap, size_floor, 1)
            ) + spill_cost
            if s_t < best[2]:
                best = (None if cap >= kp_full else cap, t, s_t)
            t *= 2
    cap, t, s_best = best
    if t > 1 and s_best * 2 > s_plain:
        # a multi-block layout must be a clear (2x) win; fall back to the
        # best single-block layout if capping alone still helps
        best_cap, best_cost = None, s_plain
        for cap, spill_cost in caps:
            if cap >= kp_full:
                continue
            cost = routing.valid_size(
                max(n * K, d * cap, size_floor, 1)
            ) + spill_cost
            if cost < best_cost:
                best_cap, best_cost = cap, cost
        return best_cap, 1
    return cap, t


def make_row_block_k(rows, cols, n: int, d: int, pow2: bool = False):
    """Per-block row group size estimator for the layout planner: for a
    t-way column split, the max nnz any single row holds within one block
    (each block sees only its columns' entries, so its ELL width K is
    smaller than the global K). Memoized per t; ``pow2`` rounds up for the
    fused engine's power-of-two slot groups."""
    cache: dict = {}

    def row_block_k(t: int) -> int:
        if t not in cache:
            d_b = -(-d // t)
            key = rows * t + (cols // d_b)
            # unique, not bincount: memory stays O(nnz) (a bincount over
            # n*t bins would transiently allocate ~13 GB at n=1e8, t=16)
            if key.size:
                _, counts = np.unique(key, return_counts=True)
                k = int(counts.max())
            else:
                k = 1
            if pow2:
                k = 1 << max(int(k) - 1, 0).bit_length()
            cache[t] = max(k, 1)
        return cache[t]

    return row_block_k


def resolve_kp_cap(
    kp_cap,
    col_counts: np.ndarray,
    n: int,
    d: int,
    K: int,
    kp_full: int,
    size_floor: int = 0,
) -> Optional[int]:
    """Normalize a ``kp_cap`` argument ("auto" | int | None/0) to an
    effective cap strictly below ``kp_full``, or None."""
    if not kp_cap:
        return None
    if kp_cap == "auto":
        return auto_kp_cap(col_counts, n, d, K, kp_full, size_floor)
    cap = int(kp_cap)
    if cap <= 0 or cap >= kp_full:
        return None
    if cap & (cap - 1):
        raise ValueError(f"kp_cap={cap} must be a power of two (or 'auto')")
    return cap


def build_column_split(
    builder,
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n: int,
    d: int,
    t: int,
    cap: Optional[int],
    hot_matrix: Optional[np.ndarray],
    hot_ids: Optional[np.ndarray],
    plan_cache: Optional[str],
) -> ColumnSplitFeatures:
    """Partition COLD entries into ``t`` column blocks and build each with
    ``builder`` (a from_coo-compatible callable); the hot side stays global.
    Shared by the stage-by-stage and fused engines."""
    d_b = -(-d // t)
    bounds = [min(b * d_b, d) for b in range(t + 1)]
    blk_of = cols // d_b
    blocks = []
    for b in range(t):
        width = bounds[b + 1] - bounds[b]
        m = blk_of == b
        if width <= 0 or not m.any():
            blocks.append(_ZeroColumnsBlock(num_rows_=n, num_cols_=max(width, 0)))
            continue
        blocks.append(
            builder(
                rows[m], cols[m] - bounds[b], vals[m], (n, width),
                plan_cache=plan_cache, max_hot_cols=0,
                kp_cap=cap, col_split=1,
            )
        )
    return ColumnSplitFeatures(
        blocks=tuple(blocks),
        hot_matrix=None if hot_matrix is None else jnp.asarray(hot_matrix),
        hot_cols=(
            None if hot_ids is None else jnp.asarray(hot_ids, dtype=jnp.int32)
        ),
        col_bounds=tuple(bounds),
        num_rows_=int(n),
        num_cols_=int(d),
    )


def _best_split(
    n: int, d: int, K: int, kp_eff: int, max_blocks: int = 16,
    size_floor: int = 0,
) -> int:
    """Best block count for a FIXED effective KP (2x-win hysteresis)."""
    s_one = routing.valid_size(max(n * K, d * kp_eff, size_floor, 1))
    best_t, best_s = 1, s_one
    t = 2
    while t <= max_blocks:
        s_t = t * routing.valid_size(
            max(n * K, -(-d // t) * kp_eff, size_floor, 1)
        )
        if s_t < best_s:
            best_t, best_s = t, s_t
        t *= 2
    return best_t if best_s * 2 <= s_one else 1


def resolve_layout(kp_cap, col_split, col_counts, n, d, K, kp_full,
                   size_floor: int = 0, row_block_k=None,
                   spill_scale: float = 1.0):
    """Normalize (kp_cap, col_split) arguments to an effective
    ``(cap_or_None, n_blocks)`` layout. "auto"/"auto" runs the joint
    planner; manual values are validated and used as-is."""
    if kp_cap == "auto" and col_split == "auto":
        return plan_column_layout(
            col_counts, n, d, K, kp_full, size_floor=size_floor,
            row_block_k=row_block_k, spill_scale=spill_scale,
        )
    cap = resolve_kp_cap(kp_cap, col_counts, n, d, K, kp_full, size_floor)
    if col_split == "auto":
        t = _best_split(n, d, K, cap or kp_full, size_floor=size_floor)
    else:
        t = max(int(col_split or 1), 1)
        if t > 1 and t & (t - 1):
            raise ValueError(f"col_split={t} must be a power of two")
    return cap, t


def from_coo(
    rows,
    cols,
    vals,
    shape,
    max_nnz_row: Optional[int] = None,
    plan_cache: Optional[str] = None,
    hot_col_threshold: Optional[int] = None,
    max_hot_cols: int = 128,
    kp_cap="auto",
    col_split="auto",
):
    """Build from COO triplets (host, vectorized numpy + one Benes routing).

    Duplicates are coalesced by summation (scipy COO semantics). The routing
    is the expensive one-time prep step (seconds to ~a minute at 1e7 nnz —
    the analog of the reference's one-time RDD dataset build). It is
    memoized keyed on the sparsity pattern: by default in a per-uid tempdir
    (~25 MB-1 GB of .npz per distinct large pattern; set
    ``PHOTON_ML_TPU_PLAN_CACHE`` to another directory, or to "" to disable),
    or pass ``plan_cache`` (a directory) explicitly.

    Columns with degree > ``hot_col_threshold`` (default: auto — 4x the mean
    column degree, at least 8) are split into a dense MXU side matrix, capped
    at the ``max_hot_cols`` highest-degree columns. Without the split an
    intercept column (degree n) would pad every CSC column to n slots. Pass
    ``max_hot_cols=0`` to disable.

    ``kp_cap`` ("auto" default) additionally bounds the CSC padding KP when
    the column-degree tail is thin, spilling the over-cap entries to a
    scatter-add side (auto/auto runs :func:`plan_column_layout`, which
    prices spill at _spill_slot_cost() slots per entry and bounds it at
    nnz/8); pass None/0 to disable or a power of two to pin the cap.
    ``col_split`` ("auto" default) may
    partition the column space into independent sub-networks when the
    valid-size ladder would otherwise overshoot (see
    :class:`ColumnSplitFeatures`); the result then is a ColumnSplitFeatures.
    """
    n, d = shape
    rows, cols, vals, hot_matrix, hot_ids, row_counts, col_counts = (
        prepare_cold_entries(
            rows, cols, vals, shape, max_nnz_row, hot_col_threshold, max_hot_cols
        )
    )
    nnz = rows.size
    k_needed = int(row_counts.max()) if nnz else 1
    # max_nnz_row doubles as a K floor so callers get shape-stable [n, K]
    # ELL arrays across datasets (one jit compilation serves them all).
    K = max(k_needed, int(max_nnz_row) if max_nnz_row is not None else 1, 1)
    KP = max(int(col_counts.max()) if nnz else 1, 1)

    cap, t = (None, 1)
    if nnz:
        cap, t = resolve_layout(
            kp_cap, col_split, col_counts, n, d, K, KP,
            row_block_k=make_row_block_k(rows, cols, n, d),
        )
    if t > 1:
        return build_column_split(
            from_coo, rows, cols, vals, n, d, t, cap,
            hot_matrix, hot_ids, plan_cache,
        )

    spill = (None, None, None)
    if cap is not None:
        rows, cols, vals, sr, sc, sv = split_spill_entries(
            rows, cols, vals, col_counts, cap
        )
        spill = (sr, sc, sv)
        row_counts = np.bincount(rows, minlength=n)
        col_counts = np.minimum(col_counts, cap)
        KP = cap

    return _assemble(
        rows, cols, vals, n, d, K, KP, hot_matrix, hot_ids, plan_cache,
        row_counts=row_counts, col_counts=col_counts, spill=spill,
    )


def prepare_cold_entries(
    rows,
    cols,
    vals,
    shape,
    max_nnz_row: Optional[int],
    hot_col_threshold: Optional[int],
    max_hot_cols: int,
):
    """Shared builder prologue: coalesce, validate ``max_nnz_row``, split hot
    columns, count degrees. Returns ``(rows, cols, vals, hot_matrix, hot_ids,
    row_counts, col_counts)`` with rows/cols/vals reduced to cold entries.
    Used by both permutation engines so their data prep stays in lockstep.
    """
    n, d = shape
    rows, cols, vals = coalesce_coo(rows, cols, vals, n, d)

    nnz = rows.size
    if max_nnz_row is not None and nnz:
        k_orig = int(np.bincount(rows, minlength=n).max())
        if k_orig > int(max_nnz_row):
            raise ValueError(
                f"row with {k_orig} nnz exceeds max_nnz_row={max_nnz_row}"
            )

    hot_ids = select_hot_cols(
        rows, cols, n, d, hot_col_threshold, max_hot_cols
    )
    hot_matrix = None
    if hot_ids is not None:
        rows, cols, vals, hot_matrix = split_hot_entries(
            rows, cols, vals, n, d, hot_ids
        )
        nnz = rows.size

    row_counts = np.bincount(rows, minlength=n) if nnz else np.zeros(n, np.int64)
    col_counts = np.bincount(cols, minlength=d) if nnz else np.zeros(d, np.int64)
    return rows, cols, vals, hot_matrix, hot_ids, row_counts, col_counts


def auto_kp_cap(
    col_counts: np.ndarray,
    n: int,
    d: int,
    K: int,
    kp_full: int,
    size_floor: int = 0,
) -> Optional[int]:
    """Pick a power-of-two cap on the CSC slot-group size KP, or None.

    The routed network is sized S = valid_size(max(n*K, d*KP, floor)). When
    column degrees have a thin tail (e.g. the 1B-coefficient grid shard:
    mean degree ~1, max ~12), KP = max degree pads the network by the
    max/mean ratio. Capping KP and spilling each column's entries beyond the
    cap to a tiny COO side (scatter-add at evaluation) shrinks S by that
    ratio. The cap is the smallest power of two whose spill stays under
    nnz/128 (scatter cost negligible next to the routed passes), applied
    only when it actually shrinks S.
    """
    nnz = int(col_counts.sum())
    if not nnz or kp_full <= 1:
        return None
    s_now = routing.valid_size(max(n * K, d * kp_full, size_floor, 1))
    budget = max(nnz // 128, 4096)
    p = 1
    while p < kp_full:
        spill = int(np.maximum(col_counts - p, 0).sum())
        if spill <= budget:
            s_new = routing.valid_size(max(n * K, d * p, size_floor, 1))
            return p if s_new < s_now else None
        p *= 2
    return None


def split_spill_entries(rows, cols, vals, col_counts: np.ndarray, cap: int):
    """Split entries so every column keeps at most ``cap`` routed entries.

    Returns ``(cold_rows, cold_cols, cold_vals, spill_rows, spill_cols,
    spill_vals)``. Kept entries are each column's first ``cap`` in (col,
    row) order — deterministic for plan-cache stability.
    """
    nnz = rows.size
    corder = lexsort_pairs(cols, rows)
    col_starts = np.zeros(col_counts.size + 1, dtype=np.int64)
    np.cumsum(col_counts, out=col_starts[1:])
    rank = np.arange(nnz, dtype=np.int64) - col_starts[cols[corder]]
    spill_sorted = rank >= cap
    spill = np.zeros(nnz, dtype=bool)
    spill[corder] = spill_sorted
    keep = ~spill
    return (
        rows[keep], cols[keep], vals[keep],
        rows[spill], cols[spill], vals[spill],
    )


def _spill_arrays(spill_rows, spill_cols, spill_vals):
    """Device arrays for a spill side (None when empty)."""
    if spill_rows is None or spill_rows.size == 0:
        return None, None, None
    return (
        jnp.asarray(spill_rows, dtype=jnp.int32),
        jnp.asarray(spill_cols, dtype=jnp.int32),
        jnp.asarray(spill_vals, dtype=jnp.float32),
    )


def coalesce_coo(rows, cols, vals, n: int, d: int):
    """Validate index ranges and coalesce duplicate (row, col) entries by
    summation (scipy COO semantics; accumulation in float64)."""
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    vals = np.asarray(vals, dtype=np.float32)
    if rows.size:
        if rows.min() < 0 or rows.max() >= n:
            raise ValueError(f"row index out of range [0, {n})")
        if cols.min() < 0 or cols.max() >= d:
            raise ValueError(f"column index out of range [0, {d})")
        order = lexsort_pairs(rows, cols)
        rows, cols, vals = rows[order], cols[order], vals[order]
        boundary = np.empty(rows.size, dtype=bool)
        boundary[0] = True
        boundary[1:] = (rows[1:] != rows[:-1]) | (cols[1:] != cols[:-1])
        seg = np.cumsum(boundary) - 1
        summed = np.zeros(int(boundary.sum()), dtype=np.float64)
        np.add.at(summed, seg, vals)
        rows, cols = rows[boundary], cols[boundary]
        vals = summed.astype(np.float32)
    return rows, cols, vals


def select_hot_cols(
    rows: np.ndarray,
    cols: np.ndarray,
    n_rows_per_shard: int,
    d: int,
    hot_col_threshold: Optional[int],
    max_hot_cols: int,
) -> Optional[np.ndarray]:
    """Pick the hot-column set (sorted ids) or None.

    A column only qualifies when densifying it is actually cheap: degree
    >= n/16 bounds the dense-storage inflation at 16x the entries moved
    (mildly-hot columns would waste n floats each for little KP relief).
    The n*H dense block is further capped at ~512 MB. ``n_rows_per_shard``
    is the dense side's row count (the local row count for sharded data).
    """
    nnz = rows.size
    if not nnz or max_hot_cols <= 0:
        return None
    col_counts_all = np.bincount(cols, minlength=d)
    if hot_col_threshold is None:
        thr = max(8, int(4 * np.ceil(nnz / max(d, 1))), n_rows_per_shard // 16)
    else:
        thr = int(hot_col_threshold)
    h_cap = min(
        int(max_hot_cols), max(1, (128 << 20) // max(n_rows_per_shard, 1))
    )
    hot_mask = col_counts_all > thr
    n_hot = int(hot_mask.sum())
    if n_hot > h_cap:
        top = np.argpartition(col_counts_all, -h_cap)[-h_cap:]
        return np.sort(top)
    if n_hot > 0:
        return np.flatnonzero(hot_mask)
    return None


def split_hot_entries(rows, cols, vals, n: int, d: int, hot_ids: np.ndarray):
    """Split entries into (cold rows/cols/vals, dense [n, H] hot matrix)."""
    hot_pos = np.full(d, -1, dtype=np.int64)
    hot_pos[hot_ids] = np.arange(hot_ids.size)
    is_hot = hot_pos[cols] >= 0
    hot_matrix = np.zeros((n, hot_ids.size), dtype=np.float32)
    hot_matrix[rows[is_hot], hot_pos[cols[is_hot]]] = vals[is_hot]
    return rows[~is_hot], cols[~is_hot], vals[~is_hot], hot_matrix


def build_slot_perm(
    rows: np.ndarray,
    cols: np.ndarray,
    n: int,
    d: int,
    K: int,
    KP: int,
    S: int,
    row_counts: np.ndarray,
    col_counts: np.ndarray,
):
    """(ell_pos, csc_pos, perm) for one routed layout.

    ell_pos[e]: ELL slot of entry e (row-major position row*K + slot).
    csc_pos[e]: CSC slot of entry e (column-major position col*KP + slot).
    perm: bijection on [0, S) with perm[q] = p for real entries and pads
    mapped to pads in ascending order. Shared by the stage-by-stage and
    fused engines so both route identical networks for one pattern.
    """
    nnz = rows.size
    row_starts = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(row_counts, out=row_starts[1:])
    ell_slot = np.arange(nnz, dtype=np.int64) - row_starts[rows]
    ell_pos = rows * K + ell_slot

    corder = lexsort_pairs(cols, rows)
    col_starts = np.zeros(d + 1, dtype=np.int64)
    np.cumsum(col_counts, out=col_starts[1:])
    csc_slot = np.arange(nnz, dtype=np.int64) - col_starts[cols[corder]]
    csc_pos_sorted = cols[corder] * KP + csc_slot
    csc_pos = np.empty(nnz, dtype=np.int64)
    csc_pos[corder] = csc_pos_sorted

    perm = np.full(S, -1, dtype=np.int64)
    perm[csc_pos] = ell_pos
    free_dst = np.flatnonzero(perm < 0)
    used_src = np.zeros(S, dtype=bool)
    used_src[ell_pos] = True
    perm[free_dst] = np.flatnonzero(~used_src)
    return ell_pos, csc_pos, perm


def route_layout(
    rows: np.ndarray,
    cols: np.ndarray,
    n: int,
    d: int,
    K: int,
    KP: int,
    plan_cache: Optional[str],
    size_floor: int = 0,
    row_counts: Optional[np.ndarray] = None,
    col_counts: Optional[np.ndarray] = None,
):
    """Shared routing core for both permutation engines: validate pinned
    paddings, size the network, build slot positions and the (plan,
    plan_inv) pair. Returns ``(ell_pos, csc_pos, plan, plan_inv, S)``."""
    nnz = rows.size
    if row_counts is None:
        row_counts = (
            np.bincount(rows, minlength=n) if nnz else np.zeros(n, np.int64)
        )
    if col_counts is None:
        col_counts = (
            np.bincount(cols, minlength=d) if nnz else np.zeros(d, np.int64)
        )
    assert not nnz or (
        row_counts.max() <= K and col_counts.max() <= KP
    ), "pinned paddings smaller than actual degrees"
    S = routing.valid_size(max(n * K, d * KP, size_floor, 1))

    ell_pos, csc_pos, perm = build_slot_perm(
        rows, cols, n, d, K, KP, S, row_counts, col_counts
    )
    plan = _build_plan_cached(perm, plan_cache)
    return ell_pos, csc_pos, plan, plan.invert(), S


def _assemble(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n: int,
    d: int,
    K: int,
    KP: int,
    hot_matrix: Optional[np.ndarray],
    hot_ids: Optional[np.ndarray],
    plan_cache: Optional[str],
    size_floor: int = 0,
    row_counts: Optional[np.ndarray] = None,
    col_counts: Optional[np.ndarray] = None,
    spill=(None, None, None),
) -> BenesSparseFeatures:
    """Route + lay out one (cold-entries, hot-side) pair with pinned paddings.

    K/KP/size_floor are caller-pinned so independent shards of one dataset
    can be forced onto identical network shapes (the sharded builder stacks
    them under one compiled program). Callers that already hold the degree
    bincounts pass them to skip a recount. ``spill`` is an optional
    (rows, cols, vals) COO side of over-cap entries (see auto_kp_cap).
    """
    ell_pos, csc_pos, plan, plan_inv, S = route_layout(
        rows, cols, n, d, K, KP, plan_cache, size_floor, row_counts, col_counts
    )

    ell_values = np.zeros((n, K), dtype=np.float32)
    ell_values.reshape(-1)[ell_pos] = vals
    csc_values = np.zeros((d, KP), dtype=np.float32)
    csc_values.reshape(-1)[csc_pos] = vals

    sr, sc, sv = _spill_arrays(*spill)
    return BenesSparseFeatures(
        ell_values=jnp.asarray(ell_values),
        csc_values=jnp.asarray(csc_values),
        plan=device_plan(plan),
        plan_inv=device_plan(plan_inv),
        hot_matrix=None if hot_matrix is None else jnp.asarray(hot_matrix),
        hot_cols=None if hot_ids is None else jnp.asarray(hot_ids, dtype=jnp.int32),
        num_rows_=int(n),
        num_cols_=int(d),
        spill_rows=sr,
        spill_cols=sc,
        spill_vals=sv,
    )


def from_ell(ell, plan_cache: Optional[str] = None) -> BenesSparseFeatures:
    """Convert an ``ops.features.EllFeatures`` (host round-trip)."""
    vals = np.asarray(ell.values)
    idx = np.asarray(ell.indices)
    n, k = vals.shape
    live = vals != 0.0
    rows = np.repeat(np.arange(n, dtype=np.int64), k).reshape(n, k)[live]
    return from_coo(
        rows,
        idx[live].astype(np.int64),
        vals[live],
        (n, ell.num_cols),
        max_nnz_row=k,
        plan_cache=plan_cache,
    )


def _build_plan_cached(perm: np.ndarray, cache_dir: Optional[str]):
    if cache_dir is None:
        cache_dir = default_plan_cache()
    if not cache_dir:  # None or "" — disabled
        return routing.build_plan(perm)
    import hashlib
    from pathlib import Path

    h = hashlib.sha1(perm.tobytes()).hexdigest()[:16]
    # v2: int8 stage indices. Bump on any plan-format or routing change so
    # stale entries from older code can never be served.
    path = Path(cache_dir) / f"benesplan_v2_{perm.shape[0]}_{h}.npz"
    if path.exists():
        try:
            plan = _load_plan_file(path)
        except Exception:
            plan = None  # unreadable/foreign entry: rebuild and overwrite
        if plan is not None:
            return plan

    plan = routing.build_plan(perm)
    arrays = {"size": np.int64(plan.size)}
    kinds = []
    i = 0
    for st in plan.stages:
        if isinstance(st, routing.LaneShuffle):
            kinds.append("lane")
            # lane/sublane indices are < 128/8: int8 storage quarters the
            # on-disk plan (the device uses int8 anyway, permute_net.py)
            arrays[f"idx{i}"] = st.idx.astype(np.int8)
            i += 1
        elif isinstance(st, routing.SublaneShuffle):
            kinds.append(f"sublane:{st.rows}")
            arrays[f"idx{i}"] = st.idx.astype(np.int8)
            i += 1
        elif isinstance(st, routing.Enter):
            kinds.append(f"enter:{st.blocks}:{st.rows}")
        else:
            kinds.append(f"leave:{st.blocks}:{st.rows}")
    arrays["kinds"] = np.array(kinds)
    path.parent.mkdir(parents=True, exist_ok=True)
    # atomic publish: concurrent builders of the same pattern must never
    # read a half-written file
    import os
    import tempfile as _tf

    fd, tmp = _tf.mkstemp(dir=str(path.parent), suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    # retire the pre-versioning (v1, int32) entry for this pattern, if any
    try:
        os.unlink(str(Path(cache_dir) / f"benesplan_{perm.shape[0]}_{h}.npz"))
    except OSError:
        pass
    return plan


def _load_plan_file(path) -> routing.PermPlan:
    data = np.load(path)
    stages: list = []
    i = 0
    for kind in data["kinds"]:
        kind = kind.decode() if isinstance(kind, bytes) else str(kind)
        parts = kind.split(":")
        if parts[0] == "lane":
            stages.append(routing.LaneShuffle(idx=data[f"idx{i}"]))
            i += 1
        elif parts[0] == "sublane":
            stages.append(
                routing.SublaneShuffle(idx=data[f"idx{i}"], rows=int(parts[1]))
            )
            i += 1
        elif parts[0] == "enter":
            stages.append(routing.Enter(int(parts[1]), int(parts[2])))
        elif parts[0] == "leave":
            stages.append(routing.Leave(int(parts[1]), int(parts[2])))
        else:
            raise ValueError(f"unknown cached stage kind {kind!r}")
    return routing.PermPlan(size=int(data["size"]), stages=stages)


def default_plan_cache() -> Optional[str]:
    """Default routing-plan cache directory: $PHOTON_ML_TPU_PLAN_CACHE, or a
    per-uid 0700 tempdir. Set the env var to "" to disable caching. Plans
    are keyed by the sha1 of the permutation plus a format version; entries
    that fail to load are rebuilt, so only disk space is at stake (~0.1 GB
    per distinct large pattern)."""
    import os

    from photon_ml_tpu.utils.cachedir import per_uid_cache_dir

    env = os.environ.get("PHOTON_ML_TPU_PLAN_CACHE")
    if env is not None:
        return env or None
    return per_uid_cache_dir("photon_ml_tpu_plan_cache")
