from photon_ml_tpu.ops.features import DenseFeatures, EllFeatures, FeatureMatrix
from photon_ml_tpu.ops.data import LabeledData

__all__ = ["DenseFeatures", "EllFeatures", "FeatureMatrix", "LabeledData"]
