"""Feature-matrix representations and the three linear maps every GLM needs.

The reference stores each example as a Breeze sparse vector and runs sparse
axpy per partition (ValueAndGradientAggregator.scala:132-153). On TPU the
equivalent is a struct-of-arrays batch with three primitives:

- ``matvec(w)``    : margins  z = X @ w                 (forward)
- ``rmatvec(c)``   : gradient accumulation  X^T @ c     (reverse)
- ``rmatvec_sq(c)``: Hessian diagonal  (X*X)^T @ c

Two layouts:

- :class:`DenseFeatures` — plain ``[n, d]`` matrix; MXU-friendly, used for the
  small per-entity local problems after index-map projection and for dense
  benchmarks.
- :class:`EllFeatures` — padded row-sparse (ELL) layout ``values/indices
  [n, k]`` with k = max nnz per row; used for the global fixed-effect problem
  where d is huge (up to 1e9) and rows are sparse. matvec is a gather + fused
  multiply-reduce; rmatvec is a scatter-add. Padding slots carry value 0.0 so
  they are algebraic no-ops.

Shapes are strictly 2-D per batch; wrap in ``jax.vmap`` for a leading batch
axis (the random-effect engine does exactly that).
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class DenseFeatures:
    """Dense ``[n, d]`` feature matrix."""

    matrix: jax.Array

    @property
    def num_rows(self) -> int:
        return self.matrix.shape[0]

    @property
    def dim(self) -> int:
        return self.matrix.shape[1]

    def matvec(self, w: jax.Array) -> jax.Array:
        return self.matrix @ w

    def rmatvec(self, c: jax.Array) -> jax.Array:
        return self.matrix.T @ c

    def rmatvec_sq(self, c: jax.Array) -> jax.Array:
        return (self.matrix * self.matrix).T @ c

    def row_norms_sq(self) -> jax.Array:
        return jnp.sum(self.matrix * self.matrix, axis=-1)


@struct.dataclass
class EllFeatures:
    """Padded row-sparse (ELL) feature matrix.

    values:  [n, k] float — feature values, 0.0 in padding slots.
    indices: [n, k] int32 — column index per slot, 0 in padding slots.
    num_cols: static feature dimension d.
    """

    values: jax.Array
    indices: jax.Array
    num_cols: int = struct.field(pytree_node=False)

    @property
    def num_rows(self) -> int:
        return self.values.shape[0]

    @property
    def dim(self) -> int:
        return self.num_cols

    def matvec(self, w: jax.Array) -> jax.Array:
        # gather w at indices, multiply by values, reduce over the slot axis
        return jnp.sum(self.values * w[self.indices], axis=-1)

    def rmatvec(self, c: jax.Array) -> jax.Array:
        # scatter-add c_i * v_is into column indices; padding contributes 0
        contrib = self.values * c[:, None]
        return jnp.zeros(self.num_cols, dtype=contrib.dtype).at[self.indices].add(contrib)

    def rmatvec_sq(self, c: jax.Array) -> jax.Array:
        contrib = self.values * self.values * c[:, None]
        return jnp.zeros(self.num_cols, dtype=contrib.dtype).at[self.indices].add(contrib)

    def row_norms_sq(self) -> jax.Array:
        return jnp.sum(self.values * self.values, axis=-1)

    def to_dense(self) -> DenseFeatures:
        n = self.num_rows
        dense = jnp.zeros((n, self.num_cols), dtype=self.values.dtype)
        rows = jnp.arange(n)[:, None]
        dense = dense.at[rows, self.indices].add(self.values)
        return DenseFeatures(matrix=dense)


FeatureMatrix = Union[DenseFeatures, EllFeatures]


def _coalesce_coo(rows, cols, vals, n, d):
    """Validate + duplicate-coalesce COO triplets; returns the (possibly
    re-sorted) triplets and the per-row counts. Decoder output is already
    (row, col)-sorted and duplicate-free, so both the lexsort and the
    (slow) np.add.at are skipped on that fast path — this is the streaming
    prefetcher's per-block hot loop."""
    import numpy as np

    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    vals = np.asarray(vals, dtype=np.float32)
    if rows.size:
        if rows.min() < 0 or rows.max() >= n:
            raise ValueError(f"row index out of range [0, {n})")
        if d is not None and (cols.min() < 0 or cols.max() >= d):
            raise ValueError(f"column index out of range [0, {d})")
        in_order = bool(
            np.all(
                (rows[1:] > rows[:-1])
                | ((rows[1:] == rows[:-1]) & (cols[1:] >= cols[:-1]))
            )
        )
        if not in_order:
            order = np.lexsort((cols, rows))
            rows, cols, vals = rows[order], cols[order], vals[order]
        boundary = np.empty(rows.size, dtype=bool)
        boundary[0] = True
        boundary[1:] = (rows[1:] != rows[:-1]) | (cols[1:] != cols[:-1])
        uniq = int(boundary.sum())
        if uniq != rows.size:
            seg_ids = np.cumsum(boundary) - 1
            summed = np.zeros(uniq, dtype=np.float64)
            np.add.at(summed, seg_ids, vals)
            rows, cols = rows[boundary], cols[boundary]
            vals = summed.astype(np.float32)
    counts = np.bincount(rows, minlength=n)
    return rows, cols, vals, counts


def _scatter_ell(rows, cols, vals, counts, values, indices) -> None:
    """Scatter coalesced, (row, col)-sorted triplets into ELL arrays."""
    import numpy as np

    if not rows.size:
        return
    n = values.shape[0]
    # slot index within each row: position minus that row's start offset
    starts = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    slots = np.arange(rows.size, dtype=np.int64) - starts[rows]
    values[rows, slots] = vals
    indices[rows, slots] = cols


def pack_ell_host(rows, cols, vals, shape, max_nnz: int | None = None):
    """Host-side ELL packing from COO triplets: returns numpy
    ``(values [n, k], indices [n, k])`` without touching the device.

    This is the staging half of :func:`from_scipy_like` — the streaming
    prefetcher packs blocks in a background thread and defers the
    ``device_put`` to the consumer, so packing must not allocate device
    buffers. Semantics are identical: duplicates coalesced by summation,
    ``ValueError`` when a row exceeds ``max_nnz``.
    """
    import numpy as np

    n, d = shape
    rows, cols, vals, counts = _coalesce_coo(rows, cols, vals, n, d)
    needed = int(counts.max()) if rows.size else 1
    k = max(int(max_nnz) if max_nnz is not None else needed, 1)
    if needed > k:
        raise ValueError(
            f"row with {needed} nonzeros exceeds max_nnz={k}; raise max_nnz or "
            "pre-select features"
        )
    values = np.zeros((n, k), dtype=np.float32)
    indices = np.zeros((n, k), dtype=np.int32)
    _scatter_ell(rows, cols, vals, counts, values, indices)
    return values, indices


def pack_ell_into(
    rows, cols, vals, values_out, indices_out, num_cols: int | None = None
) -> None:
    """In-place :func:`pack_ell_host`: scatter COO triplets directly into
    caller-owned, zero-initialized ``[n, k]`` staging arrays.

    The streaming block assembler packs each file piece of a block into
    the block's staging buffers as it arrives — pieces are row-disjoint,
    so piecewise packing is exactly equivalent to packing the whole block
    at once, and the intermediate per-file COO concatenation (one full
    copy of every triplet per block) disappears. Rows previously written
    by another call must not be revisited.
    """
    n, k = values_out.shape
    rows, cols, vals, counts = _coalesce_coo(rows, cols, vals, n, num_cols)
    needed = int(counts.max()) if rows.size else 0
    if needed > k:
        raise ValueError(
            f"row with {needed} nonzeros exceeds max_nnz={k}; raise max_nnz or "
            "pre-select features"
        )
    _scatter_ell(rows, cols, vals, counts, values_out, indices_out)


def from_scipy_like(rows, cols, vals, shape, max_nnz: int | None = None) -> EllFeatures:
    """Build EllFeatures from COO triplets (host-side, vectorized numpy).

    Duplicate (row, col) entries are coalesced by summation (scipy COO
    semantics) so the squared-value map ``rmatvec_sq`` stays consistent with
    the linear maps. Raises if any row exceeds ``max_nnz`` after coalescing —
    silent truncation would train a wrong model.
    """
    values, indices = pack_ell_host(rows, cols, vals, shape, max_nnz)
    return EllFeatures(
        values=jnp.asarray(values),
        indices=jnp.asarray(indices),
        num_cols=int(shape[1]),
    )
