"""Overlapped coordinate-descent schedule: the in-flight work executor.

The sync CD driver serializes every coordinate update — the FE solve, each
RE bucket round, and the residual-plane algebra each wait for the previous
step, so the device idles through every host-driven phase boundary the
telemetry can now measure. The async schedule pipelines that work instead:
solves are dispatched onto a small worker pool and reconciled into the
device score plane in dispatch order, with a ``staleness`` bound on how
many unreconciled updates a dispatch may ignore.

:class:`ScheduleExecutor` is the piece both overlap sites share (the CD
driver's coordinate pipeline and ``train_random_effects``'s bucket
overlap). It is a thin wrapper over :class:`~concurrent.futures.
ThreadPoolExecutor` that adds the two things a telemetry-instrumented
training loop needs:

* **contextvar propagation** — the dispatching thread's context is copied
  at submit time (:func:`contextvars.copy_context`), so spans opened inside
  the worker parent under the span that was live at the dispatch site
  (``cd/outer_iter``, ``re/train``, …) instead of floating as roots;
* **overlap spans** — every unit of work runs inside its own span (default
  name ``cd/overlap``) carrying the submit attrs, so ``analyze_run`` can
  attribute concurrent wall-clock per coordinate/bucket.

Determinism note: the executor itself imposes no ordering — callers get it
by construction. The CD driver computes every residual on the driver
thread *at dispatch time* and folds results back in dispatch order, so the
trained trajectory depends only on the ``staleness`` bound, never on
thread timing; RE bucket solves are mutually independent, so any
completion order yields bitwise-identical per-bucket results.
"""
from __future__ import annotations

import collections
import contextvars
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Deque, Optional

from photon_ml_tpu.telemetry import span

__all__ = ["SCHEDULES", "InFlight", "ScheduleExecutor"]

# The CD schedule axis. "sync" is the default and follows today's strictly
# sequential trajectory bitwise; "async" pipelines solves with bounded
# staleness (device score plane only — multi-controller runs force sync
# exactly like they force the host score plane).
SCHEDULES = ("sync", "async")


class InFlight:
    """One dispatched unit of work: the submit key plus its future."""

    __slots__ = ("key", "future", "attrs")

    def __init__(self, key: Any, future: Future, attrs: dict):
        self.key = key
        self.future = future
        self.attrs = attrs

    def done(self) -> bool:
        return self.future.done()

    def result(self) -> Any:
        """Block until the work completes. Worker exceptions re-raise here,
        on the thread that reconciles the result."""
        return self.future.result()


class ScheduleExecutor:
    """Bounded worker pool owning the in-flight queue of an overlapped run.

    ``max_in_flight`` caps both the pool width and therefore how many
    solves can make progress concurrently; callers additionally bound the
    *unreconciled* count (the staleness window) on their side.
    """

    def __init__(self, max_in_flight: int = 2, name: str = "cd-sched"):
        if max_in_flight < 1:
            raise ValueError(
                f"max_in_flight must be >= 1, got {max_in_flight}"
            )
        self.max_in_flight = max_in_flight
        self._pool = ThreadPoolExecutor(
            max_workers=max_in_flight, thread_name_prefix=name
        )
        self._queue: Deque[InFlight] = collections.deque()

    # ----------------------------------------------------------- dispatch
    def submit(
        self,
        key: Any,
        fn: Callable[[], Any],
        span_name: str = "cd/overlap",
        **attrs: Any,
    ) -> InFlight:
        """Dispatch ``fn`` onto the pool inside a ``span_name`` span.

        The *current* contextvars context — including the live telemetry
        span — is captured here, on the dispatching thread, and entered in
        the worker; the overlap span (and everything ``fn`` opens inside
        it) therefore chains under the span that was open at the call
        site.
        """
        ctx = contextvars.copy_context()

        def _run() -> Any:
            def _in_span() -> Any:
                with span(span_name, **attrs):
                    return fn()

            return ctx.run(_in_span)

        work = InFlight(key, self._pool.submit(_run), dict(attrs))
        self._queue.append(work)
        return work

    # -------------------------------------------------------------- queue
    def __len__(self) -> int:
        return len(self._queue)

    def oldest(self) -> Optional[InFlight]:
        return self._queue[0] if self._queue else None

    def pop_oldest(self) -> InFlight:
        """Remove and return the oldest in-flight work (FIFO — the
        reconciliation order of the bounded-staleness schedule)."""
        return self._queue.popleft()

    def drain(self) -> list:
        """Block until every queued work item completes; returns their
        results in dispatch order and empties the queue."""
        out = []
        while self._queue:
            out.append(self._queue.popleft().result())
        return out

    # ---------------------------------------------------------- lifecycle
    def shutdown(self, wait: bool = True) -> None:
        self._pool.shutdown(wait=wait)

    def __enter__(self) -> "ScheduleExecutor":
        return self

    def __exit__(self, *exc) -> bool:
        self.shutdown(wait=True)
        return False
