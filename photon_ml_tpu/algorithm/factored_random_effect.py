"""Factored random-effect coordinate: per-entity latent factors + a shared
projection matrix, trained by alternating solves.

Reference parity: algorithm/FactoredRandomEffectCoordinate.scala:40 — the
alternating loop (:112-146) interleaves (a) a per-entity random-effect solve
in the k-dimensional latent space and (b) a global solve for the projection
matrix B treated as one (d·k)-coefficient GLM over Kronecker-product features
kron(x, latent) (:227-280); FactoredRandomEffectOptimizationProblem.scala:42
pairs the two problems; MFOptimizationConfiguration.scala:29 is the
``numLatentFactors,numIterations`` config.

TPU-native design: the per-entity data stays in the index-map-projected
blocks of the RandomEffectDataset. Step (a) projects each bucket through B on
device (one einsum: X @ B[proj_indices]) and reuses the vmap'd RE trainer in
latent space. Step (b) never materializes kron(x, v): :class:`KronFeatures`
implements the three linear maps (matvec / rmatvec / rmatvec_sq) of the
implicit [n, d·k] design matrix as fused einsums + one scatter-add into the
[d, k] gradient — so the existing L-BFGS/TRON solvers run unchanged over
vec(B).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from photon_ml_tpu.data.random_effect import RandomEffectDataset, ReBucket
from photon_ml_tpu.estimators.random_effect import train_random_effects
from photon_ml_tpu.losses.objective import make_glm_objective
from photon_ml_tpu.losses.pointwise import loss_for_task
from photon_ml_tpu.models.random_effect import RandomEffectModel
from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.opt.config import GlmOptimizationConfiguration
from photon_ml_tpu.opt.solve import solve
from photon_ml_tpu.types import TaskType


@dataclasses.dataclass(frozen=True)
class MFOptimizationConfiguration:
    """Reference MFOptimizationConfiguration.scala:29
    (``numLatentFactors,numIterations``)."""

    num_latent_factors: int
    num_iterations: int = 1
    seed: int = 0

    def __post_init__(self) -> None:
        if self.num_latent_factors < 1:
            raise ValueError("num_latent_factors must be >= 1")
        if self.num_iterations < 1:
            raise ValueError("num_iterations must be >= 1")


@struct.dataclass
class KronFeatures:
    """Implicit design matrix of the projection-matrix solve.

    Row (e, s) of bucket b has features kron(latent[e], x[e, s]) laid out as
    vec(B) with B of shape [d_global, k]: coefficient (c, j) multiplies
    x_value-at-global-col-c times latent[e, j]. Bucket blocks are carried as
    parallel lists; rows are the concatenation of all buckets' flattened
    [E*S] axes (padding rows have weight 0 upstream).
    """

    xs: List[jax.Array]        # per bucket [E, S, D] local features
    pidxs: List[jax.Array]     # per bucket [E, D] global col per local col
    latents: List[jax.Array]   # per bucket [E, k]
    d_global: int = struct.field(pytree_node=False)
    k: int = struct.field(pytree_node=False)

    @property
    def num_rows(self) -> int:
        return sum(x.shape[0] * x.shape[1] for x in self.xs)

    @property
    def dim(self) -> int:
        return self.d_global * self.k

    def matvec(self, w: jax.Array) -> jax.Array:
        B = w.reshape(self.d_global, self.k)
        outs = []
        for x, pidx, v in zip(self.xs, self.pidxs, self.latents):
            # z[e,s] = x[e,s,:] . (B[pidx[e]] @ v[e]); padding cols have
            # x == 0 so their (arbitrary) B[0] gather contributes nothing
            z = jnp.einsum("esd,edk,ek->es", x, B[pidx], v)
            outs.append(z.reshape(-1))
        return jnp.concatenate(outs)

    def rmatvec(self, c: jax.Array) -> jax.Array:
        grad = jnp.zeros((self.d_global, self.k), dtype=c.dtype)
        start = 0
        for x, pidx, v in zip(self.xs, self.pidxs, self.latents):
            e_n, s_n = x.shape[0], x.shape[1]
            cb = c[start : start + e_n * s_n].reshape(e_n, s_n)
            start += e_n * s_n
            contrib = jnp.einsum("es,esd,ek->edk", cb, x, v)
            grad = grad.at[pidx].add(contrib)
        return grad.reshape(-1)

    def rmatvec_sq(self, c: jax.Array) -> jax.Array:
        out = jnp.zeros((self.d_global, self.k), dtype=c.dtype)
        start = 0
        for x, pidx, v in zip(self.xs, self.pidxs, self.latents):
            e_n, s_n = x.shape[0], x.shape[1]
            cb = c[start : start + e_n * s_n].reshape(e_n, s_n)
            start += e_n * s_n
            contrib = jnp.einsum("es,esd,ek->edk", cb, x * x, v * v)
            out = out.at[pidx].add(contrib)
        return out.reshape(-1)

    def row_norms_sq(self) -> jax.Array:
        outs = []
        for x, v in zip(self.xs, self.latents):
            # ||kron(v_e, x_es)||^2 = ||x_es||^2 * ||v_e||^2
            xn = jnp.sum(x * x, axis=-1)
            vn = jnp.sum(v * v, axis=-1)
            outs.append((xn * vn[:, None]).reshape(-1))
        return jnp.concatenate(outs)


@dataclasses.dataclass
class FactoredRandomEffectModel:
    """Latent per-entity factors + shared projection matrix (reference
    model/FactoredRandomEffectModel.scala:33). The effective per-entity
    coefficient vector in the ORIGINAL space is B @ latent_e."""

    random_effect_type: str
    task: TaskType
    latent: RandomEffectModel          # coefficients are [E, k] latent factors
    projection_matrix: jax.Array       # [d_global, k]

    @property
    def num_latent_factors(self) -> int:
        return int(self.projection_matrix.shape[1])

    def to_summary_string(self) -> str:
        """Reference Summarizable.toSummaryString (FactoredRandomEffectModel)."""
        return (
            f"factored random effect '{self.random_effect_type}': "
            f"{self.latent.num_entities} entities x "
            f"{self.num_latent_factors} latent factors, projection matrix "
            f"[{int(self.projection_matrix.shape[0])}, "
            f"{self.num_latent_factors}]"
        )

    def coefficients_for(self, entity_id: str) -> Optional[dict]:
        """Dense original-space coefficients w = B @ latent for one entity."""
        loc = self.latent.entity_to_loc.get(str(entity_id))
        if loc is None:
            return None
        b, e = loc
        v = np.asarray(self.latent.coefficients[b][e])
        w = np.asarray(self.projection_matrix) @ v
        return {int(i): float(x) for i, x in enumerate(w)}


def _latent_dataset(
    dataset: RandomEffectDataset, B: jax.Array
) -> RandomEffectDataset:
    """Project every bucket into the latent space of B (step (a) input):
    X_latent[e,s] = B[pidx[e]]^T x[e,s].

    The returned dataset's "global" space IS the k-dim latent space (identity
    projection, global_dim=k), so the latent RandomEffectModel trained on it
    exports honest {latent_axis: factor} maps rather than pretending its
    coordinates are original features.
    """
    from photon_ml_tpu.projector import ProjectorType

    k = int(B.shape[1])
    new_buckets = []
    new_passive = []
    for b, bucket in enumerate(dataset.buckets):
        Bg = B[bucket.proj_indices]  # [E, D, k]; padding cols have x == 0
        Xl = jnp.einsum("esd,edk->esk", bucket.X, Bg)
        e_n = bucket.num_entities
        new_buckets.append(
            bucket.replace(
                X=Xl,
                proj_indices=jnp.tile(jnp.arange(k, dtype=jnp.int32), (e_n, 1)),
                proj_valid=jnp.ones((e_n, k), dtype=bool),
            )
        )
        p = dataset.passive[b]
        if p is not None:
            Xp = jnp.einsum("pd,pdk->pk", p.X, Bg[p.entity_index])
            new_passive.append(p.replace(X=Xp))
        else:
            new_passive.append(None)
    return dataclasses.replace(
        dataset,
        buckets=new_buckets,
        passive=new_passive,
        global_dim=k,
        config=dataclasses.replace(
            dataset.config, projector=ProjectorType.IDENTITY, projected_dim=None
        ),
    )


@dataclasses.dataclass
class FactoredRandomEffectCoordinate:
    """Alternating MF-style coordinate (reference
    FactoredRandomEffectCoordinate.scala:40). Implements the Coordinate
    protocol (update_model / score) used by CoordinateDescent."""

    dataset: RandomEffectDataset       # INDEX_MAP/IDENTITY projected blocks
    task: TaskType
    re_configuration: GlmOptimizationConfiguration       # latent-factor solves
    matrix_configuration: GlmOptimizationConfiguration   # projection-matrix solve
    mf_configuration: MFOptimizationConfiguration
    base_offsets: np.ndarray
    # multi-chip: entity-axis sharding re-applied after every offset rebuild
    # (update_offsets produces host arrays — same contract as
    # RandomEffectCoordinate.mesh/_place)
    mesh: Optional[object] = None
    mesh_axes: Optional[tuple] = None

    def __post_init__(self) -> None:
        # RANDOM-projected datasets carry no per-column global index map
        # (proj_indices are zeros), so B gathers/scatters would silently pile
        # onto row 0 — reject at construction.
        from photon_ml_tpu.projector import ProjectorType

        if self.dataset.config.projector is ProjectorType.RANDOM:
            raise ValueError(
                "FactoredRandomEffectCoordinate requires an INDEX_MAP or "
                "IDENTITY projected dataset (the factored coordinate learns "
                "its own projection matrix)"
            )

    def _init_matrix(self) -> jax.Array:
        """Gaussian random init scaled 1/sqrt(k) (reference seeds the
        factored problem with a random ProjectionMatrix, :95)."""
        k = self.mf_configuration.num_latent_factors
        rng = np.random.default_rng(self.mf_configuration.seed)
        B = rng.standard_normal((self.dataset.global_dim, k)) / np.sqrt(k)
        return jnp.asarray(B.astype(np.float32))

    def _place(self, ds: RandomEffectDataset) -> RandomEffectDataset:
        if self.mesh is None:
            return ds
        from photon_ml_tpu.data.random_effect import place_dataset

        return place_dataset(ds, self.mesh, self.mesh_axes)

    def update_model(
        self,
        model: Optional[FactoredRandomEffectModel],
        residual_scores: np.ndarray,
    ) -> FactoredRandomEffectModel:
        ds = self._place(
            self.dataset.update_offsets(self.base_offsets + residual_scores)
        )
        B = model.projection_matrix if model is not None else self._init_matrix()
        latent_model = model.latent if model is not None else None

        for _ in range(self.mf_configuration.num_iterations):
            # (a) per-entity latent solve in the space of the current B
            latent_ds = _latent_dataset(ds, B)
            latent_model, _ = train_random_effects(
                latent_ds,
                self.task,
                self.re_configuration,
                initial_model=latent_model,
            )
            # (b) global projection-matrix solve over implicit kron features
            B = self._solve_matrix(ds, latent_model, B)

        return FactoredRandomEffectModel(
            random_effect_type=self.dataset.config.random_effect_type,
            task=self.task,
            latent=latent_model,
            projection_matrix=B,
        )

    def _solve_matrix(
        self,
        ds: RandomEffectDataset,
        latent_model: RandomEffectModel,
        B: jax.Array,
    ) -> jax.Array:
        feats = KronFeatures(
            xs=[b.X for b in ds.buckets],
            pidxs=[b.proj_indices for b in ds.buckets],
            latents=list(latent_model.coefficients),
            d_global=ds.global_dim,
            k=int(B.shape[1]),
        )
        labels = jnp.concatenate([b.labels.reshape(-1) for b in ds.buckets])
        offsets = jnp.concatenate([b.offsets.reshape(-1) for b in ds.buckets])
        weights = jnp.concatenate([b.weights.reshape(-1) for b in ds.buckets])
        data = LabeledData(
            features=feats, labels=labels, offsets=offsets, weights=weights, norm=None
        )
        objective = make_glm_objective(loss_for_task(self.task))
        result = solve(
            objective, B.reshape(-1), data, self.matrix_configuration
        )
        return result.w.reshape(B.shape)

    def score(self, model: FactoredRandomEffectModel) -> np.ndarray:
        """Active + passive scores in original row order: the latent model
        scored over B-projected blocks (RandomEffectCoordinate.score
        semantics)."""
        from photon_ml_tpu.estimators.random_effect import score_random_effects

        latent_ds = _latent_dataset(self.dataset, model.projection_matrix)
        return score_random_effects(model.latent, latent_ds)
