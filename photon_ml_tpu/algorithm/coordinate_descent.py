"""Block coordinate descent: the outer GAME training loop.

Reference parity: algorithm/CoordinateDescent.scala:40 (run :57, optimize
:97-321): per outer iteration, per coordinate — residual = total score minus
the coordinate's own score (:183), retrain the coordinate against the
residual, rescore, log the objective (:247-258), evaluate validation after
each coordinate update (:265-294), and keep the best full model seen by the
first evaluator (:299-307). The reference's aggressive RDD persist/unpersist
choreography disappears: scores are small device/host arrays.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.algorithm.coordinate import Coordinate
from photon_ml_tpu.evaluation.evaluators import nan_aware_better_than

logger = logging.getLogger("photon_ml_tpu")


@dataclasses.dataclass
class CoordinateDescentResult:
    models: Dict[str, object]                 # final per-coordinate models
    best_models: Dict[str, object]            # best by validation (== models if no validation)
    best_metric: Optional[float]
    objective_history: List[Tuple[str, float]]  # (coordinate, training objective)
    validation_history: List[Tuple[str, float]]  # (coordinate, first-evaluator metric)


class CoordinateDescent:
    """Orchestrates sequential coordinate updates (host control flow; all
    heavy math happens inside the coordinates' jit programs)."""

    def __init__(
        self,
        coordinates: Dict[str, Coordinate],
        num_rows: int,
        update_order: Optional[Sequence[str]] = None,
        training_objective: Optional[Callable[[np.ndarray], float]] = None,
        regularization_term: Optional[
            Callable[[Dict[str, object]], float]
        ] = None,
        validate: Optional[Callable[[Dict[str, object]], float]] = None,
        validation_better_than: Optional[Callable[[float, float], bool]] = None,
        emitter: Optional[object] = None,
    ) -> None:
        if not coordinates:
            raise ValueError("need at least one coordinate")
        self.coordinates = coordinates
        self.num_rows = num_rows
        self.update_order = list(update_order) if update_order else list(coordinates)
        unknown = set(self.update_order) - set(coordinates)
        if unknown:
            raise ValueError(f"unknown coordinates in update order: {unknown}")
        self.training_objective = training_objective
        # optional Σ per-coordinate regularization over the current models:
        # the reference logs the objective decomposed into loss +
        # regularization per update (CoordinateDescent.scala:247-258)
        self.regularization_term = regularization_term
        self.validate = validate
        # Evaluator.better_than semantics (larger/smaller-is-better + NaN
        # policy) come from the evaluator itself; default: larger is better.
        self.validation_better_than = validation_better_than or nan_aware_better_than
        # optional event.EventEmitter: per-bucket SolverStatsEvent after each
        # random-effect coordinate update (adaptive-solve lane telemetry)
        self.emitter = emitter

    def _emit_solver_stats(self, cid: str, coord: Coordinate) -> None:
        stats = getattr(coord, "last_solver_stats", None)
        if not stats:
            return
        for s in stats:
            logger.info("CD coordinate %s: %s", cid, s.to_summary_string())
        if self.emitter is None:
            return
        from photon_ml_tpu.event import SolverStatsEvent

        for s in stats:
            self.emitter.send_event(SolverStatsEvent.from_stats(cid, s))

    def run(
        self,
        num_iterations: int,
        initial_models: Optional[Dict[str, object]] = None,
        start_iteration: int = 0,
        initial_best: Optional[Tuple[Dict[str, object], float]] = None,
        on_iteration_end: Optional[Callable[[int, "CoordinateDescentResult"], None]] = None,
    ) -> CoordinateDescentResult:
        """``start_iteration``/``initial_best``/``on_iteration_end`` support
        checkpoint-resume: the callback fires after each outer iteration with
        the running result; resume passes the restored models and best-so-far
        back in and skips completed iterations."""
        models: Dict[str, object] = dict(initial_models or {})
        scores: Dict[str, np.ndarray] = {}

        # initial scoring for warm-started models
        for cid, model in models.items():
            scores[cid] = self.coordinates[cid].score(model)

        def total_score() -> np.ndarray:
            out = np.zeros(self.num_rows, dtype=np.float32)
            for s in scores.values():
                out += s
            return out

        objective_history: List[Tuple[str, float]] = []
        validation_history: List[Tuple[str, float]] = []
        best_metric: Optional[float] = None
        best_models: Dict[str, object] = {}
        if initial_best is not None:
            best_models, best_metric = dict(initial_best[0]), initial_best[1]

        for outer in range(start_iteration, num_iterations):
            for cid in self.update_order:
                coord = self.coordinates[cid]
                # partialScore = fullScore - ownScore (reference
                # CoordinateDescent.scala:183)
                residual = total_score()
                if cid in scores:
                    residual -= scores[cid]
                model = coord.update_model(models.get(cid), residual)
                models[cid] = model
                scores[cid] = coord.score(model)
                self._emit_solver_stats(cid, coord)

                if self.training_objective is not None:
                    loss_val = float(self.training_objective(total_score()))
                    if self.regularization_term is not None:
                        # objective = loss + regularization (reference
                        # CoordinateDescent.scala:247-258); the history and
                        # the log agree on what "objective" means
                        reg = float(self.regularization_term(models))
                        obj = loss_val + reg
                        objective_history.append((cid, obj))
                        logger.info(
                            "CD iter %d coordinate %s: loss %.6f + "
                            "regularization %.6f = objective %.6f",
                            outer, cid, loss_val, reg, obj,
                        )
                    else:
                        objective_history.append((cid, loss_val))
                        logger.info(
                            "CD iter %d coordinate %s: training objective %.6f",
                            outer, cid, loss_val,
                        )
                if self.validate is not None:
                    metric = float(self.validate(models))
                    validation_history.append((cid, metric))
                    logger.info(
                        "CD iter %d coordinate %s: validation %.6f", outer, cid, metric
                    )
                    # best-model tracking starts once EVERY coordinate has
                    # trained: a mid-first-iteration snapshot would be a
                    # partial model (missing whole coordinates on disk) —
                    # the reference's snapshots always carry all
                    # coordinates (CoordinateDescent.scala:265-294, its
                    # models hold initial coefficients from the start)
                    if all(c in models for c in self.update_order) and (
                        best_metric is None
                        or self.validation_better_than(metric, best_metric)
                    ):
                        best_metric = metric
                        best_models = dict(models)

            if on_iteration_end is not None:
                on_iteration_end(
                    outer,
                    CoordinateDescentResult(
                        models=dict(models),
                        best_models=dict(best_models) if best_models else dict(models),
                        best_metric=best_metric,
                        objective_history=list(objective_history),
                        validation_history=list(validation_history),
                    ),
                )

        if self.validate is None or not best_models:
            best_models = dict(models)
        return CoordinateDescentResult(
            models=models,
            best_models=best_models,
            best_metric=best_metric,
            objective_history=objective_history,
            validation_history=validation_history,
        )
