"""Block coordinate descent: the outer GAME training loop.

Reference parity: algorithm/CoordinateDescent.scala:40 (run :57, optimize
:97-321): per outer iteration, per coordinate — residual = total score minus
the coordinate's own score (:183), retrain the coordinate against the
residual, rescore, log the objective (:247-258), evaluate validation after
each coordinate update (:265-294), and keep the best full model seen by the
first evaluator (:299-307).

Score plane: the reference's aggressive RDD persist/unpersist choreography
becomes per-coordinate score arrays — but at production row counts those are
NOT small, so where they live matters. Two planes are supported:

- ``score_plane="device"`` (default): scores are device-resident
  ``jax.Array``s on the training mesh. The driver maintains a RUNNING total
  updated incrementally (``total += new_own - old_own``) and computes
  ``residual = total - own`` inside jitted programs with donated buffers —
  O(C·N) device work per outer iteration, ZERO row-length host transfers in
  the steady state, and the training objective re-uses the running total
  (one plane pass per update instead of two full C-way re-sums).
- ``score_plane="host"``: the numpy plane, kept for fallback and parity
  testing (and auto-selected under multi-controller runs, where the host
  path's ``fetch_global`` collectives are the proven ordering). It runs the
  SAME incremental algebra in numpy — bitwise-identical IEEE f32 ops, so
  the two planes train bitwise-equal models — but pays two row-length
  boundary crossings per update (score pull, residual push) plus the host
  memory traffic of the numpy adds.

``transfer_stats`` (opt.tracking.TransferStats) counts every row-length
array crossing the host/device boundary plus host plane re-sums; a
``TransferStatsEvent`` with per-iteration deltas is emitted after each outer
iteration.

Schedule: ``schedule="sync"`` (default) runs the strictly sequential loop
above. ``schedule="async"`` pipelines coordinate solves on the device
plane: each solve is dispatched onto a worker pool against the residual
computed from the *current* running total — which may still be missing up
to ``staleness`` in-flight updates — and completed solves are folded back
into the device total (``total += new - old``) in dispatch order. Residuals
are computed on the driver thread at dispatch time and reconciliation is
FIFO, so the trajectory is deterministic for a given ``staleness``;
``staleness=0`` reconciles everything before each dispatch and is
bitwise-identical to sync (the solve merely runs on a worker thread). A
full reconciliation barrier ends every outer iteration, so the plane never
lags across iterations.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.coordinate import Coordinate
from photon_ml_tpu.algorithm.schedule import SCHEDULES, ScheduleExecutor
from photon_ml_tpu.evaluation.evaluators import nan_aware_better_than
from photon_ml_tpu.opt.tracking import TransferStats
from photon_ml_tpu.telemetry import note_jit_trace, span

logger = logging.getLogger("photon_ml_tpu")

SCORE_PLANES = ("device", "host")


@functools.lru_cache(maxsize=None)
def _plane_programs():
    """Jitted score-plane algebra, cached per process. ``apply`` donates the
    running total so each incremental update writes in place instead of
    copying a row-length buffer (CPU ignores donation and warns, so it is
    only requested on accelerators)."""
    donate = () if jax.default_backend() == "cpu" else (0,)

    def _apply(total, new_own, old_own):
        note_jit_trace("cd_plane", "apply")  # fires only on (re)trace
        return total + new_own - old_own

    def _residual(total, own):
        note_jit_trace("cd_plane", "residual")
        return total - own

    apply_ = jax.jit(_apply, donate_argnums=donate)
    residual_ = jax.jit(_residual)
    return apply_, residual_


@dataclasses.dataclass
class CoordinateDescentResult:
    models: Dict[str, object]                 # final per-coordinate models
    best_models: Dict[str, object]            # best by validation (== models if no validation)
    best_metric: Optional[float]
    objective_history: List[Tuple[str, float]]  # (coordinate, training objective)
    validation_history: List[Tuple[str, float]]  # (coordinate, first-evaluator metric)


class CoordinateDescent:
    """Orchestrates sequential coordinate updates (host control flow; all
    heavy math happens inside the coordinates' jit programs)."""

    def __init__(
        self,
        coordinates: Dict[str, Coordinate],
        num_rows: int,
        update_order: Optional[Sequence[str]] = None,
        training_objective: Optional[Callable[[np.ndarray], float]] = None,
        regularization_term: Optional[
            Callable[[Dict[str, object]], float]
        ] = None,
        validate: Optional[Callable[[Dict[str, object]], float]] = None,
        validation_better_than: Optional[Callable[[float, float], bool]] = None,
        emitter: Optional[object] = None,
        score_plane: str = "device",
        schedule: str = "sync",
        staleness: int = 1,
        progress: Optional[object] = None,
    ) -> None:
        if not coordinates:
            raise ValueError("need at least one coordinate")
        if score_plane not in SCORE_PLANES:
            raise ValueError(
                f"score_plane must be one of {SCORE_PLANES}, got {score_plane!r}"
            )
        if schedule not in SCHEDULES:
            raise ValueError(
                f"schedule must be one of {SCHEDULES}, got {schedule!r}"
            )
        if int(staleness) < 0:
            raise ValueError(f"staleness must be >= 0, got {staleness}")
        self.coordinates = coordinates
        self.num_rows = num_rows
        self.update_order = list(update_order) if update_order else list(coordinates)
        unknown = set(self.update_order) - set(coordinates)
        if unknown:
            raise ValueError(f"unknown coordinates in update order: {unknown}")
        self.training_objective = training_objective
        # optional Σ per-coordinate regularization over the current models:
        # the reference logs the objective decomposed into loss +
        # regularization per update (CoordinateDescent.scala:247-258)
        self.regularization_term = regularization_term
        self.validate = validate
        # Evaluator.better_than semantics (larger/smaller-is-better + NaN
        # policy) come from the evaluator itself; default: larger is better.
        self.validation_better_than = validation_better_than or nan_aware_better_than
        # optional event.EventEmitter: per-bucket SolverStatsEvent after each
        # random-effect coordinate update (adaptive-solve lane telemetry) and
        # a TransferStatsEvent per outer iteration
        self.emitter = emitter
        self.score_plane = score_plane
        # pipelined coordinate solves with bounded staleness; requires the
        # device plane (the host plane's numpy algebra is driver-owned), so
        # async over a host plane falls back to the sync loop at run time
        self.schedule = schedule
        self.staleness = int(staleness)
        # optional telemetry.progress.ConvergenceTracker: per-update
        # objective/grad/delta records plus the divergence watchdog (its
        # record_coordinate may raise DivergenceError, aborting the run).
        # None (the default) touches nothing — bitwise-identical training.
        self.progress = progress
        # transfer accounting of the most recent (or in-flight) run
        self.transfer_stats = TransferStats(
            score_plane=score_plane, num_rows=num_rows
        )

    def _effective_schedule(self) -> str:
        """Async needs device-resident score algebra; a host-plane run
        (chosen directly or forced by multi-controller) drops to sync."""
        if self.schedule == "async" and self.score_plane != "device":
            logger.warning(
                "schedule='async' requires the device score plane; "
                "falling back to the sync schedule on the %r plane",
                self.score_plane,
            )
            return "sync"
        return self.schedule

    def _emit_solver_stats(self, cid: str, coord: Coordinate) -> None:
        stats = getattr(coord, "last_solver_stats", None)
        if not stats:
            return
        for s in stats:
            logger.info("CD coordinate %s: %s", cid, s.to_summary_string())
        if self.emitter is None:
            return
        from photon_ml_tpu.event import SolverStatsEvent

        for s in stats:
            self.emitter.send_event(SolverStatsEvent.from_stats(cid, s))

    def _emit_transfer_stats(self, outer: int, prev: Dict[str, object]) -> None:
        """One TransferStatsEvent with THIS iteration's deltas."""
        t = self.transfer_stats
        t.outer_iterations += 1
        if self.emitter is None:
            return
        from photon_ml_tpu.event import TransferStatsEvent

        cur = t.snapshot()
        per_row = t.bytes_per_row_array
        d_h2d = int(cur["row_transfers_h2d"]) - int(prev["row_transfers_h2d"])
        d_d2h = int(cur["row_transfers_d2h"]) - int(prev["row_transfers_d2h"])
        self.emitter.send_event(
            TransferStatsEvent(
                score_plane=t.score_plane,
                outer_iteration=outer,
                num_rows=t.num_rows,
                row_transfers_h2d=d_h2d,
                row_transfers_d2h=d_d2h,
                row_bytes_h2d=d_h2d * per_row,
                row_bytes_d2h=d_d2h * per_row,
                host_score_sums=(
                    int(cur["host_score_sums"]) - int(prev["host_score_sums"])
                ),
                device_plane_updates=(
                    int(cur["device_plane_updates"])
                    - int(prev["device_plane_updates"])
                ),
            )
        )

    def _record_progress(
        self,
        outer: int,
        cid: str,
        coord: Coordinate,
        prev_model,
        model,
        objective: float,
        loss: Optional[float],
        regularization: Optional[float],
    ) -> None:
        """Fold one coordinate update into the convergence tracker: the
        objective point, solver telemetry joined from the coordinate's
        last_tracker/last_solve_info, the coefficient-delta norm, and any
        streamed per-block stats. May raise DivergenceError (watchdog)."""
        tracker = self.progress
        if tracker is None:
            return
        solver_iterations = None
        convergence_reason = None
        grad_norm = None
        states = getattr(getattr(coord, "last_tracker", None), "states", None)
        if states is not None:
            solver_iterations = int(states.iterations)
            reason = states.convergence_reason
            convergence_reason = getattr(reason, "name", str(reason))
            grad_norm = getattr(states, "grad_norm", None)
        info = getattr(coord, "last_solve_info", None)
        line_search_trials = (
            int(info.line_search_trials) if info is not None else None
        )
        coef_delta_norm = None
        new_means = getattr(getattr(model, "coefficients", None), "means", None)
        if new_means is not None:
            old_means = getattr(
                getattr(prev_model, "coefficients", None), "means", None
            )
            delta = (
                new_means if old_means is None else new_means - old_means
            )
            coef_delta_norm = float(jnp.linalg.norm(delta))
        block_stats = getattr(coord, "last_block_stats", None)
        if block_stats:
            tracker.record_blocks(outer, cid, block_stats)
        schedule = getattr(coord, "last_schedule_decisions", None)
        if schedule:
            tracker.record_schedule(outer, cid, schedule)
            coord.last_schedule_decisions = None
        residency = getattr(coord, "last_residency_decisions", None)
        if residency:
            tracker.record_residency(outer, cid, residency)
            coord.last_residency_decisions = None
        cluster_events = getattr(coord, "last_cluster_events", None)
        if cluster_events:
            tracker.record_cluster(outer, cid, cluster_events)
            coord.last_cluster_events = None
        cluster_passes = getattr(coord, "last_cluster_passes", None)
        if cluster_passes:
            tracker.record_cluster_passes(outer, cid, cluster_passes)
            coord.last_cluster_passes = None
        skipped = getattr(coord, "last_skipped_blocks", None)
        if skipped:
            for s in skipped:
                tracker.record_resilience(
                    "block_skipped",
                    "stream.build_block",
                    s.get("error", ""),
                    outer=outer,
                    coordinate=cid,
                    block=s.get("block"),
                )
            coord.last_skipped_blocks = None
        tracker.record_coordinate(
            outer,
            cid,
            objective,
            loss=loss,
            regularization=regularization,
            grad_norm=grad_norm,
            coef_delta_norm=coef_delta_norm,
            solver_iterations=solver_iterations,
            line_search_trials=line_search_trials,
            convergence_reason=convergence_reason,
        )

    def run(
        self,
        num_iterations: int,
        initial_models: Optional[Dict[str, object]] = None,
        start_iteration: int = 0,
        initial_best: Optional[Tuple[Dict[str, object], float]] = None,
        on_iteration_end: Optional[Callable[[int, "CoordinateDescentResult"], None]] = None,
    ) -> CoordinateDescentResult:
        """``start_iteration``/``initial_best``/``on_iteration_end`` support
        checkpoint-resume: the callback fires after each outer iteration with
        the running result; resume passes the restored models and best-so-far
        back in and skips completed iterations."""
        schedule = self._effective_schedule()
        with span(
            "cd/run",
            score_plane=self.score_plane,
            num_rows=self.num_rows,
            iterations=num_iterations,
            schedule=schedule,
        ):
            run = self._run_async if schedule == "async" else self._run
            return run(
                num_iterations,
                initial_models,
                start_iteration,
                initial_best,
                on_iteration_end,
            )

    def _run(
        self,
        num_iterations: int,
        initial_models: Optional[Dict[str, object]],
        start_iteration: int,
        initial_best: Optional[Tuple[Dict[str, object], float]],
        on_iteration_end: Optional[Callable[[int, "CoordinateDescentResult"], None]],
    ) -> CoordinateDescentResult:
        device = self.score_plane == "device"
        stats = self.transfer_stats = TransferStats(
            score_plane=self.score_plane, num_rows=self.num_rows
        )
        models: Dict[str, object] = dict(initial_models or {})
        scores: Dict[str, object] = {}

        def _score(cid: str, model) -> object:
            """One coordinate's [num_rows] scores on the active plane."""
            coord = self.coordinates[cid]
            if not device:
                stats.record_d2h()  # host plane pulls every score to numpy
                return coord.score(model)
            if coord.supports_device_plane:
                return coord.score_device(model)
            # fallback coordinate (e.g. factored RE): its host scores are
            # pulled down then pushed back up onto the device plane
            stats.record_d2h()
            stats.record_h2d()
            return coord.score_device(model)

        # initial scoring for warm-started models
        for cid, model in models.items():
            scores[cid] = _score(cid, model)

        # Both planes maintain a RUNNING total (the legacy driver re-summed
        # all C coordinates TWICE per update — once for the residual, once
        # for the objective; host_score_sums stays 0 now and the regression
        # test pins that down). The two planes execute the same sequence of
        # IEEE f32 elementwise adds/subs — np on host, XLA on device — so
        # their residuals (and therefore the trained models) match bitwise.
        if device:
            apply_, residual_ = _plane_programs()
            zeros = jnp.zeros(self.num_rows, dtype=jnp.float32)
            # fresh buffer: ``apply_`` donates its first argument, and the
            # shared ``zeros`` must outlive every first-update residual
            total = jnp.zeros_like(zeros)
            for s in scores.values():
                total = total + s
        else:
            total_np = np.zeros(self.num_rows, dtype=np.float32)
            for s in scores.values():
                total_np = total_np + s

        objective_history: List[Tuple[str, float]] = []
        validation_history: List[Tuple[str, float]] = []
        best_metric: Optional[float] = None
        best_models: Dict[str, object] = {}
        if initial_best is not None:
            best_models, best_metric = dict(initial_best[0]), initial_best[1]

        for outer in range(start_iteration, num_iterations):
            with span("cd/outer_iter", outer=outer):
                prev_transfers = stats.snapshot()
                for cid in self.update_order:
                    coord = self.coordinates[cid]
                    stats.coordinate_updates += 1
                    prev_model = models.get(cid)
                    # partialScore = fullScore - ownScore (reference
                    # CoordinateDescent.scala:183)
                    with span(
                        "cd/coordinate",
                        device_sync=True,
                        coordinate=cid,
                        outer=outer,
                    ):
                        if device:
                            old_own = scores.get(cid)
                            residual = residual_(
                                total, old_own if old_own is not None else zeros
                            )
                            if coord.supports_device_plane:
                                model = coord.update_model_device(
                                    models.get(cid), residual
                                )
                            else:
                                stats.record_d2h()
                                model = coord.update_model(
                                    models.get(cid), np.asarray(residual)
                                )
                            models[cid] = model
                            new_own = _score(cid, model)
                            # incremental running total: O(N) per update
                            # instead of a C-way re-sum; the old total's
                            # buffer is donated
                            total = apply_(
                                total,
                                new_own,
                                old_own if old_own is not None else zeros,
                            )
                            stats.device_plane_updates += 1
                            scores[cid] = new_own
                        else:
                            old_own = scores.get(cid)
                            residual = (
                                total_np - old_own
                                if old_own is not None
                                else total_np.copy()
                            )
                            # the coordinate pushes the residual
                            stats.record_h2d()
                            model = coord.update_model(models.get(cid), residual)
                            models[cid] = model
                            new_own = _score(cid, model)
                            # same incremental algebra as the device plane,
                            # in numpy
                            total_np = (
                                total_np + new_own - old_own
                                if old_own is not None
                                else total_np + new_own
                            )
                            scores[cid] = new_own
                    self._emit_solver_stats(cid, coord)

                    if self.training_objective is not None:
                        with span("cd/objective", coordinate=cid, outer=outer):
                            # both planes re-use the running total — the
                            # legacy second full re-sum per update is gone
                            plane_total = total if device else total_np
                            loss_val = float(self.training_objective(plane_total))
                            if self.regularization_term is not None:
                                # objective = loss + regularization (reference
                                # CoordinateDescent.scala:247-258); the history
                                # and the log agree on what "objective" means
                                reg = float(self.regularization_term(models))
                                obj = loss_val + reg
                                objective_history.append((cid, obj))
                                logger.info(
                                    "CD iter %d coordinate %s: loss %.6f + "
                                    "regularization %.6f = objective %.6f",
                                    outer, cid, loss_val, reg, obj,
                                )
                            else:
                                reg, obj = None, loss_val
                                objective_history.append((cid, loss_val))
                                logger.info(
                                    "CD iter %d coordinate %s: training "
                                    "objective %.6f",
                                    outer, cid, loss_val,
                                )
                        self._record_progress(
                            outer, cid, coord, prev_model, models[cid],
                            obj, loss_val, reg,
                        )
                    if self.validate is not None:
                        with span("cd/validate", coordinate=cid, outer=outer):
                            metric = float(self.validate(models))
                            validation_history.append((cid, metric))
                            if self.progress is not None:
                                self.progress.record_validation(
                                    outer, cid, metric
                                )
                            logger.info(
                                "CD iter %d coordinate %s: validation %.6f",
                                outer, cid, metric,
                            )
                            # best-model tracking starts once EVERY coordinate
                            # has trained: a mid-first-iteration snapshot would
                            # be a partial model (missing whole coordinates on
                            # disk) — the reference's snapshots always carry
                            # all coordinates (CoordinateDescent.scala:265-294,
                            # its models hold initial coefficients from the
                            # start)
                            if all(c in models for c in self.update_order) and (
                                best_metric is None
                                or self.validation_better_than(metric, best_metric)
                            ):
                                best_metric = metric
                                best_models = dict(models)

                self._emit_transfer_stats(outer, prev_transfers)
                if on_iteration_end is not None:
                    on_iteration_end(
                        outer,
                        CoordinateDescentResult(
                            models=dict(models),
                            best_models=(
                                dict(best_models) if best_models else dict(models)
                            ),
                            best_metric=best_metric,
                            objective_history=list(objective_history),
                            validation_history=list(validation_history),
                        ),
                    )

        logger.info("CD %s", stats.to_summary_string())
        if self.validate is None or not best_models:
            best_models = dict(models)
        return CoordinateDescentResult(
            models=models,
            best_models=best_models,
            best_metric=best_metric,
            objective_history=objective_history,
            validation_history=validation_history,
        )

    # ------------------------------------------------------------- async
    def _solve_in_flight(self, coord, model0, residual, stats, lock):
        """Worker-thread body of one dispatched coordinate solve: train
        against the (possibly stale) residual and rescore. Runs inside the
        executor's ``cd/overlap`` span; touches no driver-owned state —
        transfer counters are the only shared mutation, taken under the
        driver's lock with the same accounting as the sync device path."""
        if coord.supports_device_plane:
            model = coord.update_model_device(model0, residual)
            new_own = coord.score_device(model)
        else:
            with lock:
                stats.record_d2h()
            model = coord.update_model(model0, np.asarray(residual))
            with lock:
                stats.record_d2h()
                stats.record_h2d()
            new_own = coord.score_device(model)
        return model, new_own

    def _run_async(
        self,
        num_iterations: int,
        initial_models: Optional[Dict[str, object]],
        start_iteration: int,
        initial_best: Optional[Tuple[Dict[str, object], float]],
        on_iteration_end: Optional[Callable[[int, "CoordinateDescentResult"], None]],
    ) -> CoordinateDescentResult:
        """Bounded-staleness pipelined schedule over the device plane.

        Per outer iteration, each coordinate's residual is computed on the
        driver from the CURRENT running total — which may still be missing
        the deltas of up to ``staleness`` unreconciled solves — and the
        solve is dispatched to the worker pool. Before every dispatch the
        driver reconciles down to the staleness bound (FIFO), folding each
        finished solve into the total (``total += new - old``) and
        recording its objective/validation entry at that point, so the
        histories keep the sync loop's one-entry-per-update structure. A
        full drain ends each iteration: the next iteration never sees a
        stale plane.
        """
        stats = self.transfer_stats = TransferStats(
            score_plane=self.score_plane, num_rows=self.num_rows
        )
        stats_lock = threading.Lock()
        models: Dict[str, object] = dict(initial_models or {})
        scores: Dict[str, object] = {}

        apply_, residual_ = _plane_programs()
        zeros = jnp.zeros(self.num_rows, dtype=jnp.float32)
        total = jnp.zeros_like(zeros)

        # initial scoring for warm-started models (same path as sync)
        for cid, model in models.items():
            coord = self.coordinates[cid]
            if not coord.supports_device_plane:
                stats.record_d2h()
                stats.record_h2d()
            scores[cid] = coord.score_device(model)
            total = total + scores[cid]

        objective_history: List[Tuple[str, float]] = []
        validation_history: List[Tuple[str, float]] = []
        best_metric: Optional[float] = None
        best_models: Dict[str, object] = {}
        if initial_best is not None:
            best_models, best_metric = dict(initial_best[0]), initial_best[1]

        # pending: (cid, old_own, in-flight work) in dispatch order
        pending: List[Tuple[str, object, object]] = []
        executor = ScheduleExecutor(
            max_in_flight=min(len(self.update_order), self.staleness + 1),
            name="cd-async",
        )

        def _reconcile_one(outer: int) -> None:
            nonlocal total, best_metric, best_models
            cid, old_own, work = pending.pop(0)
            coord = self.coordinates[cid]
            prev_model = models.get(cid)
            with span(
                "cd/reconcile", device_sync=True, coordinate=cid, outer=outer
            ):
                model, new_own = work.result()
                models[cid] = model
                total = apply_(
                    total, new_own, old_own if old_own is not None else zeros
                )
                stats.device_plane_updates += 1
                scores[cid] = new_own
            self._emit_solver_stats(cid, coord)

            if self.training_objective is not None:
                with span("cd/objective", coordinate=cid, outer=outer):
                    loss_val = float(self.training_objective(total))
                    if self.regularization_term is not None:
                        reg = float(self.regularization_term(models))
                        obj = loss_val + reg
                        objective_history.append((cid, obj))
                        logger.info(
                            "CD iter %d coordinate %s: loss %.6f + "
                            "regularization %.6f = objective %.6f",
                            outer, cid, loss_val, reg, obj,
                        )
                    else:
                        reg, obj = None, loss_val
                        objective_history.append((cid, loss_val))
                        logger.info(
                            "CD iter %d coordinate %s: training "
                            "objective %.6f",
                            outer, cid, loss_val,
                        )
                self._record_progress(
                    outer, cid, coord, prev_model, models[cid],
                    obj, loss_val, reg,
                )
            if self.validate is not None:
                with span("cd/validate", coordinate=cid, outer=outer):
                    metric = float(self.validate(models))
                    validation_history.append((cid, metric))
                    if self.progress is not None:
                        self.progress.record_validation(outer, cid, metric)
                    logger.info(
                        "CD iter %d coordinate %s: validation %.6f",
                        outer, cid, metric,
                    )
                    if all(c in models for c in self.update_order) and (
                        best_metric is None
                        or self.validation_better_than(metric, best_metric)
                    ):
                        best_metric = metric
                        best_models = dict(models)

        try:
            for outer in range(start_iteration, num_iterations):
                with span("cd/outer_iter", outer=outer, schedule="async"):
                    prev_transfers = stats.snapshot()
                    for cid in self.update_order:
                        # bound the lag BEFORE dispatch: at most `staleness`
                        # unreconciled updates may be missing from the
                        # residual this coordinate trains against
                        while len(pending) > self.staleness:
                            _reconcile_one(outer)
                        coord = self.coordinates[cid]
                        stats.coordinate_updates += 1
                        old_own = scores.get(cid)
                        residual = residual_(
                            total, old_own if old_own is not None else zeros
                        )
                        work = executor.submit(
                            cid,
                            functools.partial(
                                self._solve_in_flight,
                                coord,
                                models.get(cid),
                                residual,
                                stats,
                                stats_lock,
                            ),
                            span_name="cd/overlap",
                            coordinate=cid,
                            outer=outer,
                        )
                        pending.append((cid, old_own, work))
                    # iteration barrier: fold everything before the next
                    # outer iteration (the plane lags within an iteration
                    # only)
                    while pending:
                        _reconcile_one(outer)

                    self._emit_transfer_stats(outer, prev_transfers)
                    if on_iteration_end is not None:
                        on_iteration_end(
                            outer,
                            CoordinateDescentResult(
                                models=dict(models),
                                best_models=(
                                    dict(best_models)
                                    if best_models
                                    else dict(models)
                                ),
                                best_metric=best_metric,
                                objective_history=list(objective_history),
                                validation_history=list(validation_history),
                            ),
                        )
        finally:
            executor.shutdown(wait=True)

        logger.info("CD %s", stats.to_summary_string())
        if self.validate is None or not best_models:
            best_models = dict(models)
        return CoordinateDescentResult(
            models=models,
            best_models=best_models,
            best_metric=best_metric,
            objective_history=objective_history,
            validation_history=validation_history,
        )
