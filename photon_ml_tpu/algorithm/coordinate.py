"""Coordinates: the per-block training units of GAME coordinate descent.

Reference parity: algorithm/Coordinate.scala:27 (updateModel with residual
offsets :59-62 — ``dataSet.addScoresToOffsets(score)`` then optimize the
coordinate alone), FixedEffectCoordinate.scala:34 (whole-data GLM solve;
score :159-166) and RandomEffectCoordinate.scala:39 (per-entity local solves;
active+passive scoring :157-187).

A coordinate owns its (device-resident) dataset and knows how to (a) train
its model given residual offsets from all other coordinates, and (b) produce
raw per-row scores aligned with the global row order.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.random_effect import RandomEffectDataset
from photon_ml_tpu.estimators.model_training import train_glm
from photon_ml_tpu.estimators.random_effect import (
    score_random_effects,
    train_random_effects,
)
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.models.random_effect import RandomEffectModel
from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.opt.config import GlmOptimizationConfiguration
from photon_ml_tpu.opt.tracking import (
    FixedEffectOptimizationTracker,
    OptimizationStatesTracker,
    RandomEffectOptimizationTracker,
)
from photon_ml_tpu.sampler import down_sampler_for
from photon_ml_tpu.types import TaskType


class Coordinate(abc.ABC):
    """One block of the GAME model (reference Coordinate.scala:27)."""

    @abc.abstractmethod
    def update_model(self, model, residual_scores: np.ndarray):
        """Train this coordinate against residual scores from the others
        (the offsets trick, Coordinate.scala:59-62). model may be None
        (first pass) or the previous model (warm start)."""

    @abc.abstractmethod
    def score(self, model) -> np.ndarray:
        """Raw scores x.w per row of THIS coordinate's training data,
        aligned to global row order, zeros for rows it does not cover."""


@dataclasses.dataclass
class FixedEffectCoordinate(Coordinate):
    """Global GLM over one feature shard (reference
    FixedEffectCoordinate.scala:34). ``data`` carries the GAME-level base
    offsets; residual scores are added on top per update."""

    data: LabeledData
    task: TaskType
    configuration: GlmOptimizationConfiguration
    down_sampling_seed: int = 0
    # when data.norm is set, the shift modes need the intercept slot to map
    # coefficients back to the original space (train_glm contract)
    intercept_index: Optional[int] = None
    # telemetry from the most recent update (reference
    # FixedEffectOptimizationTracker.scala)
    last_tracker: Optional[FixedEffectOptimizationTracker] = dataclasses.field(
        default=None, repr=False
    )

    def update_model(
        self, model: Optional[GeneralizedLinearModel], residual_scores: np.ndarray
    ) -> GeneralizedLinearModel:
        data = self.data.replace(
            offsets=self.data.offsets + jnp.asarray(residual_scores)
        )
        rate = self.configuration.down_sampling_rate
        if rate < 1.0:
            # runWithSampling (reference DistributedOptimizationProblem
            # :143-155): down-sample before the solve, weights re-scaled so
            # the objective stays unbiased.
            sampler = down_sampler_for(self.task, rate)
            weights = sampler.sample_weights(
                np.asarray(data.labels), np.asarray(data.weights),
                seed=self.down_sampling_seed,
            )
            data = data.replace(weights=jnp.asarray(weights))
        fit = train_glm(
            data,
            self.task,
            self.configuration,
            initial_model=model,
            intercept_index=self.intercept_index,
        )[0]
        self.last_tracker = FixedEffectOptimizationTracker(
            states=OptimizationStatesTracker.from_result(fit.result)
        )
        return fit.model

    def score(self, model: GeneralizedLinearModel) -> np.ndarray:
        return np.asarray(model.compute_score(self.data.features))


@dataclasses.dataclass
class RandomEffectCoordinate(Coordinate):
    """Per-entity GLMs over one feature shard (reference
    RandomEffectCoordinate.scala:39). Residual offsets are re-grouped into
    the entity blocks on each update."""

    dataset: RandomEffectDataset
    task: TaskType
    configuration: GlmOptimizationConfiguration
    base_offsets: np.ndarray  # GAME-level offsets, original row order
    # telemetry from the most recent update (reference
    # RandomEffectOptimizationTracker.scala)
    last_tracker: Optional[RandomEffectOptimizationTracker] = dataclasses.field(
        default=None, repr=False
    )

    def update_model(
        self, model: Optional[RandomEffectModel], residual_scores: np.ndarray
    ) -> RandomEffectModel:
        ds = self.dataset.update_offsets(self.base_offsets + residual_scores)
        new_model, results = train_random_effects(
            ds, self.task, self.configuration, initial_model=model
        )
        # every entity lane in a bucket is a real entity (buckets are built
        # exact-size; only the sample axis is padded), so no mask is needed
        self.last_tracker = RandomEffectOptimizationTracker.from_results(results)
        return new_model

    def score(self, model: RandomEffectModel) -> np.ndarray:
        return score_random_effects(model, self.dataset)
