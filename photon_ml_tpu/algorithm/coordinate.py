"""Coordinates: the per-block training units of GAME coordinate descent.

Reference parity: algorithm/Coordinate.scala:27 (updateModel with residual
offsets :59-62 — ``dataSet.addScoresToOffsets(score)`` then optimize the
coordinate alone), FixedEffectCoordinate.scala:34 (whole-data GLM solve;
score :159-166) and RandomEffectCoordinate.scala:39 (per-entity local solves;
active+passive scoring :157-187).

A coordinate owns its (device-resident) dataset and knows how to (a) train
its model given residual offsets from all other coordinates, and (b) produce
raw per-row scores aligned with the global row order.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.parallel.mesh import fetch_global

from photon_ml_tpu.data.random_effect import RandomEffectDataset
from photon_ml_tpu.estimators.model_training import train_glm
from photon_ml_tpu.estimators.random_effect import (
    score_random_effects,
    score_random_effects_device,
    train_random_effects,
)
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.models.random_effect import RandomEffectModel
from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.opt.config import GlmOptimizationConfiguration
from photon_ml_tpu.opt.tracking import (
    FixedEffectOptimizationTracker,
    OptimizationStatesTracker,
    RandomEffectOptimizationTracker,
)
from photon_ml_tpu.sampler import down_sampler_for
from photon_ml_tpu.telemetry import span
from photon_ml_tpu.types import TaskType


class Coordinate(abc.ABC):
    """One block of the GAME model (reference Coordinate.scala:27)."""

    # True when score_device/update_model_device avoid ALL row-length
    # host<->device transfers (overridden by the concrete coordinates that
    # implement a real device path); the CD driver falls back through the
    # host methods — and counts the transfers — when False.
    supports_device_plane = False

    @abc.abstractmethod
    def update_model(self, model, residual_scores: np.ndarray):
        """Train this coordinate against residual scores from the others
        (the offsets trick, Coordinate.scala:59-62). model may be None
        (first pass) or the previous model (warm start)."""

    @abc.abstractmethod
    def score(self, model) -> np.ndarray:
        """Raw scores x.w per row of THIS coordinate's training data,
        aligned to global row order, zeros for rows it does not cover."""

    def update_model_device(self, model, residual_scores: jax.Array):
        """``update_model`` with a device-resident residual plane. The base
        implementation round-trips through host (coordinates without a
        device path, e.g. the factored RE block); FE/RE override it with
        zero-row-transfer versions."""
        return self.update_model(model, np.asarray(residual_scores))

    def score_device(self, model) -> jax.Array:
        """``score`` as a device-resident [num_rows] array. Base
        implementation uploads the host scores; overridden with direct
        device programs where the coordinate's data is device-resident."""
        return jnp.asarray(self.score(model))


@jax.jit
def _fused_residual_offsets(base: jax.Array, residual: jax.Array) -> jax.Array:
    """base_offsets + residual in one program, zero-padding the residual up
    to the (device-grid) padded batch length when needed. Shapes are static
    at trace time, so the pad + add fuse into a single XLA computation."""
    if residual.shape[0] < base.shape[0]:
        residual = jnp.pad(residual, (0, base.shape[0] - residual.shape[0]))
    return base + residual


@dataclasses.dataclass
class FixedEffectCoordinate(Coordinate):
    """Global GLM over one feature shard (reference
    FixedEffectCoordinate.scala:34). ``data`` carries the GAME-level base
    offsets; residual scores are added on top per update."""

    data: LabeledData
    task: TaskType
    configuration: GlmOptimizationConfiguration
    down_sampling_seed: int = 0
    # when data.norm is set, the shift modes need the intercept slot to map
    # coefficients back to the original space (train_glm contract)
    intercept_index: Optional[int] = None
    # attach per-coefficient variances ~ 1/(H_jj+eps) to trained models
    # (reference COMPUTE_VARIANCE -> DistributedOptimizationProblem.scala:80-94)
    compute_variances: bool = False
    # telemetry from the most recent update (reference
    # FixedEffectOptimizationTracker.scala)
    last_tracker: Optional[FixedEffectOptimizationTracker] = dataclasses.field(
        default=None, repr=False
    )
    # multi-chip layouts pad the batch and the feature axis to the device
    # grid; the coordinate speaks global (unpadded) shapes at its boundary
    # (models carry [num_real_cols] coefficients, scores are [num_real_rows])
    num_real_rows: Optional[int] = None
    num_real_cols: Optional[int] = None
    # (model, padded solve vector) for the model last returned by
    # update_model, the vector kept with the sharding the jit'd solve
    # produced (feat-sharded on a grid): warm starts and scoring reuse it
    # instead of re-materializing the full [d_pad] vector on one device
    # each outer iteration. The strong model reference keys the cache by
    # identity safely (no id() reuse after garbage collection).
    _w_padded_cache: Optional[tuple] = dataclasses.field(
        default=None, repr=False
    )

    supports_device_plane = True

    def update_model(
        self, model: Optional[GeneralizedLinearModel], residual_scores: np.ndarray
    ) -> GeneralizedLinearModel:
        residual = np.asarray(residual_scores)
        n_pad = self.data.num_rows
        if residual.shape[0] < n_pad:
            residual = np.pad(residual, (0, n_pad - residual.shape[0]))
        return self._update_with_offsets(
            model, self.data.offsets + jnp.asarray(residual)
        )

    def update_model_device(
        self, model: Optional[GeneralizedLinearModel], residual_scores: jax.Array
    ) -> GeneralizedLinearModel:
        """Device-plane update: the residual stays on device and the pad +
        base-offset add run as ONE fused jit program feeding the solve — no
        row-length host transfer anywhere on this path."""
        return self._update_with_offsets(
            model, _fused_residual_offsets(self.data.offsets, residual_scores)
        )

    def _update_with_offsets(
        self, model: Optional[GeneralizedLinearModel], offsets: jax.Array
    ) -> GeneralizedLinearModel:
        with span(
            "fe/solve",
            device_sync=True,
            optimizer=self.configuration.optimizer_config.optimizer.name,
        ):
            return self._solve_with_offsets(model, offsets)

    def _solve_with_offsets(
        self, model: Optional[GeneralizedLinearModel], offsets: jax.Array
    ) -> GeneralizedLinearModel:
        data = self.data.replace(offsets=offsets)
        rate = self.configuration.down_sampling_rate
        if rate < 1.0:
            # runWithSampling (reference DistributedOptimizationProblem
            # :143-155): down-sample before the solve, weights re-scaled so
            # the objective stays unbiased.
            sampler = down_sampler_for(self.task, rate)
            weights = sampler.sample_weights(
                fetch_global(data.labels), fetch_global(data.weights),
                seed=self.down_sampling_seed,
            )
            data = data.replace(weights=jnp.asarray(weights))
        fit = train_glm(
            data,
            self.task,
            self.configuration,
            initial_model=self._pad_model(model),
            compute_variances=self.compute_variances,
            intercept_index=self.intercept_index,
        )[0]
        self.last_tracker = FixedEffectOptimizationTracker(
            states=OptimizationStatesTracker.from_result(fit.result)
        )
        trimmed = self._trim_model(fit.model)
        if self.num_real_cols is not None:
            # fit.model's means come straight out of the jit'd solve with
            # whatever sharding GSPMD chose (feat-sharded on a grid)
            self._w_padded_cache = (trimmed, fit.model.coefficients.means)
        return trimmed

    def _cached_padded_w(self, model) -> Optional[jax.Array]:
        if self._w_padded_cache is not None and self._w_padded_cache[0] is model:
            return self._w_padded_cache[1]
        return None

    def _pad_model(
        self, model: Optional[GeneralizedLinearModel]
    ) -> Optional[GeneralizedLinearModel]:
        """Warm starts arrive in real [d]; the padded layout trains in
        [d_pad] (trailing zeros for the dead columns). The padded vector of
        the model this coordinate itself produced is served from the
        sharded cache."""
        if model is None or self.num_real_cols is None:
            return model
        return model.replace(
            coefficients=model.coefficients.replace(
                means=self._padded_w(model), variances=None
            )
        )

    def _trim_model(self, model: GeneralizedLinearModel) -> GeneralizedLinearModel:
        if self.num_real_cols is None:
            return model
        d = self.num_real_cols
        coef = model.coefficients
        if coef.means.shape[0] == d:
            return model
        return model.replace(
            coefficients=coef.replace(
                means=coef.means[:d],
                variances=None if coef.variances is None else coef.variances[:d],
            )
        )

    def _padded_w(self, model: GeneralizedLinearModel) -> jax.Array:
        """The [d_pad] solve-space weight vector for ``model``, cached by
        model identity: a miss pads once and REFILLS the cache, so repeated
        score calls against the same trimmed model (every CD residual uses
        the other coordinates' scores) never re-pad."""
        w = self._cached_padded_w(model)
        if w is None:
            w = jnp.asarray(model.coefficients.means)
            if self.num_real_cols is not None and w.shape[0] < self.data.dim:
                w = jnp.pad(w, (0, self.data.dim - w.shape[0]))
            self._w_padded_cache = (model, w)
        return w

    def score(self, model: GeneralizedLinearModel) -> np.ndarray:
        scores = fetch_global(self.data.features.matvec(self._padded_w(model)))
        if self.num_real_rows is not None:
            scores = scores[: self.num_real_rows]
        return scores

    def score_device(self, model: GeneralizedLinearModel) -> jax.Array:
        """Device-plane ``score``: the matvec result never leaves the mesh;
        padded batch rows are sliced off on device."""
        scores = self.data.features.matvec(self._padded_w(model))
        if self.num_real_rows is not None:
            scores = scores[: self.num_real_rows]
        return scores


@dataclasses.dataclass
class RandomEffectCoordinate(Coordinate):
    """Per-entity GLMs over one feature shard (reference
    RandomEffectCoordinate.scala:39). Residual offsets are re-grouped into
    the entity blocks on each update."""

    dataset: RandomEffectDataset
    task: TaskType
    configuration: GlmOptimizationConfiguration
    base_offsets: np.ndarray  # GAME-level offsets, original row order
    # telemetry from the most recent update (reference
    # RandomEffectOptimizationTracker.scala)
    last_tracker: Optional[RandomEffectOptimizationTracker] = dataclasses.field(
        default=None, repr=False
    )
    # per-bucket SolverStats from the most recent update (the convergence-
    # adaptive driver's lane-efficiency telemetry; empty before any update)
    last_solver_stats: list = dataclasses.field(default_factory=list, repr=False)
    # multi-chip: shard each bucket's entity axis over these mesh axes
    # (entity solves are independent — no collectives); re-applied after
    # every offset rebuild
    mesh: Optional[object] = None
    mesh_axes: Optional[tuple] = None
    # per-entity coefficient variances from the local Hessian diagonals
    # (reference COMPUTE_VARIANCE; SingleNodeOptimizationProblem variances)
    compute_variances: bool = False
    # >= 2 overlaps that many bucket solves on worker threads (the async CD
    # schedule sets this; 0 = sequential, the bitwise-identical default)
    overlap_buckets: int = 0
    # base_offsets uploaded once; every device-plane update reuses it in the
    # jitted regroup instead of re-pushing a row-length host array
    _base_offsets_dev: Optional[jax.Array] = dataclasses.field(
        default=None, repr=False
    )

    supports_device_plane = True

    def _place(self, ds: RandomEffectDataset) -> RandomEffectDataset:
        if self.mesh is None:
            return ds
        from photon_ml_tpu.data.random_effect import place_dataset

        return place_dataset(ds, self.mesh, self.mesh_axes)

    def update_model(
        self, model: Optional[RandomEffectModel], residual_scores: np.ndarray
    ) -> RandomEffectModel:
        ds = self._place(
            self.dataset.update_offsets(self.base_offsets + residual_scores)
        )
        return self._train(ds, model)

    def update_model_device(
        self, model: Optional[RandomEffectModel], residual_scores: jax.Array
    ) -> RandomEffectModel:
        """Device-plane update: base + residual offsets are regrouped into
        the entity-grouped blocks by the precomputed (bucket, lane, slot)
        gather on device — the per-update host rebuild disappears."""
        if self._base_offsets_dev is None:
            self._base_offsets_dev = jnp.asarray(
                np.asarray(self.base_offsets, dtype=np.float32)
            )
        ds = self._place(
            self.dataset.update_offsets_device(
                _fused_residual_offsets(self._base_offsets_dev, residual_scores)
            )
        )
        return self._train(ds, model)

    def _train(
        self, ds: RandomEffectDataset, model: Optional[RandomEffectModel]
    ) -> RandomEffectModel:
        stats: list = []
        with span("re/train", buckets=len(ds.buckets)):
            new_model, results = train_random_effects(
                ds, self.task, self.configuration, initial_model=model,
                compute_variances=self.compute_variances, stats_out=stats,
                overlap_buckets=self.overlap_buckets,
            )
        self.last_solver_stats = stats
        # entity lanes beyond the real ids (mesh padding) carry zero weights
        # and all-invalid projections: their solves are trivial, their
        # coefficients are forced to 0 by the proj_valid mask, and the
        # telemetry excludes them
        self.last_tracker = RandomEffectOptimizationTracker.from_results(
            results, real_counts=[len(ids) for ids in ds.entity_ids]
        )
        return new_model

    def score(self, model: RandomEffectModel) -> np.ndarray:
        return score_random_effects(model, self.dataset)

    def score_device(self, model: RandomEffectModel) -> jax.Array:
        return score_random_effects_device(model, self.dataset)
