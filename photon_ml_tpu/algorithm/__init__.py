from photon_ml_tpu.algorithm.coordinate import (
    Coordinate,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.algorithm.coordinate_descent import (
    CoordinateDescent,
    CoordinateDescentResult,
)
from photon_ml_tpu.algorithm.schedule import (
    SCHEDULES,
    InFlight,
    ScheduleExecutor,
)

__all__ = [
    "Coordinate",
    "FixedEffectCoordinate",
    "RandomEffectCoordinate",
    "CoordinateDescent",
    "CoordinateDescentResult",
    "SCHEDULES",
    "InFlight",
    "ScheduleExecutor",
]
