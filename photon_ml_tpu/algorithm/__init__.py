from photon_ml_tpu.algorithm.coordinate import (
    Coordinate,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.algorithm.coordinate_descent import (
    CoordinateDescent,
    CoordinateDescentResult,
)

__all__ = [
    "Coordinate",
    "FixedEffectCoordinate",
    "RandomEffectCoordinate",
    "CoordinateDescent",
    "CoordinateDescentResult",
]
