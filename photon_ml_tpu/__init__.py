"""photon-ml-tpu: a TPU-native framework with the capabilities of LinkedIn Photon-ML.

Large-scale Generalized Linear Models (linear / logistic / Poisson regression,
smoothed-hinge linear SVM) and GAME/GLMix mixed-effect models (fixed effect +
per-entity random effects + factored/matrix-factorization coordinates) trained
by block coordinate descent — re-designed for TPU:

- Losses/objectives are pure jit-compiled functions (value / gradient /
  Hessian-vector) over struct-of-array batches; feature normalization is folded
  in algebraically so sparse inputs are never densified (mirrors the contract
  of reference ValueAndGradientAggregator.scala:35-79).
- Optimizers (L-BFGS, OWL-QN, TRON) run entirely on device as
  ``lax.while_loop`` programs (reference: photon-lib optimization/*.scala,
  which wrapped Breeze on the Spark driver).
- The fixed-effect coordinate is data-parallel over a ``jax.sharding.Mesh``
  with ``psum`` all-reduce replacing Spark ``treeAggregate``.
- Random effects are millions of independent small solves batched with ``vmap``
  over padded entity blocks sharded across devices (reference:
  RandomEffectCoordinate.scala join+mapValues).
"""

from photon_ml_tpu import types
from photon_ml_tpu.types import TaskType

__version__ = "0.1.0"
__all__ = ["types", "TaskType", "__version__"]
