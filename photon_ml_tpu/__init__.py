"""photon-ml-tpu: a TPU-native framework with the capabilities of LinkedIn Photon-ML.

Large-scale Generalized Linear Models (linear / logistic / Poisson regression,
smoothed-hinge linear SVM) and GAME/GLMix mixed-effect models (fixed effect +
per-entity random effects + factored/matrix-factorization coordinates) trained
by block coordinate descent — re-designed for TPU:

- Losses/objectives are pure jit-compiled functions (value / gradient /
  Hessian-vector) over struct-of-array batches; feature normalization is folded
  in algebraically so sparse inputs are never densified (mirrors the contract
  of reference ValueAndGradientAggregator.scala:35-79).
- Optimizers (L-BFGS, OWL-QN, TRON) run entirely on device as
  ``lax.while_loop`` programs (reference: photon-lib optimization/*.scala,
  which wrapped Breeze on the Spark driver).
- The fixed-effect coordinate is data-parallel over a ``jax.sharding.Mesh``
  with ``psum`` all-reduce replacing Spark ``treeAggregate``.
- Random effects are millions of independent small solves batched with ``vmap``
  over padded entity blocks sharded across devices (reference:
  RandomEffectCoordinate.scala join+mapValues).
"""

from photon_ml_tpu import types
from photon_ml_tpu.types import NormalizationType, RegularizationType, TaskType

__version__ = "0.1.0"

# The user-facing API re-exported lazily (PEP 562): `from photon_ml_tpu
# import GameEstimator` works without paying jax-import cost for tools that
# only want the package version or types.
_LAZY = {
    "GameEstimator": "photon_ml_tpu.estimators.game",
    "FixedEffectCoordinateConfiguration": "photon_ml_tpu.estimators.game",
    "RandomEffectCoordinateConfiguration": "photon_ml_tpu.estimators.game",
    "FactoredRandomEffectCoordinateConfiguration": "photon_ml_tpu.estimators.game",
    "ParallelConfiguration": "photon_ml_tpu.estimators.game",
    "train_glm": "photon_ml_tpu.estimators.model_training",
    "GameData": "photon_ml_tpu.data.game_data",
    "FeatureShard": "photon_ml_tpu.data.game_data",
    "RandomEffectDataConfiguration": "photon_ml_tpu.data.random_effect",
    "GlmOptimizationConfiguration": "photon_ml_tpu.opt.config",
    "OptimizerConfig": "photon_ml_tpu.opt.config",
    "RegularizationContext": "photon_ml_tpu.opt.config",
    "NormalizationContext": "photon_ml_tpu.normalization",
    "summarize": "photon_ml_tpu.stat.summary",
}
# lazy submodules (the module object itself is the attribute)
_LAZY_MODULES = ("testing",)

__all__ = [
    "types", "TaskType", "NormalizationType", "RegularizationType",
    "__version__", *sorted(_LAZY), *_LAZY_MODULES,
]


def __getattr__(name: str):
    import importlib

    if name in _LAZY_MODULES:
        value = importlib.import_module(f"{__name__}.{name}")
    else:
        target = _LAZY.get(name)
        if target is None:
            raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
        value = getattr(importlib.import_module(target), name)
    globals()[name] = value  # subsequent accesses are plain dict hits
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY) | set(_LAZY_MODULES))
