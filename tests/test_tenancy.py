"""Tenancy plane tests: multi-model variant serving over ONE shared scorer.

The load-bearing guarantees, per ISSUE acceptance criteria:

- a single tenant on the base variant scores BITWISE identically through
  the tenancy plane and through the plain sharded path (the parity gate
  CI runs);
- per-variant delta overlays diverge ONLY the delta-touched entities of
  the variant they are applied to — the base variant and every other
  variant stay bitwise unchanged — and a rollback restores bitwise state;
- variant chains are fingerprint-checked: a delta built against the
  wrong chain head is refused, per variant;
- the router is deterministic and seeded, ramps are monotone (raising a
  ramp keeps every request the variant already served), pins override;
- ``route_many`` and ``route`` make identical decisions (the bulk replay
  path cannot drift from the per-request path);
- per-tenant quotas shed ONLY the flooding tenant, priority reserves the
  global pool for high-priority tenants, and sheds are charged to the
  shedding tenant's own SLO error budget — never another tenant's;
- per-tenant SLO trackers expose independent error budgets, rendered as
  tenant-labeled Prometheus series;
- the tenancy scenarios (tenant_isolation / ramped_rollout /
  nearline_loop) build and run end to end, producing the per-tenant SLO
  verdicts the scenario sentinel requires.
"""

import dataclasses
import tempfile

import numpy as np
import pytest

from photon_ml_tpu.incremental import build_delta
from photon_ml_tpu.serving import (
    DEFAULT_TENANTS,
    RequestPlane,
    ServingMetrics,
    ShardedGameScorer,
    TenancyPlane,
    TenantBudget,
    TenantQuota,
    ValidationGate,
    VariantRegistry,
    VariantRouter,
    build_scenario,
    build_tenant_slos,
    make_nearline_fn,
    run_scenario,
    tag_requests,
)
from photon_ml_tpu.serving.tenancy import BASE_VARIANT, tag_request
from photon_ml_tpu.telemetry.metrics import MetricsRegistry

from test_serving_sharded import MAX_NNZ, _artifact, _requests

BUCKETS = (1, 2, 4, 8, 16, 32)
N_ENT = 64


def _scorer(art=None, **kw):
    return ShardedGameScorer(
        art if art is not None else _artifact(),
        max_nnz=MAX_NNZ,
        num_shards=2,
        **kw,
    )


def _scores(scorer, requests, view=None):
    out = scorer.score_batch(
        requests, bucket_size=len(requests), view=view
    )
    return {r.request_id: r.score for r in out}


def _delta_for(art, entities, seed=0, scale=0.5):
    rng = np.random.default_rng(seed)
    dim = art.tables["per_user"].dim
    re_updates = {
        "per_user": {
            e: {
                int(j): float(x)
                for j, x in zip(
                    rng.integers(0, dim, 2), rng.normal(0.0, scale, 2)
                )
            }
            for e in entities
        }
    }
    return re_updates


class TestVariantRegistry:
    def test_base_parity_through_plane(self):
        """The CI parity gate: one tenant, base variant only — scores
        through the tenancy plane are bitwise identical to the plain
        sharded path."""
        art = _artifact()
        reqs = _requests(64, ghost_every=11)
        plain = _scores(_scorer(art), reqs)
        tenancy = TenancyPlane(
            VariantRegistry(_scorer(art)),
            metrics=ServingMetrics(),
            bucket_sizes=(1, 2, 4, 8, 16, 32, 64),
        )
        out = tenancy.replay(tag_requests(reqs, "solo"), poll_every=0)
        assert len(out) == len(reqs)
        for r in out:
            rid = r.request_id.split("!", 1)[1]
            assert r.score == plain[rid], rid

    def test_variant_divergence_is_isolated(self):
        art = _artifact()
        reqs = _requests(64)
        scorer = _scorer(art)
        reg = VariantRegistry(scorer)
        reg.add_variant("v1")
        reg.add_variant("v2")
        before = _scores(scorer, reqs)
        touched = ["u3", "u5"]
        report = reg.apply_delta(
            "v1", build_delta(_delta_for(art, touched), art, generation=1)
        )
        assert report.rows_updated == len(touched)
        assert report.new_overlay_rows == len(touched)
        assert not report.rolled_back
        # v1 differs exactly on requests hitting touched entities
        v1 = _scores(scorer, reqs, view=reg.view("v1"))
        for r in reqs:
            hit = r.entity_ids.get("userId") in touched
            assert (v1[r.request_id] != before[r.request_id]) == hit, (
                r.request_id
            )
        # base and v2 are bitwise untouched
        assert _scores(scorer, reqs) == before
        assert reg.view("v2") is None  # undiverged -> plain path
        assert reg.state(BASE_VARIANT).overlay_row_count == 0

    def test_rollback_restores_bitwise(self):
        art = _artifact()
        reqs = _requests(48)
        scorer = _scorer(art)
        reg = VariantRegistry(scorer)
        reg.add_variant("v1")
        before = _scores(scorer, reqs)
        reg.apply_delta(
            "v1", build_delta(_delta_for(art, ["u1"]), art, generation=1)
        )
        # second generation rewrites the SAME overlay row in place
        d2 = build_delta(
            _delta_for(art, ["u1"], seed=9),
            art,
            base_fingerprint=reg.state("v1").fingerprint,
            generation=2,
        )
        reg.apply_delta("v1", d2)
        assert reg.state("v1").generation == 2
        assert reg.rollback("v1")
        st = reg.state("v1")
        assert st.generation == 1 and st.rollbacks == 1
        assert _scores(scorer, reqs) == before  # base never moved

    def test_gated_bad_delta_rejected_and_rolled_back(self):
        """A registry built with a per-variant ValidationGate refuses a
        delta that wrecks ranking: the swap report says rolled_back, the
        variant's generation never advances, the base stays bitwise
        untouched — and a benign delta still applies afterwards."""
        art = _artifact()
        reqs = _requests(64)
        scorer = _scorer(art)
        # labels = the base scorer's own top-half ranking, so baseline
        # AUC is 1.0 by construction and the gate measures pure drift
        base = scorer.score_batch(reqs, bucket_size=len(reqs))
        scores = np.asarray([r.score for r in base], dtype=np.float32)
        labels = (scores > np.median(scores)).astype(np.float32)
        reg = VariantRegistry(
            scorer,
            gate=ValidationGate(
                reqs, labels,
                max_auc_regression=0.02,
                bucket_size=len(reqs),
            ),
        )
        reg.add_variant("candidate")
        before = _scores(scorer, reqs)
        # 12 entities is well inside the overlay-slot headroom (the
        # shards hold 2x40 slots, 64 of them the resident base) yet a
        # scale-50 perturbation on them wrecks ranking far past the gate
        bad = build_delta(
            _delta_for(
                art, [f"u{i}" for i in range(12)], seed=5, scale=50.0
            ),
            art,
            generation=1,
        )
        report = reg.apply_delta("candidate", bad)
        assert report.rolled_back is True
        assert report.baseline_metric == pytest.approx(1.0)
        assert (
            report.validation_metric
            < report.baseline_metric - 0.02
        )
        st = reg.state("candidate")
        assert st.generation == 0 and st.rollbacks == 1
        assert _scores(scorer, reqs) == before  # base never moved
        # a benign delta on the same variant still clears the gate
        good = build_delta(
            _delta_for(art, ["u1"], seed=2, scale=0.01),
            art,
            generation=1,
        )
        report2 = reg.apply_delta("candidate", good)
        assert not report2.rolled_back
        assert reg.state("candidate").generation == 1
        assert report2.validation_metric >= 1.0 - 0.02

    def test_chain_check_refuses_wrong_head(self):
        art = _artifact()
        scorer = _scorer(art)
        reg = VariantRegistry(scorer)
        reg.add_variant("v1")
        # in-memory deltas carry fingerprint=None (save_delta fills it);
        # stamp one so the variant's chain head is real and checkable
        d1 = dataclasses.replace(
            build_delta(_delta_for(art, ["u2"]), art, generation=1),
            fingerprint="f" * 16,
        )
        reg.apply_delta("v1", d1)
        stale = build_delta(
            _delta_for(art, ["u4"], seed=3),
            art,
            base_fingerprint="0" * 16,
            generation=2,
        )
        with pytest.raises(ValueError, match="chains to base"):
            reg.apply_delta("v1", stale)
        assert reg.state("v1").generation == 1

    def test_unknown_variant_raises(self):
        reg = VariantRegistry(_scorer())
        with pytest.raises(KeyError):
            reg.state("nope")


class TestVariantRouter:
    def test_deterministic_and_seeded(self):
        r1 = VariantRouter(seed=5)
        r1.set_ramp("cand", 30.0)
        r2 = VariantRouter(seed=5)
        r2.set_ramp("cand", 30.0)
        ids = [f"r{i}" for i in range(400)]
        a = [r1.route("t", i) for i in ids]
        assert a == [r2.route("t", i) for i in ids]
        r3 = VariantRouter(seed=6)
        r3.set_ramp("cand", 30.0)
        assert a != [r3.route("t", i) for i in ids]

    def test_ramp_is_monotone(self):
        """Raising a ramp keeps every request the variant already
        served — the property a rollout needs."""
        router = VariantRouter(seed=1)
        ids = [f"req-{i}" for i in range(500)]
        router.set_ramp("cand", 10.0)
        at10 = {i for i in ids if router.route("t", i) == "cand"}
        router.set_ramp("cand", 55.0)
        at55 = {i for i in ids if router.route("t", i) == "cand"}
        assert at10 <= at55
        assert len(at55) > len(at10)

    def test_route_many_matches_route(self):
        router = VariantRouter(seed=3)
        router.set_ramp("a", 15.0)
        router.set_ramp("b", 40.0)
        router.pin("pinned", "a")
        ids = [f"x{i}" for i in range(300)]
        bulk = VariantRouter(seed=3)
        bulk.set_ramp("a", 15.0)
        bulk.set_ramp("b", 40.0)
        bulk.pin("pinned", "a")
        for tenant in ("alpha", "pinned", None):
            assert bulk.route_many(tenant, ids) == [
                router.route(tenant, i) for i in ids
            ]
        assert router.decisions == bulk.decisions

    def test_ramp_validation(self):
        router = VariantRouter()
        with pytest.raises(ValueError, match="sum to"):
            router.set_ramp("a", 60.0)
            router.set_ramp("b", 60.0)
        with pytest.raises(ValueError, match="in \\[0, 100\\]"):
            router.set_ramp("a", 120.0)

    def test_pin_overrides_ramp(self):
        router = VariantRouter(seed=0)
        router.set_ramp("cand", 100.0)
        router.pin("vip", BASE_VARIANT)
        assert router.route("vip", "r1") == BASE_VARIANT
        assert router.route("other", "r1") == "cand"
        router.pin("vip", None)
        assert router.route("vip", "r1") == "cand"


class TestTenantQuota:
    def test_flooder_sheds_alone(self):
        quota = TenantQuota({
            "a": TenantBudget(rate=1.0, burst=10),
            "b": TenantBudget(rate=1.0, burst=10),
        })
        for _ in range(25):
            quota.try_admit("a")
        for _ in range(8):
            assert quota.try_admit("b")
        stats = quota.stats()["tenants"]
        assert stats["a"]["shed"] == 15
        assert stats["b"]["shed"] == 0

    def test_priority_reserve(self):
        """The reserve fraction of the global pool is spendable only by
        top-priority tenants once the pool drains low."""
        quota = TenantQuota(
            {
                "gold": TenantBudget(rate=1.0, burst=100, priority=1),
                "bronze": TenantBudget(rate=1.0, burst=100, priority=0),
            },
            global_rate=1.0,
            global_burst=10,
            reserve_fraction=0.5,
        )
        admitted_bronze = sum(
            1 for _ in range(10) if quota.try_admit("bronze")
        )
        admitted_gold = sum(1 for _ in range(5) if quota.try_admit("gold"))
        assert admitted_bronze == 5  # stops at the reserve floor
        assert admitted_gold == 5    # reserve is theirs

    def test_unbudgeted_tenant_draws_global_pool(self):
        quota = TenantQuota({}, global_rate=1.0, global_burst=3)
        got = sum(1 for _ in range(5) if quota.try_admit("stranger"))
        assert got == 3


class TestTenancyPlane:
    def _stack(self, quota=None, registry_metrics=None):
        art = _artifact()
        scorer = _scorer(art)
        reg = VariantRegistry(scorer)
        mreg = (
            registry_metrics
            if registry_metrics is not None
            else MetricsRegistry()
        )
        slos = build_tenant_slos(
            ("alpha", "beta"), registry=mreg, latency_threshold_s=5.0
        )
        plane = RequestPlane(sample_rate=4, tenant_slos=slos)
        tenancy = TenancyPlane(
            reg,
            plane=plane,
            quota=quota,
            metrics=ServingMetrics(),
            metrics_registry=mreg,
            bucket_sizes=BUCKETS,
        )
        return art, tenancy, plane, mreg

    def test_shed_charges_only_the_flooder(self):
        quota = TenantQuota({
            "alpha": TenantBudget(rate=1.0, burst=5),
            "beta": TenantBudget(rate=1.0, burst=100),
        })
        _, tenancy, plane, _ = self._stack(quota=quota)
        reqs = _requests(40)
        stream = tag_requests(reqs[:20], "alpha") + tag_requests(
            reqs[20:], "beta"
        )
        out = tenancy.replay(stream, poll_every=0)
        assert len(out) == 25  # 5 alpha + 20 beta
        assert plane.tenant_errors.get("alpha", 0) == 15
        assert plane.tenant_errors.get("beta", 0) == 0
        alpha = plane.tenant_slos["alpha"].status()
        beta = plane.tenant_slos["beta"].status()
        assert alpha["verdict"].startswith("budget_exhausted")
        assert beta["verdict"] == "ok"

    def test_tenant_metrics_are_label_scoped(self):
        from photon_ml_tpu.serving import prometheus_text

        quota = TenantQuota({
            "alpha": TenantBudget(rate=1.0, burst=2),
        })
        _, tenancy, _, mreg = self._stack(quota=quota)
        tenancy.replay(
            tag_requests(_requests(8), "alpha"), poll_every=0
        )
        text = prometheus_text(mreg.snapshot())
        assert 'photon_serving_tenant_requests{tenant="alpha"} 8' in text
        assert 'photon_serving_tenant_shed{tenant="alpha"} 6' in text

    def test_tenant_separator_rejected_in_name(self):
        with pytest.raises(ValueError, match="must not contain"):
            tag_request(_requests(1)[0], "bad!tenant")

    def test_status_reports_all_layers(self):
        quota = TenantQuota({"alpha": TenantBudget(rate=1.0, burst=50)})
        _, tenancy, _, _ = self._stack(quota=quota)
        tenancy.replay(tag_requests(_requests(8), "alpha"), poll_every=0)
        doc = tenancy.status()
        assert BASE_VARIANT in doc["variants"]
        assert "alpha" in doc["quota"]["tenants"]
        assert doc["tenants"]["alpha"]["requests"] == 8
        assert doc["tenants"]["alpha"]["slo"]["verdict"] == "ok"


class TestTenancyScenarios:
    def _scenario_stack(self, registry):
        mreg = MetricsRegistry()
        slos = build_tenant_slos(
            DEFAULT_TENANTS, registry=mreg, latency_threshold_s=5.0
        )
        plane = RequestPlane(sample_rate=4, tenant_slos=slos)
        return TenancyPlane(
            registry,
            router=VariantRouter(seed=1),
            plane=plane,
            metrics=ServingMetrics(),
            metrics_registry=mreg,
            bucket_sizes=BUCKETS,
        ), plane

    def test_tenant_isolation_scenario(self):
        art = _artifact()
        scorer = _scorer(art)
        reg = VariantRegistry(scorer)
        reg.add_variant("candidate")
        tenancy, plane = self._scenario_stack(reg)
        reqs = _requests(120)
        scenario = build_scenario(
            "tenant_isolation", reqs, seed=0, num_phases=6, pause_s=0.0
        )
        assert scenario.tenants == DEFAULT_TENANTS
        # fair total with headroom: flooder (alpha) must shed, others not
        quota = TenantQuota({
            t: TenantBudget(rate=1.0, burst=55) for t in DEFAULT_TENANTS
        })
        tenancy.quota = quota
        doc = run_scenario(
            scenario, [scorer], BUCKETS, ServingMetrics(),
            plane=plane, tenancy=tenancy,
        )
        assert doc["isolation_ok"] is True
        assert doc["flooding_tenant"] == "alpha"
        assert doc["tenant_shed"]["alpha"] > 0
        assert doc["tenants"]["beta"]["slo_verdict"] == "ok"
        assert doc["tenants"]["gamma"]["slo_verdict"] == "ok"

    def test_ramped_rollout_scenario(self):
        art = _artifact()
        scorer = _scorer(art)
        reg = VariantRegistry(scorer)
        reg.add_variant("candidate")
        reg.apply_delta(
            "candidate",
            build_delta(_delta_for(art, ["u1", "u7"]), art, generation=1),
        )
        tenancy, plane = self._scenario_stack(reg)
        reqs = _requests(120)
        scenario = build_scenario(
            "ramped_rollout", reqs, seed=0, num_phases=6, pause_s=0.0
        )
        ramps = [p.ramp_percent for p in scenario.phases]
        assert ramps[0] == 0.0 and ramps[-1] == 100.0
        assert ramps == sorted(ramps)
        doc = run_scenario(
            scenario, [scorer], BUCKETS, ServingMetrics(),
            plane=plane, tenancy=tenancy,
        )
        assert doc["num_requests"] == len(reqs)
        assert doc["variant_shares"].get("candidate", 0.0) > 0.1
        assert set(doc["tenants"]) == set(DEFAULT_TENANTS)

    def test_nearline_loop_scenario(self):
        art = _artifact()
        scorer = _scorer(art)
        reg = VariantRegistry(scorer)
        reg.add_variant("candidate")
        tenancy, plane = self._scenario_stack(reg)
        tenancy.router.set_ramp("candidate", 50.0)
        reqs = _requests(120)
        scenario = build_scenario(
            "nearline_loop", reqs, seed=0, num_phases=6, pause_s=0.0
        )
        with tempfile.TemporaryDirectory() as watch:
            nearline_fn = make_nearline_fn(
                reg,
                ["candidate"],
                {"per_user": [f"u{i}" for i in range(32)]},
                rows_per_delta=4,
                seed=3,
                watch_dir=watch,
            )
            doc = run_scenario(
                scenario, [scorer], BUCKETS, ServingMetrics(),
                plane=plane, tenancy=tenancy, nearline_fn=nearline_fn,
            )
        assert doc["num_requests"] == len(reqs)
        assert doc["nearline"]["deltas_applied"] > 0
        assert doc["nearline"]["rollbacks"] == 0
        assert doc["nearline"]["generations"]["candidate"] > 0
        # fingerprint chain advanced to the last applied generation
        st = reg.state("candidate")
        assert st.generation == doc["nearline"]["generations"]["candidate"]
        assert st.fingerprint is not None

    def test_nearline_bad_delta_rolls_back_in_scenario(self):
        """The delta-apply path of the nearline_loop scenario runs
        through the gate: a nearline trainer emitting deliberately-bad
        generations (huge-scale row updates) gets every swap rolled
        back, the scenario doc counts the rollbacks, and the variant's
        chain head never advances."""
        art = _artifact()
        scorer = _scorer(art)
        reqs = _requests(120)
        gate_reqs = reqs[:48]
        base = scorer.score_batch(gate_reqs, bucket_size=len(gate_reqs))
        scores = np.asarray([r.score for r in base], dtype=np.float32)
        labels = (scores > np.median(scores)).astype(np.float32)
        reg = VariantRegistry(
            scorer,
            gate=ValidationGate(
                gate_reqs, labels,
                max_auc_regression=0.02,
                bucket_size=len(gate_reqs),
            ),
        )
        reg.add_variant("candidate")
        tenancy, plane = self._scenario_stack(reg)
        tenancy.router.set_ramp("candidate", 50.0)
        scenario = build_scenario(
            "nearline_loop", reqs, seed=0, num_phases=6, pause_s=0.0
        )
        nearline_fn = make_nearline_fn(
            reg,
            ["candidate"],
            {"per_user": [f"u{i}" for i in range(12)]},
            rows_per_delta=12,
            scale=50.0,  # deliberately ranking-wrecking generations
            seed=3,
        )
        doc = run_scenario(
            scenario, [scorer], BUCKETS, ServingMetrics(),
            plane=plane, tenancy=tenancy, nearline_fn=nearline_fn,
        )
        assert doc["num_requests"] == len(reqs)
        assert doc["nearline"]["rollbacks"] > 0
        assert doc["nearline"]["deltas_applied"] == 0
        assert doc["nearline"]["generations"]["candidate"] == 0
        st = reg.state("candidate")
        assert st.generation == 0
        assert st.rollbacks == doc["nearline"]["rollbacks"]

    def test_tenancy_scenario_requires_plane(self):
        scenario = build_scenario("tenant_isolation", _requests(24))
        with pytest.raises(ValueError, match="tenancy"):
            run_scenario(
                scenario, [_scorer()], BUCKETS, ServingMetrics()
            )


class TestOverlayAdmissionSeed:
    def test_overlay_rows_seed_request_frequency(self):
        """A freshly claimed overlay row must not be the importance
        plane's first eviction victim: the claim seeds one request of
        frequency so ``freq x norm`` ranks it like a just-requested
        row."""
        art = _artifact()
        scorer = _scorer(art, eviction_policy="importance")
        reg = VariantRegistry(scorer)
        reg.add_variant("v1")
        touched = ["u3", "u5"]
        reg.apply_delta(
            "v1", build_delta(_delta_for(art, touched), art, generation=1)
        )
        coord = scorer.routing["per_user"]
        for eid in touched:
            row = reg.state("v1").overlay_rows["per_user"][eid]
            assert coord._freq[row] > 0.0, eid
            assert coord.importance_of(np.array([row]))[0] > 0.0

    def test_default_policy_overlay_seed_is_noop(self):
        art = _artifact()
        scorer = _scorer(art)  # "oldest": no frequency plane at all
        reg = VariantRegistry(scorer)
        reg.add_variant("v1")
        reg.apply_delta(
            "v1", build_delta(_delta_for(art, ["u2"]), art, generation=1)
        )
        assert scorer.routing["per_user"]._freq is None
