"""Multi-chip GameEstimator: (data x feat) grid FE + entity-sharded RE on
the 8-virtual-device harness must reproduce the single-device fit.

The reference validates its distributed estimator on local[4] Spark
(GameEstimatorTest); this is the mesh analog, plus the layout the reference
cannot express — coefficients sharded over a feature axis.
"""

import numpy as np
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.slow  # excluded from the fast lane (pyproject markers)

from photon_ml_tpu.data.game_data import FeatureShard, GameData
from photon_ml_tpu.estimators.game import (
    FixedEffectCoordinateConfiguration,
    GameEstimator,
    ParallelConfiguration,
    RandomEffectCoordinateConfiguration,
)
from photon_ml_tpu.data.random_effect import RandomEffectDataConfiguration
from photon_ml_tpu.opt.config import GlmOptimizationConfiguration, OptimizerConfig
from photon_ml_tpu.types import TaskType


def _glmix_data(rng, n=600, d=48, k=4, n_users=12, d_u=3):
    rows = np.repeat(np.arange(n), k + 1)
    cols = np.concatenate(
        [rng.integers(1, d, (n, k)), np.zeros((n, 1), np.int64)], axis=1
    ).reshape(-1)
    vals = np.concatenate(
        [rng.standard_normal((n, k)).astype(np.float32),
         np.ones((n, 1), np.float32)],
        axis=1,
    ).reshape(-1)
    users = [f"u{i % n_users}" for i in range(n)]
    dense = np.zeros((n, d), np.float32)
    np.add.at(dense, (rows, cols), vals)
    w_true = (rng.standard_normal(d) * 0.4).astype(np.float32)
    # small per-user shard (intercept + d_u-1 covariates): the per-entity
    # problems stay well-posed so single-vs-grid comparisons are stable
    xu = np.concatenate(
        [np.ones((n, 1), np.float32),
         rng.standard_normal((n, d_u - 1)).astype(np.float32)],
        axis=1,
    )
    wu = {f"u{u}": rng.standard_normal(d_u) * 0.5 for u in range(n_users)}
    z = dense @ w_true + np.array(
        [xu[i] @ wu[users[i]] for i in range(n)], dtype=np.float32
    )
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)
    u_rows = np.repeat(np.arange(n), d_u)
    u_cols = np.tile(np.arange(d_u), n)
    shard = FeatureShard(rows=rows, cols=cols, vals=vals, dim=d)
    u_shard = FeatureShard(
        rows=u_rows, cols=u_cols, vals=xu.reshape(-1), dim=d_u
    )
    return GameData(
        labels=y,
        feature_shards={"g": shard, "u": u_shard},
        id_tags={"userId": users},
        offsets=np.zeros(n, np.float32),
        weights=np.ones(n, np.float32),
    )


def _coords():
    opt = GlmOptimizationConfiguration(
        optimizer_config=OptimizerConfig.lbfgs(max_iterations=30),
        regularization_weight=1.0,
    )
    re_opt = GlmOptimizationConfiguration(
        optimizer_config=OptimizerConfig.lbfgs(max_iterations=30),
        regularization_weight=5.0,
    )
    return {
        "global": FixedEffectCoordinateConfiguration(
            feature_shard="g", optimizer=opt
        ),
        "per-user": RandomEffectCoordinateConfiguration(
            feature_shard="u",
            data=RandomEffectDataConfiguration(random_effect_type="userId"),
            optimizer=re_opt,
        ),
    }


class TestParallelEstimator:
    @pytest.mark.parametrize("grid", [(2, 4), (8, 1)])
    def test_matches_single_device(self, rng, grid):
        data = _glmix_data(rng)

        fits = {}
        for name, parallel in {
            "single": None,
            "grid": ParallelConfiguration(
                n_data=grid[0], n_feat=grid[1], engine="benes"
            ),
        }.items():
            est = GameEstimator(
                task=TaskType.LOGISTIC_REGRESSION,
                coordinates=_coords(),
                num_outer_iterations=2,
                parallel=parallel,
            )
            fits[name] = est.fit(data)

        m_s, m_g = fits["single"].model, fits["grid"].model
        w_s = np.asarray(m_s.models["global"].coefficients.means)
        w_g = np.asarray(m_g.models["global"].coefficients.means)
        assert w_g.shape == w_s.shape  # trimmed back to real [d]
        np.testing.assert_allclose(w_g, w_s, atol=5e-3)

        s_s = m_s.score(data)
        s_g = m_g.score(data)
        np.testing.assert_allclose(s_g, s_s, atol=1e-2)


class TestParallelCheckpointResume:
    def test_single_device_checkpoint_resumes_on_grid(self, rng, tmp_path):
        """Checkpoints carry real-dim models; a grid estimator (padded
        feature axis) must accept them (and vice versa)."""
        data = _glmix_data(rng)
        ckpt = str(tmp_path / "ckpt")

        est1 = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinates=_coords(),
            num_outer_iterations=1,
        )
        fit1 = est1.fit(data, checkpoint_dir=ckpt)

        est2 = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinates=_coords(),
            num_outer_iterations=2,
            parallel=ParallelConfiguration(n_data=2, n_feat=4, engine="benes"),
        )
        fit2 = est2.fit(data, checkpoint_dir=ckpt)  # resumes iteration 2
        w = np.asarray(fit2.model.models["global"].coefficients.means)
        assert w.shape[0] == data.feature_shards["g"].dim
        assert np.all(np.isfinite(fit2.model.score(data)))


class TestParallelTuning:
    def test_tuning_trials_keep_parallel_layout(self, rng, monkeypatch):
        """Hyperparameter tuning refits fresh estimators per trial; they
        must inherit the multi-chip layout of the base estimator."""
        from photon_ml_tpu.estimators.tuning import GameEstimatorEvaluationFunction

        data = _glmix_data(rng, n=240, n_users=8)
        base = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinates=_coords(),
            num_outer_iterations=1,
            parallel=ParallelConfiguration(n_data=2, n_feat=4, engine="benes"),
        )
        fn = GameEstimatorEvaluationFunction(
            base, data, data, warm_start=False
        )
        # spy on the trial estimator's construction: the trial must be
        # handed the base estimator's parallel layout (reverting the
        # `parallel=` pass-through in tuning.py must fail this test, not
        # just train single-device and still look finite)
        import photon_ml_tpu.estimators.tuning as tuning_mod

        captured = {}
        real_cls = tuning_mod.GameEstimator

        def spy(**kwargs):
            captured.update(kwargs)
            return real_cls(**kwargs)

        monkeypatch.setattr(tuning_mod, "GameEstimator", spy)
        value, trial = fn(np.zeros(fn.num_params))
        assert np.isfinite(value)
        assert captured["parallel"] is base.parallel
