"""Random-effect engine tests (reference RandomEffectCoordinateTest /
RandomEffectDataSetTest / LocalDataSetTest analogs): grouping/projection
correctness, vmap'd solves vs per-entity direct solves, caps, feature
selection, passive data, scoring alignment."""

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # excluded from the fast lane (pyproject markers)

from photon_ml_tpu.data import (
    RandomEffectDataConfiguration,
    build_random_effect_dataset,
)
from photon_ml_tpu.estimators import train_glm
from photon_ml_tpu.estimators.random_effect import (
    score_random_effects,
    train_random_effects,
)
from photon_ml_tpu.losses import SquaredLoss, make_glm_objective
from photon_ml_tpu.ops import DenseFeatures, LabeledData
from photon_ml_tpu.opt import GlmOptimizationConfiguration, RegularizationContext
from photon_ml_tpu.types import RegularizationType, TaskType

L2CFG = GlmOptimizationConfiguration(
    regularization=RegularizationContext(RegularizationType.L2),
    regularization_weight=0.1,
)


def _make_re_problem(rng, n_entities=12, samples_per_entity=(5, 40), global_dim=50):
    """Synthetic per-entity linear models over a sparse global feature space."""
    rows, cols, vals = [], [], []
    entity_ids, labels = [], []
    w_true = {}
    r = 0
    for e in range(n_entities):
        eid = f"user{e:03d}"
        n_e = int(rng.integers(*samples_per_entity))
        # each entity observes a small random slice of the global space
        feats = np.sort(rng.choice(global_dim, size=int(rng.integers(3, 8)), replace=False))
        w_e = rng.normal(size=len(feats)).astype(np.float32)
        w_true[eid] = dict(zip(feats.tolist(), w_e.tolist()))
        for _ in range(n_e):
            x = rng.normal(size=len(feats)).astype(np.float32)
            y = float(x @ w_e)
            for c, v in zip(feats, x):
                rows.append(r)
                cols.append(c)
                vals.append(v)
            entity_ids.append(eid)
            labels.append(y)
            r += 1
    return entity_ids, np.array(rows), np.array(cols), np.array(vals), np.array(labels), w_true


def test_grouping_and_projection_roundtrip(rng):
    ids, rows, cols, vals, labels, _ = _make_re_problem(rng)
    cfg = RandomEffectDataConfiguration(random_effect_type="userId", num_buckets=3)
    ds = build_random_effect_dataset(ids, rows, cols, vals, 50, labels, cfg)
    assert ds.num_entities == 12
    # every sample lands exactly once (weights > 0 once across buckets)
    seen = np.zeros(len(ids), dtype=int)
    for b in ds.buckets:
        wt = np.asarray(b.weights)
        pos = np.asarray(b.sample_pos)
        seen[pos[wt > 0]] += 1
    np.testing.assert_array_equal(seen, 1)
    # local features reproduce the original rows
    X_orig = np.zeros((len(ids), 50), dtype=np.float32)
    X_orig[rows, cols] = vals
    for b in ds.buckets:
        X = np.asarray(b.X)
        pidx = np.asarray(b.proj_indices)
        wt = np.asarray(b.weights)
        pos = np.asarray(b.sample_pos)
        for e in range(b.num_entities):
            for s in range(b.max_samples):
                if wt[e, s] > 0:
                    x_glob = np.zeros(50, dtype=np.float32)
                    np.add.at(x_glob, pidx[e], X[e, s])
                    np.testing.assert_allclose(x_glob, X_orig[pos[e, s]], rtol=1e-6)


def test_vmap_solves_match_per_entity_training(rng):
    """The batched RE solve must match training each entity separately with
    the plain FE trainer on its local data."""
    ids, rows, cols, vals, labels, w_true = _make_re_problem(rng, n_entities=8)
    cfg = RandomEffectDataConfiguration(random_effect_type="userId", num_buckets=2)
    ds = build_random_effect_dataset(ids, rows, cols, vals, 50, labels, cfg)
    model, results = train_random_effects(ds, TaskType.LINEAR_REGRESSION, L2CFG)

    for b, bucket in enumerate(ds.buckets):
        for e in range(bucket.num_entities):
            wt = np.asarray(bucket.weights[e])
            m = wt > 0
            data_e = LabeledData.create(
                DenseFeatures(matrix=bucket.X[e][m]),
                bucket.labels[e][m],
            )
            fit = train_glm(data_e, TaskType.LINEAR_REGRESSION, L2CFG)[0]
            np.testing.assert_allclose(
                model.coefficients[b][e][: fit.model.dim],
                fit.model.coefficients.means,
                rtol=2e-2,
                atol=2e-3,
            )


def test_recovers_per_entity_truth_and_export(rng):
    ids, rows, cols, vals, labels, w_true = _make_re_problem(
        rng, n_entities=10, samples_per_entity=(30, 60)
    )
    cfg = RandomEffectDataConfiguration(random_effect_type="userId", num_buckets=2)
    ds = build_random_effect_dataset(ids, rows, cols, vals, 50, labels, cfg)
    tiny = GlmOptimizationConfiguration(
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1e-4,
    )
    model, _ = train_random_effects(ds, TaskType.LINEAR_REGRESSION, tiny)
    for eid, truth in w_true.items():
        got = model.coefficients_for(eid)
        assert got is not None
        for feat, val in truth.items():
            assert abs(got[feat] - val) < 0.05, (eid, feat, got[feat], val)


def test_active_cap_and_passive_scoring(rng):
    ids, rows, cols, vals, labels, _ = _make_re_problem(
        rng, n_entities=6, samples_per_entity=(20, 30)
    )
    cfg = RandomEffectDataConfiguration(
        random_effect_type="userId", active_data_upper_bound=10, num_buckets=1, seed=1
    )
    ds = build_random_effect_dataset(ids, rows, cols, vals, 50, labels, cfg)
    b = ds.buckets[0]
    assert b.max_samples == 10
    # passive rows exist and cover the overflow
    n_active = int((np.asarray(b.weights) > 0).sum())
    p = ds.passive[0]
    assert p is not None
    assert n_active + p.X.shape[0] == len(ids)

    model, _ = train_random_effects(ds, TaskType.LINEAR_REGRESSION, L2CFG)
    scores = score_random_effects(model, ds)
    assert scores.shape == (len(ids),)
    # passive scores = dot of projected features with entity coefficients
    X_orig = np.zeros((len(ids), 50), dtype=np.float32)
    X_orig[rows, cols] = vals
    ppos = np.asarray(p.sample_pos)
    for k in range(min(5, len(ppos))):
        r = ppos[k]
        eid = ids[r]
        w_map = model.coefficients_for(eid)
        expected = sum(X_orig[r, f] * w for f, w in w_map.items())
        np.testing.assert_allclose(scores[r], expected, rtol=1e-4, atol=1e-5)


def test_feature_selection_caps_local_dim(rng):
    ids, rows, cols, vals, labels, _ = _make_re_problem(rng, n_entities=6)
    cfg = RandomEffectDataConfiguration(
        random_effect_type="userId", max_local_features=3, num_buckets=1
    )
    ds = build_random_effect_dataset(ids, rows, cols, vals, 50, labels, cfg)
    assert ds.buckets[0].local_dim <= 3
    # selected features should be informative: model still correlates with y
    model, _ = train_random_effects(ds, TaskType.LINEAR_REGRESSION, L2CFG)
    scores = score_random_effects(model, ds)
    corr = np.corrcoef(scores, labels)[0, 1]
    assert corr > 0.5, corr


def test_update_offsets_residual_trick(rng):
    ids, rows, cols, vals, labels, _ = _make_re_problem(rng, n_entities=4)
    cfg = RandomEffectDataConfiguration(random_effect_type="userId", num_buckets=1)
    ds = build_random_effect_dataset(ids, rows, cols, vals, 50, labels, cfg)
    residual = rng.normal(size=len(ids)).astype(np.float32)
    ds2 = ds.update_offsets(residual)
    b = ds2.buckets[0]
    wt = np.asarray(b.weights)
    pos = np.asarray(b.sample_pos)
    off = np.asarray(b.offsets)
    m = wt > 0
    np.testing.assert_allclose(off[m], residual[pos[m]], rtol=1e-6)
    # padding rows keep offset 0
    assert np.all(off[~m] == 0.0)


def test_adaptive_driver_matches_oneshot_across_buckets(rng):
    """End-to-end over multiple size buckets: the convergence-adaptive driver
    (chunked rounds + lane compaction, on by default) and the forced one-shot
    lockstep path must produce the same exported per-entity rows."""
    import dataclasses

    from photon_ml_tpu.opt import AdaptiveSolveConfig

    ids, rows, cols, vals, labels, _ = _make_re_problem(rng, n_entities=24)
    cfg = RandomEffectDataConfiguration(random_effect_type="userId", num_buckets=3)
    ds = build_random_effect_dataset(ids, rows, cols, vals, 50, labels, cfg)

    cfg_ad = dataclasses.replace(
        L2CFG, adaptive=AdaptiveSolveConfig(enabled=True, chunk_iters=4, min_lanes=2)
    )
    cfg_os = dataclasses.replace(L2CFG, adaptive=AdaptiveSolveConfig(enabled=False))
    stats = []
    m_ad, _ = train_random_effects(
        ds, TaskType.LINEAR_REGRESSION, cfg_ad, stats_out=stats
    )
    m_os, _ = train_random_effects(ds, TaskType.LINEAR_REGRESSION, cfg_os)

    rows_ad = {str(e): c for e, c in m_ad.items()}
    rows_os = {str(e): c for e, c in m_os.items()}
    assert set(rows_ad) == set(rows_os)
    for eid in rows_ad:
        for k in set(rows_ad[eid]) | set(rows_os[eid]):
            assert abs(rows_ad[eid].get(k, 0.0) - rows_os[eid].get(k, 0.0)) <= 1e-5
    # one SolverStats per bucket, each fully converged
    assert len(stats) == len(ds.buckets)
    assert all(s.converged == s.num_entities for s in stats)
