"""CLI driver tests, modeled on the reference's end-to-end DriverTest suites
(cli/game/training/DriverTest.scala, scoring DriverTest, legacy MockDriver,
FeatureIndexingJobTest): train → save → score → evaluate via the real
command-line surfaces on synthetic Avro fixtures."""

import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # excluded from the fast lane (pyproject markers)

from photon_ml_tpu.io.data_reader import write_training_examples


@pytest.fixture(scope="module")
def glmix_avro(tmp_path_factory):
    """Synthetic GLMix logistic data as TrainingExampleAvro: global features
    + per-user features, user id in metadataMap."""
    root = tmp_path_factory.mktemp("glmix")
    rng = np.random.default_rng(7)
    n_users, rows, dg, du = 8, 40, 6, 3
    wg = rng.normal(size=dg)
    wu = {f"user{i}": rng.normal(size=du) for i in range(n_users)}

    def make(n_rows, seed):
        r = np.random.default_rng(seed)
        records = []
        for i in range(n_rows):
            user = f"user{i % n_users}"
            xg = r.normal(size=dg)
            xu = r.normal(size=du)
            z = xg @ wg + xu @ wu[user]
            y = 1.0 if 1 / (1 + np.exp(-z)) > r.random() else 0.0
            records.append(
                {
                    "uid": f"r{i}",
                    "label": y,
                    "features": [("g", str(j), xg[j]) for j in range(dg)],
                    "userFeatures": [("u", str(j), xu[j]) for j in range(du)],
                    "metadataMap": {"userId": user},
                }
            )
        return records

    train_dir = root / "train"
    test_dir = root / "test"
    train_dir.mkdir()
    test_dir.mkdir()
    write_training_examples(str(train_dir / "part-00000.avro"), make(n_users * rows, 1))
    write_training_examples(str(test_dir / "part-00000.avro"), make(n_users * 10, 2))

    config = {
        "feature_shards": {
            "global": {"feature_bags": ["features"], "add_intercept": True},
            "per_user": {"feature_bags": ["userFeatures"], "add_intercept": False},
        },
        "coordinates": {
            "fixed": {
                "type": "fixed",
                "feature_shard": "global",
                "optimizer": {
                    "optimizer": "LBFGS",
                    "regularization": "L2",
                    "regularization_weight": 0.1,
                },
            },
            "per_user": {
                "type": "random",
                "feature_shard": "per_user",
                "random_effect_type": "userId",
                "optimizer": {
                    "optimizer": "LBFGS",
                    "regularization": "L2",
                    "regularization_weight": 1.0,
                },
            },
        },
        "update_order": ["fixed", "per_user"],
    }
    cfg_path = root / "game.json"
    cfg_path.write_text(json.dumps(config))
    return {"root": root, "train": train_dir, "test": test_dir, "config": cfg_path}


class TestTrainGameDriver:
    def test_end_to_end_fe_re(self, glmix_avro, tmp_path):
        from photon_ml_tpu.cli.train_game import parse_args, run

        out = tmp_path / "out"
        fit = run(parse_args([
            "--train-data-dirs", str(glmix_avro["train"]),
            "--validation-data-dirs", str(glmix_avro["test"]),
            "--coordinate-config", str(glmix_avro["config"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--evaluator", "AUC",
        ]))
        # captured-baseline style threshold (reference DriverTest RMSE gates)
        assert fit.validation_metric > 0.70
        assert (out / "best" / "model-metadata.json").is_file()
        assert (out / "best" / "fixed-effect" / "fixed" / "id-info").is_file()
        assert (out / "best" / "random-effect" / "per_user" / "id-info").is_file()

    def test_multiple_optimizer_configs_selects_best(self, glmix_avro, tmp_path):
        """Reference DriverTest.scala:324-338 "multiple optimizer configs":
        per-coordinate regularization_weights arrays sweep the cross-product
        (2x2 = 4 fits here) and the validation evaluator picks the winner —
        a crushing fixed-effect λ must not be the saved model."""
        import json as _json

        from photon_ml_tpu.cli.train_game import parse_args, run

        cfg = _json.loads(glmix_avro["config"].read_text())
        cfg["coordinates"]["fixed"]["optimizer"].pop("regularization_weight")
        cfg["coordinates"]["fixed"]["optimizer"]["regularization_weights"] = [0.1, 1e6]
        cfg["coordinates"]["per_user"]["optimizer"].pop("regularization_weight")
        cfg["coordinates"]["per_user"]["optimizer"]["regularization_weights"] = [1.0, 10.0]
        cfg_path = tmp_path / "sweep.json"
        cfg_path.write_text(_json.dumps(cfg))
        out = tmp_path / "out"
        fit = run(parse_args([
            "--train-data-dirs", str(glmix_avro["train"]),
            "--validation-data-dirs", str(glmix_avro["test"]),
            "--coordinate-config", str(cfg_path),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--evaluator", "AUC",
        ]))
        # the winner must beat the single-config gate (λ=1e6 would be ~0.5)
        assert fit.validation_metric > 0.70
        assert (out / "best" / "model-metadata.json").is_file()

    def test_precision_at_k_sharded_evaluator(self, glmix_avro, tmp_path, caplog):
        """--evaluator 'PRECISION@5:userId' AUC end-to-end (reference
        MultiEvaluatorType.scala:46-60 spelling): the per-user precision@5
        drives best-model selection, AUC is computed and logged per
        coordinate per CD iteration."""
        import logging

        from photon_ml_tpu.cli.train_game import parse_args, run

        out = tmp_path / "out"
        with caplog.at_level(logging.INFO, logger="photon_ml_tpu"):
            fit = run(parse_args([
                "--train-data-dirs", str(glmix_avro["train"]),
                "--validation-data-dirs", str(glmix_avro["test"]),
                "--coordinate-config", str(glmix_avro["config"]),
                "--task", "LOGISTIC_REGRESSION",
                "--output-dir", str(out),
                "--evaluator", "PRECISION@5:userId", "AUC",
            ]))
        # precision@5 within each user's 10 validation rows: a real model
        # must beat the 0.5 base rate
        assert fit.validation_metric > 0.55
        assert fit.validation_metric <= 1.0
        # the secondary evaluator is logged each coordinate update
        metric_lines = [
            r.message for r in caplog.records
            if "validation metrics:" in r.message
        ]
        assert metric_lines and all("AUC=" in m for m in metric_lines)

    def test_precision_at_k_bad_spellings(self, glmix_avro, tmp_path):
        import pytest as _pytest

        from photon_ml_tpu.cli.train_game import _make_evaluator
        from photon_ml_tpu.types import TaskType

        with _pytest.raises(ValueError, match="PRECISION@<int>"):
            _make_evaluator("PRECISION@x", TaskType.LOGISTIC_REGRESSION, None)
        with _pytest.raises(ValueError, match="k >= 1"):
            _make_evaluator("PRECISION@0", TaskType.LOGISTIC_REGRESSION, None)

    def test_normalization_and_stats(self, glmix_avro, tmp_path):
        from photon_ml_tpu.cli.train_game import parse_args, run

        out = tmp_path / "out_norm"
        fit = run(parse_args([
            "--train-data-dirs", str(glmix_avro["train"]),
            "--validation-data-dirs", str(glmix_avro["test"]),
            "--coordinate-config", str(glmix_avro["config"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--evaluator", "AUC",
            "--normalization-type", "STANDARDIZATION",
            "--save-feature-stats",
        ]))
        assert fit.validation_metric > 0.70
        stats = out / "feature-stats" / "global" / "part-00000.avro"
        assert stats.is_file()
        from photon_ml_tpu.io.avro import read_avro_file

        recs = list(read_avro_file(str(stats)))
        assert any(r["featureName"] == "g" for r in recs)
        assert {"mean", "variance", "min", "max", "numNonzeros"} <= set(
            recs[0]["metrics"]
        )

    def test_sharded_evaluator_fe_only_config(self, glmix_avro, tmp_path):
        """'AUC:userId' must work even when no coordinate uses userId."""
        import json as _json

        cfg = _json.loads(glmix_avro["config"].read_text())
        cfg["coordinates"] = {"fixed": cfg["coordinates"]["fixed"]}
        cfg["update_order"] = ["fixed"]
        fe_cfg = tmp_path / "fe_only.json"
        fe_cfg.write_text(_json.dumps(cfg))
        from photon_ml_tpu.cli.train_game import parse_args, run

        fit = run(parse_args([
            "--train-data-dirs", str(glmix_avro["train"]),
            "--validation-data-dirs", str(glmix_avro["test"]),
            "--coordinate-config", str(fe_cfg),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(tmp_path / "out_fe_sharded"),
            "--evaluator", "AUC:userId",
        ]))
        assert 0.3 < fit.validation_metric <= 1.0

    def test_sharded_evaluator(self, glmix_avro, tmp_path):
        from photon_ml_tpu.cli.train_game import parse_args, run

        fit = run(parse_args([
            "--train-data-dirs", str(glmix_avro["train"]),
            "--validation-data-dirs", str(glmix_avro["test"]),
            "--coordinate-config", str(glmix_avro["config"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(tmp_path / "out_sharded"),
            "--evaluator", "AUC:userId",
        ]))
        assert 0.4 < fit.validation_metric <= 1.0


class TestGameTrainingParityFlags:
    """Flags mirrored from the reference GameTrainingParams
    (GameTrainingParams.scala:274-610) beyond the core training path."""

    def test_compute_variance_output_mode_all_and_stats_dir(
        self, glmix_avro, tmp_path
    ):
        """--compute-variance attaches 1/(H_jj+eps) variances to the saved
        models; --model-output-mode ALL writes every swept config under
        all/<i> (Driver.scala:416-433); --summarization-output-dir redirects
        feature stats; --updating-sequence overrides the config order."""
        import json as _json

        from photon_ml_tpu.cli.train_game import parse_args, run
        from photon_ml_tpu.io.model_io import load_game_model

        cfg = _json.loads(glmix_avro["config"].read_text())
        cfg["coordinates"]["fixed"]["optimizer"].pop("regularization_weight")
        cfg["coordinates"]["fixed"]["optimizer"]["regularization_weights"] = [0.1, 10.0]
        cfg_path = tmp_path / "sweep.json"
        cfg_path.write_text(_json.dumps(cfg))
        out = tmp_path / "out"
        stats_dir = tmp_path / "stats"
        fit = run(parse_args([
            "--train-data-dirs", str(glmix_avro["train"]),
            "--validation-data-dirs", str(glmix_avro["test"]),
            "--coordinate-config", str(cfg_path),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--evaluator", "AUC",
            "--compute-variance",
            "--model-output-mode", "ALL",
            "--summarization-output-dir", str(stats_dir),
            "--updating-sequence", "per_user", "fixed",
        ]))
        assert fit.validation_metric > 0.70
        # both swept configurations saved, plus the best; each all/<i>
        # metadata names the λ that trained THAT model (not the sweep list)
        assert (out / "best" / "model-metadata.json").is_file()
        lams = []
        for i in range(2):
            meta = _json.loads(
                (out / "all" / str(i) / "model-metadata.json").read_text()
            )
            opt = meta["configurations"]["coordinates"]["fixed"]["optimizer"]
            assert "regularization_weights" not in opt
            lams.append(opt["regularization_weight"])
        assert sorted(lams) == [0.1, 10.0]
        # stats redirected (and computed for every shard)
        assert (stats_dir / "global" / "part-00000.avro").is_file()
        assert (stats_dir / "per_user" / "part-00000.avro").is_file()
        # variances round-trip through BayesianLinearModelAvro
        model, _ = load_game_model(str(out / "best"))
        fe = model.models["fixed"]
        assert fe.coefficients.variances is not None
        assert np.all(np.asarray(fe.coefficients.variances) > 0)

    def test_model_output_mode_none(self, glmix_avro, tmp_path):
        from photon_ml_tpu.cli.train_game import parse_args, run

        out = tmp_path / "none_out"
        fit = run(parse_args([
            "--train-data-dirs", str(glmix_avro["train"]),
            "--coordinate-config", str(glmix_avro["config"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--model-output-mode", "NONE",
        ]))
        assert fit is not None
        assert not (out / "best").exists()

    def test_delete_output_dir_if_exists(self, glmix_avro, tmp_path):
        from photon_ml_tpu.cli.train_game import parse_args, run

        out = tmp_path / "stale_out"
        (out / "best").mkdir(parents=True)
        stale = out / "best" / "stale-marker"
        stale.write_text("old run")
        run(parse_args([
            "--train-data-dirs", str(glmix_avro["train"]),
            "--coordinate-config", str(glmix_avro["config"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--delete-output-dir-if-exists",
        ]))
        assert not stale.exists()
        assert (out / "best" / "model-metadata.json").is_file()

    def test_updating_sequence_unknown_coordinate(self, glmix_avro, tmp_path):
        from photon_ml_tpu.cli.train_game import parse_args, run

        with pytest.raises(ValueError, match="updating-sequence"):
            run(parse_args([
                "--train-data-dirs", str(glmix_avro["train"]),
                "--coordinate-config", str(glmix_avro["config"]),
                "--task", "LOGISTIC_REGRESSION",
                "--output-dir", str(tmp_path / "o"),
                "--updating-sequence", "fixed", "nope",
            ]))

    def test_input_columns_names(self, glmix_avro, tmp_path):
        """Custom response field name (the reference's ResponsePrediction
        data uses 'response' where TrainingExample uses 'label' —
        InputColumnsNames exists exactly for this)."""
        import json as _json

        from photon_ml_tpu.cli.train_game import parse_args, run
        from photon_ml_tpu.io import schemas as _schemas
        from photon_ml_tpu.io.avro import read_avro_file, write_avro_file

        src = glmix_avro["train"] / "part-00000.avro"
        renamed_dir = tmp_path / "renamed"
        renamed_dir.mkdir()
        schema = _json.loads(_json.dumps(_schemas.TRAINING_EXAMPLE))  # deep copy
        schema["fields"] = [
            dict(f, name="response") if f["name"] == "label" else f
            for f in schema["fields"]
        ] + [{
            "name": "userFeatures",
            "type": {"type": "array", "items": "FeatureAvro"},
            "default": [],
        }]
        recs = []
        for rec in read_avro_file(str(src)):
            rec = dict(rec)
            rec["response"] = rec.pop("label")
            recs.append(rec)
        write_avro_file(str(renamed_dir / "part-00000.avro"), schema, recs)

        out = tmp_path / "cols_out"
        fit = run(parse_args([
            "--train-data-dirs", str(renamed_dir),
            "--coordinate-config", str(glmix_avro["config"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--input-columns-names", '{"response": "response"}',
        ]))
        assert fit is not None
        assert (out / "best" / "model-metadata.json").is_file()

    def test_input_columns_names_rejects_unknown_keys(self, glmix_avro, tmp_path):
        from photon_ml_tpu.cli.train_game import parse_args, run

        with pytest.raises(ValueError, match="unknown keys"):
            run(parse_args([
                "--train-data-dirs", str(glmix_avro["train"]),
                "--coordinate-config", str(glmix_avro["config"]),
                "--task", "LOGISTIC_REGRESSION",
                "--output-dir", str(tmp_path / "o"),
                "--input-columns-names", '{"label": "y"}',
            ]))

    def test_check_data_rejects_nonfinite(self, glmix_avro, tmp_path):
        """--check-data runs the DataValidators gate (bad-input failure
        cases, reference DriverTest.scala:470-496)."""
        from photon_ml_tpu.cli.train_game import parse_args, run
        from photon_ml_tpu.data.validators import DataValidationError

        bad_dir = tmp_path / "bad"
        bad_dir.mkdir()
        write_training_examples(str(bad_dir / "part-00000.avro"), [
            {
                "uid": "r0",
                "label": 1.0,
                "features": [("g", "0", float("nan"))],
                "userFeatures": [("u", "0", 1.0)],
                "metadataMap": {"userId": "user0"},
            },
            {
                "uid": "r1",
                "label": 0.0,
                "features": [("g", "0", 1.0)],
                "userFeatures": [("u", "0", 1.0)],
                "metadataMap": {"userId": "user1"},
            },
        ])
        with pytest.raises(DataValidationError):
            run(parse_args([
                "--train-data-dirs", str(bad_dir),
                "--coordinate-config", str(glmix_avro["config"]),
                "--task", "LOGISTIC_REGRESSION",
                "--output-dir", str(tmp_path / "o"),
                "--check-data",
            ]))

    def test_num_output_files_for_random_effect_model(
        self, glmix_avro, tmp_path
    ):
        """--num-output-files-for-random-effect-model N partitions the RE
        coefficients into N part files, and the partitioned model still
        loads (reference NUM_OUTPUT_FILES_FOR_RANDOM_EFFECT_MODEL)."""
        from photon_ml_tpu.cli.train_game import parse_args, run
        from photon_ml_tpu.io.model_io import load_game_model

        out = tmp_path / "re_parts"
        run(parse_args([
            "--train-data-dirs", str(glmix_avro["train"]),
            "--coordinate-config", str(glmix_avro["config"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--num-output-files-for-random-effect-model", "3",
        ]))
        parts = sorted(
            p.name
            for p in (
                out / "best" / "random-effect" / "per_user" / "coefficients"
            ).glob("part-*.avro")
        )
        assert len(parts) == 3, parts
        model, _ = load_game_model(str(out / "best"))
        assert model.models["per_user"].num_entities == 8

    def test_validation_date_range(self, glmix_avro, tmp_path):
        """--validation-date-range expands validation dirs to daily
        yyyy/MM/dd subdirs like the train-side flag."""
        import shutil

        from photon_ml_tpu.cli.train_game import parse_args, run

        dated = tmp_path / "dated_val"
        day = dated / "2024" / "01" / "02"
        day.mkdir(parents=True)
        shutil.copy(
            str(glmix_avro["test"] / "part-00000.avro"),
            str(day / "part-00000.avro"),
        )
        fit = run(parse_args([
            "--train-data-dirs", str(glmix_avro["train"]),
            "--validation-data-dirs", str(dated),
            "--validation-date-range", "20240101-20240103",
            "--coordinate-config", str(glmix_avro["config"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(tmp_path / "dr_out"),
            "--evaluator", "AUC",
        ]))
        assert fit.validation_metric > 0.70


class TestScoreGameDriver:
    def test_score_after_train(self, glmix_avro, tmp_path):
        from photon_ml_tpu.cli.score_game import parse_args as score_args
        from photon_ml_tpu.cli.score_game import run as score_run
        from photon_ml_tpu.cli.train_game import parse_args as train_args
        from photon_ml_tpu.cli.train_game import run as train_run

        out = tmp_path / "model_out"
        train_run(train_args([
            "--train-data-dirs", str(glmix_avro["train"]),
            "--coordinate-config", str(glmix_avro["config"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
        ]))
        scores_dir = tmp_path / "scores"
        metric = score_run(score_args([
            "--data-dirs", str(glmix_avro["test"]),
            "--model-dir", str(out / "best"),
            "--output-dir", str(scores_dir),
            "--evaluator", "AUC",
        ]))
        assert metric > 0.70
        from photon_ml_tpu.io.scores_io import load_scores

        got = list(load_scores(str(scores_dir)))
        assert len(got) == 80
        assert got[0].uid == "r0"
        assert got[0].id_tags["userId"] == "user0"

        # --num-output-files partitions the output (reference --num-files);
        # scores must be identical across the partitioning
        scores3_dir = tmp_path / "scores3"
        metric3 = score_run(score_args([
            "--data-dirs", str(glmix_avro["test"]),
            "--model-dir", str(out / "best"),
            "--output-dir", str(scores3_dir),
            "--evaluator", "AUC",
            "--num-output-files", "3",
        ]))
        assert metric3 == metric
        parts = sorted(p.name for p in scores3_dir.glob("part-*.avro"))
        assert len(parts) == 3, parts
        got3 = list(load_scores(str(scores3_dir)))
        assert [s.prediction_score for s in got3] == [
            s.prediction_score for s in got
        ]

    def test_scoring_parity_flags(self, glmix_avro, tmp_path, caplog):
        """--delete-output-dir-if-exists, --random-effect-id-set,
        --log-data-and-model-stats, --input-columns-names on the scoring
        driver (reference scoring Params.scala flags)."""
        import logging

        from photon_ml_tpu.cli.score_game import parse_args as score_args
        from photon_ml_tpu.cli.score_game import run as score_run
        from photon_ml_tpu.cli.train_game import parse_args as train_args
        from photon_ml_tpu.cli.train_game import run as train_run

        out = tmp_path / "model_out"
        train_run(train_args([
            "--train-data-dirs", str(glmix_avro["train"]),
            "--coordinate-config", str(glmix_avro["config"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
        ]))
        scores_dir = tmp_path / "scores"
        scores_dir.mkdir()
        stale = scores_dir / "part-99999.avro"
        stale.write_bytes(b"stale")
        with caplog.at_level(logging.INFO):
            metric = score_run(score_args([
                "--data-dirs", str(glmix_avro["test"]),
                "--model-dir", str(out / "best"),
                "--output-dir", str(scores_dir),
                "--evaluator", "AUC",
                "--delete-output-dir-if-exists",
                "--random-effect-id-set", "userId",
                "--log-data-and-model-stats",
            ]))
        assert metric > 0.70
        assert not stale.exists()
        text = caplog.text
        assert "samples per userId" in text
        assert "model stats [fixed]" in text
        assert "model stats [per_user]" in text

        # unknown --input-columns-names keys fail fast
        with pytest.raises(ValueError, match="unknown keys"):
            score_run(score_args([
                "--data-dirs", str(glmix_avro["test"]),
                "--model-dir", str(out / "best"),
                "--output-dir", str(tmp_path / "s2"),
                "--input-columns-names", '{"label": "y"}',
            ]))


class TestLegacyGlmDriver:
    def test_lambda_sweep_selects_best(self, glmix_avro, tmp_path):
        """λ sweep over {0.1,1,10,1000}: huge λ must not win (reference
        legacy DriverTest best-λ assertion)."""
        from photon_ml_tpu.cli.train_glm import parse_args, run

        out = tmp_path / "glm_out"
        result = run(parse_args([
            "--training-data-dirs", str(glmix_avro["train"]),
            "--validation-data-dirs", str(glmix_avro["test"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--regularization-weights", "0.1", "1", "10", "1000",
        ]))
        assert result["best_lambda"] != 1000
        assert (out / "selection.json").is_file()
        assert (out / "best-model.avro").is_file()
        assert (out / "model-lambda-0.1.txt").is_file()
        # model text has name<TAB>term<TAB>value lines
        line = (out / "model-lambda-0.1.txt").read_text().splitlines()[0]
        assert len(line.split("\t")) == 3

    def test_selected_features_summarization_and_offheap(self, glmix_avro, tmp_path):
        """Legacy Driver parity: --selected-features-file restricts training
        to the named features (GLMSuite.scala:139-146),
        --summarization-output-dir writes FeatureSummarizationResultAvro,
        and --offheap-indexmap-dir reads through prebuilt stores."""
        from photon_ml_tpu.cli.build_index import parse_args as iargs
        from photon_ml_tpu.cli.build_index import run as irun
        from photon_ml_tpu.cli.train_glm import parse_args, run
        from photon_ml_tpu.io.avro import AvroSchema, read_avro_dir, write_avro_file

        idx = tmp_path / "idx"
        irun(iargs([
            "--data-dirs", str(glmix_avro["train"]),
            "--output-dir", str(idx),
            "--feature-shard", "features=features",
        ]))

        # select only g/0 and g/1 of the six global features
        sel_schema = AvroSchema({
            "type": "record", "name": "FeatureNameTerm", "fields": [
                {"name": "name", "type": "string"},
                {"name": "term", "type": "string"},
            ],
        })
        sel_dir = tmp_path / "selected"
        sel_dir.mkdir()
        write_avro_file(
            str(sel_dir / "part-00000.avro"), sel_schema,
            [{"name": "g", "term": "0"}, {"name": "g", "term": "1"}],
        )
        out = tmp_path / "out_sel"
        summ = tmp_path / "summary"
        result = run(parse_args([
            "--training-data-dirs", str(glmix_avro["train"]),
            "--validation-data-dirs", str(glmix_avro["test"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--regularization-weights", "0.1",
            "--offheap-indexmap-dir", str(idx),
            "--selected-features-file", str(sel_dir),
            "--summarization-output-dir", str(summ),
        ]))
        # summary written directly into the given dir
        recs = list(read_avro_dir(str(summ)))
        assert any(r["featureName"] == "g" for r in recs)
        # model text: only the selected features (+ intercept) can be nonzero
        txt = (out / "model-lambda-0.1.txt").read_text().splitlines()
        names = {line.split("\t")[0] + ":" + line.split("\t")[1] for line in txt}
        allowed = {"g:0", "g:1", "(INTERCEPT):"}
        assert names <= allowed, names

    def test_normalization_types_reach_same_optimum(self, glmix_avro, tmp_path):
        """All normalization types converge to comparable validation metric
        (reference NormalizationTest invariant)."""
        from photon_ml_tpu.cli.train_glm import parse_args, run

        metrics = {}
        for norm in ["NONE", "STANDARDIZATION", "SCALE_WITH_STANDARD_DEVIATION",
                     "SCALE_WITH_MAX_MAGNITUDE"]:
            result = run(parse_args([
                "--training-data-dirs", str(glmix_avro["train"]),
                "--validation-data-dirs", str(glmix_avro["test"]),
                "--task", "LOGISTIC_REGRESSION",
                "--output-dir", str(tmp_path / f"glm_{norm}"),
                "--regularization-weights", "1.0",
                "--normalization-type", norm,
            ]))
            metrics[norm] = result["metrics"][1.0]
        vals = list(metrics.values())
        assert max(vals) - min(vals) < 0.02, metrics

    def test_diagnostic_mode_writes_report(self, glmix_avro, tmp_path):
        from photon_ml_tpu.cli.train_glm import parse_args, run

        out = tmp_path / "glm_diag"
        run(parse_args([
            "--training-data-dirs", str(glmix_avro["train"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--regularization-weights", "1.0",
            "--diagnostic-mode", "ALL",
        ]))
        html = (out / "model-diagnostic.html").read_text()
        assert "Hosmer-Lemeshow" in html
        assert "Bootstrap" in html
        assert "Feature importance" in html
        assert "<svg" in html

    def test_diagnostic_mode_train_validate_split(self, glmix_avro, tmp_path):
        """DiagnosticMode.scala TRAIN/VALIDATE split: TRAIN = training-data
        diagnostics (learning curves + bootstrap), VALIDATE = held-out
        diagnostics (HL, independence, mean+variance importance)."""
        from photon_ml_tpu.cli.train_glm import parse_args, run

        out = tmp_path / "glm_diag_train"
        run(parse_args([
            "--training-data-dirs", str(glmix_avro["train"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--regularization-weights", "1.0",
            "--diagnostic-mode", "TRAIN",
        ]))
        html = (out / "model-diagnostic.html").read_text()
        assert "Bootstrap" in html
        assert "Fitting analysis" in html
        assert "Hosmer-Lemeshow" not in html
        assert "Feature importance" not in html

        out = tmp_path / "glm_diag_validate"
        run(parse_args([
            "--training-data-dirs", str(glmix_avro["train"]),
            "--validation-data-dirs", str(glmix_avro["test"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--regularization-weights", "1.0",
            "--diagnostic-mode", "VALIDATE",
        ]))
        html = (out / "model-diagnostic.html").read_text()
        assert "Hosmer-Lemeshow" in html
        assert "Feature importance" in html
        assert "variance contribution" in html  # both importance rankings
        assert "Bootstrap" not in html
        assert "Fitting analysis" not in html

    def test_tron_and_box_constraints(self, glmix_avro, tmp_path):
        from photon_ml_tpu.cli.train_glm import parse_args, run

        result = run(parse_args([
            "--training-data-dirs", str(glmix_avro["train"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(tmp_path / "glm_tron"),
            "--optimizer", "TRON",
            "--regularization-weights", "1.0",
            "--coefficient-box-constraints", '{"lower": -0.5, "upper": 0.5}',
        ]))
        w = np.asarray(result["fits"][0].model.coefficients.means)
        assert (w <= 0.5 + 1e-6).all() and (w >= -0.5 - 1e-6).all()


class TestLegacyGlmParityFlags:
    def test_validate_per_iteration_and_delete_dirs(
        self, glmix_avro, tmp_path, caplog
    ):
        """--validate-per-iteration logs a metric for every tracked
        iteration's model (reference VALIDATE_PER_ITERATION + ModelTracker);
        --delete-output-dirs-if-exist clears stale outputs; --no-warm-start
        still converges."""
        import logging
        import re

        from photon_ml_tpu.cli.train_glm import parse_args, run

        out = tmp_path / "glm_out"
        out.mkdir()
        stale = out / "stale-marker"
        stale.write_text("old")
        with caplog.at_level(logging.INFO):
            result = run(parse_args([
                "--training-data-dirs", str(glmix_avro["train"]),
                "--validation-data-dirs", str(glmix_avro["test"]),
                "--task", "LOGISTIC_REGRESSION",
                "--output-dir", str(out),
                "--regularization-weights", "0.1", "10.0",
                "--validate-per-iteration",
                "--delete-output-dirs-if-exist",
                "--no-warm-start",
            ]))
        assert not stale.exists()
        assert result["best_lambda"] in (0.1, 10.0)
        per_iter = re.findall(r"lambda=[\d.]+ iteration=(\d+)", caplog.text)
        assert len(per_iter) >= 4  # several iterations logged per lambda
        assert per_iter[0] == "0"

    def test_per_feature_box_constraints(self, glmix_avro, tmp_path):
        """The reference's per-feature constraint-map format
        (GLMSuite.createConstraintFeatureMap): a JSON array of
        name/term/lowerBound/upperBound maps pins individual coefficients;
        the trained model must respect exactly those bounds."""
        import json as _json

        from photon_ml_tpu.cli.train_glm import parse_args, run

        out = tmp_path / "boxed"
        constraints = _json.dumps([
            {"name": "g", "term": "0", "lowerBound": -0.01, "upperBound": 0.01},
            {"name": "g", "term": "1", "lowerBound": 0.0},
        ])
        result = run(parse_args([
            "--training-data-dirs", str(glmix_avro["train"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--regularization-weights", "0.01",
            "--coefficient-box-constraints", constraints,
        ]))
        assert result["fits"], result
        # the saved model text carries name/term per coefficient
        text = (out / "model-lambda-0.01.txt").read_text()
        coefs = {}
        for line in text.splitlines():
            parts = line.split("\t")
            if len(parts) >= 3:
                coefs[(parts[0], parts[1])] = float(parts[2])
        assert -0.01 - 1e-6 <= coefs[("g", "0")] <= 0.01 + 1e-6
        assert coefs[("g", "1")] >= -1e-6
        # an unconstrained coefficient escapes those bounds (data has strong
        # signal), proving the constraint was per-feature, not global
        others = [v for (nm, t), v in coefs.items()
                  if nm == "g" and t not in ("0", "1")]
        assert max(abs(v) for v in others) > 0.011, others

    def test_per_feature_box_with_normalization_original_space(
        self, glmix_avro, tmp_path
    ):
        """Bounds are stated in the ORIGINAL feature space; with
        normalization on, the solver maps them through the factor so the
        saved original-space model still honors them. Wildcard bounds must
        leave the intercept free (reference GLMSuite semantics); null
        bounds mean unbounded."""
        import json as _json

        from photon_ml_tpu.cli.train_glm import parse_args, run

        out = tmp_path / "boxed_norm"
        constraints = _json.dumps([
            {"name": "*", "term": "*", "lowerBound": -0.05,
             "upperBound": 0.05},
        ])
        run(parse_args([
            "--training-data-dirs", str(glmix_avro["train"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--regularization-weights", "0.01",
            "--normalization-type", "SCALE_WITH_STANDARD_DEVIATION",
            "--coefficient-box-constraints", constraints,
        ]))
        text = (out / "model-lambda-0.01.txt").read_text()
        coefs = {}
        for line in text.splitlines():
            parts = line.split("\t")
            if len(parts) >= 3:
                coefs[(parts[0], parts[1])] = float(parts[2])
        g_vals = [v for (nm, _t), v in coefs.items() if nm == "g"]
        assert g_vals and all(-0.0501 <= v <= 0.0501 for v in g_vals), coefs
        # the intercept stays free of the wildcard bound
        icpt = [v for (nm, _t), v in coefs.items() if nm != "g"]
        assert icpt  # present (may or may not exceed the bound)

        # null bound == unbounded on that side
        out2 = tmp_path / "boxed_null"
        run(parse_args([
            "--training-data-dirs", str(glmix_avro["train"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out2),
            "--regularization-weights", "0.01",
            "--coefficient-box-constraints", _json.dumps([
                {"name": "g", "term": "0", "lowerBound": None,
                 "upperBound": 0.01},
            ]),
        ]))
        text2 = (out2 / "model-lambda-0.01.txt").read_text()
        for line in text2.splitlines():
            parts = line.split("\t")
            if parts[0] == "g" and parts[1] == "0":
                assert float(parts[2]) <= 0.0101

    def test_box_constraint_map_validation_errors(self, glmix_avro, tmp_path):
        import json as _json

        from photon_ml_tpu.cli.train_glm import parse_args, run

        def _run(payload):
            return run(parse_args([
                "--training-data-dirs", str(glmix_avro["train"]),
                "--task", "LOGISTIC_REGRESSION",
                "--output-dir", str(tmp_path / "o"),
                "--coefficient-box-constraints", _json.dumps(payload),
            ]))

        with pytest.raises(ValueError, match="name.*term|must name"):
            _run([{"name": "g", "lowerBound": 0}])
        with pytest.raises(ValueError, match="strictly below"):
            _run([{"name": "g", "term": "0", "lowerBound": 2, "upperBound": 1}])
        # lower == upper is rejected (reference GLMSuite.scala:228 strict <)
        with pytest.raises(ValueError, match="strictly below"):
            _run([{"name": "g", "term": "0", "lowerBound": 1, "upperBound": 1}])
        # a no-op entry (both bounds absent/infinite) is rejected
        # (reference GLMSuite.scala:224)
        with pytest.raises(ValueError, match="no-op|invalid"):
            _run([{"name": "g", "term": "0"}])
        with pytest.raises(ValueError, match="wildcard term"):
            _run([{"name": "*", "term": "0", "lowerBound": 0}])
        with pytest.raises(ValueError, match="conflict|[Oo]verlap"):
            _run([
                {"name": "*", "term": "*", "lowerBound": -1, "upperBound": 1},
                {"name": "g", "term": "0", "lowerBound": 0, "upperBound": 1},
            ])
        # a term wildcard overlapping a specific entry of the same name
        with pytest.raises(ValueError, match="[Oo]verlap"):
            _run([
                {"name": "g", "term": "0", "lowerBound": 0, "upperBound": 1},
                {"name": "g", "term": "*", "lowerBound": -1, "upperBound": 1},
            ])

    def test_parse_box_constraints_unit(self):
        """Exact bound arrays from the parser against a known index
        (reference GLMSuite.createConstraintFeatureMap semantics)."""
        import json as _json

        import numpy as np

        from photon_ml_tpu.cli.common import parse_box_constraints
        from photon_ml_tpu.indexmap import (
            INTERCEPT_KEY,
            DefaultIndexMap,
            feature_key,
        )

        imap = DefaultIndexMap({
            feature_key("g", "0"): 0,
            feature_key("g", "1"): 1,
            "g": 2,              # empty-term feature: key is the bare name
            feature_key("h", "0"): 3,
            INTERCEPT_KEY: 4,
        })

        # term wildcard: every term of name 'g' INCLUDING the empty term,
        # combining with a non-overlapping explicit entry on 'h'
        _, _, box = parse_box_constraints(_json.dumps([
            {"name": "g", "term": "*", "lowerBound": -1, "upperBound": 1},
            {"name": "h", "term": "0", "lowerBound": 0, "upperBound": 2},
        ]), imap, dim=5, intercept_index=4)
        lo, hi = box
        np.testing.assert_allclose(lo[:4], [-1, -1, -1, 0])
        np.testing.assert_allclose(hi[:4], [1, 1, 1, 2])
        assert lo[4] == -np.inf and hi[4] == np.inf  # intercept untouched

        # all-wildcard: every feature EXCEPT the intercept
        _, _, box = parse_box_constraints(_json.dumps([
            {"name": "*", "term": "*", "lowerBound": -0.5, "upperBound": 0.5},
        ]), imap, dim=5, intercept_index=4)
        lo, hi = box
        np.testing.assert_allclose(lo[:4], -0.5)
        assert lo[4] == -np.inf and hi[4] == np.inf

    def test_box_constraint_name_with_wildcard_term(self, glmix_avro, tmp_path):
        """{name, term:'*'} bounds only features whose key name-part equals
        `name` — for ALL terms — and combines with other constraints
        (reference GLMSuite.scala:249-262), unlike the exclusive
        all-wildcard entry."""
        import json as _json

        from photon_ml_tpu.cli.train_glm import parse_args, run

        out = tmp_path / "namewild"
        constraints = _json.dumps([
            {"name": "g", "term": "*", "lowerBound": -0.01,
             "upperBound": 0.01},
        ])
        result = run(parse_args([
            "--training-data-dirs", str(glmix_avro["train"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--regularization-weights", "0.01",
            "--coefficient-box-constraints", constraints,
        ]))
        assert result["fits"], result
        text = (out / "model-lambda-0.01.txt").read_text()
        coefs = {}
        for line in text.splitlines():
            parts = line.split("\t")
            if len(parts) >= 3:
                coefs[(parts[0], parts[1])] = float(parts[2])
        g_vals = [v for (nm, _t), v in coefs.items() if nm == "g"]
        assert g_vals and all(-0.0101 <= v <= 0.0101 for v in g_vals), coefs
        # the intercept (different name-part) is untouched by the name
        # wildcard — free to absorb the base rate
        icpt = [v for (nm, _t), v in coefs.items() if nm != "g"]
        assert icpt

    def test_validate_per_iteration_plot_in_report(self, glmix_avro, tmp_path):
        """--validate-per-iteration + diagnostics: the HTML report carries
        the metric-vs-iteration chapter (reference validatePerIteration
        feeding the report engine)."""
        from photon_ml_tpu.cli.train_glm import parse_args, run

        out = tmp_path / "glm_report"
        run(parse_args([
            "--training-data-dirs", str(glmix_avro["train"]),
            "--validation-data-dirs", str(glmix_avro["test"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--regularization-weights", "0.1",
            "--validate-per-iteration",
            "--diagnostic-mode", "VALIDATE",
        ]))
        html = (out / "model-diagnostic.html").read_text()
        assert "Metric vs iteration" in html
        assert "lambda=0.1" in html

    def test_validate_per_iteration_requires_validation(
        self, glmix_avro, tmp_path
    ):
        from photon_ml_tpu.cli.train_glm import parse_args, run

        with pytest.raises(ValueError, match="validation-data-dirs"):
            run(parse_args([
                "--training-data-dirs", str(glmix_avro["train"]),
                "--task", "LOGISTIC_REGRESSION",
                "--output-dir", str(tmp_path / "o"),
                "--validate-per-iteration",
            ]))


class TestBuildIndexDriver:
    def test_date_range_expansion(self, glmix_avro, tmp_path):
        """--date-range expands each data dir to daily yyyy/MM/dd subdirs
        (reference FeatureIndexingJob --date-range)."""
        import shutil

        from photon_ml_tpu.cli.build_index import parse_args, run

        dated = tmp_path / "dated"
        day = dated / "2024" / "03" / "05"
        day.mkdir(parents=True)
        shutil.copy(
            str(glmix_avro["train"] / "part-00000.avro"),
            str(day / "part-00000.avro"),
        )
        sizes = run(parse_args([
            "--data-dirs", str(dated),
            "--date-range", "20240304-20240306",
            "--output-dir", str(tmp_path / "idx"),
            "--feature-shard", "global=features",
        ]))
        assert sizes["global"] > 1  # features + intercept found via the range

    def test_build_and_use_offheap_index(self, glmix_avro, tmp_path):
        from photon_ml_tpu.cli.build_index import parse_args, run

        idx_dir = tmp_path / "indexes"
        sizes = run(parse_args([
            "--data-dirs", str(glmix_avro["train"]),
            "--output-dir", str(idx_dir),
            "--feature-shard", "global=features",
            "--feature-shard", "per_user=userFeatures",
            "--num-partitions", "2",
        ]))
        assert sizes["global"] == 7  # 6 features + intercept
        assert sizes["per_user"] == 4
        # train against the off-heap maps end to end
        from photon_ml_tpu.cli.train_game import parse_args as targs
        from photon_ml_tpu.cli.train_game import run as trun

        fit = trun(targs([
            "--train-data-dirs", str(glmix_avro["train"]),
            "--validation-data-dirs", str(glmix_avro["test"]),
            "--coordinate-config", str(glmix_avro["config"]),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(tmp_path / "out_offheap"),
            "--evaluator", "AUC",
            "--offheap-indexmap-dir", str(idx_dir),
        ]))
        assert fit.validation_metric > 0.70


class TestFullGameCli:
    def test_end_to_end_full_game_with_factored_re(self, glmix_avro, tmp_path):
        """BASELINE config 5 shape: FE + per-user RE + factored (MF)
        coordinate, trained and scored through the CLIs."""
        import json as _json

        from photon_ml_tpu.cli.score_game import main as score_main
        from photon_ml_tpu.cli.train_game import parse_args, run

        with open(glmix_avro["config"]) as f:
            config = _json.load(f)
        config["coordinates"]["factored"] = {
            "type": "factored_random",
            "feature_shard": "per_user",
            "random_effect_type": "userId",
            "mf": {"num_latent_factors": 2, "num_iterations": 1},
            "optimizer": {
                "optimizer": "LBFGS",
                "regularization": "L2",
                "regularization_weight": 5.0,
            },
        }
        config["update_order"] = ["fixed", "per_user", "factored"]
        cfg_path = tmp_path / "full-game.json"
        cfg_path.write_text(_json.dumps(config))

        out = tmp_path / "out_full"
        fit = run(parse_args([
            "--train-data-dirs", str(glmix_avro["train"]),
            "--validation-data-dirs", str(glmix_avro["test"]),
            "--coordinate-config", str(cfg_path),
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(out),
            "--evaluator", "AUC",
        ]))
        assert fit.validation_metric > 0.65
        scores_dir = tmp_path / "scores_full"
        score_main([
            "--data-dirs", str(glmix_avro["test"]),
            "--model-dir", str(out / "best"),
            "--output-dir", str(scores_dir),
            "--evaluator", "AUC",
        ])
        assert any(scores_dir.iterdir())


class TestMultihostHelpers:
    def test_single_process_degenerates(self):
        import jax
        import numpy as np
        from jax.sharding import PartitionSpec as P

        from photon_ml_tpu.parallel.grid_features import grid_mesh
        from photon_ml_tpu.parallel.multihost import (
            global_batch_from_host_rows,
            host_shard_files,
            initialize_distributed,
        )

        initialize_distributed()  # no cluster env: must be a no-op
        assert host_shard_files(["b", "a", "c"]) == ["a", "b", "c"]
        mesh = grid_mesh(8, 1)
        arr = global_batch_from_host_rows(
            np.arange(16, dtype=np.float32), mesh, P("data")
        )
        assert arr.shape == (16,)
        assert jax.process_count() == 1
