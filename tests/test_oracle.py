"""External-oracle correctness gates.

The reference's golden gates came from "an assumed-correct implementation"
(cli/game/training/DriverTest.scala:84-85) — an oracle INDEPENDENT of the
code under test, so a systematic math bug cannot pass its own capture.
These tests anchor full training paths to scipy / sklearn / float64
closed forms on the same data and the exact same objective

    f(w) = sum_i weight_i * l(z_i, y_i) + 0.5 * l2 * ||w||^2

(losses/objective.py:12-16 = the reference's L2Regularization +
PointwiseLossFunction semantics).
"""

import os

import numpy as np
import pytest

from photon_ml_tpu.data import RandomEffectDataConfiguration
from photon_ml_tpu.data.game_data import GameData
from photon_ml_tpu.estimators.game import (
    FixedEffectCoordinateConfiguration,
    GameEstimator,
    RandomEffectCoordinateConfiguration,
)
from photon_ml_tpu.opt import GlmOptimizationConfiguration, RegularizationContext
from photon_ml_tpu.opt.config import OptimizerConfig
from photon_ml_tpu.testing import dense_to_shard
from photon_ml_tpu.types import RegularizationType, TaskType

RATINGS = os.path.join(os.path.dirname(__file__), "fixtures", "ratings")

L2 = lambda lam, **kw: GlmOptimizationConfiguration(
    regularization=RegularizationContext(RegularizationType.L2),
    regularization_weight=lam,
    **kw,
)


def _scipy_logistic_l2(X, y, lam, w0=None):
    """float64 L-BFGS-B on the exact objective (independent oracle)."""
    from scipy.optimize import minimize

    X = X.astype(np.float64)
    y = y.astype(np.float64)

    def fg(w):
        z = X @ w
        # stable softplus
        f = np.sum(np.logaddexp(0.0, z) - y * z) + 0.5 * lam * w @ w
        g = X.T @ (1.0 / (1.0 + np.exp(-z)) - y) + lam * w
        return f, g

    res = minimize(
        fg, w0 if w0 is not None else np.zeros(X.shape[1]),
        jac=True, method="L-BFGS-B",
        options={"maxiter": 500, "ftol": 1e-14, "gtol": 1e-10},
    )
    return res.x, res.fun


class TestFixedEffectOracle:
    def test_logistic_l2_matches_scipy_lbfgsb(self, rng):
        """a1a-style synthetic binary problem (BASELINE config 1 shape in
        miniature): the full estimator path must land on the same optimum
        as scipy's independent float64 L-BFGS-B."""
        n, d = 600, 25
        X = (rng.random((n, d)) < 0.15).astype(np.float32)  # sparse binary
        X[:, 0] = 1.0  # intercept column
        w_true = rng.normal(size=d).astype(np.float32)
        y = (1 / (1 + np.exp(-(X @ w_true))) > rng.random(n)).astype(np.float32)
        lam = 1.0

        est = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinates={"fixed": FixedEffectCoordinateConfiguration(
                "g", L2(lam, optimizer_config=OptimizerConfig.lbfgs(
                    tolerance=1e-10, max_iterations=200)),
            )},
        )
        data = GameData(labels=y, feature_shards={"g": dense_to_shard(X)}, id_tags={})
        fit = est.fit(data)
        w_ours = np.asarray(fit.model.models["fixed"].coefficients.means)

        w_oracle, f_oracle = _scipy_logistic_l2(X, y, lam)
        # float32 path vs float64 oracle: coefficients to ~1e-3, objective tighter
        np.testing.assert_allclose(w_ours, w_oracle, rtol=2e-3, atol=2e-3)
        z = X.astype(np.float64) @ w_ours.astype(np.float64)
        f_ours = float(
            np.sum(np.logaddexp(0.0, z) - y * z) + 0.5 * lam * w_ours @ w_ours
        )
        assert f_ours <= f_oracle * (1 + 1e-5)

    def test_logistic_l2_matches_sklearn(self, rng):
        """Second independent oracle: sklearn LogisticRegression minimizes
        C*sum(losses) + ||w||^2/2, the same optimum at C = 1/λ."""
        from sklearn.linear_model import LogisticRegression

        n, d = 500, 12
        X = rng.normal(size=(n, d)).astype(np.float32)
        w_true = rng.normal(size=d).astype(np.float32)
        y = (1 / (1 + np.exp(-(X @ w_true))) > rng.random(n)).astype(np.float32)
        lam = 2.0

        est = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinates={"fixed": FixedEffectCoordinateConfiguration(
                "g", L2(lam, optimizer_config=OptimizerConfig.lbfgs(
                    tolerance=1e-10, max_iterations=200)),
            )},
        )
        data = GameData(labels=y, feature_shards={"g": dense_to_shard(X)}, id_tags={})
        fit = est.fit(data)
        w_ours = np.asarray(fit.model.models["fixed"].coefficients.means)

        sk = LogisticRegression(
            C=1.0 / lam, fit_intercept=False, tol=1e-10, max_iter=1000,
        ).fit(X.astype(np.float64), y)
        np.testing.assert_allclose(w_ours, sk.coef_[0], rtol=5e-3, atol=5e-3)

    def test_linear_l2_matches_closed_form(self, rng):
        """Ridge regression has an exact float64 oracle:
        w* solves (X'X + λI) w = X'y for loss (z-y)^2/2."""
        n, d = 300, 20
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (X @ rng.normal(size=d) + 0.1 * rng.normal(size=n)).astype(np.float32)
        lam = 3.0

        est = GameEstimator(
            task=TaskType.LINEAR_REGRESSION,
            coordinates={"fixed": FixedEffectCoordinateConfiguration(
                "g", L2(lam, optimizer_config=OptimizerConfig.lbfgs(
                    tolerance=1e-12, max_iterations=300)),
            )},
        )
        data = GameData(labels=y, feature_shards={"g": dense_to_shard(X)}, id_tags={})
        fit = est.fit(data)
        w_ours = np.asarray(fit.model.models["fixed"].coefficients.means)

        X64 = X.astype(np.float64)
        w_star = np.linalg.solve(
            X64.T @ X64 + lam * np.eye(d), X64.T @ y.astype(np.float64)
        )
        np.testing.assert_allclose(w_ours, w_star, rtol=2e-3, atol=2e-3)

    def test_tron_matches_scipy_on_logistic(self, rng):
        """The trust-region path must reach the same optimum as the oracle
        (LIBLINEAR constants, but the optimum is solver-independent)."""
        n, d = 400, 15
        X = rng.normal(size=(n, d)).astype(np.float32)
        w_true = rng.normal(size=d).astype(np.float32)
        y = (1 / (1 + np.exp(-(X @ w_true))) > rng.random(n)).astype(np.float32)
        lam = 0.5

        est = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinates={"fixed": FixedEffectCoordinateConfiguration(
                "g", L2(lam, optimizer_config=OptimizerConfig.tron(
                    tolerance=1e-10, max_iterations=50)),
            )},
        )
        data = GameData(labels=y, feature_shards={"g": dense_to_shard(X)}, id_tags={})
        fit = est.fit(data)
        w_ours = np.asarray(fit.model.models["fixed"].coefficients.means)
        w_oracle, _ = _scipy_logistic_l2(X, y, lam)
        np.testing.assert_allclose(w_ours, w_oracle, rtol=2e-3, atol=2e-3)


class TestOwlqnAndPoissonOracle:
    def test_owlqn_l1_matches_sklearn_lasso(self, rng):
        """OWL-QN on squared loss + L1 vs sklearn Lasso: our objective
        sum 0.5(z-y)² + λ||w||₁ equals n·(Lasso objective) at α = λ/n, so
        the minimizers coincide — an external oracle for the orthant-wise
        path (Andrew & Gao), which no other oracle test covers."""
        from sklearn.linear_model import Lasso

        n, d = 400, 15
        X = rng.normal(size=(n, d)).astype(np.float32)
        w_true = rng.normal(size=d).astype(np.float32)
        w_true[rng.choice(d, 6, replace=False)] = 0.0  # sparse truth
        y = (X @ w_true + 0.05 * rng.normal(size=n)).astype(np.float32)
        lam = 20.0

        est = GameEstimator(
            task=TaskType.LINEAR_REGRESSION,
            coordinates={"fixed": FixedEffectCoordinateConfiguration(
                "g", GlmOptimizationConfiguration(
                    optimizer_config=OptimizerConfig.lbfgs(
                        tolerance=1e-10, max_iterations=500),
                    regularization=RegularizationContext(RegularizationType.L1),
                    regularization_weight=lam,
                ),
            )},
        )
        data = GameData(labels=y, feature_shards={"g": dense_to_shard(X)}, id_tags={})
        fit = est.fit(data)
        w_ours = np.asarray(fit.model.models["fixed"].coefficients.means)

        sk = Lasso(alpha=lam / n, fit_intercept=False, tol=1e-12,
                   max_iter=100000).fit(X.astype(np.float64), y)
        np.testing.assert_allclose(w_ours, sk.coef_, rtol=5e-3, atol=5e-3)
        # the L1 zero pattern must agree too
        assert np.array_equal(np.abs(w_ours) < 1e-4, np.abs(sk.coef_) < 1e-4)

    def test_poisson_l2_matches_scipy(self, rng):
        """Poisson regression (BASELINE config 3's loss) vs scipy float64
        L-BFGS-B on the exact objective sum(e^z - y z) + 0.5 λ||w||²."""
        from scipy.optimize import minimize

        n, d = 300, 10
        X = (rng.normal(size=(n, d)) * 0.3).astype(np.float32)
        w_true = (rng.normal(size=d) * 0.5).astype(np.float32)
        y = rng.poisson(np.exp(X @ w_true)).astype(np.float32)
        lam = 1.0

        est = GameEstimator(
            task=TaskType.POISSON_REGRESSION,
            coordinates={"fixed": FixedEffectCoordinateConfiguration(
                "g", L2(lam, optimizer_config=OptimizerConfig.lbfgs(
                    tolerance=1e-10, max_iterations=300)),
            )},
        )
        data = GameData(labels=y, feature_shards={"g": dense_to_shard(X)}, id_tags={})
        fit = est.fit(data)
        w_ours = np.asarray(fit.model.models["fixed"].coefficients.means)

        X64, y64 = X.astype(np.float64), y.astype(np.float64)

        def fg(w):
            z = X64 @ w
            ez = np.exp(z)
            return (np.sum(ez - y64 * z) + 0.5 * lam * w @ w,
                    X64.T @ (ez - y64) + lam * w)

        res = minimize(fg, np.zeros(d), jac=True, method="L-BFGS-B",
                       options={"maxiter": 500, "ftol": 1e-14, "gtol": 1e-10})
        np.testing.assert_allclose(w_ours, res.x, rtol=2e-3, atol=2e-3)


class TestRandomEffectOracle:
    def test_re_solves_match_per_entity_scipy(self, rng):
        """Every per-entity random-effect solve must match an independent
        per-entity scipy solve of the same local objective (the vmap'd
        batched solver vs one scipy call per entity)."""
        n_entities, rows, d = 10, 25, 6
        n = n_entities * rows
        X = rng.normal(size=(n, d)).astype(np.float32)
        ids = np.repeat([f"e{i}" for i in range(n_entities)], rows)
        w_ent = {f"e{i}": rng.normal(size=d).astype(np.float32)
                 for i in range(n_entities)}
        z = np.array([X[r] @ w_ent[ids[r]] for r in range(n)], np.float32)
        y = (1 / (1 + np.exp(-z)) > rng.random(n)).astype(np.float32)
        lam = 1.0

        est = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinates={"per_e": RandomEffectCoordinateConfiguration(
                "u",
                data=RandomEffectDataConfiguration(random_effect_type="eid"),
                optimizer=L2(lam, optimizer_config=OptimizerConfig.lbfgs(
                    tolerance=1e-10, max_iterations=200)),
            )},
        )
        data = GameData(
            labels=y, feature_shards={"u": dense_to_shard(X)}, id_tags={"eid": ids},
        )
        fit = est.fit(data)
        re_model = fit.model.models["per_e"]
        ours = {eid: coefs for eid, coefs in re_model.items()}

        for i in range(n_entities):
            eid = f"e{i}"
            sel = ids == eid
            w_oracle, _ = _scipy_logistic_l2(X[sel], y[sel], lam)
            w_got = np.zeros(d)
            for j, v in ours[eid].items():
                w_got[j] = v
            np.testing.assert_allclose(
                w_got, w_oracle, rtol=5e-3, atol=5e-3,
                err_msg=f"entity {eid}",
            )


class TestRatingsFixtureOracle:
    def test_fe_ridge_on_fixture_matches_closed_form(self):
        """The golden fixture's fixed-effect-only scenario, anchored to an
        external float64 closed-form oracle instead of a self-capture
        (upgrades test_golden_fixture's gate discipline)."""
        from photon_ml_tpu.io.data_reader import (
            FeatureShardConfiguration,
            read_game_data,
        )
        shards = {"global": FeatureShardConfiguration(
            feature_bags=["features"], add_intercept=True)}
        data, index_maps, _ = read_game_data(
            [os.path.join(RATINGS, "train")], shards, None, id_tags=[],
        )
        shard = data.feature_shards["global"]
        X = np.zeros((data.num_rows, shard.dim), np.float32)
        X[shard.rows, shard.cols] = shard.vals
        lam = 10.0

        est = GameEstimator(
            task=TaskType.LINEAR_REGRESSION,
            coordinates={"fixed": FixedEffectCoordinateConfiguration(
                "global", L2(lam, optimizer_config=OptimizerConfig.lbfgs(
                    tolerance=1e-12, max_iterations=300)),
            )},
        )
        fit = est.fit(data)
        w_ours = np.asarray(fit.model.models["fixed"].coefficients.means)

        X64 = X.astype(np.float64)
        y64 = data.labels.astype(np.float64)
        w_star = np.linalg.solve(
            X64.T @ X64 + lam * np.eye(shard.dim), X64.T @ y64
        )
        np.testing.assert_allclose(w_ours, w_star, rtol=3e-3, atol=3e-3)
